# Build/check entry points (the reference's `make` + rebar gates analog:
# /root/reference/Makefile, rebar.config:16-36 dialyzer/xref/elvis).

.PHONY: check check-json lint lint-fast lint-locks test test-fast \
        native bench restore-bench chaos ds-bench ds-dump ds-soak \
        churn-bench retained-bench fanout-bench span-bench prep-bench \
        wire-bench shm-bench fleet-bench repl-soak takeover-bench \
        semantic-bench

# static-analysis gate (tools/analysis/): the dialyzer/xref/elvis
# analog, stdlib-only — whole-project AST index + call graph, thread-
# role inference + event-loop blocking-call detector, cross-thread race
# lint, lock-order graphs + deadlock cycles (lockorder.json), task/
# resource lifecycle, cancellation safety, registry cross-checks, style
# lints.  Exit 0 = empty error tier and no non-baselined warnings (same
# contract the old tools/check.py had, now tiered; see README "Static
# analysis").
lint:
	python -m tools.analysis

# fast iteration: expensive per-file passes limited to `git diff` files
lint-fast:
	python -m tools.analysis --changed

# lock-order pass alone (single-pass iteration while reordering locks)
lint-locks:
	python -m tools.analysis --only locks --stats

# machine-readable findings (CI annotations, dashboards)
check-json:
	python -m tools.analysis --json

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -x --ignore=tests/test_cluster_fvt.py

# lint + full suite = the merge gate
check: lint test

native:
	$(MAKE) -C native

bench:
	python bench.py

# warm-restart bench: snapshot+WAL restore vs cold table rebuild at
# 100k filters; writes the restore_ms/rebuild_ms row into BENCH_TABLE.md
restore-bench:
	python bench.py --restore

# retained-index sweep: stored names x lookup batch size, host trie vs
# the bucketed device index (exact parity asserted per filter), with
# the transfer-free kernel rate and the arbiter's picks recorded
retained-bench:
	python bench.py --retained

# semantic subscription plane: device top-k vs host dense scorer sweep
# + the e2e shm-hub leg (BENCH_TABLE.md "Semantic subscriptions")
semantic-bench:
	python bench.py --semantic

# delivery-plane fan-out sweep: one filter, 1k/10k/50k/100k
# subscribers; expansion vs the full wire path (scatter lane + shared
# packet prefix) with per-delivery ns; writes the BENCH_TABLE.md
# section
fanout-bench:
	python bench.py --fanout

# message-lifecycle span attribution: per-stage p50/p99 across
# hooks/submit/collect/enqueue/wire + the cross-node forward leg + the
# durable-log ds leg, plus the disarmed-overhead A/B on the fan-out
# wire path (BENCH_NO_SPANS=1 runs the disarmed leg only); writes the
# BENCH_TABLE.md "Latency attribution" section
span-bench:
	python bench.py --spans

# multi-seed chaos soak: 3-node cluster + hybrid engine under a seeded
# fault schedule; asserts no QoS1 forward loss, engine/oracle parity,
# breaker + alarm lifecycle, spool drain (tools/chaos_soak.py)
chaos:
	python tools/chaos_soak.py --seeds 5

# offline-fanout bench: N parked sessions x M offline messages —
# durable-log replay resume vs the legacy per-session JSON snapshot
# path (park-tick cost + restore + resume latency); writes the
# BENCH_TABLE.md section
ds-bench:
	python bench.py --ds

# inspect a durable-message-log directory (symmetric with ckpt_dump):
#   make ds-dump DIR=data/ds
ds-dump:
	python tools/ds_dump.py $(DIR) --records 3

# ds crash front only: kill -9 a real appender child mid-flush across
# 5 seeds; committed prefix must replay, (mid) dedup = exactly-once
ds-soak:
	python tools/chaos_soak.py --fronts ds --seeds 5

# ds replication front only: leader/follower child pairs over a real
# PeerLink, kill -9 the leader mid-flush and the follower mid-ack
# across 5 seeds; zero loss at/below the replicated watermark, the
# mirror stays a byte-identical prefix, replay is exactly-once, and a
# dead follower never blocks the leader's flush path
repl-soak:
	python tools/chaos_soak.py --fronts repl --seeds 5

# cursor-handoff takeover bench: a 10k-message parked queue crossing
# nodes — materialized session ship vs the replicated-mirror cursor
# handoff (bytes on the wire + takeover latency); writes the
# BENCH_TABLE.md section
takeover-bench:
	python bench.py --takeover

# churn-apply capacity worker sweep: parallel churn plane vs the serial
# python-dict path at 1/2/4 pool workers (ETPU_POOL_THREADS pinned per
# subprocess); writes the BENCH_TABLE.md churn-capacity section
churn-bench:
	python bench.py --churn

# fused prep op in isolation: native etpu_prep_pack vs the python
# fallback at B=512/2048 over the sharded workload's Zipf stream;
# writes the BENCH_TABLE.md fused-prep section
prep-bench:
	python bench.py --sharded 2 --prep-only

# process-sharded wire plane: aggregate wire deliveries/s over real
# sockets at 0/1/2 wire workers (hub + SO_REUSEPORT worker pool over
# unix-socket PeerLinks, per-worker occupancy + rep-spread columns);
# writes the BENCH_TABLE.md section.  On a multi-core host the gate is
# >=1.8x aggregate at 2 workers vs 1; on a 1-thread container the
# sweep measures the IPC tax (no-regression at workers=1).
wire-bench:
	python bench.py --wire

# shared-memory match plane microbench (emqx_tpu/shm/): in-process
# ring round-trip latency + multi-lane fusion + churn-ack throughput;
# the cross-process rows live in `make wire-bench`
shm-bench:
	python bench.py --shm

# fleet observability: shm-lane span legs over the real hub +
# 2-wire-worker topology — per-leg attribution, mean-sum
# reconciliation vs the measured ring round-trip, armed/disarmed
# overhead A/B; renders via tools/fleet_dump.py
fleet-bench:
	python bench.py --spans-shm
