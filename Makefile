# Build/check entry points (the reference's `make` + rebar gates analog:
# /root/reference/Makefile, rebar.config:16-36 dialyzer/xref/elvis).

.PHONY: check lint test test-fast native bench

# static-analysis gate: stdlib implementation (mypy/ruff are not in this
# image and installs are off-limits — see tools/check.py header)
lint:
	python tools/check.py

test:
	python -m pytest tests/ -q

test-fast:
	python -m pytest tests/ -q -x --ignore=tests/test_cluster_fvt.py

# lint + full suite = the merge gate
check: lint test

native:
	$(MAKE) -C native

bench:
	python bench.py
