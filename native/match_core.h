// Shared declarations for the native hot paths: the fused host match
// core (registry.cc), the registry bulk mutators (used by the churn
// plane), and the inline per-filter key computation shared by
// matchhash.cc etpu_filter_keys and churn.cc (one implementation so the
// table-key semantics cannot drift between the bulk and churn paths).
#pragma once

#include <cstdint>

// Opaque registry handle (created by etpu_reg_new).
extern "C" {

int64_t etpu_match_core(
    void* reg_h,
    const uint8_t* tbuf, const int64_t* toffs, int32_t B,
    int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* key_a, const uint32_t* key_b, const int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* incl, const uint32_t* k_a, const uint32_t* k_b,
    const int32_t* min_len, const int32_t* max_len,
    const uint8_t* wild_root, const uint8_t* valid, int32_t M, int32_t L,
    int32_t* out_fid, int32_t* out_cnt, int32_t vcap,
    int32_t* out_coll, int32_t coll_cap, int32_t* n_coll);

void etpu_reg_set_bulk(void* h, const int32_t* fids, int32_t n,
                       const uint8_t* buf, const int64_t* offs);
void etpu_reg_del_bulk(void* h, const int32_t* fids, int32_t n);

}  // extern "C"

// ---- shared hash/key helpers (ops/hashing.py semantics, bit-for-bit) ----

namespace etpu {

constexpr uint64_t kFnvOffset = 0xcbf29ce484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001b3ULL;
// ops/hashing.py _PERTURB: keeps hash("") != 0
constexpr uint64_t kPerturb = 0xD6E8FEB86659FD93ULL;

static inline uint64_t fnv1a64(const uint8_t* s, uint64_t n) {
  uint64_t h = kFnvOffset;
  for (uint64_t i = 0; i < n; i++) {
    h ^= (uint64_t)s[i];
    h *= kFnvPrime;
  }
  return h;
}

// Split ONE topic on '/' and emit its per-level mix terms — the inner
// loop of ops/hashing.py hash_topic_batch, bit-for-bit.  Shared by the
// batch prep entry (matchhash.cc etpu_prep_topics) and the memoized
// fused prep plane (prep.cc etpu_prep_hash) so the topic-hash semantics
// cannot drift between the two prep paths.  `ra`/`rb` rows must be
// zeroed by the caller for levels >= min(level count, max_levels);
// levels past max_levels are split (they count toward *ln) but not
// hashed, matching the device kernel's level cap.
static inline void topic_terms_one(
    const uint8_t* t, int64_t n, int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    uint32_t* ra, uint32_t* rb, int32_t* ln, uint8_t* dl) {
  *dl = (n > 0 && t[0] == '$') ? 1 : 0;
  int32_t level = 0;
  int64_t start = 0;
  for (int64_t p = 0; p <= n; p++) {
    if (p == n || t[p] == '/') {
      if (level < max_levels) {
        uint64_t h = fnv1a64(t + start, (uint64_t)(p - start)) ^ kPerturb;
        ra[level] = ((uint32_t)h ^ Ca[level]) * Ra[level];
        rb[level] = ((uint32_t)(h >> 32) ^ Cb[level]) * Rb[level];
      }
      level++;
      start = p + 1;
    }
  }
  // "" splits to one empty level, like Python "".split("/") == [""]
  *ln = (n == 0) ? 1 : level;
}

struct FilterKey {
  uint32_t ha, hb, plus_mask;
  int32_t plen;
  uint8_t has_hash;
};

// Table key + wildcard shape of one subscription filter —
// ops/hashing.py HashSpace.filter_key semantics (see matchhash.cc
// etpu_filter_keys for the contract notes).  plen may exceed
// max_levels: such filters are DEEP and take the host-trie path.
static inline FilterKey filter_key_one(
    const uint8_t* f, int64_t n, int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* PLUS, const uint32_t* HM,
    const uint32_t* HRa, const uint32_t* HRb) {
  FilterKey k{0, 0, 0, 0, 0};
  int64_t start = 0;
  int32_t level = 0;
  for (int64_t p = 0; p <= n; p++) {
    if (p == n || f[p] == '/') {
      int64_t wlen = p - start;
      bool last = (p == n);
      if (last && wlen == 1 && f[start] == '#') {
        k.has_hash = 1;
      } else {
        if (wlen == 1 && f[start] == '+') {
          if (level < 32) k.plus_mask |= 1u << level;
          if (level < max_levels) {
            k.ha += (PLUS[0] ^ Ca[level]) * Ra[level];
            k.hb += (PLUS[1] ^ Cb[level]) * Rb[level];
          }
        } else if (level < max_levels) {
          uint64_t h = fnv1a64(f + start, (uint64_t)wlen) ^ kPerturb;
          k.ha += ((uint32_t)h ^ Ca[level]) * Ra[level];
          k.hb += ((uint32_t)(h >> 32) ^ Cb[level]) * Rb[level];
        }
        level++;
      }
      start = p + 1;
    }
  }
  // "" splits to one empty level, which the loop above already hashed
  k.plen = level;
  if (k.has_hash && k.plen <= max_levels) {
    k.ha += HM[0] * HRa[k.plen];
    k.hb += HM[1] * HRb[k.plen];
  }
  if (k.ha == 0 && k.hb == 0) k.hb = 1;  // (0,0) = empty-slot sentinel
  return k;
}

}  // namespace etpu
