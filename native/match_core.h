// Shared declaration of the fused host match core (registry.cc) so both
// the ctypes entry point and the CPython extension (pymod.cc) call one
// implementation.
#pragma once

#include <cstdint>

// Opaque registry handle (created by etpu_reg_new).
extern "C" {

int64_t etpu_match_core(
    void* reg_h,
    const uint8_t* tbuf, const int64_t* toffs, int32_t B,
    int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* key_a, const uint32_t* key_b, const int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* incl, const uint32_t* k_a, const uint32_t* k_b,
    const int32_t* min_len, const int32_t* max_len,
    const uint8_t* wild_root, const uint8_t* valid, int32_t M, int32_t L,
    int32_t* out_fid, int32_t* out_cnt, int32_t vcap,
    int32_t* out_coll, int32_t coll_cap, int32_t* n_coll);

}  // extern "C"
