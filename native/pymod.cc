// CPython extension face of the native library (same .so as the ctypes
// entry points, so both views share one loaded image and one registry).
//
// Why an extension on top of ctypes: the broker's host match tick at
// interactive batch sizes (512) spends as much time in Python glue
// (utf-8 packing, numpy masking, list assembly) as in the fused C++
// matcher.  `match_lists` takes the Python topic list and the raw table
// pointers and returns the per-topic fid lists directly: pack, match,
// and result assembly all happen here, with the GIL released around the
// matcher core.  ops/native.py falls back to the ctypes + numpy path
// when the extension is unavailable (built without Python.h).
//
// Array arguments arrive as raw addresses (numpy .ctypes.data ints);
// the caller keeps the owning arrays alive across the call — the same
// contract the ctypes entry points already rely on.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

#include "match_core.h"

namespace {

struct Packed {
  std::vector<uint8_t> buf;
  std::vector<int64_t> offs;
};

// Pack a list of str into one utf-8 buffer + offsets. Returns false and
// sets a Python error on non-str items.
bool pack_topics(PyObject* topics, Py_ssize_t n, Packed* out) {
  out->offs.resize(n + 1);
  out->offs[0] = 0;
  size_t total = 0;
  std::vector<const char*> ptrs(n);
  std::vector<Py_ssize_t> lens(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(topics, i);  // borrowed
    Py_ssize_t sz;
    const char* s = PyUnicode_AsUTF8AndSize(it, &sz);
    if (s == nullptr) return false;
    ptrs[i] = s;
    lens[i] = sz;
    total += (size_t)sz;
    out->offs[i + 1] = (int64_t)total;
  }
  out->buf.resize(total ? total : 1);
  uint8_t* dst = out->buf.data();
  for (Py_ssize_t i = 0; i < n; i++) {
    std::memcpy(dst + out->offs[i], ptrs[i], (size_t)lens[i]);
  }
  return true;
}

// match_lists(reg, topics, max_levels, Ca, Cb, Ra, Rb,
//             key_a, key_b, val, log2cap, probe,
//             incl, k_a, k_b, min_len, max_len, wild_root, valid,
//             M, L, vcap) -> (list[list[int]], list[(topic_idx, fid)])
PyObject* match_lists(PyObject* self, PyObject* args) {
  unsigned long long reg_p, Ca_p, Cb_p, Ra_p, Rb_p, ka_p, kb_p, val_p;
  unsigned long long incl_p, sk_a_p, sk_b_p, minl_p, maxl_p, wr_p, vd_p;
  PyObject* topics;
  int max_levels, log2cap, probe, M, L, vcap;
  if (!PyArg_ParseTuple(
          args, "KO!iKKKKKKKiiKKKKKKKiii", &reg_p, &PyList_Type, &topics,
          &max_levels, &Ca_p, &Cb_p, &Ra_p, &Rb_p, &ka_p, &kb_p, &val_p,
          &log2cap, &probe, &incl_p, &sk_a_p, &sk_b_p, &minl_p, &maxl_p,
          &wr_p, &vd_p, &M, &L, &vcap))
    return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(topics);
  Packed packed;
  if (!pack_topics(topics, n, &packed)) return nullptr;
  if (vcap < 1) vcap = 1;
  std::vector<int32_t> out_fid((size_t)n * vcap);
  std::vector<int32_t> out_cnt((size_t)(n ? n : 1), 0);
  const int coll_cap = 256;
  std::vector<int32_t> out_coll(2 * coll_cap);
  int32_t n_coll = 0;
  Py_BEGIN_ALLOW_THREADS;
  etpu_match_core(
      (void*)(uintptr_t)reg_p, packed.buf.data(), packed.offs.data(),
      (int32_t)n, max_levels, (const uint32_t*)(uintptr_t)Ca_p,
      (const uint32_t*)(uintptr_t)Cb_p, (const uint32_t*)(uintptr_t)Ra_p,
      (const uint32_t*)(uintptr_t)Rb_p, (const uint32_t*)(uintptr_t)ka_p,
      (const uint32_t*)(uintptr_t)kb_p, (const int32_t*)(uintptr_t)val_p,
      log2cap, probe, (const uint32_t*)(uintptr_t)incl_p,
      (const uint32_t*)(uintptr_t)sk_a_p, (const uint32_t*)(uintptr_t)sk_b_p,
      (const int32_t*)(uintptr_t)minl_p, (const int32_t*)(uintptr_t)maxl_p,
      (const uint8_t*)(uintptr_t)wr_p, (const uint8_t*)(uintptr_t)vd_p, M, L,
      out_fid.data(), out_cnt.data(), vcap, out_coll.data(), coll_cap,
      &n_coll);
  Py_END_ALLOW_THREADS;

  // rows are TUPLES (callers only iterate/len them — the broker dispatch
  // and the engine's raw contract): tuple allocation rides the freelist
  // and the shared () singleton makes miss topics near-free.
  PyObject* empty = PyTuple_New(0);
  if (empty == nullptr) return nullptr;
  PyObject* rows = PyList_New(n);
  if (rows == nullptr) {
    Py_DECREF(empty);
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n; i++) {
    int32_t cnt = out_cnt[i];
    PyObject* row;
    if (cnt == 0) {
      Py_INCREF(empty);
      row = empty;
    } else {
      row = PyTuple_New(cnt);
      if (row == nullptr) {
        Py_DECREF(empty);
        Py_DECREF(rows);
        return nullptr;
      }
      const int32_t* src = out_fid.data() + (size_t)i * vcap;
      for (int32_t k = 0; k < cnt; k++) {
        PyObject* v = PyLong_FromLong(src[k]);
        if (v == nullptr) {
          Py_DECREF(row);
          Py_DECREF(empty);
          Py_DECREF(rows);
          return nullptr;
        }
        PyTuple_SET_ITEM(row, k, v);
      }
    }
    PyList_SET_ITEM(rows, i, row);
  }
  Py_DECREF(empty);
  int nc = n_coll < coll_cap ? n_coll : coll_cap;
  PyObject* colls = PyList_New(nc);
  if (colls == nullptr) {
    Py_DECREF(rows);
    return nullptr;
  }
  for (int k = 0; k < nc; k++) {
    PyObject* pair =
        Py_BuildValue("(ii)", out_coll[2 * k], out_coll[2 * k + 1]);
    if (pair == nullptr) {
      Py_DECREF(colls);
      Py_DECREF(rows);
      return nullptr;
    }
    PyList_SET_ITEM(colls, k, pair);
  }
  PyObject* res = Py_BuildValue("(NN)", rows, colls);
  if (res == nullptr) {
    Py_DECREF(rows);
    Py_DECREF(colls);
  }
  return res;
}

// churn_lookup(plane_ptr, filter) -> fid | -1
//
// Thin fast path over the churn plane's filter -> fid map (churn.cc):
// `engine.fid_of` sits on interactive paths and in bench loops, and the
// ctypes route costs ~1 us of argument glue per call vs ~100 ns here.
extern "C" int32_t etpu_churn_lookup(void* h, const uint8_t* s, int64_t n);

PyObject* churn_lookup(PyObject* self, PyObject* args) {
  unsigned long long plane_p;
  const char* s;
  Py_ssize_t n;
  if (!PyArg_ParseTuple(args, "Ks#", &plane_p, &s, &n)) return nullptr;
  int32_t fid =
      etpu_churn_lookup((void*)(uintptr_t)plane_p, (const uint8_t*)s, n);
  if (fid < 0) Py_RETURN_NONE;
  return PyLong_FromLong(fid);
}

PyMethodDef methods[] = {
    {"match_lists", match_lists, METH_VARARGS,
     "Fused host match: topic list in, per-topic fid lists out."},
    {"churn_lookup", churn_lookup, METH_VARARGS,
     "Churn-plane filter -> fid lookup (None when absent)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moddef = {
    PyModuleDef_HEAD_INIT, "_etpu_ext",
    "CPython face of the emqx_tpu native hot paths.", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__etpu_ext(void) { return PyModule_Create(&moddef); }
