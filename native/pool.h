// Persistent worker pool for the native host hot paths.
//
// std::thread spawn costs ~20-50us; the match/hash entry points are called
// per publish tick (ms scale), so re-spawning 8-16 threads per call wastes
// a measurable slice of the latency budget.  This pool keeps detached
// workers parked on a condition variable and hands them chunked index
// ranges via an atomic cursor.  The singleton is never destroyed (detached
// threads + intentional leak), so there is no shutdown race with the
// C++ runtime at interpreter exit.
#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

class EtpuPool {
 public:
  static EtpuPool& inst() {
    static EtpuPool* p = new EtpuPool();  // never destroyed by design
    return *p;
  }

  // Run fn(i0, i1) over [0, n) in chunks; blocks until all chunks finish.
  // The calling thread participates, so small jobs never context-switch.
  void parallel_for(int32_t n, int32_t chunk,
                    const std::function<void(int32_t, int32_t)>& fn) {
    if (n <= 0) return;
    if (n <= chunk || nworkers_ == 0) {
      fn(0, n);
      return;
    }
    std::unique_lock<std::mutex> job_lk(job_mutex_);  // one job at a time
    {
      std::lock_guard<std::mutex> lk(m_);
      fn_ = &fn;
      n_ = n;
      chunk_ = chunk;
      cursor_.store(0, std::memory_order_relaxed);
      pending_.store(nworkers_, std::memory_order_relaxed);
      generation_++;
    }
    cv_.notify_all();
    work();  // caller takes chunks too
    // wait for workers to drain (they decrement pending_ when the cursor
    // runs out)
    std::unique_lock<std::mutex> lk(m_);
    done_cv_.wait(lk, [&] { return pending_.load() == 0; });
    fn_ = nullptr;
  }

  // worker threads + the calling thread (the effective parallelism of
  // parallel_for on large jobs; the churn bench reports this)
  int32_t width() const { return nworkers_ + 1; }

 private:
  EtpuPool() {
    // ETPU_POOL_THREADS pins the pool width (worker sweeps in
    // `bench.py --churn`, single-thread A/B runs); default: one worker
    // per hardware thread beyond the caller, capped at 16 total.
    unsigned hw = std::thread::hardware_concurrency();
    const char* env = std::getenv("ETPU_POOL_THREADS");
    if (env != nullptr && *env != '\0') {
      long v = std::strtol(env, nullptr, 10);
      if (v >= 1 && v <= 64) hw = (unsigned)v;
    }
    nworkers_ = hw > 16 ? 15 : (hw > 1 ? (int32_t)hw - 1 : 0);
    for (int32_t i = 0; i < nworkers_; i++) {
      std::thread([this, gen = uint64_t{0}]() mutable {
        while (true) {
          {
            std::unique_lock<std::mutex> lk(m_);
            cv_.wait(lk, [&] { return generation_ != gen; });
            gen = generation_;
          }
          work();
          if (pending_.fetch_sub(1) == 1) {
            std::lock_guard<std::mutex> lk(m_);
            done_cv_.notify_all();
          }
        }
      }).detach();
    }
  }

  void work() {
    const std::function<void(int32_t, int32_t)>* fn = fn_;
    if (!fn) return;
    while (true) {
      int32_t i0 = cursor_.fetch_add(chunk_, std::memory_order_relaxed);
      if (i0 >= n_) break;
      int32_t i1 = i0 + chunk_ > n_ ? n_ : i0 + chunk_;
      (*fn)(i0, i1);
    }
  }

  std::mutex job_mutex_;
  std::mutex m_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int32_t, int32_t)>* fn_ = nullptr;
  int32_t n_ = 0, chunk_ = 1, nworkers_ = 0;
  uint64_t generation_ = 0;
  std::atomic<int32_t> cursor_{0};
  std::atomic<int32_t> pending_{0};
};
