// Parallel churn plane: sharded, GIL-free route bookkeeping.
//
// The reference partitions route-table writes across workers
// (`emqx_router`/mria shards, PAPER.md §1); the analog here is a
// C++-owned filter -> (fid, refcount, key) registry partitioned by
// matchhash(filter) % n_shards, mutated by the persistent worker pool
// (pool.h) with the GIL released (ctypes drops it around every call).
// One `etpu_churn_apply` call replaces the per-filter Python dict work
// of `apply_churn` — the measured single-core ceiling at config 5's
// 500k subscribe/unsubscribe ops/s (BENCH_TABLE.md north-star notes):
//
//   partition (parallel): one fnv1a64 pass over the packed batch; the
//            hash doubles as the shard id AND the map key, so no string
//            is ever hashed twice;
//   phase A (parallel over shards): remove decrements + dead harvest
//            and add lookups (refcount bumps / pending-new dedup) on
//            open-addressed hash->entry maps — no allocation per op;
//   phase B (serial, cheap): dead-slot clears (parallel sub-pass) and
//            fid allocation in INPUT order from the LIFO free list —
//            bit-for-bit the Python allocator, so fid assignment is
//            deterministic and identical to the serial oracle;
//   phase C (parallel over shards): per-new-filter key computation
//            (match_core.h filter_key_one) + open-addressed table
//            placement via CAS slot claims;
//   phase D (serial): registry string set/del for the fused host match.
//
// Table writes follow the existing benign-dirty-read model (registry.cc
// header): claims CAS `val` from -1, clears zero keys BEFORE releasing
// `val`, and every reader exact-verifies hits against the registry
// string — a torn slot can only cost a miss or a counted collision,
// never a false delivery.
//
// The caller (ops/tables.py apply_planned) turns the outputs into shape
// refcounts, entry bookkeeping, and the device-mirror Delta, so the
// merged delta rides the existing fused delta+match device dispatch
// unchanged.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "match_core.h"
#include "pool.h"

namespace {

using etpu::FilterKey;

struct PlaneEnt {
  std::string str;
  uint64_t hash64 = 0;  // fnv1a64(str): shard id + map key, computed once
  uint32_t ha = 0, hb = 0, plus_mask = 0;
  int32_t fid = -1, rc = 0, plen = 0;
  uint8_t has_hash = 0, deep = 0, live = 0;
  uint32_t batch_gen = 0;  // tag: first decrement seen this apply
  int32_t first_ridx = 0;  // remove index of that first decrement
};

// Open-addressed hash -> entry-index map (linear probing, tombstones).
// Python dicts cache each str's hash; this map gets the same economy by
// keying on the precomputed fnv1a64 and only comparing bytes on a
// 64-bit hash hit.
struct EntMap {
  std::vector<int32_t> slots;  // ent index, -1 empty, -2 tombstone
  uint32_t mask = 0;
  int32_t live = 0, tomb = 0;

  void reserve_one(const std::vector<PlaneEnt>& ents) {
    if (slots.empty()) {
      slots.assign(16, -1);
      mask = 15;
      return;
    }
    if ((live + tomb + 1) * 4 <= (int32_t)slots.size() * 3) return;
    // rebuild (dropping tombstones) at a capacity keeping load <= 1/2;
    // a tombstone-heavy map may rebuild at the same capacity
    size_t cap = slots.size();
    while ((size_t)(live + 1) * 2 >= cap) cap *= 2;
    std::vector<int32_t> old;
    old.swap(slots);
    slots.assign(cap, -1);
    mask = (uint32_t)cap - 1;
    tomb = 0;
    for (int32_t ei : old) {
      if (ei < 0) continue;
      uint32_t i = (uint32_t)ents[ei].hash64 & mask;
      while (slots[i] != -1) i = (i + 1) & mask;
      slots[i] = ei;
    }
  }

  // slot index holding the entry, or -1
  int32_t find(const std::vector<PlaneEnt>& ents, uint64_t h,
               const uint8_t* s, int64_t n) const {
    if (slots.empty()) return -1;
    uint32_t i = (uint32_t)h & mask;
    while (true) {
      int32_t ei = slots[i];
      if (ei == -1) return -1;
      if (ei >= 0) {
        const PlaneEnt& e = ents[ei];
        if (e.hash64 == h && e.str.size() == (size_t)n &&
            std::memcmp(e.str.data(), s, (size_t)n) == 0)
          return (int32_t)i;
      }
      i = (i + 1) & mask;
    }
  }

  void insert(uint64_t h, int32_t ei) {  // caller ran reserve_one
    uint32_t i = (uint32_t)h & mask;
    while (slots[i] >= 0) i = (i + 1) & mask;
    if (slots[i] == -2) tomb--;
    slots[i] = ei;
    live++;
  }

  void erase_at(int32_t slot) {
    slots[slot] = -2;
    tomb++;
    live--;
  }
};

struct PlaneShard {
  EntMap idx;
  std::vector<PlaneEnt> ents;
  std::vector<int32_t> free_ents;

  // per-apply scratch (reused across calls)
  std::vector<int32_t> my_adds, my_rems;  // batch indices in this shard
  std::vector<int32_t> pend_first;        // first aidx per pending-new
  std::vector<int32_t> pend_fid;          // fid assigned in phase B
  std::vector<int32_t> pend_rc;           // occurrences in this batch
  std::vector<int32_t> pend_pos;          // output row (aidx rank)
  std::vector<std::pair<int32_t, int32_t>> pend_dups;  // (aidx, pend id)
  std::vector<int32_t> dead_ents;         // ent slots killed this apply
  std::vector<int32_t> pend_slots;        // open-addressed pend id table

  int32_t alloc_ent() {
    if (!free_ents.empty()) {
      int32_t e = free_ents.back();
      free_ents.pop_back();
      return e;
    }
    ents.emplace_back();
    return (int32_t)ents.size() - 1;
  }
};

struct ChurnPlane {
  int32_t nshards, max_levels;
  std::vector<PlaneShard> shards;
  std::vector<uint32_t> Ca, Cb, Ra, Rb, HRa, HRb;
  uint32_t PLUS[2], HM[2];
  std::vector<int32_t> free_fids;  // serial-phase only (LIFO, like Python)
  int32_t next_fid = 0;
  uint32_t gen = 0;
  int64_t n_live = 0;
  // scratch reused across applies: per-item hashes (the partition pass
  // computes them once; every later lookup reuses them)
  std::vector<uint64_t> a_hash, r_hash;

  int32_t shard_of(uint64_t h) const {
    return (int32_t)(h % (uint64_t)nshards);
  }
  FilterKey key_of(const uint8_t* s, int64_t n) const {
    return etpu::filter_key_one(s, n, max_levels, Ca.data(), Cb.data(),
                                Ra.data(), Rb.data(), PLUS, HM,
                                HRa.data(), HRb.data());
  }
};

constexpr uint32_t MIX1 = 0x85EBCA77u, MIX2 = 0x9E3779B1u;

static inline uint32_t home_of(uint32_t ha, uint32_t hb, int32_t log2cap) {
  return ((ha + hb * MIX1) * MIX2) >> (32 - log2cap);
}

// Clear a dying entry's table slot: zero the keys FIRST (probes then
// skip the slot on key mismatch), release val last — a concurrent
// placement can only claim the slot after the release, so the clearer
// never stomps the claimer's key writes.
static void clear_slot(uint32_t* key_a, uint32_t* key_b, int32_t* val,
                       int32_t log2cap, int32_t probe,
                       uint32_t ha, uint32_t hb, int32_t fid,
                       int32_t* out_slot) {
  uint32_t cap_mask = (1u << log2cap) - 1;
  uint32_t home = home_of(ha, hb, log2cap);
  for (int32_t off = 0; off < probe; off++) {
    uint32_t slot = (home + (uint32_t)off) & cap_mask;
    if (__atomic_load_n(&val[slot], __ATOMIC_RELAXED) == fid &&
        key_a[slot] == ha && key_b[slot] == hb) {
      key_a[slot] = 0;
      key_b[slot] = 0;
      __atomic_store_n(&val[slot], -1, __ATOMIC_RELEASE);
      *out_slot = (int32_t)slot;
      return;
    }
  }
  *out_slot = -1;  // not in the table (deep, or raced a rebuild)
}

// CAS-claim placement (etpu_bulk_place semantics, thread-safe): claim
// `val` -1 -> fid, then write the keys.  Readers that see the claimed
// slot before the keys land reject on key mismatch (or exact-verify).
static int32_t place_slot_cas(uint32_t* key_a, uint32_t* key_b,
                              int32_t* val, int32_t log2cap, int32_t probe,
                              uint32_t ha, uint32_t hb, int32_t fid) {
  uint32_t cap_mask = (1u << log2cap) - 1;
  uint32_t home = home_of(ha, hb, log2cap);
  for (int32_t off = 0; off < probe; off++) {
    uint32_t slot = (home + (uint32_t)off) & cap_mask;
    int32_t expected = -1;
    if (__atomic_load_n(&val[slot], __ATOMIC_RELAXED) != -1) continue;
    if (__atomic_compare_exchange_n(&val[slot], &expected, fid, false,
                                    __ATOMIC_ACQ_REL, __ATOMIC_RELAXED)) {
      key_a[slot] = ha;
      key_b[slot] = hb;
      return (int32_t)slot;
    }
  }
  return -1;  // window full: caller grows + rebuilds with the pending tail
}

}  // namespace

extern "C" {

void* etpu_churn_new(int32_t n_shards, int32_t max_levels,
                     const uint32_t* Ca, const uint32_t* Cb,
                     const uint32_t* Ra, const uint32_t* Rb,
                     const uint32_t* PLUS, const uint32_t* HM,
                     const uint32_t* HRa, const uint32_t* HRb) {
  ChurnPlane* p = new ChurnPlane();
  p->nshards = n_shards > 0 ? n_shards : 1;
  p->max_levels = max_levels;
  p->shards.resize(p->nshards);
  p->Ca.assign(Ca, Ca + max_levels);
  p->Cb.assign(Cb, Cb + max_levels);
  p->Ra.assign(Ra, Ra + max_levels);
  p->Rb.assign(Rb, Rb + max_levels);
  p->HRa.assign(HRa, HRa + max_levels + 1);
  p->HRb.assign(HRb, HRb + max_levels + 1);
  p->PLUS[0] = PLUS[0]; p->PLUS[1] = PLUS[1];
  p->HM[0] = HM[0]; p->HM[1] = HM[1];
  return p;
}

void etpu_churn_free(void* h) { delete (ChurnPlane*)h; }

// Effective parallel_for width (workers + caller): the churn bench
// reports it so capacity rows carry their worker count.
int32_t etpu_pool_width() { return EtpuPool::inst().width(); }

int64_t etpu_churn_count(void* h) { return ((ChurnPlane*)h)->n_live; }

int32_t etpu_churn_next_fid(void* h) { return ((ChurnPlane*)h)->next_fid; }

int64_t etpu_churn_free_count(void* h) {
  return (int64_t)((ChurnPlane*)h)->free_fids.size();
}

int32_t etpu_churn_shards(void* h) { return ((ChurnPlane*)h)->nshards; }

int32_t etpu_churn_lookup(void* h, const uint8_t* s, int64_t n) {
  ChurnPlane* p = (ChurnPlane*)h;
  uint64_t hh = etpu::fnv1a64(s, (uint64_t)n);
  PlaneShard& sh = p->shards[p->shard_of(hh)];
  int32_t si = sh.idx.find(sh.ents, hh, s, n);
  return si < 0 ? -1 : sh.ents[sh.idx.slots[si]].fid;
}

int64_t etpu_churn_ref(void* h, const uint8_t* s, int64_t n) {
  ChurnPlane* p = (ChurnPlane*)h;
  uint64_t hh = etpu::fnv1a64(s, (uint64_t)n);
  PlaneShard& sh = p->shards[p->shard_of(hh)];
  int32_t si = sh.idx.find(sh.ents, hh, s, n);
  return si < 0 ? 0 : (int64_t)sh.ents[sh.idx.slots[si]].rc;
}

// One churn tick: batched removes then adds (the apply_churn contract).
// Caller-allocated outputs: out_fid [n_adds]; new_* sized n_adds;
// dead_* sized n_removes.  place=0 skips table writes (the sharded
// engine places per device shard; bootstrap bulk-rebuilds instead).
// Returns 0.
int32_t etpu_churn_apply(
    void* h, void* reg_h,
    const uint8_t* abuf, const int64_t* aoffs, int32_t n_adds,
    const uint8_t* rbuf, const int64_t* roffs, int32_t n_removes,
    uint32_t* key_a, uint32_t* key_b, int32_t* val,
    int32_t log2cap, int32_t probe, int32_t place,
    int32_t* out_fid,
    int32_t* new_fid, uint32_t* new_ha, uint32_t* new_hb,
    int32_t* new_plen, uint32_t* new_mask, uint8_t* new_hash,
    int32_t* new_slot, uint8_t* new_deep, int32_t* new_aidx,
    int32_t* n_new_out,
    int32_t* dead_fid, uint32_t* dead_ha, uint32_t* dead_hb,
    int32_t* dead_plen, uint32_t* dead_mask, uint8_t* dead_hash,
    int32_t* dead_slot, uint8_t* dead_deep, int32_t* dead_ridx,
    int32_t* n_dead_out) {
  ChurnPlane* p = (ChurnPlane*)h;
  p->gen++;
  const uint32_t gen = p->gen;
  const int32_t NS = p->nshards;
  const bool do_place = place && key_a != nullptr;

  // ---- partition: one parallel hash pass (the hash is kept — it is
  // also the map key) + a serial scatter of indices
  p->a_hash.resize(n_adds);
  p->r_hash.resize(n_removes);
  EtpuPool::inst().parallel_for(n_adds, 512, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++)
      p->a_hash[i] = etpu::fnv1a64(abuf + aoffs[i],
                                   (uint64_t)(aoffs[i + 1] - aoffs[i]));
  });
  EtpuPool::inst().parallel_for(n_removes, 512, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++)
      p->r_hash[i] = etpu::fnv1a64(rbuf + roffs[i],
                                   (uint64_t)(roffs[i + 1] - roffs[i]));
  });
  for (int32_t s = 0; s < NS; s++) {
    PlaneShard& sh = p->shards[s];
    sh.my_adds.clear(); sh.my_rems.clear();
    sh.pend_first.clear(); sh.pend_fid.clear(); sh.pend_rc.clear();
    sh.pend_pos.clear(); sh.pend_dups.clear(); sh.dead_ents.clear();
  }
  for (int32_t i = 0; i < n_removes; i++)
    p->shards[p->shard_of(p->r_hash[i])].my_rems.push_back(i);
  for (int32_t i = 0; i < n_adds; i++)
    p->shards[p->shard_of(p->a_hash[i])].my_adds.push_back(i);

  // ---- phase A (parallel): removes, then add lookups, per shard
  EtpuPool::inst().parallel_for(NS, 1, [&](int32_t s0, int32_t s1) {
    for (int32_t s = s0; s < s1; s++) {
      PlaneShard& sh = p->shards[s];
      for (int32_t ridx : sh.my_rems) {
        uint64_t hh = p->r_hash[ridx];
        int32_t si = sh.idx.find(sh.ents, hh, rbuf + roffs[ridx],
                                 roffs[ridx + 1] - roffs[ridx]);
        if (si < 0) continue;  // unknown / already dead: no-op
        PlaneEnt& e = sh.ents[sh.idx.slots[si]];
        if (e.batch_gen != gen) {  // dead order = FIRST-decrement order,
          e.batch_gen = gen;       // matching the serial dict.fromkeys walk
          e.first_ridx = ridx;
        }
        if (--e.rc > 0) continue;
        sh.dead_ents.push_back(sh.idx.slots[si]);
        sh.idx.erase_at(si);
      }
      // pending-new dedup table: open-addressed pend ids over the
      // SAME precomputed hashes (cleared by size, no rehash cost)
      size_t pcap = 16;
      while (pcap < sh.my_adds.size() * 2) pcap *= 2;
      sh.pend_slots.assign(pcap, -1);
      const uint32_t pmask = (uint32_t)pcap - 1;
      for (int32_t aidx : sh.my_adds) {
        uint64_t hh = p->a_hash[aidx];
        const uint8_t* s8 = abuf + aoffs[aidx];
        const int64_t sn = aoffs[aidx + 1] - aoffs[aidx];
        int32_t si = sh.idx.find(sh.ents, hh, s8, sn);
        if (si >= 0) {
          PlaneEnt& e = sh.ents[sh.idx.slots[si]];
          e.rc++;
          out_fid[aidx] = e.fid;
          continue;
        }
        uint32_t i = (uint32_t)hh & pmask;
        int32_t pid = -1;
        while (true) {
          int32_t v = sh.pend_slots[i];
          if (v == -1) break;
          int32_t fa = sh.pend_first[v];
          if (p->a_hash[fa] == hh &&
              aoffs[fa + 1] - aoffs[fa] == sn &&
              std::memcmp(abuf + aoffs[fa], s8, (size_t)sn) == 0) {
            pid = v;
            break;
          }
          i = (i + 1) & pmask;
        }
        if (pid >= 0) {
          sh.pend_rc[pid]++;
          sh.pend_dups.emplace_back(aidx, pid);
          continue;
        }
        pid = (int32_t)sh.pend_first.size();
        sh.pend_slots[i] = pid;
        sh.pend_first.push_back(aidx);
        sh.pend_rc.push_back(1);
      }
    }
  });

  // ---- phase B (serial): dead harvest in first-decrement order, then
  // fid allocation for pending news in input order (LIFO free list —
  // exactly the Python allocator, for deterministic fid parity)
  std::vector<std::pair<int32_t, std::pair<int32_t, int32_t>>> deads;
  for (int32_t s = 0; s < NS; s++)
    for (int32_t ei : p->shards[s].dead_ents)
      deads.push_back({p->shards[s].ents[ei].first_ridx, {s, ei}});
  std::sort(deads.begin(), deads.end());
  int32_t n_dead = 0;
  std::vector<int32_t> reg_del;
  for (auto& d : deads) {
    PlaneShard& sh = p->shards[d.second.first];
    PlaneEnt& e = sh.ents[d.second.second];
    dead_fid[n_dead] = e.fid;
    dead_ha[n_dead] = e.ha;
    dead_hb[n_dead] = e.hb;
    dead_plen[n_dead] = e.plen;
    dead_mask[n_dead] = e.plus_mask;
    dead_hash[n_dead] = e.has_hash;
    dead_deep[n_dead] = e.deep;
    dead_ridx[n_dead] = e.first_ridx;
    dead_slot[n_dead] = -1;
    if (!e.deep) reg_del.push_back(e.fid);
    p->free_fids.push_back(e.fid);
    e = PlaneEnt();  // reclaim the string
    sh.free_ents.push_back(d.second.second);
    n_dead++;
  }
  // parallel clear pass: dead fids own distinct slots, and placement
  // (phase C) only runs after this barrier, so clears never race claims
  if (do_place && n_dead) {
    EtpuPool::inst().parallel_for(n_dead, 256, [&](int32_t i0, int32_t i1) {
      for (int32_t i = i0; i < i1; i++)
        if (!dead_deep[i])
          clear_slot(key_a, key_b, val, log2cap, probe, dead_ha[i],
                     dead_hb[i], dead_fid[i], &dead_slot[i]);
    });
  }
  std::vector<std::pair<int32_t, std::pair<int32_t, int32_t>>> news;
  for (int32_t s = 0; s < NS; s++) {
    PlaneShard& sh = p->shards[s];
    sh.pend_fid.resize(sh.pend_first.size());
    sh.pend_pos.resize(sh.pend_first.size());
    for (int32_t pid = 0; pid < (int32_t)sh.pend_first.size(); pid++)
      news.push_back({sh.pend_first[pid], {s, pid}});
  }
  std::sort(news.begin(), news.end());
  int32_t n_new = (int32_t)news.size();
  for (int32_t k = 0; k < n_new; k++) {
    PlaneShard& sh = p->shards[news[k].second.first];
    int32_t pid = news[k].second.second;
    int32_t fid;
    if (!p->free_fids.empty()) {
      fid = p->free_fids.back();
      p->free_fids.pop_back();
    } else {
      fid = p->next_fid++;
    }
    sh.pend_fid[pid] = fid;
    sh.pend_pos[pid] = k;  // output row: global input (aidx) order
  }
  p->n_live += n_new - n_dead;

  // ---- phase C (parallel): key computation + map insert + placement
  EtpuPool::inst().parallel_for(NS, 1, [&](int32_t s0, int32_t s1) {
    for (int32_t s = s0; s < s1; s++) {
      PlaneShard& sh = p->shards[s];
      for (int32_t pid = 0; pid < (int32_t)sh.pend_first.size(); pid++) {
        int32_t aidx = sh.pend_first[pid];
        int32_t k = sh.pend_pos[pid];
        int32_t fid = sh.pend_fid[pid];
        const uint8_t* s8 = abuf + aoffs[aidx];
        const int64_t sn = aoffs[aidx + 1] - aoffs[aidx];
        FilterKey fk = p->key_of(s8, sn);
        uint8_t deep = fk.plen > p->max_levels ? 1 : 0;
        int32_t ei = sh.alloc_ent();
        PlaneEnt& e = sh.ents[ei];
        e.str.assign((const char*)s8, (size_t)sn);
        e.hash64 = p->a_hash[aidx];
        e.ha = fk.ha; e.hb = fk.hb; e.plus_mask = fk.plus_mask;
        e.fid = fid; e.rc = sh.pend_rc[pid]; e.plen = fk.plen;
        e.has_hash = fk.has_hash; e.deep = deep; e.live = 1;
        e.batch_gen = 0;
        sh.idx.reserve_one(sh.ents);
        sh.idx.insert(e.hash64, ei);
        new_fid[k] = fid;
        new_ha[k] = fk.ha;
        new_hb[k] = fk.hb;
        new_plen[k] = fk.plen;
        new_mask[k] = fk.plus_mask;
        new_hash[k] = fk.has_hash;
        new_deep[k] = deep;
        new_aidx[k] = aidx;
        new_slot[k] = (do_place && !deep)
            ? place_slot_cas(key_a, key_b, val, log2cap, probe,
                             fk.ha, fk.hb, fid)
            : -1;
        out_fid[aidx] = fid;
      }
      for (auto& du : sh.pend_dups)
        out_fid[du.first] = sh.pend_fid[du.second];
    }
  });

  // ---- phase D (serial): registry string maintenance (fused host
  // match + device-hit verify read these under the registry lock)
  if (reg_h != nullptr) {
    if (!reg_del.empty())
      etpu_reg_del_bulk(reg_h, reg_del.data(), (int32_t)reg_del.size());
    std::vector<int32_t> reg_fids;
    std::vector<uint8_t> blob;
    std::vector<int64_t> offs(1, 0);
    for (int32_t k = 0; k < n_new; k++) {
      if (new_deep[k]) continue;  // deep strings live in the host trie
      int64_t a = aoffs[new_aidx[k]], b = aoffs[new_aidx[k] + 1];
      blob.insert(blob.end(), abuf + a, abuf + b);
      offs.push_back((int64_t)blob.size());
      reg_fids.push_back(new_fid[k]);
    }
    if (!reg_fids.empty())
      etpu_reg_set_bulk(reg_h, reg_fids.data(), (int32_t)reg_fids.size(),
                        blob.empty() ? (const uint8_t*)"" : blob.data(),
                        offs.data());
  }

  *n_new_out = n_new;
  *n_dead_out = n_dead;
  return 0;
}

// ------------------------------------------------------- export / ingest

void etpu_churn_export_sizes(void* h, int64_t* n_entries,
                             int64_t* str_bytes, int64_t* n_free) {
  ChurnPlane* p = (ChurnPlane*)h;
  int64_t n = 0, bytes = 0;
  for (auto& sh : p->shards)
    for (auto& e : sh.ents)
      if (e.live) {
        n++;
        bytes += (int64_t)e.str.size();
      }
  *n_entries = n;
  *str_bytes = bytes;
  *n_free = (int64_t)p->free_fids.size();
}

void etpu_churn_export(void* h, uint8_t* buf, int64_t* offs, int32_t* fids,
                       int64_t* rcs, uint8_t* deep, int32_t* free_out) {
  ChurnPlane* p = (ChurnPlane*)h;
  int64_t k = 0, pos = 0;
  offs[0] = 0;
  for (auto& sh : p->shards)
    for (auto& e : sh.ents) {
      if (!e.live) continue;
      std::memcpy(buf + pos, e.str.data(), e.str.size());
      pos += (int64_t)e.str.size();
      offs[k + 1] = pos;
      fids[k] = e.fid;
      rcs[k] = (int64_t)e.rc;
      deep[k] = e.deep;
      k++;
    }
  for (size_t i = 0; i < p->free_fids.size(); i++)
    free_out[i] = p->free_fids[i];
}

// Bulk load (checkpoint restore / snapshot adoption): keys recomputed
// here, in parallel per shard — restore stays array adoption + one
// parallel hash pass, no per-filter Python work.
void etpu_churn_ingest(void* h, const uint8_t* buf, const int64_t* offs,
                       const int32_t* fids, const int64_t* rcs,
                       int32_t n, const int32_t* free_fids, int32_t n_free,
                       int32_t next_fid) {
  ChurnPlane* p = (ChurnPlane*)h;
  std::vector<uint64_t> hashes(n);
  EtpuPool::inst().parallel_for(n, 512, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++)
      hashes[i] = etpu::fnv1a64(buf + offs[i],
                                (uint64_t)(offs[i + 1] - offs[i]));
  });
  std::vector<std::vector<int32_t>> by_shard(p->nshards);
  for (int32_t i = 0; i < n; i++)
    by_shard[p->shard_of(hashes[i])].push_back(i);
  EtpuPool::inst().parallel_for(p->nshards, 1, [&](int32_t s0, int32_t s1) {
    for (int32_t s = s0; s < s1; s++) {
      PlaneShard& sh = p->shards[s];
      for (int32_t i : by_shard[s]) {
        const uint8_t* s8 = buf + offs[i];
        const int64_t sn = offs[i + 1] - offs[i];
        FilterKey fk = p->key_of(s8, sn);
        int32_t ei = sh.alloc_ent();
        PlaneEnt& e = sh.ents[ei];
        e.str.assign((const char*)s8, (size_t)sn);
        e.hash64 = hashes[i];
        e.ha = fk.ha; e.hb = fk.hb; e.plus_mask = fk.plus_mask;
        e.fid = fids[i]; e.rc = (int32_t)rcs[i]; e.plen = fk.plen;
        e.has_hash = fk.has_hash;
        e.deep = fk.plen > p->max_levels ? 1 : 0;
        e.live = 1;
        sh.idx.reserve_one(sh.ents);
        sh.idx.insert(e.hash64, ei);
      }
    }
  });
  p->free_fids.assign(free_fids, free_fids + n_free);
  p->next_fid = next_fid;
  p->n_live += n;
}

}  // extern "C"
