// bcrypt (EksBlowfish) password hashing — the C++ analog of the
// reference's bcrypt C NIF dependency (mix.exs bcrypt_dep; used by
// emqx_passwd / authn password hashing).
//
// Implemented from the algorithm description in Provos & Mazieres,
// "A Future-Adaptable Password Scheme" (USENIX '99) and the OpenBSD
// manual semantics ($2b$: 72-byte key cap, trailing NUL included).
//
// The Blowfish initial state (P-array + S-boxes = 1,042 words of pi's
// fractional hex expansion) is NOT embedded here: the Python wrapper
// derives it numerically (Machin arctan series, bcrypt_hash.py) and
// injects it once via etpu_bcrypt_init — constants from mathematics,
// not from someone else's source file.

#include <cstdint>
#include <cstring>

namespace {

struct BlowfishState {
    uint32_t P[18];
    uint32_t S[4][256];
};

uint32_t g_init_P[18];
uint32_t g_init_S[4][256];
bool g_ready = false;

inline uint32_t F(const BlowfishState& st, uint32_t x) {
    return ((st.S[0][(x >> 24) & 0xff] + st.S[1][(x >> 16) & 0xff]) ^
            st.S[2][(x >> 8) & 0xff]) +
           st.S[3][x & 0xff];
}

inline void encrypt_block(const BlowfishState& st, uint32_t& L, uint32_t& R) {
    for (int i = 0; i < 16; i += 2) {
        L ^= st.P[i];
        R ^= F(st, L);
        R ^= st.P[i + 1];
        L ^= F(st, R);
    }
    L ^= st.P[16];
    R ^= st.P[17];
    uint32_t t = L;
    L = R;
    R = t;
}

// Next 32 bits of the cyclic key stream (bytes, big-endian packing).
inline uint32_t key_word(const uint8_t* key, int keylen, int& pos) {
    uint32_t w = 0;
    for (int i = 0; i < 4; i++) {
        w = (w << 8) | key[pos];
        pos = (pos + 1) % keylen;
    }
    return w;
}

// ExpandKey(state, salt, key).  salt == nullptr means the 128-bit zero
// salt (the plain Blowfish key schedule).
void expand_key(BlowfishState& st, const uint8_t* salt16, const uint8_t* key,
                int keylen) {
    int kp = 0;
    for (int i = 0; i < 18; i++) st.P[i] ^= key_word(key, keylen, kp);

    uint32_t sw[4] = {0, 0, 0, 0};
    if (salt16 != nullptr) {
        for (int h = 0; h < 4; h++)
            sw[h] = (uint32_t(salt16[h * 4]) << 24) |
                    (uint32_t(salt16[h * 4 + 1]) << 16) |
                    (uint32_t(salt16[h * 4 + 2]) << 8) |
                    uint32_t(salt16[h * 4 + 3]);
    }
    uint32_t L = 0, R = 0;
    int shalf = 0;  // alternate the two 64-bit salt halves
    for (int i = 0; i < 18; i += 2) {
        L ^= sw[shalf * 2];
        R ^= sw[shalf * 2 + 1];
        shalf ^= 1;
        encrypt_block(st, L, R);
        st.P[i] = L;
        st.P[i + 1] = R;
    }
    for (int b = 0; b < 4; b++) {
        for (int i = 0; i < 256; i += 2) {
            L ^= sw[shalf * 2];
            R ^= sw[shalf * 2 + 1];
            shalf ^= 1;
            encrypt_block(st, L, R);
            st.S[b][i] = L;
            st.S[b][i + 1] = R;
        }
    }
}

}  // namespace

extern "C" {

// words: 18 P words followed by 4*256 S words (pi fractional hex digits).
void etpu_bcrypt_init(const uint32_t* words) {
    std::memcpy(g_init_P, words, sizeof(g_init_P));
    std::memcpy(g_init_S, words + 18, sizeof(g_init_S));
    g_ready = true;
}

// password: key stream bytes — the wrapper passes password[:72] + NUL
// ($2b$ semantics: cap then append, so up to 73 bytes);
// salt16: 16 bytes; cost: log2 rounds (4..31); out24: 24-byte ciphertext
// (callers encode the first 23, per the $2b$ format).
// Returns 0 on success, -1 on bad input / uninitialized tables.
int etpu_bcrypt_hash(const uint8_t* password, int pwlen,
                     const uint8_t* salt16, int cost, uint8_t* out24) {
    if (!g_ready || pwlen <= 0 || pwlen > 73 || cost < 4 || cost > 31)
        return -1;

    BlowfishState st;
    std::memcpy(st.P, g_init_P, sizeof(st.P));
    std::memcpy(st.S, g_init_S, sizeof(st.S));

    // EksBlowfishSetup
    expand_key(st, salt16, password, pwlen);
    uint64_t rounds = 1ull << cost;
    for (uint64_t r = 0; r < rounds; r++) {
        expand_key(st, nullptr, password, pwlen);
        expand_key(st, nullptr, salt16, 16);
    }

    // 64 ECB encryptions of "OrpheanBeholderScryDoubt"
    static const char magic[25] = "OrpheanBeholderScryDoubt";
    uint32_t blocks[6];
    for (int i = 0; i < 6; i++)
        blocks[i] = (uint32_t(uint8_t(magic[i * 4])) << 24) |
                    (uint32_t(uint8_t(magic[i * 4 + 1])) << 16) |
                    (uint32_t(uint8_t(magic[i * 4 + 2])) << 8) |
                    uint32_t(uint8_t(magic[i * 4 + 3]));
    for (int r = 0; r < 64; r++)
        for (int i = 0; i < 6; i += 2) encrypt_block(st, blocks[i], blocks[i + 1]);

    for (int i = 0; i < 6; i++) {
        out24[i * 4] = uint8_t(blocks[i] >> 24);
        out24[i * 4 + 1] = uint8_t(blocks[i] >> 16);
        out24[i * 4 + 2] = uint8_t(blocks[i] >> 8);
        out24[i * 4 + 3] = uint8_t(blocks[i]);
    }
    return 0;
}

}  // extern "C"
