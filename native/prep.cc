// Fused native prep pipeline: one GIL-released pass over the packed
// topic blob that splits, hashes, consults/updates the two-generation
// topic memo, dedups repeated topics within the tick, and writes the
// bucket-padded [B, 2L+2] u32 upload buffer directly.
//
// This replaces the per-tick Python prep of the sharded mesh path
// (`parallel/sharded.py _hash_topics_memo` + staging-buffer fill): the
// memo arrays move behind the native boundary — a C++-owned PrepPlane,
// the ChurnPlane ownership discipline (churn.cc) — and the whole pass
// runs with the GIL released (ctypes drops it around every call),
// parallelized over the persistent worker pool (pool.h) with per-worker
// index slices.
//
// The op is split into TWO entry points because the packed level budget
// L (ops/match.live_levels) depends on the batch's real depth, which is
// only known after hashing — the caller sizes the staging buffer
// between the calls:
//
//   etpu_prep_hash   swap check + memo lookup (live, then old
//                    generation with promotion) + in-tick dedup +
//                    split/hash of the unique misses (parallel) into
//                    the row store; returns the batch's max level count
//   etpu_prep_pack   gather the batch's rows into the caller's
//                    [B, 2L+2] staging buffer (parallel) + pad the tail
//                    (length 0xFFFFFFFF: the padded row can never match)
//
// Memo semantics are bit-for-bit the Python two-generation memo
// (PR 7, now `ops/prep.py` — the lib-less fallback AND the property-
// test oracle): the swap condition (live + batch > cap/2), second-
// chance promotion of old-generation hits, first-occurrence miss order,
// and the hit/miss counter arithmetic (in-tick duplicates past a
// name's first occurrence count as hits) all match exactly.
//
// Thread-safety: calls on one plane must be externally serialized (the
// Python wrapper holds one lock) — the plane itself fans work out to
// the pool but has no internal synchronization, like ChurnPlane.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "match_core.h"
#include "pool.h"

namespace {

struct MemoEnt {
  std::string str;
  uint64_t h64 = 0;  // fnv1a64(topic bytes): map key, computed once
  int64_t row = -1;  // index into the plane's row store
};

// Open-addressed hash -> entry map, insertion-ordered entry vector.
// No per-entry deletes: a whole generation drops at once (swap), so
// there are no tombstones — the EntMap economy of churn.cc without the
// erase machinery.
struct MemoGen {
  std::vector<int32_t> slots;  // ent index, -1 empty
  uint32_t mask = 0;
  std::vector<MemoEnt> ents;   // insertion order

  void reset() {
    slots.clear();
    mask = 0;
    ents.clear();
  }

  void reserve_one() {
    if (slots.empty()) {
      slots.assign(32, -1);
      mask = 31;
      return;
    }
    if ((ents.size() + 1) * 2 <= slots.size()) return;
    size_t cap = slots.size() * 2;
    slots.assign(cap, -1);
    mask = (uint32_t)cap - 1;
    for (size_t ei = 0; ei < ents.size(); ei++) {
      uint32_t i = (uint32_t)ents[ei].h64 & mask;
      while (slots[i] != -1) i = (i + 1) & mask;
      slots[i] = (int32_t)ei;
    }
  }

  int32_t find(uint64_t h, const uint8_t* s, int64_t n) const {
    if (slots.empty()) return -1;
    uint32_t i = (uint32_t)h & mask;
    while (true) {
      int32_t ei = slots[i];
      if (ei == -1) return -1;
      const MemoEnt& e = ents[ei];
      if (e.h64 == h && e.str.size() == (size_t)n &&
          std::memcmp(e.str.data(), s, (size_t)n) == 0)
        return ei;
      i = (i + 1) & mask;
    }
  }

  void insert(MemoEnt&& e) {
    reserve_one();
    uint32_t i = (uint32_t)e.h64 & mask;
    while (slots[i] != -1) i = (i + 1) & mask;
    ents.push_back(std::move(e));
    slots[i] = (int32_t)ents.size() - 1;
  }
};

struct PrepPlane {
  int32_t L = 0;    // HashSpace.max_levels (row width)
  int64_t cap = 0;  // topic_memo_cap (swap at half)
  std::vector<uint32_t> Ca, Cb, Ra, Rb;

  // row store shared by both generations (terms zero-padded past the
  // hashed levels, exactly like ops/hashing.hash_topics output)
  std::vector<uint32_t> ta, tb;  // [rows_cap * L]
  std::vector<int32_t> ln;
  std::vector<uint8_t> dl;
  int64_t rows_n = 0;

  MemoGen live, old;
  int64_t hits = 0, misses = 0;

  // per-batch scratch, valid between _hash and _pack
  std::vector<int64_t> rows;     // per-topic row index
  std::vector<uint64_t> h64s;    // per-topic memo key
  std::vector<int32_t> miss_i;   // first-occurrence miss batch indices

  void grow_rows(int64_t need) {
    int64_t cap_rows = ln.empty() ? 1024 : (int64_t)ln.size();
    while (cap_rows < need) cap_rows *= 2;
    if ((int64_t)ln.size() >= cap_rows) return;
    ta.resize(cap_rows * L);
    tb.resize(cap_rows * L);
    ln.resize(cap_rows);
    dl.resize(cap_rows);
  }

  // Second-chance generation swap (ops/prep.py _memo_swap, bit-for-bit
  // observables): the live generation's rows compact to the front of
  // the row store — gather-then-write, the numpy fancy-index temporary,
  // so a promoted entry's low source row is never clobbered before it
  // is read — the previous old generation drops, and the live memo
  // restarts empty with the compacted generation as `old`.
  void swap_gens() {
    int64_t n = (int64_t)live.ents.size();
    if (n) {
      std::vector<uint32_t> tta((size_t)n * L), ttb((size_t)n * L);
      std::vector<int32_t> tln(n);
      std::vector<uint8_t> tdl(n);
      for (int64_t j = 0; j < n; j++) {
        int64_t r = live.ents[j].row;
        std::memcpy(&tta[j * L], &ta[r * L], (size_t)L * 4);
        std::memcpy(&ttb[j * L], &tb[r * L], (size_t)L * 4);
        tln[j] = ln[r];
        tdl[j] = dl[r];
        live.ents[j].row = j;
      }
      std::memcpy(ta.data(), tta.data(), tta.size() * 4);
      std::memcpy(tb.data(), ttb.data(), ttb.size() * 4);
      std::memcpy(ln.data(), tln.data(), tln.size() * 4);
      std::memcpy(dl.data(), tdl.data(), tdl.size());
    }
    old = std::move(live);
    live.reset();
    rows_n = n;
  }
};

using Clock = std::chrono::steady_clock;

}  // namespace

extern "C" {

void* etpu_prep_new(int32_t max_levels, int64_t cap,
                    const uint32_t* Ca, const uint32_t* Cb,
                    const uint32_t* Ra, const uint32_t* Rb) {
  PrepPlane* p = new PrepPlane();
  p->L = max_levels;
  p->cap = cap;
  p->Ca.assign(Ca, Ca + max_levels);
  p->Cb.assign(Cb, Cb + max_levels);
  p->Ra.assign(Ra, Ra + max_levels);
  p->Rb.assign(Rb, Rb + max_levels);
  return p;
}

void etpu_prep_free(void* h) { delete (PrepPlane*)h; }

void etpu_prep_set_cap(void* h, int64_t cap) { ((PrepPlane*)h)->cap = cap; }

// out8: hits, misses, live entries, old entries, row-store rows, 0, 0, 0
void etpu_prep_stats(void* h, int64_t* out8) {
  PrepPlane* p = (PrepPlane*)h;
  out8[0] = p->hits;
  out8[1] = p->misses;
  out8[2] = (int64_t)p->live.ents.size();
  out8[3] = (int64_t)p->old.ents.size();
  out8[4] = p->rows_n;
  out8[5] = out8[6] = out8[7] = 0;
}

// generation holding the topic: 0 live, 1 old (and not live), -1 absent
int32_t etpu_prep_lookup(void* h, const uint8_t* s, int64_t n) {
  PrepPlane* p = (PrepPlane*)h;
  uint64_t h64 = etpu::fnv1a64(s, (uint64_t)n);
  if (p->live.find(h64, s, n) >= 0) return 0;
  if (p->old.find(h64, s, n) >= 0) return 1;
  return -1;
}

// Memo+hash phase over a packed topic batch: returns the batch's max
// level count (for the caller's live_levels bucket choice) and leaves
// the per-topic row map in plane scratch for etpu_prep_pack /
// etpu_prep_rows.  out3 = {phase ns, batch hits, batch misses}.
int32_t etpu_prep_hash(void* h, const uint8_t* tbuf, const int64_t* toffs,
                       int32_t n, int64_t* out3) {
  PrepPlane* p = (PrepPlane*)h;
  auto t0 = Clock::now();
  // swap condition: strict Python parity (ops/prep.py)
  if ((int64_t)p->live.ents.size() + n > (p->cap >> 1)) p->swap_gens();
  p->rows.resize(n);
  p->h64s.resize(n);
  p->miss_i.clear();
  // phase 1 (parallel): memo keys — one fnv pass per topic
  EtpuPool::inst().parallel_for(n, 256, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++)
      p->h64s[i] = etpu::fnv1a64(tbuf + toffs[i],
                                 (uint64_t)(toffs[i + 1] - toffs[i]));
  });
  // phase 2 (serial): lookup / promote / in-tick dedup.  Misses insert
  // into the live generation immediately, so a repeated new topic later
  // in the tick resolves to the same row (first-occurrence order).
  for (int32_t i = 0; i < n; i++) {
    const uint8_t* s = tbuf + toffs[i];
    int64_t sn = toffs[i + 1] - toffs[i];
    int32_t ei = p->live.find(p->h64s[i], s, sn);
    if (ei >= 0) {
      p->rows[i] = p->live.ents[ei].row;
      continue;
    }
    ei = p->old.find(p->h64s[i], s, sn);
    if (ei >= 0) {  // second chance: promote into the live generation
      MemoEnt e = p->old.ents[ei];
      p->rows[i] = e.row;
      p->live.insert(std::move(e));
      continue;
    }
    MemoEnt e;
    e.str.assign((const char*)s, (size_t)sn);
    e.h64 = p->h64s[i];
    e.row = p->rows_n + (int64_t)p->miss_i.size();
    p->rows[i] = e.row;
    p->live.insert(std::move(e));
    p->miss_i.push_back(i);
  }
  int32_t nmiss = (int32_t)p->miss_i.size();
  p->grow_rows(p->rows_n + nmiss);
  // phase 3 (parallel): split+hash the unique misses into the row store
  // (disjoint rows per miss — no synchronization needed)
  int32_t L = p->L;
  EtpuPool::inst().parallel_for(nmiss, 64, [&](int32_t i0, int32_t i1) {
    for (int32_t k = i0; k < i1; k++) {
      int32_t i = p->miss_i[k];
      int64_t r = p->rows_n + k;
      std::memset(&p->ta[r * L], 0, (size_t)L * 4);
      std::memset(&p->tb[r * L], 0, (size_t)L * 4);
      etpu::topic_terms_one(tbuf + toffs[i], toffs[i + 1] - toffs[i], L,
                            p->Ca.data(), p->Cb.data(), p->Ra.data(),
                            p->Rb.data(), &p->ta[r * L], &p->tb[r * L],
                            &p->ln[r], &p->dl[r]);
    }
  });
  p->rows_n += nmiss;
  p->hits += n - nmiss;
  p->misses += nmiss;
  int32_t maxlen = 0;
  for (int32_t i = 0; i < n; i++) {
    int32_t l = p->ln[p->rows[i]];
    if (l > maxlen) maxlen = l;
  }
  out3[0] = std::chrono::duration_cast<std::chrono::nanoseconds>(
                Clock::now() - t0).count();
  out3[1] = n - nmiss;
  out3[2] = nmiss;
  return maxlen;
}

// Gather phase: write the hashed batch (plane scratch from the last
// etpu_prep_hash) into the caller's [B, 2L+2] u32 staging buffer —
// terms_a | terms_b | length (i32 bit view) | dollar — and pad rows
// [n, B) with length 0xFFFFFFFF (-1: fails every shape's min_len, so a
// padded row can never match).  Pad rows' other columns are left as-is,
// the same contract as the recycled Python staging buffers.
void etpu_prep_pack(void* h, int32_t n, int32_t B, int32_t L,
                    uint32_t* out, int64_t* out_ns) {
  PrepPlane* p = (PrepPlane*)h;
  auto t0 = Clock::now();
  int32_t maxL = p->L;
  int32_t W = 2 * L + 2;
  EtpuPool::inst().parallel_for(n, 128, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++) {
      int64_t r = p->rows[i];
      uint32_t* dst = out + (int64_t)i * W;
      std::memcpy(dst, &p->ta[r * maxL], (size_t)L * 4);
      std::memcpy(dst + L, &p->tb[r * maxL], (size_t)L * 4);
      dst[2 * L] = (uint32_t)p->ln[r];
      dst[2 * L + 1] = p->dl[r];
    }
  });
  for (int32_t i = n; i < B; i++) out[(int64_t)i * W + 2 * L] = 0xFFFFFFFFu;
  *out_ns = std::chrono::duration_cast<std::chrono::nanoseconds>(
                Clock::now() - t0).count();
}

// Full-width row gather (the TopicBatch path + the engine's memo-hash
// compat surface): [n, max_levels] terms + lengths + dollar flags from
// the last etpu_prep_hash's batch.
void etpu_prep_rows(void* h, int32_t n, uint32_t* out_ta, uint32_t* out_tb,
                    int32_t* out_ln, uint8_t* out_dl) {
  PrepPlane* p = (PrepPlane*)h;
  int32_t L = p->L;
  EtpuPool::inst().parallel_for(n, 128, [&](int32_t i0, int32_t i1) {
    for (int32_t i = i0; i < i1; i++) {
      int64_t r = p->rows[i];
      std::memcpy(out_ta + (int64_t)i * L, &p->ta[r * L], (size_t)L * 4);
      std::memcpy(out_tb + (int64_t)i * L, &p->tb[r * L], (size_t)L * 4);
      out_ln[i] = p->ln[r];
      out_dl[i] = p->dl[r];
    }
  });
}

}  // extern "C"
