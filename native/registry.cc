// Native filter registry + fused host match pipeline.
//
// The reference keeps its route/trie tables in ETS (C-implemented shared
// tables behind the BEAM); the analog here is a C++-owned fid -> filter
// string registry plus a single-call host match pipeline that does
// split + hash + table probe + exact verification in one threaded pass
// over a packed topic batch.  This is the data plane of the hybrid
// host/device arbitration (models/engine.py): when the host<->device
// link is degraded the broker matches here, at memory speed, with the
// same table arrays the device mirrors.
//
// Concurrency: the broker mutates the registry from its event-loop
// thread while match batches run on an executor thread; a shared_mutex
// gives writers exclusivity and match batches shared access.  Slot
// writes to the table arrays themselves are benign dirty reads (same
// semantics as concurrent ETS mutation in the reference's router).

#include <cstdint>
#include <cstring>
#include <shared_mutex>
#include <string>
#include <vector>

#ifdef __x86_64__
#include <immintrin.h>
#endif

#include "match_core.h"
#include "pool.h"

namespace {

struct Registry {
  std::vector<std::string> strs;  // by fid ("" = absent)
  std::vector<uint8_t> present;
  std::shared_mutex mu;
};

// ---- shared helpers (match_core.h; semantics identical to matchhash.cc)

static const uint64_t PERTURB = etpu::kPerturb;

static inline uint64_t fnv1a64(const uint8_t* s, uint64_t n) {
  return etpu::fnv1a64(s, n);
}

// Exact MQTT topic-vs-filter match (broker/topic.py match_words semantics;
// mirror of the logic in matchhash.cc etpu_verify_pairs).
static bool topic_matches(const uint8_t* t, int64_t tn,
                          const uint8_t* f, int64_t fn) {
  int64_t ti = 0, fi = 0;
  bool first = true;
  while (true) {
    int64_t fe = fi;
    while (fe < fn && f[fe] != '/') fe++;
    int64_t flen = fe - fi;
    bool f_hash = (flen == 1 && f[fi] == '#');
    bool f_plus = (flen == 1 && f[fi] == '+');
    if (first && tn > 0 && t[0] == '$' && (f_hash || f_plus)) return false;
    first = false;
    if (f_hash) return true;
    if (ti > tn) return false;
    int64_t te = ti;
    while (te < tn && t[te] != '/') te++;
    if (!f_plus) {
      if (te - ti != flen || std::memcmp(t + ti, f + fi, flen) != 0)
        return false;
    }
    ti = te + 1;
    fi = fe + 1;
    bool t_done = ti > tn;
    bool f_done = fi > fn;
    if (f_done) return t_done;
    if (t_done) {
      int64_t ge = fi;
      while (ge < fn && f[ge] != '/') ge++;
      return (ge - fi == 1 && f[fi] == '#');
    }
  }
}

}  // namespace

extern "C" {

void* etpu_reg_new() { return new Registry(); }

void etpu_reg_free(void* h) { delete (Registry*)h; }

int64_t etpu_reg_count(void* h) {
  Registry* r = (Registry*)h;
  std::shared_lock<std::shared_mutex> lk(r->mu);
  int64_t n = 0;
  for (uint8_t p : r->present) n += p;
  return n;
}

// Bulk insert/overwrite: fids[i] <- buf[offs[i]:offs[i+1]]
void etpu_reg_set_bulk(void* h, const int32_t* fids, int32_t n,
                       const uint8_t* buf, const int64_t* offs) {
  Registry* r = (Registry*)h;
  std::unique_lock<std::shared_mutex> lk(r->mu);
  int32_t maxfid = -1;
  for (int32_t i = 0; i < n; i++)
    if (fids[i] > maxfid) maxfid = fids[i];
  if (maxfid >= (int32_t)r->strs.size()) {
    size_t cap = r->strs.size() ? r->strs.size() : 1024;
    while ((int32_t)cap <= maxfid) cap *= 2;
    r->strs.resize(cap);
    r->present.resize(cap, 0);
  }
  for (int32_t i = 0; i < n; i++) {
    r->strs[fids[i]].assign((const char*)(buf + offs[i]),
                            (size_t)(offs[i + 1] - offs[i]));
    r->present[fids[i]] = 1;
  }
}

void etpu_reg_del_bulk(void* h, const int32_t* fids, int32_t n) {
  Registry* r = (Registry*)h;
  std::unique_lock<std::shared_mutex> lk(r->mu);
  for (int32_t i = 0; i < n; i++) {
    int32_t fid = fids[i];
    if (fid >= 0 && fid < (int32_t)r->strs.size()) {
      r->strs[fid].clear();
      r->strs[fid].shrink_to_fit();
      r->present[fid] = 0;
    }
  }
}

// ---------------------------------------------------- fused host pipeline
//
// One threaded pass per topic: split on '/', hash levels, enumerate valid
// shapes, probe the open-addressed table, and exact-verify each hit
// against the registry string — emitting only verified fids.
//
//   out_fid   [B * vcap] verified fids, row-major per topic
//   out_cnt   [B] verified hits per topic
//   out_coll  [2 * coll_cap] (topic_idx, fid) refuted/raced pairs
//   n_coll    out: refuted pair count (may exceed coll_cap; excess dropped)
//
// Probe order within the window is slot order, first (key_a, key_b,
// val>=0) match wins — identical to the original scalar loop; the AVX
// paths only change how non-matching slots are rejected (key_a compare
// first, one vector op for the whole window, instead of val/key_a/key_b
// loads per slot).
//
// Returns total verified hits.
int64_t etpu_match_core(
    void* reg_h,
    const uint8_t* tbuf, const int64_t* toffs, int32_t B,
    int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* key_a, const uint32_t* key_b, const int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* incl, const uint32_t* k_a, const uint32_t* k_b,
    const int32_t* min_len, const int32_t* max_len,
    const uint8_t* wild_root, const uint8_t* valid, int32_t M, int32_t L,
    int32_t* out_fid, int32_t* out_cnt, int32_t vcap,
    int32_t* out_coll, int32_t coll_cap, int32_t* n_coll) {
  Registry* reg = (Registry*)reg_h;
  std::shared_lock<std::shared_mutex> reg_lk(reg->mu);
  const uint32_t MIX1 = 0x85EBCA77u, MIX2 = 0x9E3779B1u;
  const uint32_t cap = 1u << log2cap;
  const uint32_t cap_mask = cap - 1;
  std::atomic<int32_t> coll_cursor{0};

  // valid shape rows, hoisted once (M can exceed the live shape count)
  std::vector<int32_t> vshapes;
  vshapes.reserve(M);
  for (int32_t m = 0; m < M; m++)
    if (valid[m]) vshapes.push_back(m);
  const int32_t NV = (int32_t)vshapes.size();

  // Two-phase candidate batching: phase 1 hashes topics and PREFETCHES
  // every candidate's probe line; phase 2 probes an accumulated batch.
  // On big tables (10M filters = hundreds of MB) each probe line is a
  // DRAM miss — batching the prefetches overlaps those misses with the
  // next topics' hash compute instead of stalling once per shape.  The
  // flush threshold is a CANDIDATE count (not a topic count) so the
  // prefetch distance stays inside cache capacity for any live shape
  // count NV.
  constexpr int32_t FLUSH = 64;
  EtpuPool::inst().parallel_for(B, 64, [&](int32_t i0, int32_t i1) {
    // terms need no zeroing between topics: incl rows are 0 beyond each
    // shape's prefix, and the length filters bound which shapes see a
    // topic, so stale lanes are always multiplied by 0.
    std::vector<uint32_t> terms_a(L, 0), terms_b(L, 0);
    const size_t ccap = (size_t)FLUSH + NV;  // one topic may overshoot
    std::vector<uint32_t> homes(ccap), has(ccap), hbs(ccap);
    std::vector<int32_t> c_topic(ccap);
    int32_t ncand = 0;

    auto probe_batch = [&]() {
      for (int32_t c = 0; c < ncand; c++) {
        uint32_t home = homes[c], ha = has[c], hb = hbs[c];
        int32_t i = c_topic[c];
        uint32_t lanes;  // bitmask of window slots with key_a == ha
#if defined(__AVX2__)
        if (probe == 8 && home + 8 <= cap) {
          __m256i w = _mm256_loadu_si256((const __m256i*)(key_a + home));
          __m256i eq = _mm256_cmpeq_epi32(w, _mm256_set1_epi32((int32_t)ha));
          lanes = (uint32_t)_mm256_movemask_ps(_mm256_castsi256_ps(eq));
        } else
#endif
        {
          lanes = 0;
          for (int32_t off = 0; off < probe; off++) {
            uint32_t slot = (home + (uint32_t)off) & cap_mask;
            if (key_a[slot] == ha) lanes |= 1u << off;
          }
        }
        while (lanes) {
          uint32_t off = (uint32_t)__builtin_ctz(lanes);
          lanes &= lanes - 1;
          uint32_t slot = (home + off) & cap_mask;
          int32_t v = val[slot];
          if (v >= 0 && key_b[slot] == hb) {
            bool ok = false;
            if (v < (int32_t)reg->strs.size() && reg->present[v]) {
              const std::string& f = reg->strs[v];
              const uint8_t* t = tbuf + toffs[i];
              int64_t tn = toffs[i + 1] - toffs[i];
              ok = topic_matches(t, tn, (const uint8_t*)f.data(),
                                 (int64_t)f.size());
            }
            if (ok) {
              if (out_cnt[i] < vcap)
                out_fid[(int64_t)i * vcap + out_cnt[i]++] = v;
            } else {
              int32_t k = coll_cursor.fetch_add(1);
              if (k < coll_cap) {
                out_coll[2 * k] = i;
                out_coll[2 * k + 1] = v;
              }
            }
            break;  // one hit per shape, like the device kernel
          }
        }
      }
      ncand = 0;
    };

    {
      // ---- phase 1: split + hash + candidate homes + prefetch
      for (int32_t i = i0; i < i1; i++) {
        const uint8_t* t = tbuf + toffs[i];
        int64_t tn = toffs[i + 1] - toffs[i];
        bool dol = (tn > 0 && t[0] == '$');
        int32_t level = 0;
        int64_t start = 0;
        for (int64_t p = 0; p <= tn; p++) {
          if (p == tn || t[p] == '/') {
            if (level < L) {
              uint64_t h =
                  fnv1a64(t + start, (uint64_t)(p - start)) ^ PERTURB;
              terms_a[level] = ((uint32_t)h ^ Ca[level]) * Ra[level];
              terms_b[level] = ((uint32_t)(h >> 32) ^ Cb[level]) * Rb[level];
            }
            level++;
            start = p + 1;
          }
        }
        for (int32_t l = level; l < L; l++) terms_a[l] = terms_b[l] = 0;
        int32_t len = (tn == 0) ? 1 : level;
        out_cnt[i] = 0;
#if defined(__AVX512F__)
        if (L == 16) {
          __m512i ta = _mm512_loadu_si512((const void*)terms_a.data());
          __m512i tb = _mm512_loadu_si512((const void*)terms_b.data());
          for (int32_t c = 0; c < NV; c++) {
            int32_t m = vshapes[c];
            if (len < min_len[m] || len > max_len[m]) continue;
            if (dol && wild_root[m]) continue;
            __m512i row =
                _mm512_loadu_si512((const void*)(incl + (int64_t)m * 16));
            uint32_t ha = k_a[m] + (uint32_t)_mm512_reduce_add_epi32(
                                       _mm512_mullo_epi32(ta, row));
            uint32_t hb = k_b[m] + (uint32_t)_mm512_reduce_add_epi32(
                                       _mm512_mullo_epi32(tb, row));
            uint32_t home = ((ha + hb * MIX1) * MIX2) >> (32 - log2cap);
            __builtin_prefetch(key_a + home);
            homes[ncand] = home;
            has[ncand] = ha;
            hbs[ncand] = hb;
            c_topic[ncand] = i;
            ncand++;
          }
        } else
#endif
        {
          for (int32_t c = 0; c < NV; c++) {
            int32_t m = vshapes[c];
            if (len < min_len[m] || len > max_len[m]) continue;
            if (dol && wild_root[m]) continue;
            const uint32_t* row = incl + (int64_t)m * L;
            uint32_t ha = k_a[m], hb = k_b[m];
            for (int32_t l = 0; l < L; l++) {
              ha += terms_a[l] * row[l];
              hb += terms_b[l] * row[l];
            }
            uint32_t home = ((ha + hb * MIX1) * MIX2) >> (32 - log2cap);
            __builtin_prefetch(key_a + home);
            homes[ncand] = home;
            has[ncand] = ha;
            hbs[ncand] = hb;
            c_topic[ncand] = i;
            ncand++;
          }
        }
        // ---- phase 2 flush: probe + inline exact verification.
        // Reject on key_a first (the selective test — one cache line
        // per window) and touch key_b/val only on candidate lanes.
        // Candidates stay grouped per topic in shape order, preserving
        // hit order (a topic's candidates never split across flushes:
        // the check runs between topics).
        if (ncand >= FLUSH) probe_batch();
      }
      probe_batch();
    }
  });
  *n_coll = coll_cursor.load();
  int64_t total = 0;
  for (int32_t i = 0; i < B; i++) total += out_cnt[i];
  return total;
}

// ctypes-facing alias (kept stable for ops/native.py).
int64_t etpu_match_host_verified(
    void* reg_h,
    const uint8_t* tbuf, const int64_t* toffs, int32_t B,
    int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* key_a, const uint32_t* key_b, const int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* incl, const uint32_t* k_a, const uint32_t* k_b,
    const int32_t* min_len, const int32_t* max_len,
    const uint8_t* wild_root, const uint8_t* valid, int32_t M, int32_t L,
    int32_t* out_fid, int32_t* out_cnt, int32_t vcap,
    int32_t* out_coll, int32_t coll_cap, int32_t* n_coll) {
  return etpu_match_core(
      reg_h, tbuf, toffs, B, max_levels, Ca, Cb, Ra, Rb, key_a, key_b, val,
      log2cap, probe, incl, k_a, k_b, min_len, max_len, wild_root, valid, M,
      L, out_fid, out_cnt, vcap, out_coll, coll_cap, n_coll);
}

// Registry-backed exact verification for DEVICE hash hits: same contract
// as etpu_verify_pairs but the filter strings come from the registry (no
// per-call Python blob assembly).
void etpu_verify_pairs_reg(
    void* reg_h, const uint8_t* tbuf, const int64_t* toffs,
    const int32_t* tidx, const int32_t* fids, int32_t n_pairs,
    uint8_t* out_ok) {
  Registry* reg = (Registry*)reg_h;
  std::shared_lock<std::shared_mutex> lk(reg->mu);
  EtpuPool::inst().parallel_for(n_pairs, 256, [&](int32_t p0, int32_t p1) {
    for (int32_t p = p0; p < p1; p++) {
      const uint8_t* t = tbuf + toffs[tidx[p]];
      int64_t tn = toffs[tidx[p] + 1] - toffs[tidx[p]];
      int32_t fid = fids[p];
      bool ok = false;
      if (fid >= 0 && fid < (int32_t)reg->strs.size() && reg->present[fid]) {
        const std::string& f = reg->strs[fid];
        ok = topic_matches(t, tn, (const uint8_t*)f.data(),
                           (int64_t)f.size());
      }
      out_ok[p] = ok ? 1 : 0;
    }
  });
}

}  // extern "C"
