// Native host hot paths for emqx_tpu — the analog of the reference's C NIF
// deps (jiffy/quicer/bcrypt pattern: Erlang control plane, C data plane;
// rebar.config:46-73).  Compiled to a shared library and loaded via ctypes
// (emqx_tpu/ops/native.py); every entry point has a pure-Python fallback.
//
// Contents:
//   * fnv1a64            — deterministic word hash (shared with Python impl)
//   * etpu_prep_topics   — split a packed batch of topic strings on '/',
//                          hash each level, and emit the per-level mix terms
//                          consumed by the TPU match kernel
//                          (ops/hashing.py hash_topic_batch semantics)
//   * etpu_scan_frames   — MQTT fixed-header scan: frame boundaries +
//                          malformed/oversize detection (broker/frame.py
//                          Parser.feed hot loop)
//
// Build: see native/Makefile (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstddef>
#include <thread>
#include <vector>

#include "match_core.h"

extern "C" {

// ---------------------------------------------------------------- fnv1a64

// shared with registry.cc / churn.cc via match_core.h so the word-hash
// semantics cannot drift between the prep, match, and churn planes
static inline uint64_t fnv1a64(const uint8_t* s, uint64_t n) {
    return etpu::fnv1a64(s, n);
}

uint64_t etpu_fnv1a64(const uint8_t* s, uint64_t n) { return fnv1a64(s, n); }

// ------------------------------------------------------------ prep_topics

// Split each topic on '/', hash each level (fnv1a64 ^ PERTURB), and emit
// mix terms  term[l] = ((lane ^ C[l]) * R[l]) mod 2^32  for both lanes.
//
//   data      packed UTF-8 topic bytes, topics concatenated
//   offsets   [n_topics+1] byte offsets into data
//   max_levels, Ca/Cb/Ra/Rb  the HashSpace constants ([max_levels] u32 each)
//   ta, tb    out [n_topics * max_levels] u32, zero-filled by caller
//   ln        out [n_topics] i32: level count (NOT capped; caller compares
//             against shape lengths, deeper topics still match '#' shapes)
//   dl        out [n_topics] u8: 1 if topic starts with '$'
//
// Topic-level semantics match broker/topic.py words(): splitting "a//b"
// yields an empty middle level whose hash is fnv1a64("") ^ PERTURB.
static void prep_topics_range(const uint8_t* data, const int64_t* offsets,
                              int32_t i0, int32_t i1, int32_t max_levels,
                              const uint32_t* Ca, const uint32_t* Cb,
                              const uint32_t* Ra, const uint32_t* Rb,
                              uint32_t* ta, uint32_t* tb, int32_t* ln,
                              uint8_t* dl) {
    // per-topic split+hash shared with the memoized fused prep plane
    // (match_core.h topic_terms_one) — one implementation, zero drift
    for (int32_t i = i0; i < i1; i++) {
        etpu::topic_terms_one(
            data + offsets[i], offsets[i + 1] - offsets[i], max_levels,
            Ca, Cb, Ra, Rb,
            ta + (int64_t)i * max_levels, tb + (int64_t)i * max_levels,
            ln + i, dl + i);
    }
}

// Threaded over the batch when it is large enough to amortize spawn
// cost: host topic hashing is the end-to-end bottleneck at ~1.8M
// topics/s single-threaded (round-2 VERDICT weak #1), and each topic is
// independent.
void etpu_prep_topics(const uint8_t* data, const int64_t* offsets,
                      int32_t n_topics, int32_t max_levels,
                      const uint32_t* Ca, const uint32_t* Cb,
                      const uint32_t* Ra, const uint32_t* Rb,
                      uint32_t* ta, uint32_t* tb, int32_t* ln, uint8_t* dl) {
    int32_t nthreads = 1;
    if (n_topics >= 2048) {
        unsigned hw = std::thread::hardware_concurrency();
        nthreads = (int32_t)(hw > 8 ? 8 : (hw ? hw : 1));
    }
    if (nthreads <= 1) {
        prep_topics_range(data, offsets, 0, n_topics, max_levels,
                          Ca, Cb, Ra, Rb, ta, tb, ln, dl);
        return;
    }
    std::vector<std::thread> ts;
    int32_t chunk = (n_topics + nthreads - 1) / nthreads;
    for (int32_t t = 0; t < nthreads; t++) {
        int32_t i0 = t * chunk;
        int32_t i1 = i0 + chunk > n_topics ? n_topics : i0 + chunk;
        if (i0 >= i1) break;
        ts.emplace_back(prep_topics_range, data, offsets, i0, i1, max_levels,
                        Ca, Cb, Ra, Rb, ta, tb, ln, dl);
    }
    for (auto& th : ts) th.join();
}

// ------------------------------------------------------------ scan_frames

// Scan an MQTT byte stream for complete frames.
//
// Returns the number of complete frames found (<= max_frames) and fills,
// per frame: header byte, body offset, body length.  *consumed is the
// number of bytes covered by complete frames; *err is 0 ok, 1 malformed
// varint (>4 bytes), 2 frame exceeds max_size.
// On error the frames found before the bad frame remain valid.
int32_t etpu_scan_frames(const uint8_t* buf, int64_t n, int64_t max_size,
                         uint8_t* headers, int64_t* body_offs,
                         int64_t* body_lens, int32_t max_frames,
                         int64_t* consumed, int32_t* err) {
    int32_t count = 0;
    int64_t pos = 0;
    *err = 0;
    while (pos < n && count < max_frames) {
        // fixed header byte + up-to-4-byte varint remaining length
        int64_t p = pos + 1;
        int64_t rl = 0;
        int shift = 0;
        bool complete = false;
        while (p < n) {
            uint8_t b = buf[p++];
            rl |= (int64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) { complete = true; break; }
            shift += 7;
            if (shift > 21) { *err = 1; *consumed = pos; return count; }
        }
        if (!complete) break;                     // need more bytes
        if (1 + (p - pos - 1) + rl > max_size) {  // whole-packet cap
            *err = 2; *consumed = pos; return count;
        }
        if (p + rl > n) break;                    // body incomplete
        headers[count] = buf[pos];
        body_offs[count] = p;
        body_lens[count] = rl;
        count++;
        pos = p + rl;
    }
    *consumed = pos;
    return count;
}

}  // extern "C"

extern "C" {

// ------------------------------------------------------------ filter_keys

// Compute the table key + wildcard shape of each subscription filter —
// ops/hashing.py HashSpace.filter_key semantics, bit-for-bit:
//   * trailing "#" level sets has_hash and is excluded from plen
//   * "+" levels set plus_mask bits and contribute the PLUS sentinel term
//     via the per-shape constant K (added here directly)
//   * (ha, hb) == (0, 0) is remapped to (0, 1): empty-slot sentinel
// Caller guarantees plen <= max_levels (deeper filters take the host-trie
// fallback path, models/engine.py _is_deep).
void etpu_filter_keys(
    const uint8_t* data, const int64_t* offsets, int32_t n_filters,
    int32_t max_levels,
    const uint32_t* Ca, const uint32_t* Cb,
    const uint32_t* Ra, const uint32_t* Rb,
    const uint32_t* PLUS,            // [2]
    const uint32_t* HM,              // [2]
    const uint32_t* HRa, const uint32_t* HRb,  // [max_levels+1]
    uint32_t* ha_out, uint32_t* hb_out,
    int32_t* plen_out, uint32_t* plus_mask_out, uint8_t* has_hash_out) {
    // per-filter key computation shared with the churn plane
    // (match_core.h filter_key_one) — one implementation, zero drift
    for (int32_t i = 0; i < n_filters; i++) {
        etpu::FilterKey k = etpu::filter_key_one(
            data + offsets[i], offsets[i + 1] - offsets[i], max_levels,
            Ca, Cb, Ra, Rb, PLUS, HM, HRa, HRb);
        ha_out[i] = k.ha;
        hb_out[i] = k.hb;
        plen_out[i] = k.plen;
        plus_mask_out[i] = k.plus_mask;
        has_hash_out[i] = k.has_hash;
    }
}

// ------------------------------------------------------------- bulk_place

// Open-addressed placement of n entries into the table arrays in place —
// ops/tables.py MatchTables._place semantics (home bucket + PROBE-slot
// linear window).  Returns the index of the first entry that could not be
// placed (caller grows and retries), or n on success.
int32_t etpu_bulk_place(
    uint32_t* key_a, uint32_t* key_b, int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* ha, const uint32_t* hb, const int32_t* fids,
    int32_t n) {
    uint32_t cap_mask = (1u << log2cap) - 1;
    const uint32_t MIX1 = 0x85EBCA77u, MIX2 = 0x9E3779B1u;
    for (int32_t i = 0; i < n; i++) {
        uint32_t home = ((ha[i] + hb[i] * MIX1) * MIX2) >> (32 - log2cap);
        bool placed = false;
        for (int32_t off = 0; off < probe; off++) {
            uint32_t slot = (home + (uint32_t)off) & cap_mask;
            if (val[slot] == -1) {
                key_a[slot] = ha[i];
                key_b[slot] = hb[i];
                val[slot] = fids[i];
                placed = true;
                break;
            }
        }
        if (!placed) return i;
    }
    return n;
}

// Incremental churn placement: like etpu_bulk_place, but records the
// chosen slot per key in out_slots so the caller can scatter the same
// writes into the HBM mirror (delta tracking for apply_delta).
int32_t etpu_bulk_place_slots(
    uint32_t* key_a, uint32_t* key_b, int32_t* val,
    int32_t log2cap, int32_t probe,
    const uint32_t* ha, const uint32_t* hb, const int32_t* fids,
    int32_t n, int32_t* out_slots) {
    uint32_t cap_mask = (1u << log2cap) - 1;
    const uint32_t MIX1 = 0x85EBCA77u, MIX2 = 0x9E3779B1u;
    for (int32_t i = 0; i < n; i++) {
        uint32_t home = ((ha[i] + hb[i] * MIX1) * MIX2) >> (32 - log2cap);
        bool placed = false;
        for (int32_t off = 0; off < probe; off++) {
            uint32_t slot = (home + (uint32_t)off) & cap_mask;
            if (val[slot] == -1) {
                key_a[slot] = ha[i];
                key_b[slot] = hb[i];
                val[slot] = fids[i];
                out_slots[i] = (int32_t)slot;
                placed = true;
                break;
            }
        }
        if (!placed) return i;
    }
    return n;
}

// Exact MQTT topic-vs-filter verification for a batch of device hash
// hits (broker/topic.py match_words semantics, including the rule that
// a root-level wildcard never matches a '$'-topic).  Each pair p checks
// topic tidx[p] against filter p; out_ok[p] = 1 on an exact match.
// This is the per-hit verify loop of engine.match() moved off Python
// (round-2 VERDICT weak #3).
static inline bool level_eq(const uint8_t* a, int64_t an,
                            const uint8_t* b, int64_t bn) {
    if (an != bn) return false;
    for (int64_t i = 0; i < an; i++)
        if (a[i] != b[i]) return false;
    return true;
}

void etpu_verify_pairs(
    const uint8_t* tbuf, const int64_t* toffs,   // packed topic strings
    const uint8_t* fbuf, const int64_t* foffs,   // packed per-pair filters
    const int32_t* tidx, int32_t n_pairs, uint8_t* out_ok) {
    for (int32_t p = 0; p < n_pairs; p++) {
        const uint8_t* t = tbuf + toffs[tidx[p]];
        int64_t tn = toffs[tidx[p] + 1] - toffs[tidx[p]];
        const uint8_t* f = fbuf + foffs[p];
        int64_t fn = foffs[p + 1] - foffs[p];

        int64_t ti = 0, fi = 0;
        bool ok = true, first = true;
        while (true) {
            // next filter level [fi, fe)
            int64_t fe = fi;
            while (fe < fn && f[fe] != '/') fe++;
            int64_t flen = fe - fi;
            bool f_hash = (flen == 1 && f[fi] == '#');
            bool f_plus = (flen == 1 && f[fi] == '+');
            // root wildcard vs $-topic
            if (first && tn > 0 && t[0] == '$' && (f_hash || f_plus)) {
                ok = false;
                break;
            }
            first = false;
            if (f_hash) {
                ok = true;  // '#' swallows the rest (including zero levels)
                break;
            }
            if (ti > tn) {  // topic exhausted on the previous level
                ok = false;
                break;
            }
            // next topic level [ti, te)
            int64_t te = ti;
            while (te < tn && t[te] != '/') te++;
            if (!f_plus && !level_eq(t + ti, te - ti, f + fi, flen)) {
                ok = false;
                break;
            }
            // advance; 'past end' encodes exhaustion (a trailing empty
            // level like "a/" still yields one more empty word)
            ti = te + 1;
            fi = fe + 1;
            bool t_done = ti > tn;
            bool f_done = fi > fn;
            if (f_done) {
                ok = t_done;
                break;
            }
            if (t_done) {
                // only an immediately-following '#' can still match
                // (exact match_words parity: no look-ahead past it)
                int64_t ge = fi;
                while (ge < fn && f[ge] != '/') ge++;
                ok = (ge - fi == 1 && f[fi] == '#');
                break;
            }
        }
        out_ok[p] = ok ? 1 : 0;
    }
}

}  // extern "C"
