// Hub-side doorbell wait for the shared-memory match plane.
//
// The hub's drain thread blocks here (ctypes releases the GIL for the
// duration) on one poll(2) across every lane's eventfd plus the stop
// doorbell.  Workers ring their lane fd on slot commit; the 8-byte
// counter read below clears the level-triggered state so the next wait
// blocks again.  A bounded timeout keeps the housekeeping path (worker
// generation checks, kill -9 reclaim, ack retries) alive even when no
// doorbell ever rings.

#include <cstdint>
#include <cerrno>
#include <poll.h>
#include <unistd.h>

extern "C" {

// Wait for any of n fds to become readable, then read-clear every ready
// fd (eventfd semantics: one 8-byte read resets the counter).  Returns
// the number of ready fds, 0 on timeout, -1 on error (errno preserved
// by the caller being in-process).  ready_mask (optional, may be null)
// gets bit i set when fds[i] rang — the hub uses it to mark hot lanes
// without a full-ring scan.
int32_t etpu_drain_wait(const int32_t* fds, int32_t n, int32_t timeout_ms,
                        uint64_t* ready_mask) {
    if (n <= 0 || n > 64) return -1;
    struct pollfd pfds[64];
    for (int32_t i = 0; i < n; ++i) {
        pfds[i].fd = fds[i];
        pfds[i].events = POLLIN;
        pfds[i].revents = 0;
    }
    int rc;
    do {
        rc = poll(pfds, n, timeout_ms);
    } while (rc < 0 && errno == EINTR);
    if (rc <= 0) {
        if (ready_mask) *ready_mask = 0;
        return rc < 0 ? -1 : 0;
    }
    uint64_t mask = 0;
    int32_t ready = 0;
    for (int32_t i = 0; i < n; ++i) {
        if (pfds[i].revents & (POLLIN | POLLERR | POLLHUP)) {
            uint64_t buf;
            // nonblocking read-clear; a worker that died between poll
            // and read just leaves the counter unread (EAGAIN), fine.
            ssize_t r = read(pfds[i].fd, &buf, sizeof(buf));
            (void)r;
            mask |= (uint64_t)1 << i;
            ++ready;
        }
    }
    if (ready_mask) *ready_mask = mask;
    return ready;
}

}  // extern "C"
