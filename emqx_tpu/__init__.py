"""emqx_tpu — a TPU-native messaging framework with the capabilities of EMQ X.

The data plane centerpiece is a TPU-resident topic-matching automaton
(`emqx_tpu.models.engine.TopicMatchEngine`): subscription filters are mirrored
into flattened hash tables in HBM and publish batches are matched with
fully-static-shape JAX kernels (`emqx_tpu.ops.match`), sharded across a device
mesh (`emqx_tpu.parallel`).  The host control plane (`emqx_tpu.broker`)
provides the MQTT codec, channel FSM, sessions/QoS, hooks, authn/authz,
retainer, shared subscriptions and the asyncio listeners.

Reference structural blueprint: /root/repo/SURVEY.md (EMQ X 5.0.0-beta.3).
"""

__version__ = "0.1.0"
