"""Per-shard write-behind buffer for the durable message log.

Appends on the event loop are one list-append + byte count; the actual
write+fsync happens when either watermark trips — `ds.flush_bytes` of
buffered payload (flushed inline by the appending call) or
`ds.flush_interval` elapsed (flushed by the node ticker, off-loop via
`asyncio.to_thread`).  This is the reference's async-rlog bounded-loss
contract with the window measured in BYTES, not housekeeping ticks: a
crash loses at most `flush_bytes` of QoS>=1 offline traffic per shard,
and `loss_window()` reports the exact exposure.

Offsets are assigned at buffer time (single writer per shard, flushes
serialized under the shard lock), so `next_offset` runs ahead of the
log's durable end by exactly the buffered records.
"""

from __future__ import annotations

import threading
from typing import List, Tuple

from ..observe.tracepoints import tp
from .log import ShardLog, _REC


class WriteBuffer:
    def __init__(self, log: ShardLog, flush_bytes: int = 256 << 10):
        self.log = log
        self.flush_bytes = max(1, int(flush_bytes))
        self._items: List[Tuple[int, bytes]] = []
        self._bytes = 0
        self._lock = threading.Lock()
        self.next_offset = log.next_offset
        self.flushes = 0
        # post-flush hook (shard, first_offset, items) — set by the ds
        # replicator to queue the flushed range for shipment; must never
        # block (one deque append + a loop wakeup)
        self.on_flush = None

    @property
    def durable_offset(self) -> int:
        return self.log.next_offset

    def pending_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def pending_count(self) -> int:
        with self._lock:
            return len(self._items)

    def loss_window(self) -> int:
        """Bytes of appended-but-not-fsync'd payload (the crash
        exposure this instant; bounded by flush_bytes + one record)."""
        with self._lock:
            return self._bytes

    def append(self, payload: bytes) -> int:
        """Buffer one record; returns its (pre-assigned) offset.
        Flushes inline when the byte watermark trips."""
        with self._lock:
            off = self.next_offset
            self.next_offset += 1
            self._items.append((off, payload))
            self._bytes += len(payload) + _REC.size
            due = self._bytes >= self.flush_bytes
        if due:
            self.flush()
        return off

    def flush(self) -> int:
        """Write + fsync everything buffered; returns records flushed.
        Serialized under the shard lock so concurrent flushers (ticker
        thread vs inline watermark) cannot interleave segments."""
        with self._lock:
            if not self._items:
                return 0
            items, self._items = self._items, []
            n_bytes, self._bytes = self._bytes, 0
            self.log.append_payloads(items)
            self.flushes += 1
            hook = self.on_flush
            if hook is not None:
                # inside the lock so ranges reach the replicator in
                # append order even when the ticker thread and an
                # inline-watermark flush race
                hook(self.log.shard, items[0][0], items)
        tp("ds.flush", shard=self.log.shard, records=len(items),
           bytes=n_bytes)
        return len(items)
