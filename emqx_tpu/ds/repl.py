"""Per-shard leader->follower replication of durable-log appends.

The ds plane (PR 5) made parked-session delivery durable on ONE node;
this module replicates it across the cluster so a kill -9 / node loss
preserves every record at or below a **replicated watermark**, and a
cross-node takeover becomes a cursor handoff instead of a materialized
queue ship.

Topology — every node runs one `DsReplicator` playing both roles:

* leader (its own shards): each `WriteBuffer.flush` hands the flushed
  contiguous range to `offer()` (one deque append inside the shard
  lock + a loop wakeup — the flush path never blocks on the network).
  A retained drain task ships ranges over the elected follower's
  PeerLink as REPL frames (`transport.pack_repl`) and awaits the
  REPL_ACK carrying the follower's durable end: `watermark[shard]`.
  Every record at/below the watermark exists fsync'd on two nodes.
* follower (peers' shards): `handle_repl` appends the range to a
  mirror ShardLog under `<ds.dir>/mirror/<leader>/shard-<k>` — byte-
  and offset-identical to the leader's chain, fsync'd BEFORE the ack
  leaves.  Mirrors left by a previous incarnation are re-adopted at
  construction, so the takeover path works across restarts.

Follower election is `sorted(up_peers)[shard % n]`, sticky while the
pick stays up, so a 2-node cluster mirrors everything at the other
node and larger meshes spread shards.

Degrade ladder (never the flush path's problem):

1. ack timeout / link down / nack -> the shard flips to leader-only
   appends; the RAM ship-queue is dropped (the records stay durable in
   the leader's own log) and the `ds_repl_degraded` alarm raises off
   `degraded` via `poll_health_alarms`.
2. heal probe every `ds.repl.retry_interval`: when the follower link
   is back, catch-up re-reads `[watermark, durable_end)` from the
   leader's log in `ds.repl.catchup_batch` batches and re-ships; the
   alarm clears when the watermark catches the durable end.
3. if retention GC already dropped part of that window, the catch-up
   ships a `reset` range: the follower rebuilds its mirror at the
   oldest surviving offset and the gap is reported (tp field), never
   silently absorbed.

Takeover (cluster/node.py `session_takeover` v2) ships the session
record plus ONLY the per-shard `[cursor|mirror_end, durable_end)` tail
the taker's mirror lacks — O(replication lag), not O(queue).  The
taker folds the tail into its mirror where contiguous (durable before
the client resumes) and `DsManager._replay_handoff` rebuilds the
mqueue from mirror + tail with the usual mid dedup and honest gap
reporting.
"""

from __future__ import annotations

import asyncio
import base64
import logging
import os
import shutil
import struct
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from .. import fault as _fault
from ..observe import spans as _spans
from ..observe.tracepoints import tp
from .log import SegmentError, ShardLog

log = logging.getLogger("emqx_tpu.ds.repl")

_LEN = struct.Struct("<I")


def pack_records(items: List[Tuple[int, bytes]]) -> bytes:
    """Record blob for one REPL range: repeated `u32 len | payload`.
    Offsets are implicit — a range is contiguous by construction (the
    flush hands over exactly the flushed run), so the header's `first`
    plus position recovers every offset."""
    parts = []
    for _off, payload in items:
        parts.append(_LEN.pack(len(payload)))
        parts.append(payload)
    return b"".join(parts)


def unpack_records(first: int, blob: bytes) -> List[Tuple[int, bytes]]:
    out: List[Tuple[int, bytes]] = []
    pos = 0
    off = first
    while pos + _LEN.size <= len(blob):
        (ln,) = _LEN.unpack_from(blob, pos)
        pos += _LEN.size
        if pos + ln > len(blob):
            break  # torn blob: keep the whole-record prefix
        out.append((off, blob[pos:pos + ln]))
        pos += ln
        off += 1
    return out


class DsReplicator:
    """Both halves of the replication plane for one node (see module
    docstring).  Construction wires itself into the ds buffers'
    `on_flush` hooks and the cluster's REPL frame handler; `start()`
    (on the running loop) spawns the retained drain task and `stop()`
    cancels it (PR 10 lifecycle rules)."""

    def __init__(self, cluster, ds, conf, metrics=None) -> None:
        self.cluster = cluster
        self.ds = ds
        self.metrics = metrics if metrics is not None else ds.metrics
        self.ack_timeout = float(conf.get("ds.repl.ack_timeout"))
        self.queue_max = int(conf.get("ds.repl.queue_max"))
        self.catchup_batch = int(conf.get("ds.repl.catchup_batch"))
        self.retry_interval = float(conf.get("ds.repl.retry_interval"))
        self.seg_bytes = int(conf.get("ds.seg_bytes"))
        # ---- leader state -------------------------------------------
        n = ds.n_shards
        # replication starts at the durable end as of construction:
        # records below it predate the plane and are not claimed
        self.base: Dict[int, int] = {
            k: ds.logs[k].next_offset for k in range(n)
        }
        self.watermark: Dict[int, int] = dict(self.base)
        self.followers: Dict[int, str] = {}
        self._degraded: Set[int] = set()
        # flushed-but-unshipped ranges, appended by offer() from
        # whatever thread flushed; drained in order by the ship task
        self._queues: Dict[int, Deque[Tuple[int, list]]] = {
            k: deque() for k in range(n)
        }
        self._qlock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._event: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        self.ships = 0
        self.degrades = 0
        # ---- follower state -----------------------------------------
        self.mirror_dir = os.path.join(ds.dir, "mirror")
        self.mirrors: Dict[str, Dict[int, ShardLog]] = {}
        self._adopt_mirrors()
        # ---- wiring -------------------------------------------------
        for buf in ds.buffers:
            buf.on_flush = self.offer
        ds.repl = self
        cluster.attach_ds_repl(self)

    # ------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Spawn the drain task on the RUNNING loop (after
        cluster.start())."""
        self._loop = asyncio.get_running_loop()
        self._event = asyncio.Event()
        self._stopping = False
        self._task = self._loop.create_task(self._run())

    async def stop(self) -> None:
        # flag BEFORE cancel: if wait_for swallows the cancellation
        # (py3.10 done-future race, see ClusterNode._heartbeat) the
        # drain loop still exits at its next condition check
        self._stopping = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.exception("ds repl drain task died during stop")
            self._task = None
        self._event = None
        self._loop = None

    def close_mirrors(self) -> None:
        for by in self.mirrors.values():
            for m in by.values():
                m.close()
        self.mirrors.clear()

    # ---------------------------------------------------- leader: intake

    def offer(self, shard: int, first: int, items: list) -> None:
        """WriteBuffer post-flush hook: queue one flushed range for
        shipment.  Runs on whatever thread flushed (loop inline or the
        ticker's to_thread hop) — one lock'd deque append + a loop
        wakeup, never blocking the flush."""
        with self._qlock:
            q = self._queues.get(shard)
            if q is None:
                return
            q.append((first, list(items)))
            if len(q) > self.queue_max:
                # bounded backlog: drop the RAM queue whole — the
                # records stay durable in the leader's own log and the
                # heal-time catch-up re-reads them from the watermark
                q.clear()
                overflow = True
            else:
                overflow = False
        if overflow:
            self._degrade(shard, "ship-queue overflow")
        self._wake()

    def _wake(self) -> None:
        loop, evt = self._loop, self._event
        if loop is None or evt is None:
            return
        try:
            loop.call_soon_threadsafe(evt.set)
        except RuntimeError:
            pass  # loop already closed (shutdown race)

    # ----------------------------------------------------- leader: ship

    async def _run(self) -> None:
        while not self._stopping:
            try:
                await asyncio.wait_for(
                    self._event.wait(), self.retry_interval
                )
            except asyncio.TimeoutError:
                pass  # heal-probe tick for degraded shards
            if self._stopping:
                break
            self._event.clear()
            try:
                await self._drain()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("ds repl drain failed")

    async def _drain(self) -> None:
        for shard in range(self.ds.n_shards):
            if shard in self._degraded:
                await self._try_heal(shard)
                continue
            while True:
                with self._qlock:
                    q = self._queues[shard]
                    rng = q.popleft() if q else None
                if rng is None:
                    break
                first, items = rng
                wm = self.watermark[shard]
                if first < wm:
                    # overlap with a catch-up read: trim the resend
                    items = [(o, p) for o, p in items if o >= wm]
                    if not items:
                        continue
                    first = items[0][0]
                if first > wm:
                    # a hole (dropped backlog): catch-up owns the range
                    self._degrade(shard, "ship-queue hole")
                    break
                if not await self._ship(shard, first, items):
                    break

    def _follower(self, shard: int) -> Optional[str]:
        """Deterministic per-shard follower over the sorted up-peers,
        sticky while the current pick stays up so a transient third-
        node flap does not re-home every mirror."""
        up = self.cluster.up_peers()
        cur = self.followers.get(shard)
        if cur is not None and cur in up:
            return cur
        peers = sorted(up)
        if not peers:
            return None
        return peers[shard % len(peers)]

    async def _ship(
        self, shard: int, first: int, items: list, kind: str = "ship",
        gap: int = 0,
    ) -> bool:
        """Ship one contiguous range; True advanced the watermark."""
        follower = self._follower(shard)
        if follower is None:
            self._degrade(shard, "no follower peer up")
            return False
        link = self.cluster.links.get(follower)
        if link is None or not link.connected:
            self._degrade(shard, f"link to {follower} down")
            return False
        header = {
            "node": self.cluster.name,
            "shard": shard,
            "first": first,
            "count": len(items),
            # retention floor: the leader's own log dropped everything
            # below this, so the mirror may trim sealed segments wholly
            # behind it — the follower's disk is bounded by the
            # leader's retention, not by total history
            "floor": self.ds.logs[shard].oldest_offset,
        }
        if kind == "reset":
            # part of the window was GC'd: the mirror rebuilds at
            # `first` and the gap below it is reported, not hidden
            header["reset"] = True
            header["gap"] = gap
        t0 = time.perf_counter()
        try:
            if _fault.enabled():
                a = await _fault.ainject(
                    "ds.repl.send", err=ConnectionError
                )
                if a is not None and a.kind == "drop":
                    raise ConnectionError("ds.repl.send dropped (fault)")
            ack = await link.repl_request(
                header, pack_records(items), timeout=self.ack_timeout
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._degrade(shard, f"{type(e).__name__}: {e}")
            return False
        if ack is None:
            self._degrade(shard, f"link to {follower} down")
            return False
        if not ack.get("ok"):
            need = ack.get("need")
            if need is not None and int(need) < first:
                # the follower's mirror ends short of this range (fresh
                # follower / lost disk): pull the watermark back so the
                # catch-up re-ships from where the mirror actually ends
                self.watermark[shard] = max(
                    self.base[shard], min(self.watermark[shard], int(need))
                )
                self._degrade(shard, f"follower behind at {need}")
            else:
                self._degrade(shard, str(ack.get("error", "nack")))
            return False
        end = int(ack.get("end", first + len(items)))
        self.watermark[shard] = max(self.watermark[shard], end)
        self.followers[shard] = follower
        self.ships += 1
        if _spans.enabled():
            # the replication hop: leader flush handed off -> follower
            # mirror fsync'd + acked (per-range, shm-leg style)
            p = _spans.plane()
            p.observe_stage("repl", time.perf_counter() - t0)
        tp("ds.repl.ship", shard=shard, first=first, count=len(items),
           follower=follower, watermark=end, catchup=(kind != "ship"),
           gap=gap)
        if self.metrics is not None:
            self.metrics.inc("ds.repl.ranges")
            self.metrics.inc("ds.repl.records", len(items))
        return True

    def _degrade(self, shard: int, reason: str) -> None:
        if self.metrics is not None:
            self.metrics.inc("ds.repl.send_failures")
        if shard in self._degraded:
            return
        self._degraded.add(shard)
        self.degrades += 1
        log.warning("ds repl shard %d degraded to leader-only: %s",
                    shard, reason)
        tp("ds.repl.degrade", shard=shard, state="degraded",
           reason=reason)

    async def _try_heal(self, shard: int) -> None:
        """Heal probe for a degraded shard: when the follower link is
        back, re-read `[watermark, durable_end)` from the leader's own
        log and re-ship until caught up."""
        follower = self._follower(shard)
        if follower is None:
            return
        link = self.cluster.links.get(follower)
        if link is None or not link.connected:
            return
        with self._qlock:
            # queued RAM ranges are a subset of the catch-up window
            self._queues[shard].clear()
        shard_log = self.ds.logs[shard]
        while True:
            start = self.watermark[shard]
            records, _nxt, gap = shard_log.read_from(
                start, self.catchup_batch
            )
            if not records:
                if gap:
                    # the whole remaining window was GC'd out from
                    # under the watermark; nothing left to ship
                    self.watermark[shard] = shard_log.next_offset
                break
            kind = "reset" if gap else "catchup"
            self._degraded.discard(shard)  # let _ship re-degrade on failure
            ok = await self._ship(shard, records[0][0], records,
                                  kind=kind, gap=gap)
            if not ok:
                return
            if self.metrics is not None:
                self.metrics.inc("ds.repl.catchup_ranges")
            tp("ds.repl.catchup", shard=shard, first=records[0][0],
               count=len(records), gap=gap)
        self._degraded.discard(shard)
        tp("ds.repl.degrade", shard=shard, state="healed")
        log.info("ds repl shard %d healed (watermark=%d)",
                 shard, self.watermark[shard])

    # ------------------------------------------------- follower: mirror

    def _adopt_mirrors(self) -> None:
        """Re-adopt mirror chains left by a previous incarnation — the
        takeover path reads them after a restart.  One-shot boot work
        from __init__, like ShardLog._recover."""
        if not os.path.isdir(self.mirror_dir):
            return
        for leader in sorted(os.listdir(self.mirror_dir)):
            ldir = os.path.join(self.mirror_dir, leader)
            if not os.path.isdir(ldir):
                continue
            for name in sorted(os.listdir(ldir)):
                if not name.startswith("shard-"):
                    continue
                try:
                    shard = int(name.split("-", 1)[1])
                except ValueError:
                    continue
                try:
                    self._open_mirror(leader, shard)
                except (SegmentError, OSError):
                    log.exception("mirror %s/%s unreadable; skipped",
                                  leader, name)

    def _open_mirror(
        self, leader: str, shard: int, base: int = 0, reset: bool = False
    ) -> ShardLog:
        by = self.mirrors.setdefault(leader, {})
        cur = by.get(shard)
        path = os.path.join(self.mirror_dir, leader, f"shard-{shard}")
        if reset and cur is not None:
            cur.close()
            shutil.rmtree(path, ignore_errors=True)
            by.pop(shard, None)
            cur = None
        if cur is None:
            cur = ShardLog(path, shard, seg_bytes=self.seg_bytes,
                           base=base)
            by[shard] = cur
        return cur

    def handle_repl(
        self, peer: str, header: dict, payload: bytes
    ) -> Optional[dict]:
        """Transport `on_repl` handler: append one replicated range to
        the mirror of the leader's shard and ack the durable end.  Runs
        on the server read loop (like on_forward); the append is one
        batched write+fsync — the same budget the leader's own flush
        pays.  Returning None (fault drop) sends no ack: the leader
        times out and degrades, exactly like real ack loss."""
        if _fault.enabled():
            a = _fault.inject("ds.repl.ack", err=False)
            if a is not None:
                if a.kind == "drop":
                    return None
                if a.kind == "error":
                    return {"ok": False, "error": "ds.repl.ack fault"}
        leader = str(header.get("node") or peer)
        shard = int(header.get("shard", 0))
        first = int(header.get("first", 0))
        items = unpack_records(first, payload)
        try:
            mirror = self._open_mirror(
                leader, shard, base=first,
                reset=bool(header.get("reset")),
            )
            end = mirror.next_offset
            if first > end:
                return {"ok": False, "need": end}
            if first < end:
                items = [(o, p) for o, p in items if o >= end]
            if items:
                mirror.append_payloads(items)
        except (SegmentError, OSError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        new_end = mirror.next_offset
        floor = int(header.get("floor", 0))
        if floor > 0:
            self._gc_mirror(mirror, leader, shard, floor)
        tp("ds.repl.mirror", leader=leader, shard=shard, first=first,
           count=len(items), end=new_end)
        if self.metrics is not None:
            self.metrics.inc("ds.repl.mirror_appends")
        return {"ok": True, "end": new_end}

    def _gc_mirror(self, mirror: ShardLog, leader: str, shard: int,
                   floor: int) -> int:
        """Trim sealed mirror segments wholly behind the leader's
        advertised retention floor.  The leader's own log already
        dropped those offsets (it can never re-ship them, and a
        takeover serves nothing below the leader's floor), so keeping
        them would grow the follower's disk with total history instead
        of the leader's retention window.  Whole sealed generations
        only — the same unlink granularity as the leader's GC."""
        dropped = 0
        for seg in list(mirror.segments):
            if seg.sealed and seg.end <= floor:
                if mirror.drop_generation(seg.generation):
                    dropped += 1
        if dropped:
            if self.metrics is not None:
                self.metrics.inc("ds.repl.mirror_gc", dropped)
            tp("ds.repl.mirror_gc", leader=leader, shard=shard,
               floor=floor, dropped=dropped)
        return dropped

    # ------------------------------------------------ takeover support

    def mirror_state(self, leader: str) -> Dict[int, Tuple[int, int]]:
        """Per-shard (oldest, end) coverage of this node's mirror of
        `leader`'s log — the takeover RPC's handoff negotiation."""
        return {
            shard: (m.oldest_offset, m.next_offset)
            for shard, m in self.mirrors.get(leader, {}).items()
        }

    def mirror_log(self, leader: str, shard: int) -> Optional[ShardLog]:
        return self.mirrors.get(leader, {}).get(shard)

    def absorb_tail(
        self, leader: str, tail: Dict[int, dict]
    ) -> Dict[int, dict]:
        """Fold a takeover's shipped tail into the local mirror wherever
        it extends the chain contiguously — making it durable before
        the client resumes.  Returns the ranges that could not be
        absorbed (they replay from RAM, surviving only this process)."""
        rest: Dict[int, dict] = {}
        for shard, info in tail.items():
            records = [
                base64.b64decode(x) for x in (info.get("records") or [])
            ]
            first = int(info.get("first", 0))
            if not records:
                if info.get("gap"):
                    rest[shard] = info
                continue
            try:
                mirror = self.mirrors.get(leader, {}).get(shard)
                if mirror is None:
                    mirror = self._open_mirror(leader, shard, base=first)
                if mirror.next_offset == first:
                    mirror.append_payloads(
                        [(first + i, p) for i, p in enumerate(records)]
                    )
                    if info.get("gap"):
                        rest[shard] = {
                            "first": first, "records": [],
                            "gap": info["gap"],
                        }
                    continue
            except (SegmentError, OSError):
                log.exception("tail absorb failed for %s shard %d",
                              leader, shard)
            rest[shard] = info
        return rest

    # ------------------------------------------------------ observation

    @property
    def degraded(self) -> bool:
        return bool(self._degraded)

    def degraded_shards(self) -> List[int]:
        return sorted(self._degraded)

    def lag(self) -> int:
        """Records appended-durably but not yet follower-acked, summed
        over shards (the watermark exposure this instant)."""
        return sum(
            max(0, self.ds.logs[k].next_offset - self.watermark[k])
            for k in range(self.ds.n_shards)
        )

    def stats(self) -> dict:
        return {
            "base": dict(self.base),
            "watermark": dict(self.watermark),
            "followers": dict(self.followers),
            "degraded": self.degraded_shards(),
            "lag": self.lag(),
            "ships": self.ships,
            "degrades": self.degrades,
            "mirrors": {
                leader: {
                    shard: [m.oldest_offset, m.next_offset]
                    for shard, m in by.items()
                }
                for leader, by in self.mirrors.items()
            },
        }
