"""Durable-message-log manager: broker wiring + retention GC.

The inversion of the `broker/persist.py` data model:

* dispatch time — a QoS>=1 publish that reaches at least one PARKED
  persistent session (one holding a replay cursor) is appended ONCE to
  `matchhash(topic) % ds.shards`'s stream (`Broker._deliver_to` calls
  `on_offline_publish`; a bounded recent-mid table suppresses the
  duplicate appends N parked receivers would otherwise cause);
* park time — `park_session` takes the end cursor FIRST, then spills
  the session's leftover QoS>=1 mqueue overflow into the log (landing
  past the cursor, so resume replays it back), leaving a session
  record of only `(subscriptions, inflight, dedup, cursor)`;
* resume time — `replay_into` rebuilds the mqueue by iterating every
  shard from the cursor through the session's topic filters, skipping
  mids already pending (inflight/mqueue) so an in-process resume never
  duplicates, and falling back to the retainer's current state for
  filters whose log window was GC'd away (`gap` recovery);
* GC — the per-shard min-cursor over parked sessions advances as
  sessions resume/expire; sealed generations fully behind it are
  dropped whole once `ds.retention_bytes`/`ds.retention` pressure
  says so, and hard retention can drop unconsumed generations too (the
  cursor then reports the gap instead of blocking the disk forever).

Config keys are read here (and only here) from the validated schema —
the static-analysis gate (`tools/analysis/registry.py`) lints every
config namespace in both directions: a key read must be declared in
`config/config.py`, a declared key must be read somewhere.
"""

from __future__ import annotations

import base64
import json
import os
import time
from collections import OrderedDict
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

from ..broker import topic as topiclib
from ..broker.message import Message
from ..broker.persist import message_from_dict
from ..observe import spans as _spans
from ..observe.tracepoints import tp
from ..ops.hashing import word_hash64
from .buffer import WriteBuffer
from .iterator import Cursor, ShardIterator, encode_message
from .log import ShardLog

_RECENT_MIDS = 8192  # append-dedup window (per manager, all shards)


class DsManager:
    def __init__(self, broker, directory: str, conf, metrics=None):
        self.broker = broker
        self.dir = directory
        self.n_shards = int(conf.get("ds.shards"))
        self.flush_interval = float(conf.get("ds.flush_interval"))
        self.flush_bytes = int(conf.get("ds.flush_bytes"))
        self.gc_interval = float(conf.get("ds.gc_interval"))
        self.retention_bytes = int(conf.get("ds.retention_bytes"))
        self.retention_s = float(conf.get("ds.retention"))
        seg_bytes = int(conf.get("ds.seg_bytes"))
        self.logs: List[ShardLog] = [
            ShardLog(os.path.join(directory, f"shard-{k}"), k,
                     seg_bytes=seg_bytes)
            for k in range(self.n_shards)
        ]
        self.buffers: List[WriteBuffer] = [
            WriteBuffer(log, flush_bytes=self.flush_bytes)
            for log in self.logs
        ]
        self.metrics = metrics
        # replication plane (ds/repl.py); DsReplicator sets itself here
        # at construction — replay then understands handed-off cursors
        self.repl = None
        self._recent_mids: "OrderedDict[bytes, int]" = OrderedDict()
        self._last_flush = 0.0
        self._last_gc = 0.0
        self.gc_forced_drops = 0  # generations dropped past live cursors

    # ------------------------------------------------------------- append

    def shard_of(self, topic: str) -> int:
        """`matchhash(topic) % ds.shards` — the deterministic FNV lane
        the engine's table keys use, so shard placement survives
        restarts and agrees across processes."""
        return word_hash64(topic) % self.n_shards

    def append(
        self, msg: Message, dedup: bool = True
    ) -> Optional[Tuple[int, int]]:
        """Append one message; returns (shard, offset), or None when the
        mid was appended recently (dispatch reaches this once per parked
        receiver; the stream wants the message once).  `dedup=False`
        forces the append — the park-time mqueue spill uses it because
        its messages may already exist in the log BEFORE the new cursor
        (replayed-then-reparked), where suppression would lose them."""
        if dedup and msg.mid in self._recent_mids:
            return None
        self._recent_mids[msg.mid] = 1
        while len(self._recent_mids) > _RECENT_MIDS:
            self._recent_mids.popitem(last=False)
        shard = self.shard_of(msg.topic)
        off = self.buffers[shard].append(encode_message(msg))
        tp("ds.append", shard=shard, offset=off, topic=msg.topic,
           mid=msg.mid)
        if self.metrics is not None:
            self.metrics.inc("ds.appends")
        if _spans.enabled():
            # parked-session leg: the durable append closes a sampled
            # span (observe/spans.py "ds" stage) — the offline analog
            # of the wire-flush boundary
            ctx = msg.headers.get("__span")
            if ctx is not None:
                _spans.mark(ctx, "ds")
                _spans.finish(ctx)
        return shard, off

    def on_offline_publish(self, msg: Message) -> None:
        """Dispatch-time hook (`Broker._deliver_to`): the publish
        matched a parked persistent session's subscription."""
        self.append(msg)

    # ------------------------------------------------------------ cursors

    def end_cursor(self) -> Dict[int, Tuple[int, int]]:
        """Per-shard (generation, next-append offset) this instant —
        the cursor a session parking NOW resumes from.  Uses the
        buffered head (not the durable head): appends already buffered
        happened-before the park.  `park_session` flushes before the
        cursor is persisted, so the durable end catches up to every
        cursor that reaches disk."""
        return {
            k: (self.logs[k].generation, self.buffers[k].next_offset)
            for k in range(self.n_shards)
        }

    def park_session(self, session) -> Dict[int, Tuple[int, int]]:
        """Take the park cursor, spill QoS>=1 mqueue overflow into the
        log (past the cursor, so resume replays it), keep QoS0/shared
        overflow in the in-memory mqueue (persisted as the residual
        mqueue section of the cursor-form record).  Returns the
        cursor; also set on the session."""
        cursor = self.end_cursor()
        leftovers = session.mqueue.drain_all()
        for m in leftovers:
            if m.qos >= 1 and not m.headers.get("shared"):
                self.append(m, dedup=False)
            else:
                session.mqueue.insert(m)
        # the persisted cursor must never run ahead of the durable
        # end: a crash would otherwise recover the log to a lower
        # offset, hand the lost offsets to NEW post-restart messages,
        # and this session's resume would silently skip them (its
        # cursor claims they were already seen).  Flushing here makes
        # cursor <= durable end at every save point.
        self.flush_all()
        session.ds_cursor = cursor
        return cursor

    # ------------------------------------------------------------- replay

    def replay_into(self, session, batch: int = 512) -> Tuple[int, int]:
        """Rebuild the session's mqueue from the log (resume path).

        Returns (messages inserted, offsets lost to GC).  Filters are
        the session's non-shared subscriptions (shared-group copies are
        owned by the dispatch-time failover path, never the log); mids
        already pending in the session are skipped, so an in-process
        resume (mqueue still warm) converges instead of duplicating.
        Advances the session's cursor to the durable end."""
        cursor = getattr(session, "ds_cursor", None)
        if cursor is None:
            return 0, 0
        origin = getattr(session, "ds_cursor_node", None)
        if origin:
            # the cursor points into ANOTHER node's log (cursor-handoff
            # takeover): rebuild from this node's mirror + shipped tail,
            # then re-home the cursor to the local log
            return self._replay_handoff(session, origin, batch=batch)
        subs = []  # (real filter words-key, subscription key, opts)
        for filt, opts in session.subscriptions.items():
            group, real = topiclib.parse_share(filt)
            if group is None:
                subs.append((real, filt, opts))
        self.flush_all()  # replay must see every buffered append
        seen = session.pending_mids()
        n = gap = 0
        t0 = time.monotonic()
        for shard in range(self.n_shards):
            gen, off = cursor.get(shard, (0, 0))
            it = ShardIterator(
                self.logs[shard], Cursor(shard, gen, off),
                filters=[r for r, _f, _o in subs] or None,
            )
            if not subs:
                # no plain filters: nothing can match; fast-forward
                cursor[shard] = (self.logs[shard].generation,
                                 self.buffers[shard].next_offset)
                continue
            while True:
                got = it.next(batch)
                if not got:
                    break
                for _offset, msg in got:
                    if msg.mid in seen or msg.expired():
                        continue
                    seen.add(msg.mid)
                    for real, skey, opts in subs:
                        if not topiclib.match(msg.topic, real):
                            continue
                        if opts.no_local and \
                                msg.from_client == session.clientid:
                            continue
                        qos = (max(msg.qos, opts.qos)
                               if session.upgrade_qos
                               else min(msg.qos, opts.qos))
                        session.mqueue.insert(replace(msg, qos=qos))
                        n += 1
            gap += it.gap
            cursor[shard] = (it.cursor.generation, it.cursor.offset)
        session.ds_cursor = cursor
        if gap:
            n += self._gap_recover(session, [r for r, _f, _o in subs], seen)
        tp("ds.replay", clientid=session.clientid, messages=n, gap=gap,
           ms=(time.monotonic() - t0) * 1e3)
        if self.metrics is not None:
            self.metrics.inc("ds.replays")
            self.metrics.inc("ds.replayed_messages", n)
        return n, gap

    def _replay_handoff(
        self, session, origin: str, batch: int = 512
    ) -> Tuple[int, int]:
        """Resume a session imported via cursor handoff (ds/repl.py):
        the mqueue is rebuilt from this node's MIRROR of the origin's
        shard logs plus the shipped unreplicated tail — the origin
        never materialized the queue.  Mirror windows lost to resets
        and tails the origin could not read count as gaps (recovered
        via the retainer like any GC gap).  Afterwards the cursor is
        re-homed to this node's own log end: new offline traffic for
        the session lands locally from here on."""
        cursor = dict(getattr(session, "ds_cursor", None) or {})
        tail = getattr(session, "ds_handoff_tail", None) or {}
        subs = []
        for filt, opts in session.subscriptions.items():
            group, real = topiclib.parse_share(filt)
            if group is None:
                subs.append((real, filt, opts))
        seen = session.pending_mids()
        n = gap = 0
        t0 = time.monotonic()

        def deliver(msg) -> int:
            if msg.mid in seen or msg.expired():
                return 0
            seen.add(msg.mid)
            d = 0
            for real, _skey, opts in subs:
                if not topiclib.match(msg.topic, real):
                    continue
                if opts.no_local and msg.from_client == session.clientid:
                    continue
                qos = (max(msg.qos, opts.qos) if session.upgrade_qos
                       else min(msg.qos, opts.qos))
                session.mqueue.insert(replace(msg, qos=qos))
                d += 1
            return d

        for shard in sorted(set(cursor) | set(tail)):
            _gen, off = cursor.get(shard, (0, 0))
            info = tail.get(shard)
            # the tail covers [first, ...): bound the mirror read there
            stop = (int(info["first"])
                    if info and info.get("records") else None)
            mirror = (self.repl.mirror_log(origin, shard)
                      if self.repl is not None else None)
            if mirror is None and info is None:
                # no local coverage at all for this shard's window —
                # an honest gap, not a silent skip
                gap += 1
                continue
            if mirror is not None and subs and (stop is None or stop > off):
                while True:
                    got, nxt, g = mirror.read_from(off, batch)
                    gap += g
                    if not got:
                        break
                    for o, payload in got:
                        if stop is not None and o >= stop:
                            break
                        try:
                            msg = message_from_dict(
                                json.loads(payload.decode("utf-8")))
                        except (ValueError, KeyError):
                            continue  # torn/alien record: skip
                        n += deliver(msg)
                    off = nxt
                    if stop is not None and off >= stop:
                        break
            if stop is not None and off < stop:
                # coverage hole: the mirror ran dry before the shipped
                # tail begins (mirror reset/trim raced the handoff) —
                # reported, never silently skipped
                gap += stop - off
            if info:
                gap += int(info.get("gap", 0))
                first = int(info.get("first", 0))
                floor = cursor.get(shard, (0, 0))[1]
                for i, b64 in enumerate(info.get("records") or []):
                    if first + i < floor:
                        continue  # below the park cursor
                    try:
                        msg = message_from_dict(json.loads(
                            base64.b64decode(b64).decode("utf-8")))
                    except (ValueError, KeyError):
                        continue
                    n += deliver(msg)
        if gap:
            n += self._gap_recover(session, [r for r, _f, _o in subs],
                                   seen)
        session.ds_cursor = self.end_cursor()
        session.ds_cursor_node = None
        session.ds_handoff_tail = None
        tp("ds.replay", clientid=session.clientid, messages=n, gap=gap,
           handoff=True, origin=origin,
           ms=(time.monotonic() - t0) * 1e3)
        if self.metrics is not None:
            self.metrics.inc("ds.replays")
            self.metrics.inc("ds.replayed_messages", n)
        return n, gap

    def _gap_recover(self, session, reals: List[str], seen) -> int:
        """Part of the session's log window was GC'd: deliver the
        retainer's CURRENT state for its filters so it at least holds
        the last value of every retained topic it missed (the
        documented degradation, reported via the replay gap)."""
        retainer = getattr(self.broker, "retainer", None)
        if retainer is None:
            return 0
        n = 0
        for msg in retainer.iter_matching(reals):
            if msg.mid in seen:
                continue
            seen.add(msg.mid)
            session.mqueue.insert(msg)
            n += 1
        return n

    # ----------------------------------------------------------- flush/GC

    def flush_all(self) -> int:
        n = 0
        for buf in self.buffers:
            if buf.pending_count():
                n += buf.flush()
        if n and self.metrics is not None:
            self.metrics.inc("ds.flushes")
        return n

    def min_cursors(self) -> Dict[int, int]:
        """Per-shard minimum resume offset over parked sessions (the
        session-GC output retention runs behind).  Shards no parked
        session holds a cursor into float to the buffered end —
        everything there is reclaimable.  Must run on the event loop
        (like everything that reads cm.pending): resume pops the
        session from pending before replaying it, so an off-loop
        snapshot here could GC a generation mid-replay."""
        mins = {k: self.buffers[k].next_offset
                for k in range(self.n_shards)}
        for _cid, (session, _exp) in list(self.broker.cm.pending.items()):
            cur = getattr(session, "ds_cursor", None)
            if not cur:
                continue
            for k, (_g, off) in cur.items():
                if off < mins.get(k, off + 1):
                    mins[k] = off
        return mins

    def gc(self, now: Optional[float] = None) -> int:
        """Seal + drop generations behind the min-cursor under
        retention pressure; hard-expire past `ds.retention` even
        ahead of a lagging cursor (replay then reports the gap)."""
        now = now if now is not None else time.time()
        mins = self.min_cursors()
        dropped = 0
        for shard, log in enumerate(self.logs):
            min_off = mins[shard]
            total = log.total_bytes
            for seg in list(log.segments):
                over = (self.retention_bytes > 0
                        and total > self.retention_bytes)
                expired = (self.retention_s > 0
                           and now - seg.mtime > self.retention_s)
                if not (over or expired):
                    break  # oldest-first: nothing further is due either
                consumed = seg.end <= min_off
                if not consumed:
                    # hard retention ahead of a lagging cursor: the
                    # session replays a gap instead of pinning the disk
                    self.gc_forced_drops += 1
                total -= seg.nbytes
                log.drop_generation(seg.generation)
                dropped += 1
                tp("ds.gc", shard=shard, generation=seg.generation,
                   offsets=seg.count, forced=not consumed)
        if dropped and self.metrics is not None:
            self.metrics.inc("ds.gc_segments", dropped)
        return dropped

    def flush_due(self, now: Optional[float] = None) -> bool:
        """True (and arms the next interval) when the periodic flush
        is due.  The node ticker checks this on the loop and runs the
        fsync-heavy `flush_all` on a worker thread."""
        now = now if now is not None else time.monotonic()
        if now - self._last_flush >= self.flush_interval:
            self._last_flush = now
            return True
        return False

    def tick_gc(self, now: Optional[float] = None) -> None:
        """Loop-side tick half: periodic retention GC + gauge refresh.
        Must stay ON the event loop — `min_cursors()` walks cm.pending,
        which the loop mutates (resume pops entries mid-replay); an
        off-loop run races that and can GC a generation a resuming
        session is concurrently replaying."""
        now = now if now is not None else time.monotonic()
        if now - self._last_gc >= self.gc_interval:
            self._last_gc = now
            self.gc()
        self.sync_metrics()

    def tick(self, now: Optional[float] = None) -> None:
        """Single-threaded convenience (tests/bench/tools): interval
        flush + GC in one call.  The node splits the two halves —
        see `flush_due`/`tick_gc`."""
        if self.flush_due(now):
            self.flush_all()
        self.tick_gc(now)

    def sync_metrics(self) -> None:
        if self.metrics is None:
            return
        mins = self.min_cursors()
        self.metrics.gauge_set(
            "ds.bytes", sum(log.total_bytes for log in self.logs))
        self.metrics.gauge_set(
            "ds.segments",
            sum(len(log.segments) + 1 for log in self.logs))
        self.metrics.gauge_set(
            "ds.lag",
            max((self.buffers[k].next_offset - mins[k]
                 for k in range(self.n_shards)), default=0))
        if self.repl is not None:
            self.metrics.gauge_set("ds.repl.lag", self.repl.lag())

    # -------------------------------------------------------------- stats

    def stats(self) -> dict:
        """`GET /ds/stats` shape: per-shard occupancy + cursor lag."""
        mins = self.min_cursors()
        shards = []
        for k, log in enumerate(self.logs):
            buf = self.buffers[k]
            shards.append({
                "shard": k,
                "generation": log.generation,
                "oldest_offset": log.oldest_offset,
                "durable_offset": buf.durable_offset,
                "next_offset": buf.next_offset,
                "min_cursor": mins[k],
                "lag": buf.next_offset - mins[k],
                "segments": len(log.segments) + 1,
                "bytes": log.total_bytes,
                "buffered_bytes": buf.pending_bytes(),
            })
        return {
            "shards": shards,
            "totals": {
                "bytes": sum(s["bytes"] for s in shards),
                "segments": sum(s["segments"] for s in shards),
                "buffered_bytes": sum(
                    s["buffered_bytes"] for s in shards),
                "lag": max((s["lag"] for s in shards), default=0),
                "gc_forced_drops": self.gc_forced_drops,
            },
            "config": {
                "shards": self.n_shards,
                "flush_interval": self.flush_interval,
                "flush_bytes": self.flush_bytes,
                "retention_bytes": self.retention_bytes,
                "retention": self.retention_s,
            },
        }

    def close(self) -> None:
        self.flush_all()
        for log in self.logs:
            log.close()
        if self.repl is not None:
            self.repl.close_mirrors()
