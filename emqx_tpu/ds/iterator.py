"""Resumable cursors over one shard of the durable message log.

A cursor is `(shard, generation, offset)`: the offset is the resume
point (global, monotonic per shard); the generation records which
segment the offset lived in when the cursor was taken, so a stale
cursor is detectable — a cursor landing in a GC-dropped generation
skips to the oldest surviving record and reports the hole in `gap`,
and a cursor pointing PAST what its generation durably holds (crash
recovery truncated the generation and a newer one reused the offsets)
rewinds to the truncation point and reports the lost window as `gap`
instead of silently skipping the reused offsets' new messages.

Filtering is server-side: records are decoded lazily and matched
against the session's topic filters through the host golden matcher
(`broker/topic.py`) BEFORE a Message is materialized, so replaying a
million-record stream for a session subscribed to one narrow filter
deserializes one JSON dict per record and builds Messages only for
hits — the `emqx_ds` "stream + topic-filter iterator" contract.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from ..broker import topic as topiclib
from ..broker.message import Message
from ..broker.persist import message_from_dict
from .log import ShardLog


@dataclass
class Cursor:
    shard: int
    generation: int
    offset: int

    def to_json(self) -> list:
        return [self.generation, self.offset]

    @staticmethod
    def from_json(shard: int, v) -> "Cursor":
        g, o = int(v[0]), int(v[1])
        return Cursor(shard=shard, generation=g, offset=o)


def encode_message(msg: Message) -> bytes:
    """Log record payload: the session-snapshot JSON message dict (one
    serialization discipline for both durability planes)."""
    from ..broker.persist import message_to_dict

    return json.dumps(
        message_to_dict(msg), separators=(",", ":")
    ).encode("utf-8")


class ShardIterator:
    """Batched reader over one shard from a cursor, with topic filters.

    `filters` are REAL topic filters (no $share prefix); None = every
    record.  `next(n)` returns up to n matched messages and advances
    the cursor past every record it *examined* (matched or not), so a
    session replaying a busy shared stream makes forward progress even
    when nothing matches.  `gap` accumulates offsets lost to retention
    GC underneath the cursor; `exhausted` flips when the durable end
    was reached.
    """

    def __init__(
        self,
        log: ShardLog,
        cursor: Cursor,
        filters: Optional[Sequence[str]] = None,
        batch_records: int = 512,
    ):
        self.log = log
        self.cursor = cursor
        self.filter_words = (
            None if filters is None
            else [topiclib.words(f) for f in filters]
        )
        self.batch_records = batch_records
        self.gap = 0
        self.exhausted = False
        self._validate_cursor()

    def _validate_cursor(self) -> None:
        """Check the (generation, offset) pair against the segment
        chain.  Offsets alone cannot distinguish "resume point" from
        "post-crash timeline where the offsets were reused for new
        messages"; the generation can.  Callers must flush the shard's
        write buffer first (replay does) — buffered appends are ahead
        of the durable end by design and are not a mismatch."""
        gen, off = self.cursor.generation, self.cursor.offset
        log = self.log
        if gen <= 0:
            return  # unknown-generation cursor: plain offset seek
        for seg in [*log.segments, log._active]:
            if seg.generation != gen:
                continue
            if off > seg.end:
                # crash recovery truncated this generation below the
                # cursor and reopened at seg.end: records now on disk
                # in [seg.end, off) are NEW messages on the post-crash
                # timeline; the pre-crash ones the cursor had advanced
                # past are the hole.  Rewind and report.
                self.gap += off - seg.end
                self.cursor = Cursor(log.shard, gen, seg.end)
            return
        if gen > log.generation:
            # cursor from a lost timeline (log directory replaced or
            # rolled back wholesale): restart at the oldest surviving
            # record, reporting everything the cursor thought it had
            oldest = log.oldest_offset
            self.gap += max(0, off - oldest)
            self.cursor = Cursor(
                log.shard, log.generation_at(oldest), oldest)
        # else: generation GC'd behind the chain — read_from's offset
        # accounting reports that hole when the seek lands past it

    def _matches(self, topic: str) -> bool:
        if self.filter_words is None:
            return True
        name = topiclib.words(topic)
        return any(
            topiclib.match_words(name, fw) for fw in self.filter_words
        )

    def next(self, n: int = 256) -> List[Tuple[int, Message]]:
        """Up to n matched (offset, Message) pairs; [] at durable end."""
        out: List[Tuple[int, Message]] = []
        while len(out) < n:
            recs, next_off, gap = self.log.read_from(
                self.cursor.offset, self.batch_records
            )
            self.gap += gap
            if not recs:
                self.exhausted = True
                break
            for off, payload in recs:
                if len(out) >= n:
                    # batch full mid-segment: resume exactly here
                    self.cursor = Cursor(
                        self.log.shard, self.log.generation_at(off), off
                    )
                    return out
                try:
                    d = json.loads(payload.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    continue  # undecodable record: skip, keep offset
                topic = d.get("topic", "")
                if self._matches(topic):
                    out.append((off, message_from_dict(d)))
            self.cursor = Cursor(
                self.log.shard, self.log.generation_at(next_off), next_off
            )
        return out
