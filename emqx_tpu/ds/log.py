"""Per-shard append-only segment files for the durable message log.

One shard = one directory of numbered *generations*; one generation =
one segment file.  The record framing reuses the `checkpoint/store.py`
discipline — a fixed header, then CRC32-framed records — so the same
torn-tail reasoning applies: a kill at any byte leaves a prefix of
whole records plus at most one torn record, which recovery truncates.

File layout (little-endian):

    header:  magic "ETPUDSEG" | u32 version | u32 shard
             | u64 generation | u64 base_offset
    record:  u32 payload_crc | u32 payload_len | payload bytes

Offsets are monotonic per shard and global across generations: record
`i` of a segment holds offset `base_offset + i`.  The ACTIVE segment is
`seg.<gen>.open` and is appended + fsync'd in place; a segment *roll*
is flush + fsync + rename to `seg.<gen>.log` (+ directory fsync) — the
same temp+fsync+rename step the snapshot store uses, so a sealed
segment can never surface half-rolled.  Sealed generations are
immutable, which is what lets retention GC drop them as whole files
behind the session min-cursor (`manager.py`).
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

MAGIC = b"ETPUDSEG"
VERSION = 1
_HDR = struct.Struct("<8sIIQQ")  # magic, version, shard, generation, base
_REC = struct.Struct("<II")  # payload crc, payload len
MAX_RECORD = 64 << 20  # sanity bound against a corrupt length field


class SegmentError(Exception):
    """A segment file failed its header/frame check."""


@dataclass
class SegmentInfo:
    generation: int
    base: int  # first offset in this segment
    count: int  # whole records present
    nbytes: int  # file size on disk
    path: str
    sealed: bool
    mtime: float

    @property
    def end(self) -> int:
        """One past the last offset in this segment."""
        return self.base + self.count


def _scan_segment(path: str, shard: Optional[int] = None):
    """Parse header + count whole records; returns (info-tuple, good_len).

    `good_len` is the byte length of the valid prefix — a torn final
    record (short header, short payload, or CRC mismatch) ends the
    scan there, the recovery contract of `ShardLog._recover`."""
    with open(path, "rb") as f:
        data = f.read()  # analysis: allow-blocking(boot-time recovery scan; no traffic served yet)
    if len(data) < _HDR.size:
        raise SegmentError("file shorter than segment header")
    magic, version, seg_shard, gen, base = _HDR.unpack_from(data, 0)
    if magic != MAGIC:
        raise SegmentError("bad segment magic")
    if version != VERSION:
        raise SegmentError(f"unsupported segment version {version}")
    if shard is not None and seg_shard != shard:
        raise SegmentError(f"segment belongs to shard {seg_shard}")
    off = _HDR.size
    count = 0
    while off + _REC.size <= len(data):
        crc, ln = _REC.unpack_from(data, off)
        if ln > MAX_RECORD or off + _REC.size + ln > len(data):
            break  # torn length/payload
        payload = data[off + _REC.size:off + _REC.size + ln]
        if zlib.crc32(payload) != crc:
            break  # torn or corrupt record: everything after is suspect
        off += _REC.size + ln
        count += 1
    return (seg_shard, gen, base, count), off


class ShardLog:
    """One shard's segment chain: sealed generations + one active file."""

    def __init__(self, directory: str, shard: int, seg_bytes: int = 4 << 20,
                 base: int = 0):
        self.dir = directory
        self.shard = shard
        self.seg_bytes = max(1, int(seg_bytes))
        # first offset when the chain is empty: a replication MIRROR
        # (ds/repl.py) starts at the leader's replication base, not 0,
        # so mirror offsets stay identical to the leader's
        self._base0 = max(0, int(base))
        # appends arrive via WriteBuffer.flush on EITHER the event loop
        # (inline watermark) or the ticker's to_thread hop, while reads
        # (resume replay, GC bookkeeping) stay on the loop: every access
        # to the segment chain + active handle is serialized here.
        # RLock because append_payloads -> roll nests an acquire.
        self._lock = threading.RLock()
        self.segments: List[SegmentInfo] = []  # sealed, ascending gen
        self._f = None  # active segment handle (append mode)
        self._active: Optional[SegmentInfo] = None
        os.makedirs(directory, exist_ok=True)
        self._recover()

    # ------------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Adopt sealed segments, truncate+seal any torn active file,
        then open a fresh generation for new appends.  Runs once from
        __init__ (node construction, before the loop serves traffic):
        the recovery IO below is deliberately synchronous boot work."""
        with self._lock:
            sealed, opens = [], []
            for name in os.listdir(self.dir):
                if name.startswith("seg.") and name.endswith(".log"):
                    sealed.append(os.path.join(self.dir, name))
                elif name.startswith("seg.") and name.endswith(".open"):
                    opens.append(os.path.join(self.dir, name))
            for path in sealed:
                try:
                    (_s, gen, base, count), good = _scan_segment(
                        path, self.shard)
                except (SegmentError, OSError):
                    continue  # unreadable sealed segment: skip (read gap)
                if count:
                    self.segments.append(SegmentInfo(
                        gen, base, count, os.path.getsize(path), path, True,
                        os.path.getmtime(path)))
                else:
                    _unlink_quiet(path)
            # a crash can leave the active file torn mid-record: truncate
            # to the whole-record prefix, then seal it — recovery IS the
            # roll
            for path in opens:
                try:
                    (_s, gen, base, count), good = _scan_segment(
                        path, self.shard)
                except (SegmentError, OSError):
                    _unlink_quiet(path)
                    continue
                if count == 0:
                    _unlink_quiet(path)
                    continue
                if good < os.path.getsize(path):
                    with open(path, "r+b") as f:
                        f.truncate(good)  # analysis: allow-blocking(one-shot boot recovery)
                        f.flush()  # analysis: allow-blocking(one-shot boot recovery)
                        os.fsync(f.fileno())  # analysis: allow-blocking(one-shot boot recovery)
                final = os.path.join(self.dir, f"seg.{gen}.log")
                os.replace(path, final)
                self.segments.append(SegmentInfo(
                    gen, base, count, os.path.getsize(final), final, True,
                    os.path.getmtime(final)))
            self.segments.sort(key=lambda s: s.generation)
            self._fsync_dir()
            self._open_active()

    def _open_active(self) -> None:
        # called under self._lock (boot recovery or a roll mid-flush);
        # the header write rides the same flush/fsync budget as the
        # roll that triggered it
        with self._lock:
            gen = (self.segments[-1].generation + 1) if self.segments else 1
            base = self.segments[-1].end if self.segments else self._base0
            path = os.path.join(self.dir, f"seg.{gen}.open")
            f = open(path, "wb")
            f.write(_HDR.pack(MAGIC, VERSION, self.shard, gen, base))  # analysis: allow-blocking(segment-roll header, rides the flush fsync budget)
            f.flush()  # analysis: allow-blocking(segment-roll header, rides the flush fsync budget)
            os.fsync(f.fileno())  # analysis: allow-blocking(segment-roll header, rides the flush fsync budget)
            self._f = f
            self._active = SegmentInfo(
                gen, base, 0, _HDR.size, path, False, os.path.getmtime(path))

    def _fsync_dir(self) -> None:
        try:
            dfd = os.open(self.dir, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)  # analysis: allow-blocking(directory fsync rides the segment-roll/boot-recovery budget)
        except OSError:
            pass
        finally:
            os.close(dfd)

    # -------------------------------------------------------------- append

    @property
    def generation(self) -> int:
        with self._lock:
            return self._active.generation

    @property
    def next_offset(self) -> int:
        """Next offset a durable append would take (buffered appends in
        `WriteBuffer` run ahead of this)."""
        with self._lock:
            return self._active.end

    @property
    def oldest_offset(self) -> int:
        with self._lock:
            if self.segments:
                return self.segments[0].base
            return self._active.base

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return sum(
                s.nbytes for s in self.segments
            ) + self._active.nbytes

    def generation_at(self, offset: int) -> int:
        """Generation whose segment holds (or will hold) `offset` —
        the active generation for offsets at/past the active base.
        Cursors stamp THIS, not the active generation, so a mid-chain
        cursor names the generation its offset actually lives in and
        a post-crash (generation, offset) mismatch stays detectable
        (`ShardIterator._validate_cursor`)."""
        with self._lock:
            if offset >= self._active.base:
                return self._active.generation
            for seg in reversed(self.segments):
                if seg.base <= offset:
                    return seg.generation
            return (self.segments[0].generation if self.segments
                    else self._active.generation)

    def append_payloads(self, items: List[Tuple[int, bytes]]) -> None:
        """Write (offset, payload) records — offsets MUST continue the
        shard's sequence (the write-behind buffer guarantees this) —
        then fsync; rolls the segment past `seg_bytes`."""
        if not items:
            return
        # the fsync below is the ds durability contract: WriteBuffer
        # batches appends to `ds.flush_bytes` precisely so this runs
        # once per watermark (inline on the loop) or per ticker flush
        # (to_thread) — bounded-loss by BYTES, PR 5's design decision
        with self._lock:
            first = items[0][0]
            if first != self._active.end:
                raise SegmentError(
                    f"append at offset {first}, "
                    f"expected {self._active.end}")
            parts = []
            for _off, payload in items:
                parts.append(_REC.pack(zlib.crc32(payload), len(payload)))
                parts.append(payload)
            blob = b"".join(parts)
            self._f.write(blob)  # analysis: allow-blocking(ds durability contract: one batched write per flush_bytes watermark)
            self._f.flush()  # analysis: allow-blocking(ds durability contract: one batched flush per flush_bytes watermark)
            os.fsync(self._f.fileno())  # analysis: allow-blocking(ds durability contract: one fsync per flush_bytes watermark)
            self._active.count += len(items)
            self._active.nbytes += len(blob)
            if self._active.nbytes >= self.seg_bytes:
                self.roll()

    def roll(self) -> Optional[SegmentInfo]:
        """Seal the active segment (fsync + rename + dir fsync) and open
        the next generation.  No-op on an empty active segment."""
        with self._lock:
            if self._active.count == 0:
                return None
            self._f.flush()  # analysis: allow-blocking(segment seal, once per seg_bytes)
            os.fsync(self._f.fileno())  # analysis: allow-blocking(segment seal, once per seg_bytes)
            self._f.close()
            final = os.path.join(
                self.dir, f"seg.{self._active.generation}.log")
            os.replace(self._active.path, final)
            self._fsync_dir()
            info = SegmentInfo(
                self._active.generation, self._active.base,
                self._active.count, self._active.nbytes, final, True,
                os.path.getmtime(final))
            self.segments.append(info)
            self._open_active()
            return info

    # ---------------------------------------------------------------- read

    def read_from(
        self, offset: int, max_records: int = 256
    ) -> Tuple[List[Tuple[int, bytes]], int, int]:
        """Durable records starting at `offset`.

        Returns (records, next_offset, gap): `records` is a list of
        (offset, payload); `gap` is the number of offsets skipped
        because retention GC dropped the generation they lived in
        (the cursor lands on the oldest surviving record).  Only
        fsync'd data is visible — buffered appends are not."""
        with self._lock:
            gap = 0
            oldest = self.oldest_offset
            if offset < oldest:
                gap = oldest - offset
                offset = oldest
            out: List[Tuple[int, bytes]] = []
            for seg in [*self.segments, self._active]:
                if seg.end <= offset or not seg.count:
                    continue
                if seg.base > offset:
                    # a middle generation was dropped (forced retention):
                    # skip forward and report the hole
                    gap += seg.base - offset
                    offset = seg.base
                out.extend(self._read_segment(seg, offset,
                                              max_records - len(out)))
                if out:
                    offset = out[-1][0] + 1
                if len(out) >= max_records:
                    break
            return out, offset, gap

    def _read_segment(
        self, seg: SegmentInfo, offset: int, limit: int
    ) -> List[Tuple[int, bytes]]:
        if limit <= 0:
            return []
        try:
            with open(seg.path, "rb") as f:
                # resume replay is DELIBERATELY serialized with tick_gc
                # on the loop (PR 5 fix #2: an off-loop replay can race
                # the min-cursor walk and lose the generation it reads);
                # the read is bounded by seg_bytes and page-cache-warm
                data = f.read(seg.nbytes)  # analysis: allow-blocking(replay serialized with GC on the loop by design; bounded by seg_bytes)
        except OSError:
            return []
        out: List[Tuple[int, bytes]] = []
        off, rec_off = _HDR.size, seg.base
        while off + _REC.size <= len(data) and len(out) < limit:
            crc, ln = _REC.unpack_from(data, off)
            if ln > MAX_RECORD or off + _REC.size + ln > len(data):
                break
            if rec_off >= offset:
                payload = data[off + _REC.size:off + _REC.size + ln]
                if zlib.crc32(payload) != crc:
                    break  # corrupt mid-file: stop at the valid prefix
                out.append((rec_off, payload))
            off += _REC.size + ln
            rec_off += 1
        return out

    # ------------------------------------------------------------------ gc

    def drop_generation(self, generation: int) -> bool:
        """Unlink one SEALED generation (retention GC)."""
        with self._lock:
            for i, seg in enumerate(self.segments):
                if seg.generation == generation:
                    _unlink_quiet(seg.path)
                    del self.segments[i]
                    return True
            return False

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.flush()  # analysis: allow-blocking(shutdown: final durable handoff)
                    os.fsync(self._f.fileno())  # analysis: allow-blocking(shutdown: final durable handoff)
                except (OSError, ValueError):
                    pass
                self._f.close()
                self._f = None


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass
