"""Durable message log (`emqx_durable_storage` analog).

A log-structured durability subsystem for persistent sessions: QoS>=1
publishes that match at least one parked persistent-session
subscription are appended ONCE to a sharded append-only topic stream,
and parked sessions persist only `(subscriptions, inflight, dedup,
cursor)` — the mqueue is reconstructed by replaying the shared log
from the cursor on resume.  This inverts the `broker/persist.py` data
model (per-session queue snapshots -> shared log + cursors): a million
parked sessions share the bytes of one stream, the park tick stops
being O(sessions x queue depth), and the loss window is measured in
bytes (`ds.flush_bytes`) instead of housekeeping ticks.

Layout:
  log.py      per-shard CRC32-framed segment files, generation headers,
              temp+fsync+rename segment rolls, torn-tail recovery
  buffer.py   per-shard write-behind buffer (flush_interval/flush_bytes
              watermarks — the bounded-loss contract)
  iterator.py resumable `(shard, generation, offset)` cursors with
              server-side topic-filter matching and GC-gap reporting
  manager.py  broker wiring: dispatch-time append, park/resume replay,
              retention GC behind the per-shard min-cursor
"""

from .log import SegmentError, ShardLog
from .iterator import Cursor, ShardIterator
from .buffer import WriteBuffer
from .manager import DsManager

__all__ = [
    "Cursor",
    "DsManager",
    "SegmentError",
    "ShardIterator",
    "ShardLog",
    "WriteBuffer",
]
