"""Built-in broker modules: delayed publish, topic rewrite, auto-subscribe,
topic metrics, event messages.

Analog of `apps/emqx_modules` (SURVEY.md §2.2): each module is a small
hook-driven component over the broker core.
"""

from __future__ import annotations

import heapq
import json
import re
import time
from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Tuple

from .broker import topic as topiclib
from .broker.broker import Broker
from .broker.hooks import Hooks
from .broker.message import Message
from .broker.packet import SubOpts
from .utils.net import peer_host as _peer_host


# ------------------------------------------------------------ delayed pub

class DelayedPublish:
    """`$delayed/<sec>/<topic>` scheduling (`emqx_delayed.erl`).

    A publish to `$delayed/5/a/b` is withheld and re-published to `a/b`
    after 5 seconds.  Driven either by `tick()` (tests, housekeeping loop)
    or an asyncio runner.

    With `store_path` set, scheduled messages persist across restarts
    (the reference keeps them in a disc-copies mnesia table): schedules
    and completions append to a JSON-lines log, compacted at boot and
    when completions pile up.  `max_delayed_messages` bounds the table
    like the reference's config; overflow drops the NEW message and
    counts it.
    """

    PREFIX = "$delayed/"
    MAX_DELAY = 4294967.0
    _COMPACT_DEAD = 1024  # rewrite the log after this many done-records

    def __init__(self, broker: Broker, enable: bool = True,
                 max_delayed_messages: int = 0,
                 store_path: Optional[str] = None):
        self.broker = broker
        self.enable = enable
        self.max_delayed_messages = int(max_delayed_messages)
        self.dropped = 0
        self._heap: List[Tuple[float, int, Message]] = []
        self._seq = 0
        self._live: Dict[str, Tuple[float, int]] = {}  # msgid -> (due, seq)
        self._canceled: set = set()  # seqs removed before firing
        self._store_path = store_path
        self._store = None
        self._hooks = None  # set by install(); cleared by close()
        self._dead_records = 0
        if store_path is not None:
            self._load()
            self._compact()

    # --------------------------------------------------------- persistence

    @staticmethod
    def _enc_val(v):
        import base64

        if isinstance(v, (bytes, bytearray)):
            return {"__b": base64.b64encode(bytes(v)).decode()}
        return v

    @staticmethod
    def _dec_val(v):
        import base64

        if isinstance(v, dict) and "__b" in v:
            return base64.b64decode(v["__b"])
        return v

    @classmethod
    def _msg_to_rec(cls, msg: Message) -> Dict:
        import base64

        return {
            "topic": msg.topic,
            "payload": base64.b64encode(msg.payload).decode(),
            "qos": msg.qos,
            "retain": msg.retain,
            "dup": msg.dup,
            "from_client": msg.from_client,
            "from_username": msg.from_username,
            "mid": msg.mid.hex(),
            "timestamp": msg.timestamp,
            # v5 properties must survive the restart: expiry intervals,
            # response-topic/correlation-data, user properties
            "props": {
                (str(int(k)) if isinstance(k, int) else str(k)):
                cls._enc_val(v)
                for k, v in msg.properties.items()
            },
        }

    @classmethod
    def _rec_to_msg(cls, rec: Dict) -> Message:
        import base64

        props = {}
        for k, v in (rec.get("props") or {}).items():
            props[int(k) if k.lstrip("-").isdigit() else k] = \
                cls._dec_val(v)
        return Message(
            topic=rec["topic"],
            payload=base64.b64decode(rec["payload"]),
            qos=int(rec.get("qos", 0)),
            retain=bool(rec.get("retain")),
            dup=bool(rec.get("dup")),
            from_client=rec.get("from_client", ""),
            from_username=rec.get("from_username"),
            mid=bytes.fromhex(rec["mid"]),
            timestamp=int(rec.get("timestamp", 0)),
            properties=props,
        )

    def _append(self, rec: Dict) -> None:
        if self._store_path is None:
            return
        if self._store is None:
            self._store = open(self._store_path, "a", encoding="utf-8")
        # one JSON line per (rare) delayed-publish schedule: page-cache
        # append + flush, no fsync — same at-least-once writeback
        # contract as utils/replayq.py
        self._store.write(json.dumps(rec, separators=(",", ":")) + "\n")  # analysis: allow-blocking(one page-cache line per delayed schedule, no fsync)
        self._store.flush()  # analysis: allow-blocking(page-cache flush, no fsync)

    def _load(self) -> None:
        import os

        if not os.path.exists(self._store_path):
            return
        live: Dict[str, Dict] = {}
        with open(self._store_path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail from a crash mid-append
                if rec.get("op") == "sched":
                    live[rec["msg"]["mid"]] = rec
                else:  # done / cancel
                    live.pop(rec.get("id", ""), None)
        for rec in live.values():
            msg = self._rec_to_msg(rec["msg"])
            self._schedule(float(rec["due"]), msg, persist=False)

    def _compact(self) -> None:
        """Rewrite the log with only live schedules (boot + threshold)."""
        import os

        if self._store_path is None:
            return
        if self._store is not None:
            self._store.close()
            self._store = None
        tmp = self._store_path + ".tmp"
        by_seq = sorted(
            ((seq, due, mid) for mid, (due, seq) in self._live.items())
        )
        msgs = {seq: msg for due, seq, msg in self._heap}
        with open(tmp, "w", encoding="utf-8") as f:
            for seq, due, mid in by_seq:
                if seq in msgs:
                    # live-set rewrite: runs at boot or past the dead-
                    # record threshold; the set is small by construction
                    # (delayed messages, not broker traffic)
                    f.write(json.dumps(  # analysis: allow-blocking(compaction of the small delayed-publish live set)
                        {"op": "sched", "due": due,
                         "msg": self._msg_to_rec(msgs[seq])},
                        separators=(",", ":")) + "\n")
        os.replace(tmp, self._store_path)
        self._dead_records = 0

    # ----------------------------------------------------------- schedule

    def _schedule(self, due: float, msg: Message, persist: bool = True
                  ) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (due, self._seq, msg))
        self._live[msg.mid.hex()] = (due, self._seq)
        if persist:
            self._append({"op": "sched", "due": due,
                          "msg": self._msg_to_rec(msg)})

    def on_message_publish(self, msg: Message):
        if not self.enable or not isinstance(msg, Message):
            return None
        if not msg.topic.startswith(self.PREFIX):
            return None
        rest = msg.topic[len(self.PREFIX):]
        delay_s, sep, real = rest.partition("/")
        try:
            delay = min(float(delay_s), self.MAX_DELAY)
        except ValueError:
            return None
        if not sep or not real:
            return None
        out = replace(msg, topic=real, headers=dict(msg.headers, allow_publish=False, delayed=delay))
        from .broker.hooks import STOP

        if self.max_delayed_messages and \
                len(self._live) >= self.max_delayed_messages:
            # table full: drop the new message (reference behavior)
            self.dropped += 1
            return (STOP, out)
        self._schedule(time.time() + delay,
                       replace(out, headers=dict(msg.headers)))
        # STOP the fold (like emqx_delayed): downstream publish hooks (rule
        # engine, metrics) must not observe the withheld message now — they
        # run when tick() republishes it
        return (STOP, out)  # broker sees allow_publish=False and drops it

    def tick(self, now: Optional[float] = None) -> int:
        now = now if now is not None else time.time()
        n = 0
        while self._heap and self._heap[0][0] <= now:
            due, seq, msg = heapq.heappop(self._heap)
            if seq in self._canceled:
                self._canceled.discard(seq)
                continue
            self._live.pop(msg.mid.hex(), None)
            self._append({"op": "done", "id": msg.mid.hex()})
            if self._store_path is not None:
                self._dead_records += 1
            self.broker.publish(msg)
            n += 1
        if self._store_path is not None and \
                self._dead_records >= self._COMPACT_DEAD:
            self._compact()
        return n

    # --------------------------------------------------------- management

    def list(self) -> List[Dict]:
        """Pending messages for GET /mqtt/delayed/messages."""
        now = time.time()
        msgs = {seq: (due, msg) for due, seq, msg in self._heap
                if seq not in self._canceled}
        out = []
        for mid, (due, seq) in sorted(self._live.items(),
                                      key=lambda kv: kv[1][0]):
            ent = msgs.get(seq)
            if ent is None:
                continue
            _, msg = ent
            out.append({
                "msgid": mid,
                "topic": msg.topic,
                "qos": msg.qos,
                "payload_size": len(msg.payload),
                "from_clientid": msg.from_client,
                "delayed_remaining": max(0, int(due - now)),
                "expected_at": int(due * 1000),
            })
        return out

    def delete(self, msgid: str) -> bool:
        """DELETE /mqtt/delayed/messages/{msgid}."""
        ent = self._live.pop(msgid, None)
        if ent is None:
            return False
        self._canceled.add(ent[1])
        self._append({"op": "done", "id": msgid})
        if self._store_path is not None:
            self._dead_records += 1
        # lazy heap deletion, but don't let canceled long-delay entries
        # (and their payloads) dominate memory until their due time
        if len(self._canceled) > max(64, len(self._live)):
            self._heap = [(due, seq, msg) for due, seq, msg in self._heap
                          if seq not in self._canceled]
            heapq.heapify(self._heap)
            self._canceled.clear()
        return True

    def status(self) -> Dict:
        return {
            "enable": self.enable,
            "max_delayed_messages": self.max_delayed_messages,
            "pending": len(self._live),
            "dropped": self.dropped,
        }

    def close(self) -> None:
        if self._hooks is not None:
            # a closed scheduler must stop intercepting $delayed
            # publishes (its store is gone; withheld messages would
            # vanish silently)
            self._hooks.delete("message.publish", self.on_message_publish)
            self._hooks = None
        if self._store is not None:
            self._store.close()
            self._store = None

    @property
    def pending(self) -> int:
        return len(self._live)

    def install(self, hooks: Hooks) -> None:
        self._hooks = hooks
        hooks.put("message.publish", self.on_message_publish, priority=50)


# ---------------------------------------------------------- topic rewrite

@dataclass
class RewriteRule:
    action: str  # publish | subscribe | all
    source: str  # topic filter selecting affected topics
    regex: str
    dest: str  # template with \1 backrefs + %c/%u


class TopicRewrite:
    """`emqx_rewrite.erl`: regex rewrite of publish topics and
    subscribe filters."""

    def __init__(self, rules: Optional[List[RewriteRule]] = None):
        self.rules = rules or []

    def _rewrite(self, topic: str, action: str, clientid: str = "", username: str = "") -> str:
        for r in self.rules:
            if r.action not in ("all", action):
                continue
            if not topiclib.match(topic, r.source):
                continue
            m = re.match(r.regex, topic)
            if m:
                dest = r.dest.replace("%c", clientid).replace("%u", username or "")
                try:
                    return m.expand(dest.replace("$", "\\"))
                except re.error:
                    return dest
        return topic

    def on_message_publish(self, msg: Message):
        if not isinstance(msg, Message):
            return None
        new_topic = self._rewrite(msg.topic, "publish", msg.from_client, msg.from_username or "")
        if new_topic != msg.topic:
            return replace(msg, topic=new_topic)
        return None

    def on_client_subscribe(self, clientinfo, props, filters):
        out = []
        for tf, opts in filters:
            out.append(
                (self._rewrite(tf, "subscribe", clientinfo.clientid, clientinfo.username or ""), opts)
            )
        return out

    def install(self, hooks: Hooks) -> None:
        hooks.put("message.publish", self.on_message_publish, priority=60)
        hooks.put("client.subscribe", self.on_client_subscribe, priority=60)


# --------------------------------------------------------- auto-subscribe

class AutoSubscribe:
    """Server-side subscriptions applied at connect
    (`apps/emqx_auto_subscribe`)."""

    def __init__(self, broker: Broker, topics: List[Tuple[str, SubOpts]]):
        self.broker = broker
        self.topics = topics

    def on_client_connected(self, clientinfo, *_):
        ch = self.broker.cm.lookup(clientinfo.clientid)
        if ch is None or ch.session is None:
            return None
        for tf, opts in self.topics:
            tf = tf.replace("%c", clientinfo.clientid).replace(
                "%u", clientinfo.username or ""
            )
            if ch.session.subscribe(tf, opts):
                self.broker.subscribe(clientinfo.clientid, tf, opts)
        return None

    def install(self, hooks: Hooks) -> None:
        hooks.put("client.connected", self.on_client_connected)


# ---------------------------------------------------------- event message

class EventMessage:
    """Publish broker lifecycle events as `$event/...` JSON messages
    (`apps/emqx_modules/src/emqx_event_message.erl`): each enabled
    event kind installs one hook that republishes the event payload to
    its `$event/<kind>` topic for clients to subscribe to."""

    TOPICS = (
        "client_connected", "client_disconnected",
        "client_subscribed", "client_unsubscribed",
        "message_delivered", "message_acked", "message_dropped",
    )

    def __init__(self, broker: Broker, enabled: Dict[str, bool]):
        self.broker = broker
        self.enabled = {k: bool(enabled.get(k)) for k in self.TOPICS}

    def install(self, hooks: Hooks) -> None:
        on = self.enabled
        if on["client_connected"]:
            hooks.put("client.connected", self.on_client_connected)
        if on["client_disconnected"]:
            hooks.put("client.disconnected", self.on_client_disconnected)
        if on["client_subscribed"]:
            hooks.put("session.subscribed", self.on_client_subscribed)
        if on["client_unsubscribed"]:
            hooks.put("session.unsubscribed", self.on_client_unsubscribed)
        if on["message_delivered"]:
            hooks.put("message.delivered", self.on_message_delivered)
        if on["message_acked"]:
            hooks.put("message.acked", self.on_message_acked)
        if on["message_dropped"]:
            hooks.put("message.dropped", self.on_message_dropped)

    def _publish(self, kind: str, payload: Dict) -> None:
        payload.setdefault("ts", int(time.time() * 1000))
        self.broker.publish(Message(
            topic=f"$event/{kind}",
            payload=json.dumps(payload).encode(),
            qos=0,
            from_client="event_message",
            headers={"sys": True},  # loop guard (reference sys flag)
        ))

    @staticmethod
    def _is_event_msg(msg) -> bool:
        return getattr(msg, "topic", "").startswith("$event/")

    def on_client_connected(self, clientinfo, *_):
        self._publish("client_connected", {
            "clientid": clientinfo.clientid,
            "username": clientinfo.username,
            "ipaddress": _peer_host(clientinfo.peerhost),
            "proto_ver": getattr(clientinfo, "proto_ver", None),
            "keepalive": getattr(clientinfo, "keepalive", 0),
            "connected_at": int(time.time() * 1000),
        })
        return None

    def on_client_disconnected(self, clientinfo, normal=True, *_):
        self._publish("client_disconnected", {
            "clientid": clientinfo.clientid,
            "username": clientinfo.username,
            "reason": "normal" if normal else "abnormal",
            "disconnected_at": int(time.time() * 1000),
        })
        return None

    def on_client_subscribed(self, clientid, filt, opts):
        self._publish("client_subscribed", {
            "clientid": clientid,
            "topic": filt,
            "subopts": {"qos": getattr(opts, "qos", 0)},
        })
        return None

    def on_client_unsubscribed(self, clientid, filt):
        self._publish("client_unsubscribed", {
            "clientid": clientid,
            "topic": filt,
        })
        return None

    def on_message_delivered(self, clientid, msg):
        if self._is_event_msg(msg):  # never event-message an event msg
            return None
        self._publish("message_delivered", {
            "from_clientid": msg.from_client,
            "from_username": msg.from_username,
            "clientid": clientid,
            "topic": msg.topic,
            "payload": msg.payload.decode("utf-8", "replace"),
            "qos": msg.qos,
            "retain": msg.retain,
        })
        return None

    def on_message_acked(self, clientid, msg):
        if self._is_event_msg(msg):
            return None
        self._publish("message_acked", {
            "from_clientid": msg.from_client,
            "clientid": clientid,
            "topic": msg.topic,
            "qos": msg.qos,
        })
        return None

    def on_message_dropped(self, msg, reason):
        if msg is None or self._is_event_msg(msg):
            return None
        self._publish("message_dropped", {
            "from_clientid": msg.from_client,
            "topic": msg.topic,
            "qos": msg.qos,
            "reason": reason,
        })
        return None


# ---------------------------------------------------------- topic metrics

class TopicMetrics:
    """Per-registered-topic counters (`emqx_topic_metrics.erl`)."""

    MAX_TOPICS = 512

    def __init__(self):
        self.topics: Dict[str, Dict[str, int]] = {}

    def register(self, topic: str) -> bool:
        if len(self.topics) >= self.MAX_TOPICS:
            return False
        self.topics.setdefault(
            topic, {"messages.in": 0, "messages.out": 0, "messages.qos0.in": 0,
                    "messages.qos1.in": 0, "messages.qos2.in": 0, "messages.dropped": 0}
        )
        return True

    def unregister(self, topic: str) -> None:
        self.topics.pop(topic, None)

    def on_message_publish(self, msg: Message):
        if isinstance(msg, Message):
            m = self.topics.get(msg.topic)
            if m is not None:
                m["messages.in"] += 1
                m[f"messages.qos{msg.qos}.in"] += 1
        return None

    def on_message_delivered(self, clientid, msg):
        m = self.topics.get(msg.topic)
        if m is not None:
            m["messages.out"] += 1
        return None

    def on_message_dropped(self, msg, reason):
        if msg is not None:
            m = self.topics.get(msg.topic)
            if m is not None:
                m["messages.dropped"] += 1
        return None

    def install(self, hooks: Hooks) -> None:
        hooks.put("message.publish", self.on_message_publish, priority=40)
        hooks.put("message.delivered", self.on_message_delivered)
        hooks.put("message.dropped", self.on_message_dropped)
