"""CLI command registry — `emqx_ctl` analog.

The reference registers command modules into a registry consumed by
`bin/emqx_ctl`; here `Cli` holds the registry and two frontends:
  * in-process: `Cli(api=ManagementApi(...)).run(["clients", "list"])`
  * remote: `python -m emqx_tpu.mgmt.cli --url http://.. --token T ...`
    drives a running node over the REST API (urllib only).
Commands mirror `emqx_mgmt_cli`: status, broker, clients, subscriptions,
topics, publish, ban, listeners, metrics, stats, trace, cluster.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Callable, Dict, List, Optional
from urllib import request as urlrequest


class RemoteApi:
    """Thin REST client used by the remote CLI frontend."""

    def __init__(self, url: str, token: Optional[str] = None, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.token = token
        self.timeout = timeout

    def call(self, method: str, path: str, body=None):
        req = urlrequest.Request(
            self.url + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urlrequest.urlopen(req, timeout=self.timeout) as resp:
            data = resp.read()
            return json.loads(data) if data else None


class Cli:
    def __init__(self, api=None, remote: Optional[RemoteApi] = None, out=None):
        """api: an in-process ManagementApi; remote: a RemoteApi."""
        self.api = api
        self.remote = remote
        self.out = out if out is not None else sys.stdout
        self.commands: Dict[str, Callable[[List[str]], int]] = {}
        self.usage: Dict[str, str] = {}
        for name in ("status", "broker", "clients", "subscriptions", "topics",
                     "publish", "ban", "listeners", "metrics", "stats",
                     "trace", "cluster", "plugins", "telemetry", "node_dump",
                     "vm", "log", "olp", "authz", "bridges", "rules",
                     "gateways", "retainer", "delayed", "api_key"):
            self.register(name, getattr(self, "cmd_" + name),
                          getattr(getattr(self, "cmd_" + name), "__doc__", ""))

    def register(self, name: str, fn: Callable[[List[str]], int], usage: str = "") -> None:
        """Plugin commands hook in here (`emqx_ctl:register_command`)."""
        self.commands[name] = fn
        self.usage[name] = usage or ""

    # ------------------------------------------------------------- plumbing

    def _get(self, path: str):
        if self.remote is not None:
            return self.remote.call("GET", "/api/v5" + path)
        return self._inproc("GET", path)

    def _post(self, path: str, body=None):
        if self.remote is not None:
            return self.remote.call("POST", "/api/v5" + path, body)
        return self._inproc("POST", path, body)

    def _delete(self, path: str):
        if self.remote is not None:
            return self.remote.call("DELETE", "/api/v5" + path)
        return self._inproc("DELETE", path)

    def _put(self, path: str, body=None):
        if self.remote is not None:
            return self.remote.call("PUT", "/api/v5" + path, body)
        return self._inproc("PUT", path, body)

    def _inproc(self, method: str, path: str, body=None):
        import asyncio

        from .http import HttpApi

        # run the same handlers the REST server uses, without sockets
        http = HttpApi()
        self.api.install(http)
        target = "/api/v5" + path
        payload = json.dumps(body).encode() if body is not None else b""

        async def go():
            return await http._dispatch(method, target, {}, payload)

        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is not None:
            raise RuntimeError("in-process CLI must run outside the event loop")
        status, out = asyncio.run(go())
        if status >= 400:
            raise RuntimeError(f"{status}: {out}")
        return out

    def p(self, *args) -> None:
        print(*args, file=self.out)

    # ------------------------------------------------------------- commands

    def run(self, argv: List[str]) -> int:
        if not argv or argv[0] in ("-h", "--help", "help"):
            self.p("usage: ctl <command> [...]\ncommands:")
            for name in sorted(self.commands):
                self.p(f"  {name:<15} {self.usage.get(name, '').strip().splitlines()[0] if self.usage.get(name) else ''}")
            return 0
        cmd = self.commands.get(argv[0])
        if cmd is None:
            self.p(f"unknown command {argv[0]!r}")
            return 1
        try:
            return cmd(argv[1:]) or 0
        except Exception as e:
            self.p(f"error: {e}")
            return 1

    def cmd_node_dump(self, args):
        """node_dump [file] — full state dump for support bundles
        (bin/node_dump + emqx_node_dump analog)."""
        import json as _json
        import time as _time

        dump = {"generated_at": int(_time.time())}
        for key, path in (
            ("status", "/status"), ("stats", "/stats"),
            ("metrics", "/metrics"), ("clients", "/clients"),
            ("subscriptions", "/subscriptions"), ("routes", "/topics"),
            ("listeners", "/listeners"), ("alarms", "/alarms"),
            ("banned", "/banned"), ("configs", "/configs"),
            ("nodes", "/nodes"),
        ):
            try:
                dump[key] = self._get(path)
            except Exception as e:
                dump[key] = {"error": str(e)}
        text = _json.dumps(dump, indent=2, default=str)
        if args:
            with open(args[0], "w", encoding="utf-8") as f:
                f.write(text)
            self.p(f"wrote {args[0]} ({len(text)} bytes)")
        else:
            self.p(text)

    def cmd_status(self, args):
        """Show node status."""
        st = self._get("/status")
        self.p(f"Node {st['node']} is {st['status']}")
        self.p(f"Version {st['version']}, uptime {st['uptime']}s")

    def cmd_broker(self, args):
        """Broker stats summary."""
        st = self._get("/stats")
        for k in sorted(st):
            self.p(f"{k:<30} {st[k]}")

    def cmd_clients(self, args):
        """clients list | show <id> | kick <id>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for row in self._get("/clients")["data"]:
                self.p(f"{row['clientid']} connected={row.get('connected')}")
        elif sub == "show":
            self.p(json.dumps(self._get(f"/clients/{args[1]}"), indent=2))
        elif sub == "kick":
            self._delete(f"/clients/{args[1]}")
            self.p(f"kicked {args[1]}")
        else:
            self.p("usage: clients list|show <id>|kick <id>")
            return 1

    def cmd_subscriptions(self, args):
        """List subscriptions (optionally for one client)."""
        if args:
            rows = self._get(f"/clients/{args[0]}/subscriptions")
        else:
            rows = self._get("/subscriptions")["data"]
        for row in rows:
            self.p(f"{row.get('clientid', args[0] if args else '?')} {row['topic']} qos{row['qos']}")

    def cmd_topics(self, args):
        """List the route table."""
        for row in self._get("/topics")["data"]:
            self.p(f"{row['topic']} -> {row['node']}")

    def cmd_publish(self, args):
        """publish <topic> <payload> [qos] [--retain]"""
        if len(args) < 2:
            self.p("usage: publish <topic> <payload> [qos] [--retain]")
            return 1
        qos = int(args[2]) if len(args) > 2 and args[2].isdigit() else 0
        out = self._post("/publish", {
            "topic": args[0], "payload": args[1], "qos": qos,
            "retain": "--retain" in args,
        })
        self.p(f"published id={out['id']} delivered={out['delivered']}")

    def cmd_ban(self, args):
        """ban list | add <kind> <who> [seconds] | del <kind> <who>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for row in self._get("/banned")["data"]:
                self.p(f"{row['as']} {row['who']} until={row['until']}")
        elif sub == "add":
            body = {"as": args[1], "who": args[2]}
            if len(args) > 3:
                body["seconds"] = float(args[3])
            self._post("/banned", body)
            self.p(f"banned {args[1]} {args[2]}")
        elif sub == "del":
            self._delete(f"/banned/{args[1]}/{args[2]}")
            self.p(f"unbanned {args[1]} {args[2]}")
        else:
            return 1

    def cmd_listeners(self, args):
        """listeners [start|stop|restart <id>] — list or manage."""
        if args and args[0] in ("start", "stop", "restart"):
            if len(args) < 2:
                self.p("usage: listeners start|stop|restart <id>")
                return 1
            out = self._post(f"/listeners/{args[1]}/{args[0]}")
            self.p(f"{out['id']} running={out['running']}")
            return
        for row in self._get("/listeners"):
            self.p(f"{row['id']} {row['bind']} running={row['running']} "
                   f"conns={row['current_connections']}")

    def cmd_metrics(self, args):
        """Counter table."""
        for k, v in sorted(self._get("/metrics").items()):
            self.p(f"{k:<40} {v}")

    def cmd_stats(self, args):
        """Gauge table."""
        for k, v in sorted(self._get("/stats").items()):
            self.p(f"{k:<40} {v}")

    def cmd_trace(self, args):
        """trace list | start <name> <clientid|topic|ip> <value> | stop <name>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for row in self._get("/trace"):
                self.p(f"{row['name']} {row['type']}={row.get(row['type'])}")
        elif sub == "start":
            self._post("/trace", {"name": args[1], "type": args[2], "value": args[3]})
            self.p(f"trace {args[1]} started")
        elif sub == "stop":
            self._delete(f"/trace/{args[1]}")
            self.p(f"trace {args[1]} stopped")
        else:
            return 1

    def cmd_cluster(self, args):
        """Cluster node status."""
        for row in self._get("/nodes"):
            self.p(f"{row['node']} {row['node_status']}")


    def cmd_plugins(self, args):
        """plugins list | install|start|stop|enable|disable|uninstall <name-vsn>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for row in self._get("/plugins"):
                state = "running" if row["running"] else (
                    "enabled" if row["enabled"] else "installed")
                self.p(f"{row['name_vsn']:<30} {state}")
        elif sub == "install":
            self._post(f"/plugins/{args[1]}/install")
        elif sub == "uninstall":
            self._delete(f"/plugins/{args[1]}")
        elif sub in ("start", "stop", "enable", "disable"):
            self._put(f"/plugins/{args[1]}/{sub}")
        else:
            self.p(self.usage["plugins"])
            return 1

    def cmd_telemetry(self, args):
        """telemetry status | enable | disable | data"""
        sub = args[0] if args else "status"
        if sub == "status":
            st = self._get("/telemetry/status")
            self.p("enabled" if st["enable"] else "disabled")
        elif sub in ("enable", "disable"):
            self._put("/telemetry/status", {"enable": sub == "enable"})
        elif sub == "data":
            self.p(json.dumps(self._get("/telemetry/data"), indent=2))
        else:
            self.p(self.usage["telemetry"])
            return 1


    def cmd_vm(self, args):
        """Process/runtime stats (emqx_ctl vm analog)."""
        for k, v in self._get("/vm").items():
            self.p(f"{k:<16} {v}")

    def cmd_log(self, args):
        """log | log set-level <DEBUG|INFO|WARNING|ERROR|CRITICAL>"""
        if args and args[0] == "set-level":
            out = self._put("/log", {"level": args[1]})
            self.p(f"level set to {out['level']}")
        else:
            self.p(self._get("/log")["level"])

    def cmd_olp(self, args):
        """olp status | enable | disable (emqx_ctl olp analog)"""
        sub = args[0] if args else "status"
        if sub == "status":
            for k, v in self._get("/olp").items():
                self.p(f"{k:<14} {v}")
        elif sub in ("enable", "disable"):
            self._put("/olp", {"enable": sub == "enable"})
            self.p(f"olp {sub}d")
        else:
            return 1

    def cmd_authz(self, args):
        """authz cache-clean — drain all clients' verdict caches"""
        if args and args[0] == "cache-clean":
            out = self._post("/authorization/cache/clean")
            self.p(f"cleaned {out['cleaned']} client caches")
        else:
            self.p(self.usage["authz"])
            return 1

    def cmd_bridges(self, args):
        """bridges list | enable|disable|restart <name>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for b in self._get("/bridges"):
                res = b.get("resource") or {}
                self.p(f"{b['name']:<20} {b['type']} {b['direction']} "
                       f"enabled={b['enable']} "
                       f"status={res.get('status')}")
        elif sub in ("enable", "disable", "restart"):
            self._put(f"/bridges/{args[1]}/{sub}")
            self.p(f"bridge {args[1]} {sub}ed")
        else:
            return 1

    def cmd_rules(self, args):
        """rules list | show <id>"""
        sub = args[0] if args else "list"
        if sub == "list":
            for r_ in self._get("/rules")["data"]:
                self.p(f"{r_['id']:<16} enabled={r_['enabled']} "
                       f"matched={r_['metrics']['matched']}")
        elif sub == "show":
            self.p(json.dumps(self._get(f"/rules/{args[1]}"), indent=2))
        else:
            return 1

    def cmd_gateways(self, args):
        """List protocol gateways."""
        for g in self._get("/gateways")["data"]:
            self.p(f"{g['name']:<12} {g['type']} :{g['port']} "
                   f"clients={g['clients']}")

    def cmd_retainer(self, args):
        """retainer info | topics | clean [topic] (emqx_retainer_cli)"""
        sub = args[0] if args else "info"
        if sub == "info":
            for k, v in self._get("/mqtt/retainer").items():
                self.p(f"{k:<22} {v}")
        elif sub == "topics":
            for row in self._get("/mqtt/retainer/messages")["data"]:
                self.p(f"{row['topic']} qos{row['qos']} "
                       f"{row['payload_size']}B")
        elif sub == "clean":
            if len(args) > 1:
                from urllib.parse import quote

                self._delete(f"/mqtt/retainer/message/"
                             f"{quote(args[1], safe='')}")
                self.p(f"cleaned {args[1]}")
            else:
                from urllib.parse import quote

                n = 0
                while True:  # loop until the store is empty, not one page
                    rows = self._get(
                        "/mqtt/retainer/messages?limit=10000"
                    )["data"]
                    if not rows:
                        break
                    for row in rows:
                        self._delete(f"/mqtt/retainer/message/"
                                     f"{quote(row['topic'], safe='')}")
                        n += 1
                self.p(f"cleaned {n} retained messages")
        else:
            return 1

    def cmd_delayed(self, args):
        """delayed info | list | cancel <msgid>"""
        sub = args[0] if args else "info"
        if sub == "info":
            for k, v in self._get("/mqtt/delayed").items():
                self.p(f"{k:<22} {v}")
        elif sub == "list":
            for row in self._get("/mqtt/delayed/messages")["data"]:
                self.p(f"{row['msgid']} {row['topic']} "
                       f"in {row['delayed_remaining']}s")
        elif sub == "cancel":
            if len(args) < 2:
                self.p("usage: delayed cancel <msgid>")
                return 1
            self._delete(f"/mqtt/delayed/messages/{args[1]}")
            self.p(f"canceled {args[1]}")
        else:
            return 1

    def cmd_api_key(self, args):
        """api_key list | create <name> | enable|disable|delete <name>"""
        sub = args[0] if args else "list"
        if sub != "list" and len(args) < 2:
            self.p(self.usage["api_key"])
            return 1
        if sub == "list":
            for k in self._get("/api_key"):
                self.p(f"{k['name']:<16} key={k['api_key']} "
                       f"enabled={k['enable']}")
        elif sub == "create":
            rec = self._post("/api_key", {"name": args[1]})
            self.p(f"api_key: {rec['api_key']}")
            self.p(f"api_secret: {rec['api_secret']} (shown once)")
        elif sub in ("enable", "disable"):
            self._put(f"/api_key/{args[1]}",
                      {"enable": sub == "enable"})
            self.p(f"{args[1]} {sub}d")
        elif sub == "delete":
            self._delete(f"/api_key/{args[1]}")
            self.p(f"deleted {args[1]}")
        else:
            return 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="emqx_tpu-ctl")
    ap.add_argument("--url", default="http://127.0.0.1:18083")
    ap.add_argument("--token", default=None)
    ap.add_argument("command", nargs=argparse.REMAINDER)
    ns = ap.parse_args(argv)
    cli = Cli(remote=RemoteApi(ns.url, ns.token))
    return cli.run(ns.command)


if __name__ == "__main__":
    sys.exit(main())
