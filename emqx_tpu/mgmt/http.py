"""Minimal asyncio HTTP/1.1 JSON API server — the minirest analog.

Route patterns use `{name}` path params; handlers are sync or async
callables `handler(req) -> (status, body)` or `body` (200 implied).
Bearer-token auth is enforced for every route except those registered
with `public=True` (login, /status).  The route table doubles as the
source for the generated OpenAPI document (the reference generates
swagger from its config schemas; here the route registry + schema
hints fill the same role).
"""

from __future__ import annotations

import asyncio
import inspect
import json
import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlsplit

MAX_BODY = 8 * 1024 * 1024


class HttpError(Exception):
    def __init__(self, status: int, message: str = ""):
        super().__init__(message)
        self.status = status
        self.message = message or {400: "bad request", 401: "unauthorized",
                                   404: "not found"}.get(status, "error")


@dataclass
class Request:
    method: str
    path: str
    params: Dict[str, str]
    query: Dict[str, List[str]]
    headers: Dict[str, str]
    body: bytes
    # set by dispatch after auth: "dashboard" | "api_key" | None
    principal: Optional[str] = None

    def json(self) -> Any:
        if not self.body:
            return None
        try:
            return json.loads(self.body)
        except json.JSONDecodeError:
            raise HttpError(400, "invalid json body")

    def q(self, name: str, default: Optional[str] = None) -> Optional[str]:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def q_int(self, name: str, default: int) -> int:
        v = self.q(name)
        if v is None:
            return default
        try:
            return int(v)
        except ValueError:
            raise HttpError(400, f"bad integer parameter {name!r}")


@dataclass
class Route:
    method: str
    pattern: str
    handler: Callable
    public: bool = False
    doc: str = ""
    regex: Any = None

    def __post_init__(self):
        parts = []
        for seg in self.pattern.strip("/").split("/"):
            if seg.startswith("{") and seg.endswith("}"):
                parts.append(f"(?P<{seg[1:-1]}>[^/]+)")
            else:
                parts.append(re.escape(seg))
        self.regex = re.compile("^/" + "/".join(parts) + "$")


STATUS_TEXT = {
    200: "OK", 201: "Created", 204: "No Content", 302: "Found",
    400: "Bad Request",
    401: "Unauthorized", 404: "Not Found", 405: "Method Not Allowed",
    409: "Conflict", 500: "Internal Server Error", 503: "Service Unavailable",
}


class RawResponse:
    """Non-JSON handler result: raw bytes with an explicit content type
    (dashboard HTML pages, trace log downloads, redirects, ...)."""

    def __init__(self, body: bytes,
                 content_type: str = "text/html; charset=utf-8",
                 status: Optional[int] = None,
                 headers: Optional[Dict[str, str]] = None):
        self.body = body
        self.content_type = content_type
        self.status = status  # None = the dispatch status (200)
        self.headers = headers or {}


class HttpApi:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        auth: Optional[Callable[[str], bool]] = None,
        base: str = "/api/v5",
    ):
        self.host = host
        self.port = port
        self.auth = auth  # token -> bool; None = open API
        self.base = base.rstrip("/")
        self.routes: List[Route] = []
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()

    def route(self, method: str, pattern: str, handler: Callable,
              public: bool = False, doc: str = "") -> None:
        self.routes.append(Route(method.upper(), self.base + pattern, handler,
                                 public=public, doc=doc))

    # ------------------------------------------------------------ server

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    method, target, _ver = line.decode().split(None, 2)
                except ValueError:
                    return
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = h.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                length = int(headers.get("content-length", 0) or 0)
                if length > MAX_BODY:
                    await self._respond(writer, 400, {"message": "body too large"})
                    return
                body = await reader.readexactly(length) if length else b""
                status, payload = await self._dispatch(method, target, headers, body)
                keep = headers.get("connection", "keep-alive").lower() != "close"
                await self._respond(writer, status, payload, keep)
                if not keep:
                    return
        except asyncio.CancelledError:
            raise  # api server stop cancels handlers; finally closes
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception:
            try:
                await self._respond(writer, 500, {"message": "internal error"}, False)
            except Exception:
                pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _respond(self, writer, status: int, payload, keep: bool = True) -> None:
        ctype = "application/json"
        extra = ""
        if payload is None:
            body = b""
        elif isinstance(payload, RawResponse):
            body = payload.body
            ctype = payload.content_type
            if payload.status is not None:
                status = payload.status
            for k, v in payload.headers.items():
                extra += f"{k}: {v}\r\n"
        elif isinstance(payload, (bytes, bytearray)):
            body = bytes(payload)
        else:
            body = json.dumps(payload).encode()
        head = (
            f"HTTP/1.1 {status} {STATUS_TEXT.get(status, 'OK')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n{extra}"
            f"Connection: {'keep-alive' if keep else 'close'}\r\n\r\n"
        )
        writer.write(head.encode() + body)
        await writer.drain()

    # ---------------------------------------------------------- dispatch

    async def _dispatch(self, method: str, target: str, headers: Dict[str, str],
                        body: bytes) -> Tuple[int, Any]:
        parts = urlsplit(target)
        # match on the RAW path: a %2F inside a path param (retained
        # topic names) must not split into segments; params are
        # unquoted individually after the match
        path = parts.path
        query = parse_qs(parts.query)
        matched_path = False
        for route in self.routes:
            m = route.regex.match(path)
            if m is None:
                continue
            matched_path = True
            if route.method != method:
                continue
            principal = None
            if not route.public and self.auth is not None:
                tok = headers.get("authorization", "")
                if tok.lower().startswith("bearer "):
                    tok = tok[7:]
                elif tok.lower().startswith("basic "):
                    tok = tok[6:]
                principal = self.auth(tok)
                if not principal:
                    return 401, {"code": "BAD_TOKEN", "message": "unauthorized"}
            req = Request(method, path, {k: unquote(v) for k, v in m.groupdict().items()},
                          query, headers, body)
            # who authenticated (truthy auth result): "dashboard" for
            # admin tokens, "api_key" for machine credentials — some
            # routes are dashboard-only (key management)
            req.principal = principal if isinstance(principal, str) \
                else None
            try:
                result = route.handler(req)
                if inspect.isawaitable(result):
                    result = await result
            except HttpError as e:
                return e.status, {"code": "ERROR", "message": e.message}
            except Exception as e:
                return 500, {"code": "INTERNAL_ERROR", "message": f"{type(e).__name__}: {e}"}
            if isinstance(result, tuple) and len(result) == 2 and isinstance(result[0], int):
                return result
            return 200, result
        if matched_path:
            return 405, {"message": "method not allowed"}
        return 404, {"code": "NOT_FOUND", "message": f"no route {path}"}

    # ----------------------------------------------------------- openapi

    def openapi(self) -> dict:
        paths: Dict[str, dict] = {}
        for r in self.routes:
            entry = paths.setdefault(r.pattern, {})
            entry[r.method.lower()] = {
                "summary": r.doc or r.handler.__doc__ or "",
                "security": [] if r.public else [{"bearerAuth": []}],
                "responses": {"200": {"description": "OK"}},
                "parameters": [
                    {"name": n, "in": "path", "required": True,
                     "schema": {"type": "string"}}
                    for n in r.regex.groupindex
                ],
            }
        return {
            "openapi": "3.0.0",
            "info": {"title": "emqx_tpu management API", "version": "5.0.0"},
            "paths": paths,
            "components": {"securitySchemes": {"bearerAuth": {
                "type": "http", "scheme": "bearer"}}},
        }
