"""Admin tokens + admin user store — `emqx_dashboard_token`/`_admin` analog.

Tokens are HMAC-SHA256 signed (stdlib-only JWT equivalent) with expiry;
admin passwords are salted PBKDF2 (the reference salts+hashes admin
passwords in mnesia and issues signed tokens with a TTL).
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import time
from typing import Dict, Optional


def _b64(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def _unb64(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class TokenStore:
    def __init__(self, secret: Optional[bytes] = None, ttl_s: float = 3600.0):
        self.secret = secret or os.urandom(32)
        self.ttl_s = ttl_s
        self._admins: Dict[str, Dict[str, bytes]] = {}  # user -> {salt, hash}
        self._revoked: set = set()

    # -------------------------------------------------------------- admins

    @staticmethod
    def _hash(password: str, salt: bytes) -> bytes:
        return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10_000)

    def add_admin(self, username: str, password: str) -> None:
        salt = os.urandom(16)
        self._admins[username] = {"salt": salt, "hash": self._hash(password, salt)}

    def remove_admin(self, username: str) -> bool:
        return self._admins.pop(username, None) is not None

    def change_password(self, username: str, old: str, new: str) -> bool:
        if not self.check_password(username, old):
            return False
        self.add_admin(username, new)
        return True

    def check_password(self, username: str, password: str) -> bool:
        ent = self._admins.get(username)
        if ent is None:
            return False
        return hmac.compare_digest(ent["hash"], self._hash(password, ent["salt"]))

    # -------------------------------------------------------------- tokens

    def sign(self, username: str, now: Optional[float] = None) -> str:
        now = now if now is not None else time.time()
        claims = {"sub": username, "iat": int(now), "exp": int(now + self.ttl_s)}
        body = _b64(json.dumps(claims, separators=(",", ":")).encode())
        sig = _b64(hmac.new(self.secret, body.encode(), hashlib.sha256).digest())
        return f"{body}.{sig}"

    def login(self, username: str, password: str) -> Optional[str]:
        if not self.check_password(username, password):
            return None
        return self.sign(username)

    def verify(self, token: str, now: Optional[float] = None) -> Optional[str]:
        """Returns the username or None."""
        if token in self._revoked:
            return None
        try:
            body, sig = token.split(".")
            want = _b64(hmac.new(self.secret, body.encode(), hashlib.sha256).digest())
            if not hmac.compare_digest(want, sig):
                return None
            claims = json.loads(_unb64(body))
        except (ValueError, json.JSONDecodeError):
            return None
        now = now if now is not None else time.time()
        if claims.get("exp", 0) <= now:
            return None
        sub = claims.get("sub")
        if sub not in self._admins:
            return None
        return sub

    def revoke(self, token: str) -> None:
        self._revoked.add(token)


class ApiKeyStore:
    """Long-lived machine credentials — the `emqx_mgmt_api_app` /
    `emqx_mgmt_auth` analog: named API keys used over HTTP basic auth
    (api_key:api_secret).  The secret is generated once, stored only
    as salted PBKDF2, and never returned again."""

    def __init__(self):
        self._keys: Dict[str, Dict] = {}  # name -> record
        self._by_key: Dict[str, str] = {}  # api_key -> name

    def create(self, name: str, desc: str = "",
               expired_at: Optional[float] = None,
               enable: bool = True) -> Dict:
        if name in self._keys:
            raise ValueError(f"api key {name!r} exists")
        api_key = _b64(os.urandom(12))
        secret = _b64(os.urandom(24))
        salt = os.urandom(16)
        self._keys[name] = {
            "name": name,
            "api_key": api_key,
            "salt": salt,
            "hash": TokenStore._hash(secret, salt),
            "desc": desc,
            "enable": bool(enable),
            "expired_at": expired_at,
            "created_at": time.time(),
        }
        self._by_key[api_key] = name
        # the ONLY response that carries the secret
        return {"name": name, "api_key": api_key, "api_secret": secret,
                "desc": desc, "enable": bool(enable),
                "expired_at": expired_at}

    def verify(self, api_key: str, secret: str,
               now: Optional[float] = None) -> bool:
        name = self._by_key.get(api_key)
        if name is None:
            return False
        rec = self._keys[name]
        if not rec["enable"]:
            return False
        if rec["expired_at"] is not None and \
                (now if now is not None else time.time()) > rec["expired_at"]:
            return False
        return hmac.compare_digest(
            rec["hash"], TokenStore._hash(secret, rec["salt"])
        )

    def verify_basic(self, b64cred: str) -> bool:
        """`Basic base64(api_key:api_secret)` credentials."""
        try:
            key, _, secret = base64.b64decode(b64cred).decode().partition(":")
        except Exception:
            return False
        return self.verify(key, secret)

    @staticmethod
    def _public(rec: Dict) -> Dict:
        return {k: rec[k] for k in ("name", "api_key", "desc", "enable",
                                    "expired_at", "created_at")}

    def list(self):
        return [self._public(r) for r in self._keys.values()]

    def get(self, name: str) -> Optional[Dict]:
        rec = self._keys.get(name)
        return self._public(rec) if rec else None

    def update(self, name: str, **changes) -> Optional[Dict]:
        rec = self._keys.get(name)
        if rec is None:
            return None
        for k in ("desc", "enable", "expired_at"):
            if k in changes and changes[k] is not ...:
                rec[k] = changes[k]
        rec["enable"] = bool(rec["enable"])
        return self._public(rec)

    def delete(self, name: str) -> bool:
        rec = self._keys.pop(name, None)
        if rec is None:
            return False
        self._by_key.pop(rec["api_key"], None)
        return True
