"""Management plane: REST API, admin tokens, CLI (SURVEY.md §1.12).

`http.py` is the minirest analog (asyncio HTTP/1.1 + route table +
OpenAPI doc), `api.py` registers the per-noun handlers
(`emqx_mgmt_api_*` analogs), `token.py` issues HMAC admin tokens
(`emqx_dashboard_token` analog), `cli.py` is the `emqx ctl` command
registry usable in-process or against the REST API.
"""

from .api import ManagementApi
from .http import HttpApi, HttpError
from .token import TokenStore

__all__ = ["ManagementApi", "HttpApi", "HttpError", "TokenStore"]
