"""REST handlers per management noun — `emqx_mgmt_api_*` analogs.

Registered nouns mirror the reference's API surface: status, nodes,
clients (+kick, +subscriptions), subscriptions, topics/routes, publish
(+bulk), metrics, stats, alarms, banned, listeners, configs, trace,
slow_subscriptions, api-docs (OpenAPI from the route table + config
schema).  Pagination uses page/limit query params like the reference.
"""

from __future__ import annotations

import base64
import time
from typing import Any, Dict, List, Optional

from ..broker.broker import Broker
from ..utils.net import peer_host
from ..broker.message import Message
from .http import HttpApi, HttpError, Request
from .token import TokenStore


# node version string, parity-shaped like the reference release
# (`emqx_release.hrl`); one source for /status and /nodes/{name}
VERSION = "5.0.0-tpu.1"


def paginate(items: List[Any], req: Request) -> dict:
    limit = min(req.q_int("limit", 100), 10_000)
    page = max(req.q_int("page", 1), 1)
    count = len(items)
    start = (page - 1) * limit
    return {
        "data": items[start : start + limit],
        "meta": {"page": page, "limit": limit, "count": count},
    }


class ManagementApi:
    def __init__(
        self,
        broker: Broker,
        node: str = "emqx_tpu",
        tokens: Optional[TokenStore] = None,
        stats=None,
        alarms=None,
        traces=None,
        slow_subs=None,
        banned=None,
        config=None,
        cluster=None,
        listeners: Optional[list] = None,
        sys_heartbeat=None,
        plugins=None,
        psk=None,
        telemetry=None,
        monitor=None,
        rule_engine=None,
        authn=None,
        authz=None,
        gateways=None,
        bridges=None,
        olp=None,
        delayed=None,
        exporters=None,
        api_keys=None,
        ds=None,
    ):
        self.broker = broker
        self.node = node
        self.tokens = tokens
        self.stats = stats
        self.alarms = alarms
        self.traces = traces
        self.slow_subs = slow_subs
        self.banned = banned
        self.config = config
        self.cluster = cluster
        self.listeners = listeners or []
        self.sys_heartbeat = sys_heartbeat
        self.plugins = plugins
        self.psk = psk
        self.telemetry = telemetry
        self.monitor = monitor
        self.rule_engine = rule_engine
        self.authn = authn
        self.authz = authz
        self.gateways = gateways
        self.bridges = bridges
        self.olp = olp
        self.delayed = delayed
        self.exporters = exporters
        self.api_keys = api_keys
        self.ds = ds
        self.started_at = time.time()
        self.http: Optional[HttpApi] = None

    # ------------------------------------------------------------- install

    def install(self, http: HttpApi) -> None:
        self.http = http
        r = http.route
        r("POST", "/login", self.login, public=True, doc="Issue an admin token")
        r("POST", "/logout", self.logout, doc="Revoke the presented token")
        r("GET", "/status", self.status, public=True, doc="Node liveness")
        r("GET", "/nodes", self.nodes, doc="Cluster node list")
        r("GET", "/nodes/{name}", self.node_get, doc="One node's detail")
        r("GET", "/nodes/{name}/metrics", self.node_metrics,
          doc="One node's counters")
        r("GET", "/nodes/{name}/stats", self.node_stats,
          doc="One node's gauges")
        r("GET", "/clients", self.clients, doc="List connected clients")
        r("GET", "/clients/{clientid}", self.client_get, doc="One client")
        r("DELETE", "/clients/{clientid}", self.client_kick, doc="Kick a client")
        r("GET", "/clients/{clientid}/subscriptions", self.client_subs,
          doc="A client's subscriptions")
        r("GET", "/subscriptions", self.subscriptions, doc="All subscriptions")
        r("GET", "/topics", self.topics, doc="Route table")
        r("GET", "/routes", self.topics, doc="Route table (alias)")
        r("POST", "/publish", self.publish, doc="Publish one message")
        r("POST", "/publish/bulk", self.publish_bulk, doc="Publish a batch")
        r("GET", "/metrics", self.metrics, doc="Counter table")
        r("GET", "/stats", self.stats_get, doc="Gauge table")
        r("GET", "/engine", self.engine_get,
          doc="Match-engine telemetry summary (flight recorder plane)")
        r("GET", "/engine/flight", self.engine_flight,
          doc="Flight recorder: recent ticks + arbitration flips")
        r("GET", "/ds/stats", self.ds_stats,
          doc="Durable message log: per-shard occupancy + cursor lag")
        r("GET", "/alarms", self.alarms_get, doc="Active/history alarms")
        r("DELETE", "/alarms", self.alarms_clear, doc="Clear deactivated alarms")
        r("GET", "/banned", self.banned_get, doc="Ban table")
        r("POST", "/banned", self.banned_post, doc="Ban a client/ip/user")
        r("DELETE", "/banned/{kind}/{value}", self.banned_delete, doc="Unban")
        r("GET", "/listeners", self.listeners_get, doc="Listener status")
        r("GET", "/configs", self.configs_get, doc="Config dump")
        r("GET", "/configs/{path}", self.config_get_one, doc="One config key")
        r("PUT", "/configs/{path}", self.config_put_one, doc="Update config key")
        r("GET", "/trace", self.trace_list, doc="Trace sessions")
        r("POST", "/trace", self.trace_start, doc="Start a trace")
        r("DELETE", "/trace/{name}", self.trace_stop, doc="Stop a trace")
        r("GET", "/trace/{name}/log", self.trace_log, doc="Download trace log")
        r("GET", "/slow_subscriptions", self.slow_get, doc="Slowest subscribers")
        r("GET", "/plugins", self.plugins_get, doc="Installed plugins")
        r("POST", "/plugins/{name_vsn}/install", self.plugin_install,
          doc="Install a plugin package")
        r("PUT", "/plugins/{name_vsn}/{action}", self.plugin_action,
          doc="start|stop|enable|disable a plugin")
        r("DELETE", "/plugins/{name_vsn}", self.plugin_uninstall,
          doc="Uninstall a plugin")
        r("GET", "/psk", self.psk_get, doc="TLS-PSK identities")
        r("POST", "/psk", self.psk_post, doc="Add a PSK identity")
        r("DELETE", "/psk/{psk_id}", self.psk_delete, doc="Remove a PSK identity")
        r("GET", "/telemetry/status", self.telemetry_status, doc="Telemetry on/off")
        r("PUT", "/telemetry/status", self.telemetry_set, doc="Toggle telemetry")
        r("GET", "/telemetry/data", self.telemetry_data, doc="Telemetry report")
        r("GET", "/api-docs", self.api_docs, public=True, doc="OpenAPI document")
        r("GET", "/api_key", self.api_keys_list, doc="API keys")
        r("POST", "/api_key", self.api_key_create,
          doc="Create an API key (secret returned once)")
        r("GET", "/api_key/{name}", self.api_key_get, doc="One API key")
        r("PUT", "/api_key/{name}", self.api_key_update,
          doc="Enable/disable or describe an API key")
        r("DELETE", "/api_key/{name}", self.api_key_delete,
          doc="Remove an API key")
        r("POST", "/listeners/{listener_id}/{action}",
          self.listener_action, doc="start|stop|restart a listener")
        r("GET", "/prometheus", self.prometheus_get,
          doc="Prometheus push-exporter config + counters")
        r("PUT", "/prometheus", self.prometheus_put,
          doc="Update the Prometheus push exporter")
        r("GET", "/prometheus/stats", self.prometheus_stats,
          doc="Prometheus text exposition (pull mode)")
        r("GET", "/statsd", self.statsd_get, doc="StatsD exporter config")
        r("PUT", "/statsd", self.statsd_put, doc="Update the StatsD exporter")
        r("GET", "/mqtt/retainer", self.retainer_status,
          doc="Retainer status")
        r("PUT", "/mqtt/retainer", self.retainer_put,
          doc="Enable/disable the retainer, set limits")
        r("GET", "/mqtt/retainer/messages", self.retainer_messages,
          doc="Retained messages (paginated)")
        r("GET", "/mqtt/retainer/message/{topic}", self.retainer_get_one,
          doc="One retained message (topic url-encoded)")
        r("DELETE", "/mqtt/retainer/message/{topic}",
          self.retainer_delete_one, doc="Drop one retained message")
        r("GET", "/mqtt/delayed", self.delayed_status,
          doc="Delayed-publish status")
        r("PUT", "/mqtt/delayed", self.delayed_put,
          doc="Enable/disable delayed publish, set the cap")
        r("GET", "/mqtt/delayed/messages", self.delayed_messages,
          doc="Pending delayed messages")
        r("DELETE", "/mqtt/delayed/messages/{msgid}",
          self.delayed_delete, doc="Cancel one delayed message")
        r("GET", "/olp", self.olp_get, doc="Overload protection status")
        r("PUT", "/olp", self.olp_put, doc="Enable/disable OLP")
        r("GET", "/log", self.log_get, doc="Framework log level")
        r("PUT", "/log", self.log_put, doc="Set framework log level")
        r("GET", "/vm", self.vm_get, doc="Runtime/process stats")
        r("POST", "/authorization/cache/clean", self.authz_cache_clean,
          doc="Drain every connected client's authz verdict cache")
        r("GET", "/bridges", self.bridges_list,
          doc="Data bridges with resource status + stats")
        r("POST", "/bridges", self.bridge_create, doc="Create a bridge")
        r("GET", "/bridges/{name}", self.bridge_get, doc="One bridge")
        r("DELETE", "/bridges/{name}", self.bridge_delete,
          doc="Remove a bridge")
        r("PUT", "/bridges/{name}/{action}", self.bridge_action,
          doc="enable|disable|restart a bridge")
        r("PUT", "/gateways/{name}", self.gateway_update,
          doc="Enable/disable a gateway (stops/starts its listener)")
        r("GET", "/gateways", self.gateways_list,
          doc="Gateway instances + listen addresses")
        r("GET", "/gateways/{name}/clients", self.gateway_clients,
          doc="One gateway's connected clients")
        r("GET", "/authentication", self.authn_list,
          doc="Authenticator chain")
        r("GET", "/authentication/{name}/users", self.authn_users,
          doc="Built-in database users")
        r("POST", "/authentication/{name}/users", self.authn_user_add,
          doc="Add a user")
        r("DELETE", "/authentication/{name}/users/{user_id}",
          self.authn_user_del, doc="Delete a user")
        r("GET", "/authorization/sources", self.authz_list,
          doc="ACL source chain")
        r("POST", "/authorization/sources/built_in_database/rules",
          self.authz_rule_add, doc="Add a built-in ACL rule")
        r("POST", "/rule_test", self.rule_test, doc="Test a rule SQL "
          "against a synthetic event (no side effects)")
        r("GET", "/rules", self.rules_list, doc="Rule list with metrics")
        r("POST", "/rules", self.rule_create, doc="Create a rule")
        r("GET", "/rules/{rule_id}", self.rule_get, doc="One rule")
        r("PUT", "/rules/{rule_id}", self.rule_update,
          doc="Enable/disable or replace a rule")
        r("DELETE", "/rules/{rule_id}", self.rule_delete, doc="Drop a rule")
        r("GET", "/monitor", self.monitor_get,
          doc="Dashboard time series (per-interval deltas)")
        r("GET", "/monitor_current", self.monitor_current,
          doc="Instantaneous levels + last-interval rates")
        r("GET", "/dashboard", self.dashboard_page, public=True,
          doc="Dashboard frontend (redirects to the overview page)")
        r("GET", "/dashboard/{page}", self.dashboard_page, public=True,
          doc="Dashboard frontend pages (overview/clients/subscriptions/"
              "topics/retained/listeners/metrics)")


    # -------------------------------------------------------------- plugins

    def _need(self, attr: str):
        obj = getattr(self, attr)
        if obj is None:
            raise HttpError(404, f"{attr} subsystem not configured")
        return obj

    def plugins_get(self, req: Request):
        return self._need("plugins").list()

    def plugin_install(self, req: Request):
        from ..plugins import PluginError

        try:
            st = self._need("plugins").ensure_installed(req.params["name_vsn"])
        except PluginError as e:
            raise HttpError(400, str(e))
        return {"name_vsn": st.name_vsn, **st.manifest}

    def plugin_action(self, req: Request):
        from ..plugins import PluginError

        pm = self._need("plugins")
        nv = req.params["name_vsn"]
        action = req.params["action"]
        fn = {"start": pm.ensure_started, "stop": pm.ensure_stopped,
              "enable": pm.ensure_enabled, "disable": pm.ensure_disabled}.get(action)
        if fn is None:
            raise HttpError(400, f"unknown action {action!r}")
        try:
            fn(nv)
        except PluginError as e:
            raise HttpError(400, str(e))
        return 204, None

    def plugin_uninstall(self, req: Request):
        from ..plugins import PluginError

        try:
            self._need("plugins").ensure_uninstalled(req.params["name_vsn"])
        except PluginError as e:
            raise HttpError(400, str(e))
        return 204, None

    # ------------------------------------------------------------------ psk

    def psk_get(self, req: Request):
        return {"ids": self._need("psk").all_ids()}

    def psk_post(self, req: Request):
        body = req.json() or {}
        psk_id, secret = body.get("psk_id"), body.get("secret")
        if not psk_id or secret is None:
            raise HttpError(400, "psk_id and secret required")
        self._need("psk").insert(psk_id, secret.encode())
        return 204, None

    def psk_delete(self, req: Request):
        if not self._need("psk").delete(req.params["psk_id"]):
            raise HttpError(404, "unknown psk_id")
        return 204, None

    # ------------------------------------------------------------ telemetry

    def telemetry_status(self, req: Request):
        return {"enable": self._need("telemetry").enable}

    def telemetry_set(self, req: Request):
        body = req.json() or {}
        self._need("telemetry").set_enabled(bool(body.get("enable", True)))
        return 204, None

    def telemetry_data(self, req: Request):
        return self._need("telemetry").get_telemetry()

    def auth_check(self, token: str):
        """Returns a truthy principal kind ("dashboard"/"api_key") or
        False — the HTTP layer records it on the request so key
        management can stay dashboard-only."""
        if self.tokens is None:
            return "dashboard"
        if self.tokens.verify(token) is not None:
            return "dashboard"
        # basic-auth machine credentials (api_key:api_secret) — the
        # emqx_mgmt_auth application credentials
        if self.api_keys is not None and \
                self.api_keys.verify_basic(token):
            return "api_key"
        return False

    # ---------------------------------------------------------------- auth

    def login(self, req: Request):
        if self.tokens is None:
            raise HttpError(404, "token auth disabled")
        body = req.json() or {}
        tok = self.tokens.login(body.get("username", ""), body.get("password", ""))
        if tok is None:
            return 401, {"code": "BAD_USERNAME_OR_PWD", "message": "bad credentials"}
        return {"token": tok, "license": {"edition": "opensource"}, "version": "5.0.0"}

    def logout(self, req: Request):
        if self.tokens is not None:
            tok = req.headers.get("authorization", "")
            if tok.lower().startswith("bearer "):
                self.tokens.revoke(tok[7:])
        return 204, None

    # ---------------------------------------------------------------- node

    def status(self, req: Request):
        """Unauthenticated liveness + READINESS (the docker-compose FVT
        health-check analog: the reference waits on container health
        before driving clients).  `ready` is true once this node serves
        traffic (boot — including engine warm-up — finished before the
        HTTP listener opened) AND every CONFIGURED cluster peer link is
        up (pre-seeded down at boot).  Cluster-less nodes — and listen-
        only nodes with no configured peers, which cannot know who will
        dial in — are ready as soon as they serve; gate mesh formation
        by polling every member's /status, not just a hub's."""
        mesh = self.cluster.status() if self.cluster is not None else {}
        return {
            "node": self.node,
            "status": "running",
            "version": VERSION,
            "uptime": int(time.time() - self.started_at),
            "ready": all(st == "up" for st in mesh.values()),
            "mesh": mesh,
        }

    def nodes(self, req: Request):
        me = {
            "node": self.node,
            "node_status": "running",
            "connections": self.broker.cm.connection_count,
            "subscriptions": self.broker.subscription_count,
            "routes": self.broker.route_count,
        }
        out = [me]
        if self.cluster is not None:
            for peer, st in self.cluster.status().items():
                out.append({
                    "node": peer,
                    # degraded = heartbeats missing but below the down
                    # limit: the peer is still serving
                    "node_status": (
                        "running" if st in ("up", "degraded") else "stopped"
                    ),
                    "routes": len(self.cluster.remote.filters_of(peer)),
                })
        return out

    # -------------------------------------------------------------- clients

    def _client_info(self, ch) -> dict:
        ci = getattr(ch, "clientinfo", None)
        session = getattr(ch, "session", None)
        out = {
            "clientid": ch.clientid,
            "node": self.node,
            "connected": True,
            "username": getattr(ci, "username", None) if ci else None,
            "peername": getattr(ci, "peerhost", None) if ci else None,
            "proto_ver": getattr(ch, "proto_ver", None),
            "connected_at": getattr(ch, "connected_at", None),
        }
        if session is not None:
            out.update(session.info())
        return out

    def clients(self, req: Request):
        """Query params mirror `emqx_mgmt_api_clients`: like_clientid
        (fuzzy), username, ip_address, proto_ver, conn_state."""
        like = req.q("like_clientid")
        username = req.q("username")
        ip = req.q("ip_address")
        proto = req.q("proto_ver")
        state = req.q("conn_state")  # connected | disconnected
        rows = []
        if state != "disconnected":
            for cid, ch in self.broker.cm.channels.items():
                if like and like not in cid:
                    continue
                ci = getattr(ch, "clientinfo", None)
                if username and getattr(ci, "username", None) != username:
                    continue
                if ip and peer_host(
                    str(getattr(ci, "peerhost", "") or "")
                ) != ip:
                    continue
                if proto and str(getattr(ci, "proto_ver", "")) != proto:
                    continue
                rows.append(self._client_info(ch))
        if state != "connected":
            for cid, (session, _exp) in self.broker.cm.pending.items():
                if like and like not in cid:
                    continue
                if username and getattr(session, "username",
                                        None) != username:
                    continue
                if ip or proto:
                    # connection-scoped attributes don't exist for an
                    # offline session: these filters exclude them
                    continue
                row = {"clientid": cid, "node": self.node,
                       "connected": False}
                row.update(session.info())
                rows.append(row)
        return paginate(rows, req)

    def _require_local_node(self, req: Request) -> None:
        name = req.params["name"]
        if name != self.node:
            raise HttpError(
                404, f"node {name!r} is not this node; query it directly"
            )

    def node_get(self, req: Request):
        """GET /nodes/{name} (`emqx_mgmt_api_nodes` detail)."""
        self._require_local_node(req)
        return {
            "node": self.node,
            "node_status": "running",
            "version": VERSION,  # same source as /status
            "uptime": int(time.time() - self.started_at),
            "connections": self.broker.cm.connection_count,
            "subscriptions": self.broker.subscription_count,
            "routes": self.broker.route_count,
            "retained": self.broker.retainer.count,
            "listeners": [self._listener_id(l) for l in self.listeners],
        }

    def node_metrics(self, req: Request):
        self._require_local_node(req)
        return self.broker.metrics.all()

    def node_stats(self, req: Request):
        self._require_local_node(req)
        return self.stats_get(req)

    def _find_client(self, clientid: str):
        ch = self.broker.cm.lookup(clientid)
        if ch is not None:
            return self._client_info(ch)
        ent = self.broker.cm.pending.get(clientid)
        if ent is not None:
            row = {"clientid": clientid, "node": self.node, "connected": False}
            row.update(ent[0].info())
            return row
        return None

    def client_get(self, req: Request):
        row = self._find_client(req.params["clientid"])
        if row is None:
            raise HttpError(404, "client not found")
        return row

    def client_kick(self, req: Request):
        if not self.broker.cm.kick_session(req.params["clientid"]):
            raise HttpError(404, "client not found")
        return 204, None

    def client_subs(self, req: Request):
        s = self.broker.cm.lookup_session(req.params["clientid"])
        if s is None:
            raise HttpError(404, "client not found")
        return [
            {"topic": f, "qos": o.qos, "no_local": o.no_local,
             "rap": o.retain_as_published, "rh": o.retain_handling}
            for f, o in s.subscriptions.items()
        ]

    def subscriptions(self, req: Request):
        """Query params mirror `emqx_mgmt_api_subscriptions`: clientid,
        topic (exact filter), qos, share (group name), match_topic
        (filters that would match a given topic name)."""
        from ..broker import topic as topiclib

        want_cid = req.q("clientid")
        want_topic = req.q("topic")
        want_qos = req.q("qos")
        want_share = req.q("share")
        match_topic = req.q("match_topic")

        def keep(cid, f, o):
            if want_cid and cid != want_cid:
                return False
            if want_topic and f != want_topic:
                return False
            if want_qos is not None and want_qos != "" and \
                    str(o.qos) != want_qos:
                return False
            group, real = topiclib.parse_share(f)
            if want_share and group != want_share:
                return False
            if match_topic and not topiclib.match(match_topic, real):
                return False
            return True

        rows = []
        seen = set()
        for cid, ch in self.broker.cm.channels.items():
            s = getattr(ch, "session", None)
            if s is None or cid in seen:
                continue
            seen.add(cid)
            for f, o in s.subscriptions.items():
                if keep(cid, f, o):
                    rows.append({"clientid": cid, "topic": f,
                                 "qos": o.qos, "node": self.node})
        for cid, (s, _exp) in self.broker.cm.pending.items():
            for f, o in s.subscriptions.items():
                if keep(cid, f, o):
                    rows.append({"clientid": cid, "topic": f,
                                 "qos": o.qos, "node": self.node})
        return paginate(rows, req)

    # --------------------------------------------------------------- routes

    def topics(self, req: Request):
        rows = [
            {"topic": route.filt, "node": self.node}
            for route in self.broker._routes.values()
        ]
        if self.cluster is not None:
            for filt, nodes in self.cluster.remote.topics().items():
                for n in nodes:
                    rows.append({"topic": filt, "node": n})
        return paginate(rows, req)

    # -------------------------------------------------------------- publish

    def _decode_publish(self, body: dict) -> Message:
        if not body or "topic" not in body:
            raise HttpError(400, "missing topic")
        payload = body.get("payload", "")
        if body.get("payload_encoding") == "base64":
            try:
                payload = base64.b64decode(payload)
            except Exception:
                raise HttpError(400, "bad base64 payload")
        else:
            payload = str(payload).encode()
        return Message(
            topic=body["topic"],
            payload=payload,
            qos=int(body.get("qos", 0)),
            retain=bool(body.get("retain", False)),
            from_client=body.get("clientid", "http_api"),
        )

    def publish(self, req: Request):
        msg = self._decode_publish(req.json())
        n = self.broker.publish(msg)
        return {"id": msg.mid.hex(), "delivered": n}

    def publish_bulk(self, req: Request):
        body = req.json()
        if not isinstance(body, list):
            raise HttpError(400, "expected a list")
        msgs = [self._decode_publish(b) for b in body]
        ns = self.broker.publish_many(msgs)
        return [{"id": m.mid.hex(), "delivered": n} for m, n in zip(msgs, ns)]

    # ------------------------------------------------------- metrics/stats

    def metrics(self, req: Request):
        if hasattr(self.broker, "sync_engine_metrics"):
            self.broker.sync_engine_metrics()
        return self.broker.metrics.all()

    def engine_get(self, req: Request):
        from ..observe.flight import engine_summary

        return engine_summary(self.broker.engine)

    def engine_flight(self, req: Request):
        fl = getattr(self.broker.engine, "flight", None)
        if fl is None:
            raise HttpError(404, "flight recorder disabled "
                                 "(engine.flight_ring=0)")
        n = int(req.q("n", "32"))
        return {"recent": fl.recent(n), "flips": fl.flips()}

    def ds_stats(self, req: Request):
        if self.ds is None:
            raise HttpError(404, "durable message log disabled "
                                 "(ds.enable=false)")
        return self.ds.stats()

    def stats_get(self, req: Request):
        if self.stats is None:
            raise HttpError(404, "stats disabled")
        return self.stats.collect()

    def alarms_get(self, req: Request):
        if self.alarms is None:
            raise HttpError(404, "alarms disabled")
        activated = req.q("activated", "true") == "true"
        if activated:
            return [a.to_dict() for a in self.alarms.active.values()]
        return [a.to_dict() for a in self.alarms.history]

    def alarms_clear(self, req: Request):
        if self.alarms is None:
            raise HttpError(404, "alarms disabled")
        self.alarms.delete_all_deactivated()
        return 204, None

    # --------------------------------------------------------------- banned

    def banned_get(self, req: Request):
        if self.banned is None:
            raise HttpError(404, "banned disabled")
        return paginate(
            [
                {"as": e.kind, "who": e.value, "reason": e.reason,
                 "by": e.by,
                 "until": None if e.until == float("inf") else e.until}
                for e in self.banned.all()
            ],
            req,
        )

    def banned_post(self, req: Request):
        if self.banned is None:
            raise HttpError(404, "banned disabled")
        b = req.json() or {}
        kind, who = b.get("as"), b.get("who")
        if kind not in ("clientid", "username", "peerhost") or not who:
            raise HttpError(400, "need as=clientid|username|peerhost and who")
        self.banned.create(kind, who, reason=b.get("reason", ""),
                           by=b.get("by", "mgmt_api"),
                           duration=b.get("seconds"))
        return 201, {"as": kind, "who": who}

    def banned_delete(self, req: Request):
        if self.banned is None:
            raise HttpError(404, "banned disabled")
        if not self.banned.delete(req.params["kind"], req.params["value"]):
            raise HttpError(404, "not banned")
        return 204, None

    # ------------------------------------------------------------ listeners

    @staticmethod
    def _listener_id(l) -> str:
        """One id scheme for listing AND addressing (type:port, the
        reference's listener id shape)."""
        is_ws = type(l).__name__.startswith("Ws")
        is_tls = getattr(l, "tls", None) is not None
        kind = ("wss" if is_ws and is_tls else "ws" if is_ws
                else "ssl" if is_tls else "tcp")
        return f"{kind}:{getattr(l, 'port', '?')}"

    def listeners_get(self, req: Request):
        return [
            {
                "id": self._listener_id(l),
                "type": type(l).__name__,
                "bind": f"{getattr(l, 'host', '?')}:{getattr(l, 'port', '?')}",
                "running": getattr(l, "_server", None) is not None,
                "current_connections": len(getattr(l, "_conns", ())),
                "max_connections": getattr(l, "max_connections", 0),
            }
            for l in self.listeners
        ]

    # -------------------------------------------------------------- configs

    def configs_get(self, req: Request):
        if self.config is None:
            raise HttpError(404, "config disabled")
        return self.config.dump()

    def config_get_one(self, req: Request):
        if self.config is None:
            raise HttpError(404, "config disabled")
        path = req.params["path"]
        value = self.config.get(path, zone=req.q("zone"))
        if value is None:
            raise HttpError(404, f"no config {path}")
        return {path: value}

    def config_put_one(self, req: Request):
        if self.config is None:
            raise HttpError(404, "config disabled")
        body = req.json() or {}
        if "value" not in body:
            raise HttpError(400, "need {\"value\": ...}")
        path = req.params["path"]
        try:
            value = self.config.put(path, body["value"])
        except Exception as e:
            raise HttpError(400, str(e))
        return {path: value}

    # ---------------------------------------------------------------- trace

    def trace_list(self, req: Request):
        if self.traces is None:
            raise HttpError(404, "trace disabled")
        return [
            {"name": t.name, "type": t.kind, t.kind: t.value,
             "start_at": t.start_at, "end_at": t.end_at}
            for t in self.traces.list_traces()
        ]

    def trace_start(self, req: Request):
        if self.traces is None:
            raise HttpError(404, "trace disabled")
        b = req.json() or {}
        try:
            spec = self.traces.start_trace(
                b.get("name", ""), b.get("type", ""),
                b.get(b.get("type", ""), b.get("value", "")),
                end_at=b.get("end_at"),
            )
        except ValueError as e:
            raise HttpError(400, str(e))
        return 201, {"name": spec.name}

    def trace_stop(self, req: Request):
        if self.traces is None:
            raise HttpError(404, "trace disabled")
        if not self.traces.stop_trace(req.params["name"]):
            raise HttpError(404, "no such trace")
        return 204, None

    def trace_log(self, req: Request):
        if self.traces is None:
            raise HttpError(404, "trace disabled")
        import os

        name = req.params["name"]
        path = os.path.join(self.traces.dir, f"trace_{name}.log")
        if not os.path.exists(path):
            raise HttpError(404, "no such trace log")
        with open(path, "rb") as f:
            return 200, f.read()

    # ------------------------------------------------------------ slow subs

    def slow_get(self, req: Request):
        if self.slow_subs is None:
            raise HttpError(404, "slow_subs disabled")
        return self.slow_subs.top()

    # -------------------------------------------------------------- gateways

    @staticmethod
    def _gateway_cm(gw):
        ctx = getattr(gw, "ctx", None)
        return getattr(ctx, "cm", None)

    # ------------------------------------------------------------ api_key

    @staticmethod
    def _dashboard_only(req: Request) -> None:
        """Machine credentials must not manage credentials: a leaked
        expiring key could otherwise mint itself a permanent one (the
        reference's emqx_mgmt_auth forbids this the same way)."""
        if req.principal == "api_key":
            raise HttpError(
                403, "api_key credentials cannot manage api keys"
            )

    @staticmethod
    def _check_expired_at(body: Dict):
        v = body.get("expired_at")
        if v is not None and not isinstance(v, (int, float)):
            raise HttpError(
                400, "expired_at must be a unix timestamp or null"
            )
        return v

    def api_keys_list(self, req: Request):
        self._dashboard_only(req)
        return self._need("api_keys").list()

    def api_key_create(self, req: Request):
        self._dashboard_only(req)
        body = req.json() or {}
        if not body.get("name") or not isinstance(body["name"], str):
            raise HttpError(400, "name required (string)")
        try:
            return 201, self._need("api_keys").create(
                body["name"],
                desc=str(body.get("desc", "")),
                expired_at=self._check_expired_at(body),
                enable=bool(body.get("enable", True)),
            )
        except ValueError as e:
            raise HttpError(400, str(e))

    def api_key_get(self, req: Request):
        self._dashboard_only(req)
        rec = self._need("api_keys").get(req.params["name"])
        if rec is None:
            raise HttpError(404, "no such api key")
        return rec

    def api_key_update(self, req: Request):
        self._dashboard_only(req)
        body = req.json() or {}
        if "expired_at" in body:
            self._check_expired_at(body)
        rec = self._need("api_keys").update(
            req.params["name"],
            desc=body.get("desc", ...),
            enable=body.get("enable", ...),
            expired_at=body.get("expired_at", ...),
        )
        if rec is None:
            raise HttpError(404, "no such api key")
        return rec

    def api_key_delete(self, req: Request):
        self._dashboard_only(req)
        if not self._need("api_keys").delete(req.params["name"]):
            raise HttpError(404, "no such api key")
        return 204, None

    # ---------------------------------------------------------- listeners

    async def listener_action(self, req: Request):
        """start|stop|restart one listener
        (`emqx_mgmt_api_listeners.erl` manage_listeners)."""
        lid = req.params["listener_id"]
        action = req.params["action"]
        if action not in ("start", "stop", "restart"):
            raise HttpError(400, f"unknown action {action!r}")
        target = None
        for l in self.listeners:
            if self._listener_id(l) == lid:
                target = l
                break
        if target is None:
            raise HttpError(404, f"no such listener {lid!r}")
        if action in ("stop", "restart") and \
                getattr(target, "_server", None) is not None:
            await target.stop()
        if action in ("start", "restart") and \
                getattr(target, "_server", None) is None:
            await target.start()
        return {
            "id": self._listener_id(target),
            "running": getattr(target, "_server", None) is not None,
        }

    # ----------------------------------------------- exporters / retainer

    def prometheus_get(self, req: Request):
        return self._need("exporters").prometheus_status()

    def prometheus_put(self, req: Request):
        try:
            return self._need("exporters").update_prometheus(
                req.json() or {}
            )
        except ValueError as e:
            raise HttpError(400, str(e))

    def prometheus_stats(self, req: Request):
        from .http import RawResponse

        return 200, RawResponse(
            self._need("exporters").render().encode(),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )

    def statsd_get(self, req: Request):
        return self._need("exporters").statsd_status()

    def statsd_put(self, req: Request):
        try:
            return self._need("exporters").update_statsd(req.json() or {})
        except ValueError as e:
            raise HttpError(400, str(e))

    def _retainer(self):
        return self.broker.retainer

    def retainer_status(self, req: Request):
        rt = self._retainer()
        return {
            "enable": rt.enable,
            "count": rt.count,
            "max_retained_messages": rt.max_retained,
            "max_payload_size": rt.max_payload,
            "backend": "disc" if rt.store is not None else "ram",
        }

    def retainer_put(self, req: Request):
        rt = self._retainer()
        body = req.json() or {}
        if "enable" in body:
            rt.enable = bool(body["enable"])
        for key, attr in (("max_retained_messages", "max_retained"),
                          ("max_payload_size", "max_payload")):
            if key in body:
                try:
                    val = int(body[key])
                except (TypeError, ValueError):
                    raise HttpError(400, f"{key} must be an int")
                if val < 0:
                    # 0 means UNLIMITED here; silently clamping a
                    # negative would invert the caller's intent
                    raise HttpError(400, f"{key} must be >= 0")
                setattr(rt, attr, val)
        return self.retainer_status(req)

    def retainer_messages(self, req: Request):
        rows = [
            {
                "topic": m.topic,
                "qos": m.qos,
                "payload_size": len(m.payload),
                "from_clientid": m.from_client,
                "publish_at": m.timestamp,
            }
            for m in self._retainer().walk_all()
        ]
        rows.sort(key=lambda r_: r_["topic"])
        return paginate(rows, req)

    def retainer_get_one(self, req: Request):
        m = self._retainer().get(req.params["topic"])
        if m is None:
            raise HttpError(404, "no retained message on that topic")
        return {
            "topic": m.topic,
            "qos": m.qos,
            "payload": base64.b64encode(m.payload).decode(),
            "from_clientid": m.from_client,
            "publish_at": m.timestamp,
        }

    def retainer_delete_one(self, req: Request):
        if not self._retainer().delete(req.params["topic"]):
            raise HttpError(404, "no retained message on that topic")
        return 204, None

    # ------------------------------------------------------------ delayed

    def delayed_status(self, req: Request):
        return self._need("delayed").status()

    def delayed_put(self, req: Request):
        d = self._need("delayed")
        body = req.json() or {}
        if "enable" in body:
            d.enable = bool(body["enable"])
        if "max_delayed_messages" in body:
            try:
                d.max_delayed_messages = max(
                    0, int(body["max_delayed_messages"])
                )
            except (TypeError, ValueError):
                raise HttpError(400, "max_delayed_messages must be int")
        return d.status()

    def delayed_messages(self, req: Request):
        return paginate(self._need("delayed").list(), req)

    def delayed_delete(self, req: Request):
        if not self._need("delayed").delete(req.params["msgid"]):
            raise HttpError(404, "no such delayed message")
        return 204, None

    # -------------------------------------------------- olp / log / vm

    def olp_get(self, req: Request):
        """`emqx_ctl olp status` analog (emqx_olp.erl)."""
        return self._need("olp").status()

    def olp_put(self, req: Request):
        olp = self._need("olp")
        body = req.json() or {}
        if "enable" in body:
            olp.enabled = bool(body["enable"])
        return olp.status()

    _LOG_LEVELS = ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL")

    def log_get(self, req: Request):
        import logging

        lvl = logging.getLogger("emqx_tpu").getEffectiveLevel()
        return {"level": logging.getLevelName(lvl)}

    def log_put(self, req: Request):
        """`emqx_ctl log set-level` analog: runtime level for the whole
        framework logger tree."""
        import logging

        level = str((req.json() or {}).get("level", "")).upper()
        if level not in self._LOG_LEVELS:
            raise HttpError(
                400, f"level must be one of {', '.join(self._LOG_LEVELS)}"
            )
        logging.getLogger("emqx_tpu").setLevel(level)
        return {"level": level}

    def vm_get(self, req: Request):
        """`emqx_ctl vm` analog: process/runtime gauges."""
        import gc
        import os
        import resource
        import sys
        import threading

        ru = resource.getrusage(resource.RUSAGE_SELF)
        try:
            fds = len(os.listdir("/proc/self/fd"))
        except OSError:
            fds = None
        return {
            "python": sys.version.split()[0],
            "pid": os.getpid(),
            "max_rss_kb": ru.ru_maxrss,
            "cpu_user_s": ru.ru_utime,
            "cpu_system_s": ru.ru_stime,
            "threads": threading.active_count(),
            "gc_counts": list(gc.get_count()),
            "open_fds": fds,
        }

    def authz_cache_clean(self, req: Request):
        """`emqx_ctl authz cache-clean all` analog: drain the per-channel
        verdict caches so source changes take effect immediately."""
        n = 0
        for ch in list(self.broker.cm.channels.values()):
            cache = getattr(ch, "authz_cache", None)
            if cache is not None:
                cache.drain()
                n += 1
        return {"cleaned": n}

    # ------------------------------------------------------------ bridges

    def bridges_list(self, req: Request):
        return self._need("bridges").list()

    def bridge_get(self, req: Request):
        info = self._need("bridges").describe(req.params["name"])
        if info is None:
            raise HttpError(404, "no such bridge")
        return info

    async def bridge_create(self, req: Request):
        mgr = self._need("bridges")
        body = req.json() or {}
        if not body.get("name"):
            raise HttpError(400, "bridge name required")
        try:
            await mgr.create(body)
        except ValueError as e:
            raise HttpError(400, str(e))
        return 201, mgr.describe(body["name"])

    async def bridge_delete(self, req: Request):
        if not await self._need("bridges").remove(req.params["name"]):
            raise HttpError(404, "no such bridge")
        return 204, None

    async def bridge_action(self, req: Request):
        mgr = self._need("bridges")
        name = req.params["name"]
        action = req.params["action"]
        if action not in ("enable", "disable", "restart"):
            raise HttpError(400, f"unknown action {action!r}")
        ok = await getattr(mgr, action)(name)
        if not ok:
            raise HttpError(404, "no such bridge")
        return mgr.describe(name)

    @staticmethod
    def _gateway_running(gw) -> bool:
        """Covers every gateway transport shape: UDP (mqttsn/coap/
        lwm2m `transport`), TCP (stomp `_server`), dual-socket exproto
        (`_device_srv`)."""
        return any(
            getattr(gw, attr, None) is not None
            for attr in ("transport", "_server", "_device_srv")
        )

    async def gateway_update(self, req: Request):
        """PUT /gateways/{name} {enable} — stop/start the gateway's
        listener (`emqx_gateway_api` update analog)."""
        reg = self._need("gateways")
        gw = reg.lookup(req.params["name"])
        if gw is None:
            raise HttpError(404, "no such gateway")
        body = req.json() or {}
        if "enable" in body:
            want = bool(body["enable"])
            running = self._gateway_running(gw)
            if want and not running and hasattr(gw, "start"):
                await gw.start()
            elif not want and running and hasattr(gw, "stop"):
                await gw.stop()
        return {
            "name": req.params["name"],
            "enable": self._gateway_running(gw),
        }

    def gateways_list(self, req: Request):
        reg = self._need("gateways")
        out = []
        for name in reg.list():
            gw = reg.lookup(name)
            cm = self._gateway_cm(gw)
            out.append(
                {
                    "name": name,
                    "type": type(gw).__name__,
                    "host": getattr(gw, "host", None),
                    "port": getattr(gw, "port", None),
                    "clients": len(cm.channels) if cm is not None else None,
                }
            )
        return {"data": out}

    def gateway_clients(self, req: Request):
        reg = self._need("gateways")
        gw = reg.lookup(req.params["name"])
        if gw is None:
            raise HttpError(404, "no such gateway")
        cm = self._gateway_cm(gw)
        if cm is None:
            return paginate([], req)
        rows = []
        for cid, ch in sorted(cm.channels.items()):
            ci = getattr(ch, "clientinfo", None)
            rows.append(
                {
                    "clientid": cid,
                    "username": getattr(ci, "username", None),
                    "peerhost": getattr(ci, "peerhost", None),
                    "subscriptions": len(
                        getattr(getattr(ch, "session", None),
                                "subscriptions", {}) or {}
                    ),
                }
            )
        return paginate(rows, req)

    # ----------------------------------------------------------- authn/authz

    def authn_list(self, req: Request):
        chain = self._need("authn")
        return {
            "allow_anonymous": chain.allow_anonymous,
            "authenticators": [
                {"name": a.name, "backend": type(a).__name__}
                for a in chain.authenticators
            ],
        }

    def _builtin_authenticator(self, name: str):
        chain = self._need("authn")
        for a in chain.authenticators:
            if a.name == name:
                if not hasattr(a, "users"):
                    raise HttpError(400, f"{name!r} has no user store")
                return a
        raise HttpError(404, f"no authenticator {name!r}")

    def authn_users(self, req: Request):
        a = self._builtin_authenticator(req.params["name"])
        return paginate(
            [
                {"user_id": uid, "is_superuser": rec.is_superuser}
                for uid, rec in sorted(a.users.items())
            ],
            req,
        )

    _HASH_ALGOS = ("pbkdf2_sha256", "sha256", "sha512", "plain", "bcrypt")

    def authn_user_add(self, req: Request):
        a = self._builtin_authenticator(req.params["name"])
        body = req.json() or {}
        uid, pw = body.get("user_id"), body.get("password")
        if not isinstance(uid, str) or not uid or not isinstance(pw, str) or not pw:
            raise HttpError(400, "user_id and password (strings) required")
        if uid in a.users:
            raise HttpError(400, "user exists")
        algo = body.get("algorithm", "pbkdf2_sha256")
        if algo not in self._HASH_ALGOS:
            raise HttpError(
                400, f"unsupported algorithm {algo!r}; "
                     f"one of {list(self._HASH_ALGOS)}"
            )
        a.add_user(
            uid,
            pw,
            is_superuser=bool(body.get("is_superuser")),
            algorithm=algo,
        )
        return {"user_id": uid}

    def authn_user_del(self, req: Request):
        a = self._builtin_authenticator(req.params["name"])
        if not a.delete_user(req.params["user_id"]):
            raise HttpError(404, "no such user")
        return None

    def authz_list(self, req: Request):
        chain = self._need("authz")
        return {
            "no_match": chain.default,
            "sources": [
                {"type": s.name, "enabled": s.enabled} for s in chain.sources
            ],
        }

    def authz_rule_add(self, req: Request):
        from ..authz import BuiltInSource, Rule

        chain = self._need("authz")
        src = next(
            (s for s in chain.sources if isinstance(s, BuiltInSource)), None
        )
        if src is None:
            raise HttpError(404, "no built_in_database authz source")
        body = req.json() or {}
        permission = body.get("permission", "allow")
        if permission not in ("allow", "deny"):
            raise HttpError(400, "permission must be 'allow' or 'deny'")
        action = body.get("action", "all")
        if action not in ("publish", "subscribe", "all"):
            raise HttpError(400, "action must be publish|subscribe|all")
        topics = body.get("topics")
        if not isinstance(topics, list) or not topics or not all(
            isinstance(t, str) and t for t in topics
        ):
            raise HttpError(400, "topics must be a non-empty list of filters")
        rule = Rule(
            permission=permission,
            who="all",
            action=action,
            topics=list(topics),
        )
        if body.get("clientid"):
            src.by_clientid.setdefault(body["clientid"], []).append(rule)
        elif body.get("username"):
            src.by_username.setdefault(body["username"], []).append(rule)
        else:
            src.all_rules.append(rule)
        return {"ok": True}

    # ---------------------------------------------------------------- rules

    @staticmethod
    def _rule_info(rule) -> dict:
        return {
            "id": rule.rule_id,
            "sql": rule.sql,
            "enabled": rule.enabled,
            "description": rule.description,
            "outputs": [type(o).__name__.lower() for o in rule.outputs],
            "metrics": dict(rule.metrics),
        }

    def rules_list(self, req: Request):
        eng = self._need("rule_engine")
        return {"data": [self._rule_info(r) for r in eng.rules.values()]}

    def rule_get(self, req: Request):
        eng = self._need("rule_engine")
        rule = eng.get_rule(req.params["rule_id"])
        if rule is None:
            raise HttpError(404, "no such rule")
        return self._rule_info(rule)

    def rule_test(self, req: Request):
        """POST {sql, context{event_type,...}} -> selected output, 412
        when the SQL doesn't match (emqx_rule_sqltester analog)."""
        from ..rules.engine import EvalError, RuleTestNoMatch, rule_sql_test
        from ..rules.sql import SqlError

        body = req.json() or {}
        if not body.get("sql"):
            raise HttpError(400, "sql required")
        try:
            return rule_sql_test(body["sql"], body.get("context"))
        except SqlError as e:
            raise HttpError(400, f"bad sql: {e}")
        except (EvalError, ValueError, TypeError) as e:
            # runtime eval problems (unknown function, bad context
            # shape) are client errors, not 500s
            raise HttpError(400, f"sql evaluation failed: {e}")
        except RuleTestNoMatch as e:
            raise HttpError(412, str(e))

    def rule_create(self, req: Request):
        from ..rules.engine import build_outputs
        from ..rules.sql import SqlError

        eng = self._need("rule_engine")
        body = req.json() or {}
        rule_id = body.get("id")
        if rule_id is None:
            i = len(eng.rules) + 1
            while f"rule_{i}" in eng.rules:
                i += 1
            rule_id = f"rule_{i}"
        elif rule_id in eng.rules:
            raise HttpError(400, f"rule {rule_id!r} exists")
        if not body.get("sql"):
            raise HttpError(400, "sql required")
        try:
            rule = eng.create_rule(
                rule_id,
                body["sql"],
                build_outputs(body.get("outputs"),
                              lambda: self.bridges),
                description=body.get("description", ""),
            )
        except SqlError as e:
            raise HttpError(400, f"bad sql: {e}")
        except ValueError as e:
            raise HttpError(400, f"bad outputs: {e}")
        return self._rule_info(rule)

    def rule_update(self, req: Request):
        from ..rules.engine import build_outputs
        from ..rules.sql import SqlError

        eng = self._need("rule_engine")
        rule = eng.get_rule(req.params["rule_id"])
        if rule is None:
            raise HttpError(404, "no such rule")
        body = req.json() or {}
        was_enabled = rule.enabled
        if "sql" in body or "outputs" in body:
            try:
                rule = eng.create_rule(  # replace wholesale
                    rule.rule_id,
                    body.get("sql", rule.sql),
                    build_outputs(body.get("outputs"),
                                  lambda: self.bridges)
                    if "outputs" in body
                    else rule.outputs,
                    description=body.get("description", rule.description),
                )
            except SqlError as e:
                raise HttpError(400, f"bad sql: {e}")
            except ValueError as e:
                raise HttpError(400, f"bad outputs: {e}")
            rule.enabled = was_enabled  # editing must not re-enable
        if "enabled" in body:
            rule.enabled = bool(body["enabled"])
        if "description" in body and "sql" not in body:
            rule.description = body["description"]
        return self._rule_info(rule)

    def rule_delete(self, req: Request):
        eng = self._need("rule_engine")
        if not eng.delete_rule(req.params["rule_id"]):
            raise HttpError(404, "no such rule")
        return None

    # ------------------------------------------------------------ dashboard

    def monitor_get(self, req: Request):
        """Time series for dashboard charts (`emqx_dashboard_monitor_api`)."""
        mon = self._need("monitor")
        try:
            n = int(req.query.get("latest", ["60"])[0])
        except ValueError:
            raise HttpError(400, "latest must be an integer")
        return {"data": mon.latest(max(1, min(n, 1000)))}

    def monitor_current(self, req: Request):
        return self._need("monitor").current()

    def dashboard_page(self, req: Request):
        """Multi-page dashboard frontend (mgmt/dashboard.py): each page
        is a thin HTML view over the same REST endpoints operator
        tooling uses — the reference's packaged SPA, minus the bundler
        (`apps/emqx_dashboard` serving a built frontend)."""
        from .dashboard import exists, render
        from .http import RawResponse

        page = req.params.get("page")
        if page is None:
            return RawResponse(
                b"", status=302,
                headers={"Location": "dashboard/overview"},
            )
        if not exists(page):
            raise HttpError(404, f"no dashboard page {page!r}")
        return RawResponse(render(page, self.node).encode())

    # ------------------------------------------------------------- api-docs

    def api_docs(self, req: Request):
        doc = self.http.openapi()
        if self.config is not None:
            # component schemas come from the SAME Field/Struct defs that
            # validate config (config.py openapi_schemas) — doc and
            # validator cannot disagree by construction
            doc["components"]["schemas"] = self.config.openapi_schemas()
            ref = {"$ref": "#/components/schemas/config"}
            content = {"application/json": {"schema": ref}}
            base = self.http.base
            cfg_get = doc["paths"].get(base + "/configs", {}).get("get")
            if cfg_get is not None:
                cfg_get["responses"]["200"]["content"] = content
            one = doc["paths"].get(base + "/configs/{path}", {})
            if "put" in one:
                one["put"]["requestBody"] = {
                    "content": {"application/json": {"schema": {
                        "description": "value for the dotted config path; "
                        "validated against the matching field schema",
                    }}},
                }
        return doc
