"""Multi-page dashboard frontend (served by the mgmt HTTP app).

The reference ships a packaged SPA (`apps/emqx_dashboard` serving a
built frontend); the equivalent here is a small server-rendered shell —
one layout, one nav, per-page tables — where every page is a thin HTML
view over the SAME REST endpoints an operator's tooling uses
(`emqx_mgmt_api_*` analogs in api.py).  No build step, no bundler: the
pages are the API made visible.

Pages: overview (live gauges + monitor history), clients (+search),
subscriptions, topics/routes, retained, listeners, metrics, settings
(token).  Auth: the dashboard token from POST /api/v5/login, held in
localStorage; 401s route to the login view.
"""

from __future__ import annotations

_STYLE = """
 body { font: 14px system-ui, sans-serif; margin: 0; color: #222; }
 nav { display: flex; gap: .2rem; padding: .6rem 1.2rem; background: #1b2430;
       align-items: center; flex-wrap: wrap; }
 nav a { color: #cfd8e3; text-decoration: none; padding: .35rem .7rem;
         border-radius: 6px; font-size: 13px; }
 nav a.on, nav a:hover { background: #324055; color: #fff; }
 nav .brand { color: #7ee0c0; font-weight: 600; margin-right: 1rem; }
 main { padding: 1.2rem 1.6rem; }
 .cards { display: flex; gap: 1rem; flex-wrap: wrap; margin-bottom: 1rem; }
 .card { border: 1px solid #ddd; border-radius: 8px; padding: .8rem 1.2rem;
         min-width: 9rem; }
 .card b { display: block; font-size: 1.5rem; }
 small { color: #777; }
 table { border-collapse: collapse; width: 100%; margin-top: .8rem; }
 th, td { text-align: left; padding: .35rem .6rem; border-bottom: 1px solid
          #eee; font-size: 13px; }
 th { background: #f7f8fa; position: sticky; top: 0; }
 input[type=text], input[type=password] { padding: .4rem .6rem;
   border: 1px solid #ccc; border-radius: 6px; }
 button { padding: .4rem .9rem; border: 0; border-radius: 6px;
          background: #1b2430; color: #fff; cursor: pointer; }
 #err { color: #b00020; }
 .muted { color: #888; font-size: 12px; }
"""

_HELPERS = """
const TOK = () => localStorage.getItem('emqx_tpu_token');
async function api(path) {
  // pages live at <base>/dashboard/<page>; the API root is one level up
  const r = await fetch('..' + path,
      {headers: {Authorization: 'Bearer ' + TOK()}});
  if (r.status === 401) { location.href = 'login'; throw new Error('auth'); }
  if (!r.ok) throw new Error(path + ': HTTP ' + r.status);
  return r.json();
}
// MQTT data (clientids, topics, usernames) is attacker-controlled and
// MUST be HTML-escaped before hitting innerHTML — a clientid like
// <img onerror=...> would otherwise run in the operator's session
const esc = v => String(v).replace(/[&<>"']/g, ch => ({'&': '&amp;',
  '<': '&lt;', '>': '&gt;', '"': '&quot;', "'": '&#39;'}[ch]));
function table(el, cols, rows) {
  const h = ['<table><tr>' + cols.map(c => '<th>' + esc(c) + '</th>')
             .join('') + '</tr>'];
  for (const r of rows)
    h.push('<tr>' + r.map(v => '<td>' + (v === undefined || v === null ?
           '' : esc(v)) + '</td>').join('') + '</tr>');
  h.push('</table>');
  el.innerHTML = h.join('');
}
function rowsOf(resp) { return resp.data !== undefined ? resp.data : resp; }
"""

_PAGES = {
    "overview": ("Overview", """
<div class="cards">
 <div class="card"><small>connections</small><b id="c">–</b></div>
 <div class="card"><small>subscriptions</small><b id="s">–</b></div>
 <div class="card"><small>topics</small><b id="t">–</b></div>
 <div class="card"><small>retained</small><b id="r">–</b></div>
 <div class="card"><small>msgs in/s</small><b id="in">–</b></div>
 <div class="card"><small>msgs out/s</small><b id="out">–</b></div>
 <div class="card"><small>uptime</small><b id="up">–</b></div>
</div>
<h3>Cluster</h3><div id="nodes"></div>
<h3>History <span class="muted">(GET /api/v5/monitor)</span></h3>
<div id="hist"></div>
<script>
async function tick() {
  try {
    const st = await (await fetch('../status')).json();
    document.getElementById('up').textContent = st.uptime + 's';
    const cur = await api('/monitor_current');
    for (const [k, id] of [['connections','c'], ['subscriptions','s'],
                           ['topics','t']])
      document.getElementById(id).textContent = cur[k];
    document.getElementById('in').textContent =
      (cur.received_rate || 0).toFixed(1);
    document.getElementById('out').textContent =
      (cur.sent_rate || 0).toFixed(1);
    api('/mqtt/retainer').then(r => document.getElementById('r')
      .textContent = r.count ?? r.retained_count ?? '–').catch(() => {});
    const nodes = rowsOf(await api('/nodes'));
    table(document.getElementById('nodes'),
          ['node', 'status', 'connections', 'subscriptions', 'routes'],
          nodes.map(n => [n.node, n.node_status, n.connections,
                          n.subscriptions, n.routes]));
    const hist = rowsOf(await api('/monitor?latest=20'));
    table(document.getElementById('hist'),
          ['time', 'connections', 'subscriptions', 'topics',
           'received', 'sent'],
          hist.map(h => [new Date(h.time_stamp).toLocaleTimeString(),
                         h.connections, h.subscriptions, h.topics,
                         h.received, h.sent]));
  } catch (e) { console.log(e); }
}
tick(); setInterval(tick, 5000);
</script>"""),

    "clients": ("Clients", """
<input type="text" id="q" placeholder="filter by clientid...">
<button onclick="load()">search</button>
<div id="tbl"></div>
<script>
async function load() {
  const q = document.getElementById('q').value;
  const resp = await api('/clients' + (q ? '?like_clientid=' +
                         encodeURIComponent(q) : '?limit=200'));
  table(document.getElementById('tbl'),
        ['clientid', 'username', 'peername', 'proto', 'connected',
         'connected at'],
        rowsOf(resp).map(c => [c.clientid, c.username, c.peername,
          c.proto_ver, c.connected, c.connected_at ?
          new Date(c.connected_at * 1000).toLocaleString() : '']));
}
load();
</script>"""),

    "subscriptions": ("Subscriptions", """
<input type="text" id="q" placeholder="filter by topic...">
<button onclick="load()">search</button>
<div id="tbl"></div>
<script>
async function load() {
  const q = document.getElementById('q').value;
  const resp = await api('/subscriptions' + (q ? '?match_topic=' +
                         encodeURIComponent(q) : '?limit=500'));
  table(document.getElementById('tbl'), ['clientid', 'topic', 'qos'],
        rowsOf(resp).map(s => [s.clientid, s.topic, s.qos]));
}
load();
</script>"""),

    "topics": ("Topics", """
<div id="tbl"></div>
<script>
api('/topics?limit=500').then(resp =>
  table(document.getElementById('tbl'), ['topic', 'node'],
        rowsOf(resp).map(t => [t.topic, t.node])));
</script>"""),

    "retained": ("Retained", """
<div id="tbl"></div>
<script>
api('/mqtt/retainer/messages?limit=500').then(resp =>
  table(document.getElementById('tbl'),
        ['topic', 'qos', 'payload bytes', 'from'],
        rowsOf(resp).map(m => [m.topic, m.qos, m.payload_size,
                               m.from_clientid])))
  .catch(() => document.getElementById('tbl').textContent =
         'retainer API unavailable');
</script>"""),

    "listeners": ("Listeners", """
<div id="tbl"></div><h3>Gateways</h3><div id="gw"></div>
<script>
api('/listeners').then(resp =>
  table(document.getElementById('tbl'),
        ['id', 'type', 'bind', 'running', 'connections'],
        rowsOf(resp).map(l => [l.id, l.type, l.bind, l.running,
                               l.current_connections])));
api('/gateways').then(resp =>
  table(document.getElementById('gw'), ['name', 'status'],
        rowsOf(resp).map(g => [g.name, g.status])))
  .catch(() => {});
</script>"""),

    "metrics": ("Metrics", """
<div id="stats"></div><h3>Counters</h3><div id="tbl"></div>
<script>
api('/stats').then(s => {
  const rows = Object.entries(s).map(([k, v]) => [k, v]);
  table(document.getElementById('stats'), ['stat', 'value'], rows);
});
api('/metrics').then(m => {
  const rows = Object.entries(m).sort().map(([k, v]) => [k, v]);
  table(document.getElementById('tbl'), ['metric', 'value'], rows);
});
</script>"""),

    "login": ("Login", """
<h3>Dashboard login</h3>
<p><input type="text" id="u" placeholder="username" value="admin">
   <input type="password" id="p" placeholder="password">
   <button onclick="login()">login</button></p>
<p id="err"></p>
<script>
async function login() {
  const r = await fetch('../login', {method: 'POST',
    headers: {'Content-Type': 'application/json'},
    body: JSON.stringify({username: document.getElementById('u').value,
                          password: document.getElementById('p').value})});
  if (!r.ok) { document.getElementById('err').textContent =
               'login failed (HTTP ' + r.status + ')'; return; }
  localStorage.setItem('emqx_tpu_token', (await r.json()).token);
  location.href = 'overview';
}
</script>"""),
}

PAGE_NAMES = [p for p in _PAGES if p != "login"]


def render(page: str, node: str) -> str:
    """Full HTML for one dashboard page (404 handled by caller)."""
    import html as _html

    node = _html.escape(node)  # config-sourced, but never trust it in HTML
    title, body = _PAGES[page]
    nav = "".join(
        f'<a href="{name}" class="{"on" if name == page else ""}">'
        f"{_PAGES[name][0]}</a>"
        for name in PAGE_NAMES
    )
    return f"""<!doctype html>
<html><head><meta charset="utf-8">
<title>{title} — {node} — emqx_tpu</title>
<style>{_STYLE}</style></head>
<body>
<nav><span class="brand">emqx_tpu</span>{nav}
 <span style="flex:1"></span>
 <a href="login">Login</a>
 <a href="../api-docs">API docs</a>
</nav>
<main>
<h2>{title} <small class="muted">node {node}</small></h2>
<script>{_HELPERS}</script>
{body}
</main>
</body></html>"""


def exists(page: str) -> bool:
    return page in _PAGES
