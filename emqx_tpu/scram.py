"""SCRAM-SHA-256 enhanced authentication (RFC 5802 / RFC 7677).

The reference's enhanced authenticator
(`apps/emqx_authn/src/enhanced_authn/emqx_enhanced_authn_scram_mnesia.erl`,
esasl dep) runs SCRAM over MQTT 5 AUTH packets: CONNECT carries the
client-first message under the "SCRAM-SHA-256" authentication method,
the server answers with an AUTH continue holding server-first, the
client's AUTH continue holds client-final, and the server's CONNACK
carries server-final (`v=...`).

Server-side only (the in-repo MqttClient gets a small client helper for
tests).  Stored credentials follow RFC 5802 §3: per-user salt +
iteration count + StoredKey/ServerKey — the plaintext password is never
kept and never crosses the wire.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
from typing import Dict, Optional, Tuple

from .broker.hooks import STOP, Hooks

METHOD = "SCRAM-SHA-256"
_MECH = "sha256"


def _h(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


def derive_keys(password: bytes, salt: bytes, iterations: int
                ) -> Tuple[bytes, bytes]:
    """(StoredKey, ServerKey) per RFC 5802 §3."""
    salted = hashlib.pbkdf2_hmac(_MECH, password, salt, iterations)
    client_key = _hmac(salted, b"Client Key")
    server_key = _hmac(salted, b"Server Key")
    return _h(client_key), server_key


def _parse_attrs(msg: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in msg.split(","):
        if len(part) >= 2 and part[1] == "=":
            out[part[0]] = part[2:]
    return out


class ScramUser:
    __slots__ = ("salt", "iterations", "stored_key", "server_key",
                 "is_superuser")

    def __init__(self, salt, iterations, stored_key, server_key,
                 is_superuser=False):
        self.salt = salt
        self.iterations = iterations
        self.stored_key = stored_key
        self.server_key = server_key
        self.is_superuser = is_superuser


class ScramAuthenticator:
    """User store + per-connection SCRAM conversations, installable on
    the broker hook chain ('client.enhanced_auth_start' /_auth)."""

    name = "scram"

    #: conversation state rides on clientinfo.attrs so its lifetime is
    #: the channel's — abandoned handshakes are GC'd with the connection
    #: and no cross-client id() reuse is possible
    CONV_KEY = "_scram_conv"

    def __init__(self, iterations: int = 4096):
        self.iterations = iterations
        self.users: Dict[str, ScramUser] = {}

    # ------------------------------------------------------------- users

    def add_user(self, username: str, password: str,
                 iterations: Optional[int] = None,
                 is_superuser: bool = False) -> None:
        it = iterations or self.iterations
        salt = os.urandom(16)
        stored, server = derive_keys(password.encode(), salt, it)
        self.users[username] = ScramUser(salt, it, stored, server,
                                         is_superuser)

    def delete_user(self, username: str) -> bool:
        return self.users.pop(username, None) is not None

    # ------------------------------------------------------------- hooks

    def install(self, hooks: Hooks, priority: int = 0) -> None:
        hooks.put("client.enhanced_auth_start", self.on_start, priority)
        hooks.put("client.enhanced_auth", self.on_continue, priority)

    def on_start(self, clientinfo, method: str, data: bytes, acc):
        if method != METHOD:
            return None  # not ours; let another provider claim it
        try:
            reply = self._server_first(clientinfo, bytes(data))
        except ValueError:
            return (STOP, ("fail", None))
        return (STOP, ("continue", reply))

    def on_continue(self, clientinfo, method: str, data: bytes, acc):
        if method != METHOD:
            return None
        st = clientinfo.attrs.pop(self.CONV_KEY, None)
        if st is None:
            return (STOP, ("fail", None))
        try:
            server_final, user = self._verify_final(st, bytes(data))
        except ValueError:
            return (STOP, ("fail", None))
        clientinfo.username = st["username"]
        clientinfo.is_superuser = user.is_superuser
        return (STOP, ("ok", server_final))

    # ------------------------------------------------------------ rounds

    def _server_first(self, clientinfo, client_first: bytes) -> bytes:
        """client-first-message -> server-first-message (RFC 5802 §7)."""
        text = client_first.decode("utf-8", "strict")
        # gs2 header: "n,," (no channel binding) then n=user,r=cnonce
        if not (text.startswith("n,,") or text.startswith("y,,")):
            raise ValueError("unsupported gs2 header")
        gs2, bare = text[:3], text[3:]
        attrs = _parse_attrs(bare)
        username = attrs.get("n", "").replace("=2C", ",").replace("=3D", "=")
        cnonce = attrs.get("r", "")
        if not username or not cnonce:
            raise ValueError("missing n/r attributes")
        user = self.users.get(username)
        if user is None:
            # RFC recommends continuing with fake credentials to avoid a
            # user-enumeration oracle; a simple reject keeps state clean
            # and matches the reference's not_authorized path
            raise ValueError("unknown user")
        snonce = cnonce + base64.b64encode(os.urandom(18)).decode()
        server_first = (
            f"r={snonce},s={base64.b64encode(user.salt).decode()},"
            f"i={user.iterations}"
        )
        clientinfo.attrs[self.CONV_KEY] = {
            "username": username,
            "user": user,
            "gs2": gs2,
            "client_first_bare": bare,
            "server_first": server_first,
            "snonce": snonce,
        }
        return server_first.encode()

    def _verify_final(self, st: dict, client_final: bytes
                      ) -> Tuple[bytes, ScramUser]:
        """client-final-message -> server-final-message or ValueError."""
        text = client_final.decode("utf-8", "strict")
        attrs = _parse_attrs(text)
        proof_b64 = attrs.get("p", "")
        nonce = attrs.get("r", "")
        cbind = attrs.get("c", "")
        if nonce != st["snonce"]:
            raise ValueError("nonce mismatch")
        expected_cbind = base64.b64encode(st["gs2"].encode()).decode()
        if cbind != expected_cbind:
            raise ValueError("channel-binding mismatch")
        without_proof = text[: text.rfind(",p=")]
        auth_message = (
            st["client_first_bare"]
            + ","
            + st["server_first"]
            + ","
            + without_proof
        ).encode()
        user: ScramUser = st["user"]
        client_sig = _hmac(user.stored_key, auth_message)
        try:
            proof = base64.b64decode(proof_b64, validate=True)
        except Exception as e:
            raise ValueError("bad proof encoding") from e
        client_key = _xor(proof, client_sig)
        if len(client_key) != 32 or not hmac.compare_digest(
            _h(client_key), user.stored_key
        ):
            raise ValueError("proof mismatch")
        server_sig = _hmac(user.server_key, auth_message)
        return b"v=" + base64.b64encode(server_sig), user


class ScramClient:
    """Client side, for tests and the in-repo MqttClient."""

    def __init__(self, username: str, password: str,
                 cnonce: Optional[str] = None):
        self.username = username
        self.password = password
        self.cnonce = cnonce or base64.b64encode(os.urandom(18)).decode()
        self._bare = f"n={self.username},r={self.cnonce}"
        self._server_first: Optional[str] = None
        self._salted: Optional[bytes] = None
        self._auth_message: Optional[bytes] = None

    def client_first(self) -> bytes:
        return ("n,," + self._bare).encode()

    def client_final(self, server_first: bytes) -> bytes:
        text = server_first.decode()
        attrs = _parse_attrs(text)
        snonce = attrs["r"]
        if not snonce.startswith(self.cnonce):
            raise ValueError("server nonce does not extend client nonce")
        salt = base64.b64decode(attrs["s"])
        iterations = int(attrs["i"])
        self._server_first = text
        self._salted = hashlib.pbkdf2_hmac(
            _MECH, self.password.encode(), salt, iterations
        )
        without_proof = f"c={base64.b64encode(b'n,,').decode()},r={snonce}"
        self._auth_message = (
            self._bare + "," + text + "," + without_proof
        ).encode()
        client_key = _hmac(self._salted, b"Client Key")
        client_sig = _hmac(_h(client_key), self._auth_message)
        proof = base64.b64encode(_xor(client_key, client_sig)).decode()
        return (without_proof + f",p={proof}").encode()

    def verify_server_final(self, server_final: bytes) -> bool:
        attrs = _parse_attrs(server_final.decode())
        server_key = _hmac(self._salted, b"Server Key")
        want = _hmac(server_key, self._auth_message)
        try:
            got = base64.b64decode(attrs.get("v", ""), validate=True)
        except Exception:
            return False
        return hmac.compare_digest(want, got)
