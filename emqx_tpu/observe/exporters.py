"""Metric exporters: Prometheus exposition + push, StatsD UDP.

`emqx_prometheus` pushes to a pushgateway on a timer and serves the
standard exposition format; `emqx_statsd` emits counter/gauge lines
over UDP.  Both are reproduced on the stdlib only (urllib / socket).
"""

from __future__ import annotations

import math
import re
import socket
from typing import Dict, Optional
from urllib import request as urlrequest


def _san(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _finite(value) -> bool:
    try:
        return math.isfinite(value)
    except TypeError:
        return False


def render_prometheus(
    metrics: Dict[str, float],
    stats: Optional[Dict[str, float]] = None,
    histograms: Optional[Dict[str, object]] = None,
    prefix: str = "emqx",
) -> str:
    """Prometheus text exposition: counters, gauges, and histograms.

    Non-finite values (NaN/inf from a division-by-zero gauge or an
    unmeasured rate) are SKIPPED — they would otherwise render exposition
    lines many scrapers reject wholesale, poisoning every other series in
    the payload.

    `histograms` maps metric name -> an object with `cumulative()`
    ((upper_edge, cumulative_count) pairs), `.sum` and `.count` — the
    `observe.flight.LatencyHistogram` contract.  Buckets are rendered
    cumulatively with `le` labels in SECONDS (Prometheus convention);
    empty-delta buckets are elided (legal for cumulative histograms) so
    a 40-bucket log2 histogram stays a handful of lines.
    """
    lines = []
    for name, value in sorted(metrics.items()):
        if not _finite(value):
            continue
        mn = f"{prefix}_{_san(name)}"
        lines.append(f"# TYPE {mn} counter")
        lines.append(f"{mn} {value}")
    for name, value in sorted((stats or {}).items()):
        if not _finite(value):
            continue
        mn = f"{prefix}_{_san(name)}"
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn} {value}")
    for name, hist in sorted((histograms or {}).items()):
        mn = f"{prefix}_{_san(name)}"
        lines.append(f"# TYPE {mn} histogram")
        prev = 0
        for edge, cum in hist.cumulative():
            if cum != prev:  # cumulative: elided buckets lose nothing
                lines.append(f'{mn}_bucket{{le="{edge:g}"}} {cum}')
                prev = cum
        lines.append(f'{mn}_bucket{{le="+Inf"}} {hist.count}')
        if _finite(hist.sum):
            lines.append(f"{mn}_sum {hist.sum}")
        lines.append(f"{mn}_count {hist.count}")
    return "\n".join(lines) + "\n"


class PrometheusPush:
    """Push-gateway exporter (`emqx_prometheus.erl` push mode).

    `push_failures` counts CONSECUTIVE failed pushes (reset on success)
    so a monitor can alert on a dead gateway instead of the caller
    polling a silently-returned False."""

    def __init__(self, gateway_url: str, job: str = "emqx_tpu", timeout: float = 5.0):
        self.url = gateway_url.rstrip("/") + f"/metrics/job/{job}"
        self.timeout = timeout
        self.push_failures = 0

    def push(
        self,
        metrics: Dict[str, float],
        stats: Optional[Dict[str, float]] = None,
        histograms: Optional[Dict[str, object]] = None,
    ) -> bool:
        body = render_prometheus(metrics, stats, histograms).encode()
        req = urlrequest.Request(self.url, data=body, method="POST")
        req.add_header("Content-Type", "text/plain")
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as resp:
                ok = 200 <= resp.status < 300
        except Exception:
            ok = False
        self.push_failures = 0 if ok else self.push_failures + 1
        return ok


class ExporterRuntime:
    """Config-driven export scheduling — the `emqx_prometheus` +
    `emqx_statsd` app lifecycles: a push/flush timer each, runtime
    enable/disable + endpoint updates over REST, and the pull-mode
    `/prometheus/stats` exposition rendered from the same tables."""

    def __init__(self, metrics_fn, stats_fn, hists_fn=None,
                 prometheus: Optional[Dict] = None,
                 statsd: Optional[Dict] = None):
        self.metrics_fn = metrics_fn
        self.stats_fn = stats_fn
        # histogram table source (name -> LatencyHistogram); rendered
        # only on the Prometheus surfaces — StatsD has no histogram type
        self.hists_fn = hists_fn or (lambda: {})
        self.prometheus = {
            "enable": False, "push_gateway_server": "",
            "interval": 15.0, **(prometheus or {}),
        }
        self.statsd = {
            "enable": False, "server": "127.0.0.1:8125",
            "flush_time_interval": 10.0, **(statsd or {}),
        }
        self.prom_pushes = 0
        self.prom_failures = 0
        # rebuilt on the loop by mgmt config updates, read by tick() on
        # the exporter thread: the swap is an atomic reference store and
        # tick snapshots the reference once — at worst one tick pushes
        # through the just-replaced exporter and its OSError is caught
        # by the exporter loop (node.py _exporter_loop)
        self._pusher: Optional[PrometheusPush] = None  # analysis: owner=loop
        self._statsd: Optional["StatsdExporter"] = None  # analysis: owner=loop
        self._last_prom = 0.0
        self._last_statsd = 0.0
        # boot-time validation: bad config is a clear error, not a
        # traceback from the first tick
        self._validate(self.prometheus, "interval")
        self._validate(self.statsd, "flush_time_interval")
        self._parse_server(self.statsd["server"])
        self._rebuild()

    @staticmethod
    def _parse_server(server: str):
        host, _, port = str(server).partition(":")
        try:
            return host or "127.0.0.1", int(port or 8125)
        except ValueError:
            raise ValueError(
                f"statsd server must be host:port, got {server!r}"
            )

    @staticmethod
    def _validate(cfg: Dict, interval_key: str) -> None:
        """Raise ValueError on bad values BEFORE they are committed —
        a rejected update must not poison later rebuilds or the node
        ticker."""
        try:
            cfg[interval_key] = float(cfg[interval_key])
        except (TypeError, ValueError):
            raise ValueError(
                f"{interval_key} must be a number of seconds, got "
                f"{cfg[interval_key]!r}"
            )
        if cfg[interval_key] <= 0:
            raise ValueError(f"{interval_key} must be > 0")

    def _rebuild(self) -> None:
        p = self.prometheus
        self._pusher = (
            PrometheusPush(p["push_gateway_server"])
            if p["enable"] and p["push_gateway_server"] else None
        )
        old = self._statsd
        s = self.statsd
        if s["enable"]:
            host, port = self._parse_server(s["server"])
            self._statsd = StatsdExporter(host, port)
        else:
            self._statsd = None
        if old is not None:
            old.close()  # don't leak the previous UDP socket

    def update_prometheus(self, changes: Dict) -> Dict:
        cand = dict(self.prometheus)
        for k in ("enable", "push_gateway_server", "interval"):
            if k in changes:
                cand[k] = changes[k]
        self._validate(cand, "interval")
        self.prometheus = cand
        self._rebuild()
        return self.prometheus_status()

    def update_statsd(self, changes: Dict) -> Dict:
        cand = dict(self.statsd)
        for k in ("enable", "server", "flush_time_interval"):
            if k in changes:
                cand[k] = changes[k]
        self._validate(cand, "flush_time_interval")
        self._parse_server(cand["server"])  # validate before commit
        self.statsd = cand
        self._rebuild()
        return self.statsd_status()

    def prometheus_status(self) -> Dict:
        p = self._pusher
        return {**self.prometheus, "pushes": self.prom_pushes,
                "failures": self.prom_failures,
                "push_failures": getattr(p, "push_failures", 0)}

    def statsd_status(self) -> Dict:
        return dict(self.statsd)

    def render(self) -> str:
        """Pull-mode exposition (GET /prometheus/stats)."""
        return render_prometheus(
            self.metrics_fn(), self.stats_fn(), self.hists_fn()
        )

    @property
    def active(self) -> bool:
        """Whether a tick would do anything — lets the node skip the
        per-second thread hop while both exporters are disabled."""
        return self._pusher is not None or self._statsd is not None

    def tick(self, now: float) -> None:
        """Called off the event loop (pushes block on the network).
        Locals snapshot the exporters: a concurrent update_* on the
        event-loop thread may null them mid-tick."""
        pusher = self._pusher
        if pusher is not None and \
                now - self._last_prom >= float(self.prometheus["interval"]):
            self._last_prom = now
            ok = pusher.push(
                self.metrics_fn(), self.stats_fn(), self.hists_fn()
            )
            self.prom_pushes += 1
            if not ok:
                self.prom_failures += 1
        statsd = self._statsd
        if statsd is not None and now - self._last_statsd >= \
                float(self.statsd["flush_time_interval"]):
            self._last_statsd = now
            try:
                statsd.flush(self.metrics_fn(), self.stats_fn())
            except OSError:
                pass


class StatsdExporter:
    """StatsD line protocol over UDP (`emqx_statsd` analog)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "emqx"):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def flush(self, metrics: Dict[str, float], stats: Optional[Dict[str, float]] = None) -> int:
        n = 0
        for name, value in metrics.items():
            n += self._send(f"{self.prefix}.{name}:{value}|c")
        for name, value in (stats or {}).items():
            n += self._send(f"{self.prefix}.{name}:{value}|g")
        return n

    def _send(self, line: str) -> int:
        try:
            self._sock.sendto(line.encode(), self.addr)
            return 1
        except OSError:
            return 0

    def close(self) -> None:
        self._sock.close()
