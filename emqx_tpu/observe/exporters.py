"""Metric exporters: Prometheus exposition + push, StatsD UDP.

`emqx_prometheus` pushes to a pushgateway on a timer and serves the
standard exposition format; `emqx_statsd` emits counter/gauge lines
over UDP.  Both are reproduced on the stdlib only (urllib / socket).
"""

from __future__ import annotations

import re
import socket
from typing import Dict, Optional
from urllib import request as urlrequest


def _san(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def render_prometheus(
    metrics: Dict[str, float],
    stats: Optional[Dict[str, float]] = None,
    prefix: str = "emqx",
) -> str:
    """Prometheus text exposition of the counter + gauge tables."""
    lines = []
    for name, value in sorted(metrics.items()):
        mn = f"{prefix}_{_san(name)}"
        lines.append(f"# TYPE {mn} counter")
        lines.append(f"{mn} {value}")
    for name, value in sorted((stats or {}).items()):
        mn = f"{prefix}_{_san(name)}"
        lines.append(f"# TYPE {mn} gauge")
        lines.append(f"{mn} {value}")
    return "\n".join(lines) + "\n"


class PrometheusPush:
    """Push-gateway exporter (`emqx_prometheus.erl` push mode)."""

    def __init__(self, gateway_url: str, job: str = "emqx_tpu", timeout: float = 5.0):
        self.url = gateway_url.rstrip("/") + f"/metrics/job/{job}"
        self.timeout = timeout

    def push(self, metrics: Dict[str, float], stats: Optional[Dict[str, float]] = None) -> bool:
        body = render_prometheus(metrics, stats).encode()
        req = urlrequest.Request(self.url, data=body, method="POST")
        req.add_header("Content-Type", "text/plain")
        try:
            with urlrequest.urlopen(req, timeout=self.timeout) as resp:
                return 200 <= resp.status < 300
        except Exception:
            return False


class StatsdExporter:
    """StatsD line protocol over UDP (`emqx_statsd` analog)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125, prefix: str = "emqx"):
        self.addr = (host, port)
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def flush(self, metrics: Dict[str, float], stats: Optional[Dict[str, float]] = None) -> int:
        n = 0
        for name, value in metrics.items():
            n += self._send(f"{self.prefix}.{name}:{value}|c")
        for name, value in (stats or {}).items():
            n += self._send(f"{self.prefix}.{name}:{value}|g")
        return n

    def _send(self, line: str) -> int:
        try:
            self._sock.sendto(line.encode(), self.addr)
            return 1
        except OSError:
            return 0

    def close(self) -> None:
        self._sock.close()
