"""Observability: stats, $SYS heartbeats, alarms, tracing, slow
subscribers, Prometheus/StatsD export (SURVEY.md §1.13, §5.5).
"""

from .alarm import Alarm, AlarmManager
from .slow_subs import LatencyStats, SlowSubs
from .stats import Stats
from .sysmon import SysHeartbeat, OsMon
from .trace import TraceManager, TraceSpec

__all__ = [
    "Alarm",
    "AlarmManager",
    "LatencyStats",
    "SlowSubs",
    "Stats",
    "SysHeartbeat",
    "OsMon",
    "TraceManager",
    "TraceSpec",
]
