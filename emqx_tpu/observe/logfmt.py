"""Log formatters — the `emqx_logger_jsonfmt` / text formatter analogs.

The reference ships two OTP logger formatters: a structured JSON
formatter for log aggregation (`emqx_logger_jsonfmt.erl`: one JSON
object per line, best-effort serialization that never throws out of
the formatter) and a human text formatter.  Same here, as stdlib
`logging.Formatter`s selected by the `log.format` config key:

* `JsonFormatter` — one compact JSON object per line: ts (epoch ms),
  level, logger, msg, plus exception info and any `extra={...}` fields
  the call site attached; values that json can't encode degrade to
  `repr` instead of raising (the reference's best_effort_json);
* `TextFormatter` — the existing human-readable line.

`setup_logging(level, fmt)` configures the root handler; `__main__`
drives it from `--log-format` / the `log` config section.
"""

from __future__ import annotations

import json
import logging
from typing import Any

# attributes of a LogRecord that are NOT call-site extras
_STD_ATTRS = frozenset(logging.LogRecord(
    "", 0, "", 0, "", (), None
).__dict__) | {"message", "asctime", "taskName"}


def _best_effort(v: Any) -> Any:
    """Values json.dumps can't take degrade to repr — the formatter
    must never raise (emqx_logger_jsonfmt best_effort_json)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).decode("utf-8", "replace")
    if isinstance(v, dict):
        return {str(k): _best_effort(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_best_effort(x) for x in v]
    return repr(v)


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        try:
            msg = record.getMessage()
        except Exception:
            msg = f"format_error: {record.msg!r} % {record.args!r}"
        out = {
            "ts": int(record.created * 1000),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": msg,
        }
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = self.formatException(record.exc_info)
        for k, v in record.__dict__.items():
            if k not in _STD_ATTRS and not k.startswith("_"):
                out[k] = _best_effort(v)
        try:
            return json.dumps(out, ensure_ascii=False,
                              default=lambda o: repr(o))
        except Exception:  # pragma: no cover - double best-effort
            return json.dumps({"ts": out["ts"], "level": out["level"],
                               "logger": out["logger"],
                               "msg": "jsonfmt_format_error"})


class TextFormatter(logging.Formatter):
    def __init__(self):
        super().__init__(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"
        )


def setup_logging(level: str = "INFO", fmt: str = "text") -> None:
    """Configure the root handler once (the logger handler install of
    `emqx_logger` at boot)."""
    handler = logging.StreamHandler()
    handler.setFormatter(JsonFormatter() if fmt == "json"
                         else TextFormatter())
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
