"""Message-lifecycle span plane: per-plane latency attribution.

Every bench row isolates one plane; production latency is the SUM of
planes, and "where did this message spend its 11 ms" needs stage
attribution that survives the batched publish pipeline and a cross-node
forward.  This module stamps a span context on a head-sampled fraction
of publishes at ingress and records one monotonic timestamp per plane
boundary; the per-stage deltas land in the same mergeable log2
histograms the flight recorder uses (`observe/flight.py` bucket
discipline), so stage p50/p99/p999 derive from buckets and one
implementation serves Prometheus, `$SYS`, `bench.py --spans` and
`tools/span_dump.py`.

Stages (KNOWN_STAGES is the registry the static-analysis gate lints
both ways, like tracepoint kinds and fault sites):

    hooks    publish ingress -> 'message.publish' hooks + authz fold +
             retain accepted the message into the tick
    submit   accept -> churn/match dispatch submitted (includes the
             cluster forward fan-out, which rides _pre_match)
    collect  submit -> device/host match collected (the executor-thread
             half of the three-phase publish)
    enqueue  collect -> fid expansion done, per-connection batches
             handed to the delivery plane
    wire     enqueue -> FIRST receiver's action batch flushed to its
             transport (later receivers of the same copy don't re-close
             the stage)
    forward  cross-node leg: origin publish ingress -> the REMOTE
             broker dispatched the forwarded copy.  The span context
             rides the cluster FORWARD frame header (wall-clock t0 —
             same-host clock domain; cross-host skew is the usual
             distributed-tracing caveat) and the remote broker closes
             and reports the leg exactly once (replayed/relayed dups
             are dedup-dropped before the close).
    ds       offline leg: dispatch -> durable-log append (parked
             persistent-session traffic; closes the span, so a copy
             that is both delivered live and parked attributes its
             tail to whichever leg lands first)

Shm-lane legs (hub+workers topology, `emqx_tpu/shm/`): a wire worker's
`collect` stage lumps the whole shared-memory ring round-trip into one
number, so the slab protocol carries monotonic-ns stamps in the spare
slot-header bytes (CLOCK_MONOTONIC is system-wide on Linux — hub and
worker clocks compare directly) and the worker decomposes each
hub-served tick into per-tick stage observations:

    ring_wait  worker committed the submit slot -> hub's drain pass
               picked the record off the ring (drain-loop queueing tax)
    fuse_wait  drain pick-up -> the tick entered a fused foreign_submit
               group (cross-lane geometry-coalescing wait)
    device     foreign_submit -> the hub's device collect finished
    scatter    hub committed the result slot -> the worker's drain
               decoded it (result-ring return tax)

These are per-TICK observations (the shm client batches topics per
tick and never sees individual message contexts), recorded straight
into the stage histograms via `observe_stage` — they decompose the
worker's `collect` stage rather than ride a SpanContext.

Sampling is head-based: ONE decision per message at ingress
(``observe.span_sample`` = N means 1/N publishes carry a span; 0
disarms).  Disarmed, every boundary is one module-bool test away from
returning — the fault-plane discipline — so the hot path pays nothing
until the plane is armed.  Marks are stage-idempotent (first arrival
wins) and tolerate the collect mark landing on an executor thread: a
mark is a list append + one histogram bucket add, lossy-telemetry safe
under the GIL.

Completed spans feed two bounded record stores: a recent ring and a
slowest-K keep (``observe.span_keep``) rendered by
``tools/span_dump.py`` — the tail records are the "where did the slow
one go" answer the histograms can't give.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .flight import LatencyHistogram

# Every stage recorded by this plane (spans.mark(ctx, "<stage>") /
# plane.observe_stage("<stage>", dt) in production code) MUST be
# declared here, and every declared stage must be recorded somewhere —
# the static-analysis gate (`tools/analysis/registry.py`) lints both
# directions, the same contract as tracepoint KNOWN_KINDS / fault SITES.
KNOWN_STAGES: Dict[str, str] = {
    "hooks": "ingress -> publish hooks/authz/retain accepted",
    "submit": "accept -> churn/match dispatch submitted (incl. cluster "
              "forward fan-out)",
    "collect": "submit -> device/host match collected",
    "enqueue": "collect -> delivery batches handed to the delivery plane",
    "wire": "enqueue -> first receiver's frames flushed to the transport",
    "forward": "origin ingress -> remote broker dispatched the "
               "forwarded copy (cross-node leg)",
    "ds": "dispatch -> durable-log append (parked-session leg)",
    # shared-memory match plane legs (shm/client.py decomposes the ring
    # round-trip from the slot-header timestamp lane; per-tick, not
    # per-message — see module docstring)
    "ring_wait": "submit slot committed -> hub drain picked it up",
    "fuse_wait": "hub drain pick-up -> fused foreign_submit group",
    "device": "foreign_submit -> hub device collect finished",
    "scatter": "result slot committed -> worker drain decoded it",
    # ds replication hop (ds/repl.py; per shipped range, like the shm
    # legs per-tick): prices the durability cost of the second node
    "repl": "leader flush handed off -> follower mirror fsync'd + acked",
    # semantic subscription plane (semantic/plane.py; per publish that
    # reached at least one $semantic query)
    "sem": "publish accepted -> semantic match collected + fanned out",
}

_RECENT = 256  # completed-span ring (newest-first render)


class SpanContext:
    """One sampled message's lifecycle: monotonic t0 + stage deltas.

    ``wall0`` (time.time at ingress) is what rides a cluster-forward
    frame so the remote broker can close the cross-node leg without a
    shared monotonic clock."""

    __slots__ = ("topic", "mid", "t0", "wall0", "last", "stages",
                 "seen", "finished")

    def __init__(self, topic: str, mid: bytes):
        now = time.perf_counter()
        self.topic = topic
        self.mid = mid
        self.t0 = now
        self.wall0 = time.time()
        self.last = now
        self.stages: List[Tuple[str, float]] = []  # (stage, delta_s)
        self.seen: set = set()
        self.finished = False

    def record(self) -> Dict:
        return {
            "topic": self.topic,
            "mid": self.mid.hex() if self.mid else "",
            "ts": self.wall0,
            "total_ms": (self.last - self.t0) * 1e3,
            "stages": {s: round(d * 1e3, 4) for s, d in self.stages},
        }


class SpanPlane:
    """Stage histograms + bounded completed-span record stores."""

    def __init__(self, sample: int = 0, keep: int = 64):
        self.sample = max(0, int(sample))  # 1/N; 0 = disarmed
        self.keep = max(1, int(keep))
        self.hists: Dict[str, LatencyHistogram] = {
            s: LatencyHistogram() for s in KNOWN_STAGES
        }
        self.hist_total = LatencyHistogram()
        # sampling decision runs on the publish ingress (loop) thread;
        # marks may land from the collect executor — counters are lossy
        # telemetry under the GIL (flight-recorder discipline)
        self.started = 0  # analysis: owner=any
        self.completed = 0  # analysis: owner=any
        self.remote_closed = 0  # analysis: owner=any
        self._n = 0  # head-sampling stride counter  # analysis: owner=loop
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=_RECENT)
        self._slow: List[Tuple[float, int, Dict]] = []  # min-heap by total
        self._slow_seq = 0

    # ------------------------------------------------------------ hot path

    def begin(self, topic: str, mid: bytes) -> Optional[SpanContext]:
        """The one head-sampling decision, at publish ingress."""
        if not self.sample:
            return None
        self._n += 1
        if self._n % self.sample:
            return None
        self.started += 1
        return SpanContext(topic, mid)

    def observe_stage(self, stage: str, delta_s: float) -> None:
        self.hists[stage].observe(delta_s)

    # ----------------------------------------------------------- records

    def complete(self, ctx: SpanContext) -> None:
        self.completed += 1
        self.hist_total.observe(ctx.last - ctx.t0)
        rec = ctx.record()
        with self._lock:
            self._recent.append(rec)
            self._slow_seq += 1
            item = (rec["total_ms"], self._slow_seq, rec)
            if len(self._slow) < self.keep:
                heapq.heappush(self._slow, item)
            elif rec["total_ms"] > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)

    def close_remote(self, t0_wall: float, topic: str, mid: str,
                     origin: str, node: str) -> None:
        """Remote side of a forwarded span: close the cross-node leg."""
        dt = max(0.0, time.time() - t0_wall)
        self.observe_stage("forward", dt)
        self.remote_closed += 1
        rec = {
            "topic": topic, "mid": mid, "ts": t0_wall,
            "total_ms": dt * 1e3,
            "stages": {"forward": round(dt * 1e3, 4)},
            "origin": origin, "node": node,
        }
        with self._lock:
            self._recent.append(rec)
            self._slow_seq += 1
            item = (rec["total_ms"], self._slow_seq, rec)
            if len(self._slow) < self.keep:
                heapq.heappush(self._slow, item)
            elif rec["total_ms"] > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)

    # ------------------------------------------------------------ queries

    def stage_counts(self) -> Dict[str, int]:
        return {s: h.count for s, h in self.hists.items()}

    def percentiles(self) -> Dict[str, Dict[str, float]]:
        """Bucket-derived per-stage {count, p50/p99/p999 ms}."""
        out: Dict[str, Dict[str, float]] = {}
        for s, h in self.hists.items():
            row = {"count": h.count}
            if h.count:
                row.update(h.percentiles_ms())
            out[s] = row
        return out

    def summary(self) -> Dict:
        """The `$SYS/brokers/<node>/spans` payload."""
        out = {
            "sample": self.sample,
            "keep": self.keep,
            "started": self.started,
            "completed": self.completed,
            "remote_closed": self.remote_closed,
            "stages": self.percentiles(),
        }
        if self.hist_total.count:
            out["total_ms"] = self.hist_total.percentiles_ms()
        return out

    def slowest(self) -> List[Dict]:
        """Slowest-K completed spans, slowest first (copies)."""
        with self._lock:
            return [rec for _t, _i, rec in
                    sorted(self._slow, reverse=True)]

    def recent(self, k: int = 32) -> List[Dict]:
        with self._lock:
            return list(self._recent)[-k:]

    def export(self) -> Dict:
        """Full JSON-safe dump (bench emit-stats / span_dump input)."""
        return {
            **self.summary(),
            "slowest": self.slowest(),
            "recent": self.recent(),
        }

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.export(), f)


# -------------------------------------------------- module-level fast path

_plane = SpanPlane()
# fast-path gate: every boundary is one module-attribute bool test when
# disarmed.  Hot call sites read `spans.armed` directly (an attribute
# load, no call frame); `enabled()` is the same flag behind a function
# for cold paths and tests.
armed = False


def configure(sample: int = 64, keep: int = 64) -> None:
    """Arm the plane at 1/`sample` head-sampling (0 disarms)."""
    global _plane, armed
    _plane = SpanPlane(sample=sample, keep=keep)
    armed = sample > 0


def disable() -> None:
    global armed
    armed = False


def enabled() -> bool:
    return armed


def plane() -> SpanPlane:
    return _plane


def begin(topic: str, mid: bytes) -> Optional[SpanContext]:
    """Sampling decision at publish ingress; None = not sampled.
    Callers should gate on `enabled()` first (hot loop)."""
    if not armed:
        return None
    return _plane.begin(topic, mid)


def mark(ctx: Optional[SpanContext], stage: str) -> None:
    """Stamp one plane boundary: the delta since the previous mark
    lands in `stage`'s histogram.  Stage-idempotent (first arrival
    wins); no-op on finished/unsampled contexts."""
    if ctx is None or ctx.finished or stage in ctx.seen:
        return
    now = time.perf_counter()
    delta = now - ctx.last
    ctx.last = now
    ctx.seen.add(stage)
    ctx.stages.append((stage, delta))
    _plane.observe_stage(stage, delta)


def finish(ctx: Optional[SpanContext]) -> None:
    """Close the span and record it (recent ring + slowest-K keep)."""
    if ctx is None or ctx.finished:
        return
    ctx.finished = True
    _plane.complete(ctx)


def wire(delivers: Sequence[Tuple[str, object]]) -> None:
    """Wire-flush boundary: close the wire stage for any sampled
    message in this flushed delivery batch (first flush wins).  Called
    per connection-batch, never per receiver, so the armed cost stays
    off the per-delivery hot loop."""
    if not armed:
        return
    for _filt, msg in delivers:
        ctx = msg.headers.get("__span")
        if ctx is not None:
            mark(ctx, "wire")
            finish(ctx)


def close_remote(t0_wall: float, topic: str = "", mid: str = "",
                 origin: str = "", node: str = "") -> None:
    """Remote broker closes a forwarded span's cross-node leg (called
    after the forwarded copy dispatched; dedup-dropped replays never
    reach this, so the leg reports exactly once)."""
    if not armed:
        return
    _plane.close_remote(t0_wall, topic, mid, origin, node)


def stage_histograms() -> Dict[str, LatencyHistogram]:
    """Prometheus exposition source: stage name -> histogram."""
    return dict(_plane.hists)
