"""$SYS heartbeats + OS monitoring — `emqx_sys`/`emqx_os_mon` analog.

`SysHeartbeat.tick()` publishes broker version/uptime/datetime plus the
stats and metrics tables under `$SYS/brokers/<node>/...`, exactly the
topic families the reference emits on its sys_interval timer.

`OsMon.check()` samples /proc (linux) for memory + load and raises or
clears alarms against configured thresholds (`emqx_os_mon` semantics;
the reference alarms at 70% sysmem / 5% procmem / load 0.8 defaults).
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional

from .alarm import AlarmManager

VERSION = "5.0.0-tpu.1"


class SysHeartbeat:
    def __init__(self, broker, stats=None, node: str = "emqx_tpu"):
        self.broker = broker
        self.stats = stats
        self.node = node
        self.started_at = time.time()

    @property
    def uptime_s(self) -> float:
        return time.time() - self.started_at

    def _pub(self, suffix: str, payload) -> None:
        from ..broker.message import Message

        if not isinstance(payload, (bytes, bytearray)):
            payload = (
                payload.encode()
                if isinstance(payload, str)
                else json.dumps(payload).encode()
            )
        self.broker.publish(
            Message(topic=f"$SYS/brokers/{self.node}/{suffix}", payload=payload)
        )

    def tick(self) -> None:
        """One sys_interval heartbeat (version/uptime/datetime)."""
        self._pub("version", VERSION)
        self._pub("uptime", str(int(self.uptime_s)))
        self._pub("datetime", time.strftime("%Y-%m-%d %H:%M:%S"))

    def tick_msgs(self) -> None:
        """One sys_msg_interval stats/metrics publication (the
        reference's separate `broker.sys_msg_interval` cadence), plus
        the engine flight-recorder summary on `$SYS/.../engine` (schema
        in README "Observability")."""
        if self.stats is not None:
            self._pub("stats", self.stats.collect())
        if hasattr(self.broker, "sync_engine_metrics"):
            self.broker.sync_engine_metrics()
        self._pub("metrics", self.broker.metrics.all())
        engine = getattr(self.broker, "engine", None)
        if engine is not None and getattr(engine, "hist_tick", None) is not None:
            from .flight import engine_summary

            self._pub("engine", engine_summary(engine))
        from . import spans as _spans

        if _spans.enabled():
            # per-plane latency attribution rides the same cadence:
            # `$SYS/brokers/<node>/spans` = stage p50/p99/p999 + counts
            self._pub("spans", _spans.plane().summary())


class OsMon:
    def __init__(
        self,
        alarms: AlarmManager,
        mem_high_watermark: float = 0.70,
        load_high_watermark: float = 0.80,
    ):
        self.alarms = alarms
        self.mem_high = mem_high_watermark
        self.load_high = load_high_watermark

    @staticmethod
    def mem_usage() -> Optional[float]:
        try:
            info: Dict[str, int] = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, _, rest = line.partition(":")
                    info[k] = int(rest.split()[0])
            total = info.get("MemTotal", 0)
            avail = info.get("MemAvailable", 0)
            if not total:
                return None
            return 1.0 - avail / total
        except (OSError, ValueError, IndexError):
            return None

    @staticmethod
    def load_per_core() -> Optional[float]:
        try:
            import os

            with open("/proc/loadavg") as f:
                load1 = float(f.read().split()[0])
            return load1 / max(os.cpu_count() or 1, 1)
        except (OSError, ValueError, IndexError):
            return None

    def check(self) -> None:
        mem = self.mem_usage()
        if mem is not None:
            if mem >= self.mem_high:
                self.alarms.activate(
                    "high_system_memory_usage",
                    {"usage": round(mem, 3), "high_watermark": self.mem_high},
                )
            else:
                self.alarms.deactivate("high_system_memory_usage")
        load = self.load_per_core()
        if load is not None:
            if load >= self.load_high:
                self.alarms.activate(
                    "high_cpu_load",
                    {"load_per_core": round(load, 3), "high_watermark": self.load_high},
                )
            else:
                self.alarms.deactivate("high_cpu_load")
