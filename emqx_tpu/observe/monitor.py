"""Dashboard monitor time series — `emqx_dashboard_collection.erl` analog.

The reference samples broker counters every 10s on whole-interval
boundaries, keeps a bounded history, and serves it to the dashboard via
`/monitor` (`emqx_dashboard_monitor_api.erl`).  Here `MonitorSampler`
snapshots counters + gauges into a ring buffer; counter fields are
emitted as per-interval deltas (message *rates*), gauges as levels.
Driven by `tick()` from the housekeeping loop or an asyncio runner.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, List, Optional

# counter metrics sampled as deltas-per-interval
COUNTER_FIELDS = {
    "received": "messages.received",
    "sent": "messages.sent",
    "dropped": "messages.dropped",
    # engine flight-recorder counters (synced before sampling): the
    # dashboard draws match ticks/s and arbitration flips/interval
    "engine_ticks": "engine.ticks",
    "engine_flips": "engine.path_flips",
    # parallel churn plane: shed ops/interval (demand past capacity)
    "engine_churn_shed": "engine.churn_shed",
    # delivery plane: shared packet-prefix cache traffic + per-tick
    # batched deliveries (build-once/scatter effectiveness)
    "prefix_hits": "deliver.prefix.hit",
    "prefix_misses": "deliver.prefix.miss",
    "delivered_batched": "messages.delivered.batched",
    # durable message log: parked-session appends/interval
    "ds_appends": "ds.appends",
}


class MonitorSampler:
    def __init__(self, broker, interval: float = 10.0, retention: int = 360):
        """retention=360 x 10s = 1h of samples, the reference's default
        dashboard window."""
        self.broker = broker
        self.interval = interval
        self.samples: Deque[Dict] = deque(maxlen=retention)
        self._last_counters: Optional[Dict[str, int]] = None
        self._next_at = self._align(time.time())
        # contention monitor (observe/contention.py), wired by the node:
        # adds the loop-lag level to every sample when present
        self.contention = None

    def _align(self, now: float) -> float:
        """Whole-interval boundaries like the reference's next_interval."""
        return now - (now % self.interval) + self.interval

    def _counters(self) -> Dict[str, int]:
        if hasattr(self.broker, "sync_engine_metrics"):
            self.broker.sync_engine_metrics()
        m = self.broker.metrics
        return {k: int(m.get(v)) for k, v in COUNTER_FIELDS.items()}

    def sample_now(self, ts: Optional[float] = None) -> Dict:
        ts = time.time() if ts is None else ts
        counters = self._counters()
        prev = self._last_counters or counters
        self._last_counters = counters
        s = {
            "time_stamp": int(ts * 1000),
            "node": getattr(self.broker, "node", "emqx_tpu"),
            # levels
            "connections": self.broker.cm.connection_count,
            "subscriptions": self.broker.subscription_count,
            "topics": self.broker.route_count,
            # per-interval deltas (dashboard draws rates)
            **{k: counters[k] - prev[k] for k in counters},
        }
        # level: bucket-derived per-tick p99 (observe/flight.py histogram)
        h = getattr(getattr(self.broker, "engine", None), "hist_tick", None)
        if h is not None and h.count:
            s["engine_p99_ms"] = round(h.quantile(0.99) * 1e3, 3)
        # level: event-loop lag EWMA (observe/contention.py probe)
        if self.contention is not None:
            s["loop_lag_ms"] = round(
                self.contention.probe.ewma_s * 1e3, 3
            )
        # levels: process-sharded wire plane (wire/supervisor.py stats
        # loop keeps these gauges fresh; absent = wire plane off)
        gauges = self.broker.metrics.gauges
        if "wire.workers.alive" in gauges:
            s["wire_workers_alive"] = int(gauges["wire.workers.alive"])
            s["wire_connections"] = (
                int(gauges["wire.connections"])
                if "wire.connections" in gauges else 0
            )
        self.samples.append(s)
        return s

    def tick(self, now: Optional[float] = None) -> Optional[Dict]:
        now = time.time() if now is None else now
        if now < self._next_at:
            return None
        self._next_at = self._align(now)
        return self.sample_now(now)

    # ---------------------------------------------------------------- api

    def latest(self, n: int = 60) -> List[Dict]:
        return list(self.samples)[-n:]

    def current(self) -> Dict:
        """`/monitor_current`: instantaneous levels + last-interval rates."""
        last = self.samples[-1] if self.samples else {}
        return {
            "connections": self.broker.cm.connection_count,
            "subscriptions": self.broker.subscription_count,
            "topics": self.broker.route_count,
            "received_rate": last.get("received", 0) / self.interval,
            "sent_rate": last.get("sent", 0) / self.interval,
            "dropped_rate": last.get("dropped", 0) / self.interval,
        }
