"""Periodic gauges — the `emqx_stats` analog.

The reference keeps a gauge ETS updated by timers (connections.count,
routes.count, subscriptions.count, retained.count...) plus historical
maxima.  Here `collect()` pulls the current values straight from the
broker's components; `setstat` allows ad-hoc gauges; `.max` values
track high-water marks like the reference's `connections.max`.
"""

from __future__ import annotations

from typing import Dict, Optional


class Stats:
    def __init__(self, broker=None):
        self.broker = broker
        self._gauges: Dict[str, float] = {}
        self._maxima: Dict[str, float] = {}

    def setstat(self, name: str, value: float) -> None:
        self._gauges[name] = value
        mx = name + ".max"
        if value > self._maxima.get(mx, float("-inf")):
            self._maxima[mx] = value

    def getstat(self, name: str) -> Optional[float]:
        if name.endswith(".max"):
            return self._maxima.get(name)
        return self._gauges.get(name)

    def collect(self) -> Dict[str, float]:
        """Refresh broker-derived gauges and return the full table."""
        b = self.broker
        if b is not None:
            cm = b.cm
            self.setstat("connections.count", cm.connection_count)
            self.setstat("sessions.count", cm.session_count)
            self.setstat("subscriptions.count", b.subscription_count)
            self.setstat("topics.count", b.route_count)
            self.setstat("routes.count", b.route_count)
            self.setstat("retained.count", b.retainer.count)
            cluster = getattr(b, "cluster", None)
            if cluster is not None:
                self.setstat("cluster.routes.count", cluster.remote.route_count)
                self.setstat("cluster.nodes.up", len(cluster.up_peers()))
        out = dict(self._gauges)
        out.update(self._maxima)
        return out
