"""Periodic gauges — the `emqx_stats` analog.

The reference keeps a gauge ETS updated by timers (connections.count,
routes.count, subscriptions.count, retained.count...) plus historical
maxima.  Here `collect()` pulls the current values straight from the
broker's components; `setstat` allows ad-hoc gauges; `.max` values
track high-water marks like the reference's `connections.max`.

All table access is serialized by a lock: `setstat` runs from the
listener housekeeping loop AND the sysmon/node timers concurrently with
`collect()` on the exporter thread — an unlocked dict snapshot could
tear a gauge/maximum pair mid-update (the reference gets this for free
from ETS write serialization).

`collect()` also refreshes the `engine.*` gauge family from the match
engine's flight-recorder plane (rates, histogram percentiles, wire
bytes), so every exporter surface — Prometheus, StatsD, `$SYS`, the
dashboard — reads the same engine telemetry.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class Stats:
    def __init__(self, broker=None, enable: bool = True):
        self.broker = broker
        # `stats.enable` (the reference's emqx_stats update-timer flag):
        # False freezes SAMPLING — the ticker's setstat points and
        # collect()'s broker-derived refresh are skipped wholesale, so
        # dashboards/$SYS show the last (boot-time) values
        self.enable = enable
        self._gauges: Dict[str, float] = {}
        self._maxima: Dict[str, float] = {}
        self._lock = threading.Lock()

    def setstat(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value
            mx = name + ".max"
            if value > self._maxima.get(mx, float("-inf")):
                self._maxima[mx] = value

    def getstat(self, name: str) -> Optional[float]:
        with self._lock:
            if name.endswith(".max"):
                return self._maxima.get(name)
            return self._gauges.get(name)

    def _engine_gauges(self, engine) -> None:
        """engine.* defaults in the gauge registry (flight-recorder
        plane; see observe/flight.py)."""
        rh = getattr(engine, "rate_host", None)
        rd = getattr(engine, "rate_dev", None)
        self.setstat("engine.rate_host", float(rh) if rh else 0.0)
        self.setstat("engine.rate_dev", float(rd) if rd else 0.0)
        fl = getattr(engine, "flight", None)
        if fl is not None:
            self.setstat("engine.ticks", fl.n)
            self.setstat("engine.path_flips", fl.path_flips)
            self.setstat("engine.bytes_up", fl.bytes_up_total)
            self.setstat("engine.bytes_down", fl.bytes_down_total)
        for key, attr in (
            ("engine.tick_p99_ms", "hist_tick"),
            ("engine.probe_p99_ms", "hist_probe"),
            ("engine.churn_apply_p99_ms", "hist_churn"),
        ):
            h = getattr(engine, attr, None)
            if h is not None and h.count:
                self.setstat(key, h.quantile(0.99) * 1e3)

    def collect(self) -> Dict[str, float]:
        """Refresh broker-derived gauges and return the full table."""
        b = self.broker
        if b is not None and self.enable:
            cm = b.cm
            self.setstat("connections.count", cm.connection_count)
            self.setstat("sessions.count", cm.session_count)
            self.setstat("subscriptions.count", b.subscription_count)
            self.setstat("topics.count", b.route_count)
            self.setstat("routes.count", b.route_count)
            self.setstat("retained.count", b.retainer.count)
            engine = getattr(b, "engine", None)
            if engine is not None:
                if hasattr(b, "sync_engine_metrics"):
                    b.sync_engine_metrics()
                self._engine_gauges(engine)
            cluster = getattr(b, "cluster", None)
            if cluster is not None:
                self.setstat("cluster.routes.count", cluster.remote.route_count)
                self.setstat("cluster.nodes.up", len(cluster.up_peers()))
        with self._lock:
            out = dict(self._gauges)
            out.update(self._maxima)
        return out
