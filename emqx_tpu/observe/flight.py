"""Engine flight recorder + mergeable log2 latency histograms.

The hybrid engine's whole value is an *arbitration decision* — serve
each tick from the native host probe or the device dispatch, whichever
is measured faster (the reference never pays a wire to match,
`emqx_router.erl:127-140`).  This module makes that decision, and the
wire bytes it implies, observable after the fact:

* :class:`LatencyHistogram` — fixed log2 buckets (1 us .. ~9 min),
  numpy counts, mergeable across engines/shards, with p50/p99/p999
  derivable from the buckets.  One implementation serves live telemetry
  (Prometheus ``histogram`` exposition, `$SYS` summaries, slow-subs)
  AND ``bench.py``, so BENCH JSONs and production metrics report from
  the same code.
* :class:`FlightRecorder` — a fixed-size ring buffer recording one
  struct per match tick: size, path chosen, the arbitration reason, the
  EWMA rates at decision time, bytes shipped up/down (the wire-floor
  accounting of BENCH_TABLE.md: 2 hash lanes x 4 B x L levels per topic
  up, the sparse fid block down), dedup factor, verify-mismatch count,
  churn-apply lag, and the dispatch-pipeline occupancy/depth the tick
  saw at submit.  Recording one tick is a single structured-array
  row write (~1-2 us), far below per-tick latency, so the recorder ships
  enabled by default (``engine.flight_ring``, 0 disables).

Single-sample updates are lock-free: under the GIL a racing increment
can at worst lose one count, which is acceptable for telemetry and keeps
the hot path free of lock acquisition.  ``merge``/``snapshot`` copy.
"""

from __future__ import annotations

import math
import pickle
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# ------------------------------------------------------- arbitration reasons

R_NONE = 0          # no decision recorded
R_RATE = 1          # measured EWMA rates picked this path
R_UNMEASURED = 2    # rates unknown: host serves first, probe measures device
R_HOST_REFRESH = 3  # device winning; periodic host re-measure tick
R_LINK_STALL = 4    # device fetch timed out: host served the same batch
R_COLD_MIRROR = 5   # device tick paid a full HBM mirror rebuild
R_OVERFLOW = 6      # sparse-return overflow: host probe recovered the tick
R_FORCED = 7        # hybrid off / host probe unavailable: path is forced
R_BREAKER = 8       # device breaker open: host-only until a probe heals it

REASONS = {
    R_NONE: "",
    R_RATE: "rate",
    R_UNMEASURED: "unmeasured",
    R_HOST_REFRESH: "host-refresh",
    R_LINK_STALL: "link-stall",
    R_COLD_MIRROR: "cold-mirror",
    R_OVERFLOW: "overflow",
    R_FORCED: "forced",
    R_BREAKER: "breaker",
}

PATH_HOST = 0
PATH_DEVICE = 1
PATHS = ("host", "device")


# ------------------------------------------------------------- histograms

class LatencyHistogram:
    """Fixed log2-bucket latency histogram (seconds in, seconds out).

    Bucket ``i`` counts samples in ``(base * 2**(i-1), base * 2**i]``
    (bucket 0 is ``<= base``).  With the default ``base=1e-6`` and 40
    buckets the range is 1 us .. ~9.2 min — every latency this engine
    can produce.  Buckets are cumulative-friendly and merge by addition,
    so per-shard histograms aggregate exactly.
    """

    __slots__ = ("base", "counts", "sum", "count")

    def __init__(self, base: float = 1e-6, n_buckets: int = 40):
        self.base = base
        self.counts = np.zeros(n_buckets, dtype=np.int64)
        # observed from serve/collect threads, exported on the loop:
        # GIL-atomic add per sample; a torn read skews one export tick,
        # never the histogram invariants (lossy telemetry by design)
        self.sum = 0.0  # analysis: owner=any
        self.count = 0  # analysis: owner=any

    def _index(self, seconds: float) -> int:
        r = seconds / self.base
        if r <= 1.0:
            return 0
        return min(len(self.counts) - 1, int(math.ceil(math.log2(r))))

    def observe(self, seconds: float) -> None:
        self.counts[self._index(seconds)] += 1
        self.sum += seconds
        self.count += 1

    def observe_many(self, seconds: Sequence[float]) -> None:
        a = np.asarray(seconds, dtype=np.float64)
        if not a.size:
            return
        r = np.maximum(a / self.base, 1.0)
        idx = np.clip(
            np.ceil(np.log2(r)).astype(np.int64), 0, len(self.counts) - 1
        )
        self.counts += np.bincount(idx, minlength=len(self.counts))
        self.sum += float(a.sum())
        self.count += int(a.size)

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Add `other`'s samples into self (buckets must line up)."""
        if other.base != self.base or len(other.counts) != len(self.counts):
            raise ValueError("histogram bucket layouts differ")
        self.counts += other.counts
        self.sum += other.sum
        self.count += other.count
        return self

    def reset(self) -> None:
        self.counts[:] = 0
        self.sum = 0.0
        self.count = 0

    def upper_edges(self) -> List[float]:
        """Bucket upper bounds in seconds (Prometheus `le` values)."""
        return [self.base * (1 << i) for i in range(len(self.counts))]

    def cumulative(self) -> List[Tuple[float, int]]:
        """(upper_edge_seconds, cumulative_count) pairs."""
        return list(zip(self.upper_edges(), np.cumsum(self.counts).tolist()))

    def quantile(self, q: float) -> float:
        """Bucket-derived quantile in seconds (upper bucket edge: never
        under-reports tail latency; the true value lies within one log2
        bucket width below)."""
        if self.count <= 0:
            return 0.0
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts.tolist()):
            cum += c
            if cum >= target:
                return self.base * (1 << i)
        return self.base * (1 << (len(self.counts) - 1))

    def percentiles_ms(self) -> Dict[str, float]:
        return {
            "p50": self.quantile(0.50) * 1e3,
            "p99": self.quantile(0.99) * 1e3,
            "p999": self.quantile(0.999) * 1e3,
        }

    def snapshot(self) -> "LatencyHistogram":
        h = LatencyHistogram(self.base, len(self.counts))
        h.counts = self.counts.copy()
        h.sum = self.sum
        h.count = self.count
        return h

    def to_dict(self) -> Dict:
        """JSON-safe wire form: what crosses the wire_stats RPC from a
        wire worker to the supervisor (and lands in bench emit-stats
        JSONs).  `from_dict` round-trips it; `merge` then aggregates
        per-process histograms exactly, bucket by bucket."""
        return {
            "base": self.base,
            "counts": self.counts.tolist(),
            "sum": self.sum,
            "count": self.count,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "LatencyHistogram":
        counts = d.get("counts") or []
        h = cls(base=float(d.get("base", 1e-6)),
                n_buckets=len(counts) or 40)
        if counts:
            h.counts = np.asarray(counts, dtype=np.int64)
        h.sum = float(d.get("sum", 0.0))
        h.count = int(d.get("count", 0))
        return h


# ---------------------------------------------------------- flight recorder

# one struct per tick; latencies are stored in microseconds (f4 keeps the
# row at ~60 bytes — the default 4096-tick ring is ~240 KB resident)
TICK_DTYPE = np.dtype([
    ("ts", "f8"),            # time.time() at collect completion
    ("n_topics", "u4"),      # publishes in the tick (pre-dedup)
    ("n_unique", "u4"),      # distinct names matched (dedup divisor)
    ("path", "u1"),          # PATH_HOST / PATH_DEVICE
    ("reason", "u1"),        # R_* arbitration reason
    ("flip", "u1"),          # 1 = path differs from the previous tick
    ("pipe_occ", "u1"),      # in-flight ticks at submit (incl. this one)
    ("rate_host", "f4"),     # EWMA lookups/s at decision time
    ("rate_dev", "f4"),
    ("bytes_up", "u8"),      # wire bytes: packed terms + delta (+ rebuild)
    ("bytes_down", "u8"),    # wire bytes: sparse fid return (+ refetch)
    ("verify_fail", "u4"),   # hash-collision discards within this tick
    ("churn_slots", "u4"),   # delta slots this tick's dispatch shipped
    ("lat_us", "f4"),        # submit -> collect-complete latency
    ("churn_lag_us", "f4"),  # duration of the most recent apply_churn
    ("pipe_depth", "u1"),    # engine.pipeline_depth at submit
    ("prep_group", "u1"),    # coalesced-dispatch group size (1 = solo)
    ("churn_shed", "u4"),    # churn ops shed upstream since the last tick
    # prep sub-stage attribution (PR 12): the formerly opaque prep blob
    # split so the next prep regression is attributable — hash = split+
    # hash+memo+dedup, pack = staging-buffer gather+pad, submit = group
    # assembly + device_put handoff (the mesh-execute call itself lands
    # in the dispatch phase, where it belongs)
    ("prep_hash_us", "f4"),
    ("prep_pack_us", "f4"),
    ("prep_submit_us", "f4"),
    ("memo_hits", "u4"),     # topic-memo hits within this tick
])


class FlightRecorder:
    """Fixed-size ring of per-tick match records (see module docstring).

    `record()` is the only hot-path entry: one row write + counter adds.
    Everything else (`recent`, `flips`, `summary`, `save`) is offline
    analysis and copies before decoding.  The object pickles whole, so
    a recorder can be snapshotted from a live node and inspected later
    with ``tools/flight_dump.py``.
    """

    def __init__(self, size: int = 4096):
        self.size = max(16, int(size))
        self.buf = np.zeros(self.size, dtype=TICK_DTYPE)
        # recorded from whichever thread serves the tick (loop or
        # collect executor), rendered on the loop: the ring is lossy
        # telemetry by design — a torn counter read skews one dump row,
        # never engine correctness (see module docstring)
        self.n = 0  # monotonic tick counter (ring index = n % size)  # analysis: owner=any
        self.path_flips = 0  # analysis: owner=any
        self.host_ticks = 0  # analysis: owner=any
        self.dev_ticks = 0  # analysis: owner=any
        self.bytes_up_total = 0  # analysis: owner=any
        self.bytes_down_total = 0  # analysis: owner=any
        self.verify_fail_total = 0  # analysis: owner=any
        self._last_path = -1  # analysis: owner=any

    # ------------------------------------------------------------ hot path

    def record(
        self,
        *,
        n_topics: int,
        n_unique: int,
        path: int,
        reason: int,
        rate_host: Optional[float],
        rate_dev: Optional[float],
        bytes_up: int,
        bytes_down: int,
        verify_fail: int,
        churn_slots: int,
        lat_s: float,
        churn_lag_s: float,
        ts: Optional[float] = None,
        pipe_occ: int = 0,
        pipe_depth: int = 0,
        churn_shed: int = 0,
        prep_hash_s: float = 0.0,
        prep_pack_s: float = 0.0,
        prep_submit_s: float = 0.0,
        memo_hits: int = 0,
        prep_group: int = 1,
    ) -> bool:
        """Record one tick; returns True when the path flipped."""
        flip = self._last_path >= 0 and self._last_path != path
        self._last_path = path
        self.buf[self.n % self.size] = (
            time.time() if ts is None else ts,
            n_topics, n_unique, path, reason, flip, min(pipe_occ, 255),
            rate_host or 0.0, rate_dev or 0.0,
            bytes_up, bytes_down, verify_fail, churn_slots,
            lat_s * 1e6, churn_lag_s * 1e6, min(pipe_depth, 255),
            min(prep_group, 255), churn_shed,
            prep_hash_s * 1e6, prep_pack_s * 1e6, prep_submit_s * 1e6,
            memo_hits,
        )
        self.n += 1
        if flip:
            self.path_flips += 1
        if path == PATH_HOST:
            self.host_ticks += 1
        else:
            self.dev_ticks += 1
        self.bytes_up_total += bytes_up
        self.bytes_down_total += bytes_down
        self.verify_fail_total += verify_fail
        return flip

    # ------------------------------------------------------------- queries

    def _ordered(self) -> np.ndarray:
        """Ring contents oldest-first (copy)."""
        if self.n <= self.size:
            return self.buf[: self.n].copy()
        i = self.n % self.size
        return np.concatenate([self.buf[i:], self.buf[:i]])

    @staticmethod
    def _decode(row) -> Dict:
        return {
            "ts": float(row["ts"]),
            "n_topics": int(row["n_topics"]),
            "n_unique": int(row["n_unique"]),
            "path": PATHS[int(row["path"])],
            "reason": REASONS.get(int(row["reason"]), "?"),
            "flip": bool(row["flip"]),
            "rate_host": float(row["rate_host"]),
            "rate_dev": float(row["rate_dev"]),
            "bytes_up": int(row["bytes_up"]),
            "bytes_down": int(row["bytes_down"]),
            "verify_fail": int(row["verify_fail"]),
            "churn_slots": int(row["churn_slots"]),
            "churn_shed": int(row["churn_shed"]),
            "lat_ms": float(row["lat_us"]) / 1e3,
            "churn_lag_ms": float(row["churn_lag_us"]) / 1e3,
            "pipe_occ": int(row["pipe_occ"]),
            "pipe_depth": int(row["pipe_depth"]),
            "prep_hash_ms": float(row["prep_hash_us"]) / 1e3,
            "prep_pack_ms": float(row["prep_pack_us"]) / 1e3,
            "prep_submit_ms": float(row["prep_submit_us"]) / 1e3,
            "memo_hits": int(row["memo_hits"]),
            "prep_group": int(row["prep_group"]),
        }

    def recent(self, k: int = 32) -> List[Dict]:
        """The last `k` tick records, oldest first, decoded to dicts."""
        rows = self._ordered()[-k:]
        return [self._decode(r) for r in rows]

    def flips(self) -> List[Dict]:
        """Arbitration-flip records still in the ring, oldest first."""
        rows = self._ordered()
        return [self._decode(r) for r in rows[rows["flip"] != 0]]

    def summary(self) -> Dict:
        """Aggregate counters + the newest record (for `$SYS`/REST)."""
        out = {
            "ticks": self.n,
            "ring_size": self.size,
            "path_flips": self.path_flips,
            "host_ticks": self.host_ticks,
            "dev_ticks": self.dev_ticks,
            "bytes_up": self.bytes_up_total,
            "bytes_down": self.bytes_down_total,
            "verify_mismatch": self.verify_fail_total,
        }
        if self.n:
            out["last"] = self._decode(self.buf[(self.n - 1) % self.size])
        return out

    # ----------------------------------------------------------- save/load

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump(self, f)

    @staticmethod
    def load(path: str) -> "FlightRecorder":
        with open(path, "rb") as f:
            rec = pickle.load(f)
        if not isinstance(rec, FlightRecorder):
            raise TypeError(f"{path!r} is not a pickled FlightRecorder")
        return rec


def engine_summary(engine) -> Dict:
    """One engine telemetry snapshot (the `$SYS/brokers/<node>/engine`
    payload; see README "Observability" for the schema).  Duck-typed so
    both the single-chip and the sharded engine feed it."""
    out: Dict = {
        "host_serves": getattr(engine, "host_serve_count", 0),
        "dev_serves": getattr(engine, "dev_serve_count", 0),
        "dev_timeouts": getattr(engine, "dev_timeout_count", 0),
        "verify_mismatch": getattr(engine, "collision_count", 0),
        "churn_shed": getattr(engine, "churn_shed", 0),
        "path_flips": getattr(engine, "path_flips", 0),
        "probes": getattr(engine, "probe_count", 0),
        "rate_host": getattr(engine, "rate_host", None),
        "rate_dev": getattr(engine, "rate_dev", None),
        "hybrid": bool(getattr(engine, "hybrid", False)),
        "n_filters": getattr(engine, "n_filters", 0),
    }
    fl = getattr(engine, "flight", None)
    if fl is not None:
        out["flight"] = fl.summary()
    for key, attr in (
        ("tick_latency_ms", "hist_tick"),
        ("probe_latency_ms", "hist_probe"),
        ("churn_apply_ms", "hist_churn"),
    ):
        h = getattr(engine, attr, None)
        if h is not None and h.count:
            out[key] = h.percentiles_ms()
    return out
