"""Contention telemetry: loop lag, queue depths, GC pauses.

The span plane (`observe/spans.py`) says where a message spent its
time; this module says WHY the slow stages were slow — the three
whole-process contention sources per-plane benches hide:

* **event-loop lag** (`LoopLagProbe`): an asyncio task sleeps a fixed
  interval and measures scheduled-vs-actual wakeup delta.  Any
  loop-blocking work (a long dispatch, a mis-threaded fsync, GC) shows
  up as lag, EWMA-smoothed for gauges and bucketed in the shared log2
  histogram for p99/p999 — the single most honest "is the loop
  healthy" number a one-loop broker has.
* **queue depths** (`ContentionMonitor.sample`): delivery-shard queue
  depth, publish-batcher in-flight ticks, engine dispatch-window
  occupancy and churn-delta backlog, exported as gauges through the
  existing metrics table (Prometheus / `$SYS` / monitor ride along).
* **GC pauses** (`GcPauseTracker`): `gc.callbacks` start/stop deltas —
  the collector stops every thread in this runtime, so a gen-2 sweep
  is invisible to per-stage timing yet inflates every p99 at once.

Everything here is observation-only: probes never touch broker state,
and sampling runs from the node ticker on the event loop.
"""

from __future__ import annotations

import asyncio
import gc
import time
from typing import Dict, Optional

from .flight import LatencyHistogram


class LoopLagProbe:
    """Scheduled-vs-actual tick delta of the running event loop."""

    def __init__(self, interval: float = 1.0):
        self.interval = max(0.01, float(interval))
        self.hist = LatencyHistogram()
        self.ewma_s = 0.0
        self.samples = 0
        self.max_lag_s = 0.0
        self._task: Optional[asyncio.Task] = None

    def note(self, lag_s: float) -> None:
        """Fold one observed lag sample (probe task or tests)."""
        lag_s = max(0.0, lag_s)
        self.hist.observe(lag_s)
        self.samples += 1
        self.ewma_s = (
            lag_s if self.samples == 1
            else 0.8 * self.ewma_s + 0.2 * lag_s
        )
        if lag_s > self.max_lag_s:
            self.max_lag_s = lag_s

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            await asyncio.sleep(self.interval)
            self.note(loop.time() - t0 - self.interval)

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(
                self._run()
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


class GcPauseTracker:
    """Cyclic-GC pause accounting via `gc.callbacks`.

    Collections run with the GIL held on whichever thread triggered
    them, and callbacks fire start/stop in pairs on that thread, so the
    single `_t0` slot cannot interleave; a torn sample under reentrancy
    would skew one histogram bucket, never break the tracker."""

    def __init__(self):
        self.hist = LatencyHistogram()
        self.pauses = 0  # analysis: owner=any
        self.max_pause_s = 0.0  # analysis: owner=any
        self._t0: Optional[float] = None  # analysis: owner=any
        self._installed = False

    def _cb(self, phase: str, info: Dict) -> None:
        if phase == "start":
            self._t0 = time.perf_counter()
        elif self._t0 is not None:
            dt = time.perf_counter() - self._t0
            self._t0 = None
            self.hist.observe(dt)
            self.pauses += 1
            if dt > self.max_pause_s:
                self.max_pause_s = dt

    def install(self) -> None:
        if not self._installed:
            gc.callbacks.append(self._cb)
            self._installed = True

    def uninstall(self) -> None:
        if self._installed:
            try:
                gc.callbacks.remove(self._cb)
            except ValueError:
                pass
            self._installed = False


class ContentionMonitor:
    """Composition root: loop-lag probe + GC tracker + gauge sampling.

    Built by the node, started/stopped with it; `sample()` runs from
    the node ticker and lands the queue-depth gauges in the broker's
    metrics table so every existing export path picks them up."""

    def __init__(self, interval: float = 1.0):
        self.probe = LoopLagProbe(interval=interval)
        self.gc = GcPauseTracker()

    def start(self) -> None:
        self.gc.install()
        self.probe.start()

    async def stop(self) -> None:
        await self.probe.stop()
        self.gc.uninstall()

    def sample(self, broker, delivery=None, batcher=None) -> None:
        g = broker.metrics.gauge_set
        g("contention.loop_lag_ms", self.probe.ewma_s * 1e3)
        if self.probe.hist.count:
            g("contention.loop_lag_p99_ms",
              self.probe.hist.quantile(0.99) * 1e3)
        g("contention.gc_pauses", self.gc.pauses)
        g("contention.gc_pause_max_ms", self.gc.max_pause_s * 1e3)
        if delivery is not None:
            depths = delivery.queue_depths()
            g("deliver.queue_depth", max(depths, default=0))
            g("deliver.queue_depth_total", sum(depths))
        if batcher is not None:
            g("engine.tick_backlog", batcher.inflight_ticks)
        e = broker.engine
        g("engine.inflight_ticks", getattr(e, "inflight_ticks", 0))
        g("engine.delta_backlog", getattr(e, "delta_backlog", 0))

    def histograms(self) -> Dict[str, LatencyHistogram]:
        """Prometheus exposition source (node `hists_fn`)."""
        return {"loop_lag": self.probe.hist, "gc_pause": self.gc.hist}

    def summary(self) -> Dict:
        out = {
            "loop_lag_ewma_ms": round(self.probe.ewma_s * 1e3, 4),
            "loop_lag_max_ms": round(self.probe.max_lag_s * 1e3, 4),
            "loop_lag_samples": self.probe.samples,
            "gc_pauses": self.gc.pauses,
            "gc_pause_max_ms": round(self.gc.max_pause_s * 1e3, 4),
        }
        if self.probe.hist.count:
            out["loop_lag_ms"] = self.probe.hist.percentiles_ms()
        if self.gc.hist.count:
            out["gc_pause_ms"] = self.gc.hist.percentiles_ms()
        return out
