"""Slow-subscriber tracking — `emqx_slow_subs` analog.

Per-session EMA + peak delivery latency
(`emqx_message_latency_stats.erl`) feeding a bounded top-K table of the
slowest subscribers; entries expire so recovered clients drop out.
Latency = deliver time - message timestamp, the same definition the
reference uses for its `latency_stats`.

Broker-side per-TICK latency (the match-path component of delivery
latency) is NOT re-sampled here: it comes from the engine's
`hist_tick` log2 histogram (`observe/flight.py`), attached by the node
via :meth:`SlowSubs.attach_tick_hist`.  Before the flight recorder this
module's per-message wall-clock samples were the only way to estimate
the broker's own latency floor; now `tick_percentiles()` derives
p50/p99/p999 from the same buckets every other surface reports, and the
per-message path is purely per-CLIENT accounting.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class LatencyStats:
    ema_ms: float = 0.0
    peak_ms: float = 0.0
    samples: int = 0
    alpha: float = 0.3  # reference's default smoothing

    def update(self, latency_ms: float) -> None:
        self.samples += 1
        if self.samples == 1:
            self.ema_ms = latency_ms
        else:
            self.ema_ms = self.alpha * latency_ms + (1 - self.alpha) * self.ema_ms
        self.peak_ms = max(self.peak_ms, latency_ms)


class SlowSubs:
    def __init__(
        self,
        top_k: int = 10,
        threshold_ms: float = 500.0,
        expire_s: float = 300.0,
    ):
        self.top_k = top_k
        self.threshold_ms = threshold_ms
        self.expire_s = expire_s
        self.stats: Dict[str, LatencyStats] = {}
        self._table: Dict[str, Tuple[float, float]] = {}  # cid -> (ema, ts)
        self._tick_hist = None  # engine hist_tick (attach_tick_hist)

    def install(self, hooks) -> None:
        hooks.put("message.delivered", self._on_delivered, priority=-400)

    def attach_tick_hist(self, hist) -> None:
        """Source broker per-tick latency from the engine's histogram
        (one bucket increment per match tick) instead of this module
        sampling wall clock per delivered message."""
        self._tick_hist = hist

    def tick_percentiles(self) -> Optional[dict]:
        """Engine per-tick latency p50/p99/p999 (ms), bucket-derived;
        None until a histogram is attached and has samples."""
        h = self._tick_hist
        if h is None or not h.count:
            return None
        return h.percentiles_ms()

    def _on_delivered(self, clientid: str, msg) -> None:
        now_ms = time.time() * 1000.0
        if not msg.timestamp:
            return
        self.record(clientid, max(now_ms - msg.timestamp, 0.0))

    def record(self, clientid: str, latency_ms: float) -> None:
        st = self.stats.setdefault(clientid, LatencyStats())
        st.update(latency_ms)
        if st.ema_ms >= self.threshold_ms:
            self._table[clientid] = (st.ema_ms, time.time())
            self._trim()

    def _trim(self) -> None:
        if len(self._table) <= self.top_k:
            return
        ranked = sorted(self._table.items(), key=lambda kv: -kv[1][0])
        self._table = dict(ranked[: self.top_k])

    def clear_client(self, clientid: str) -> None:
        self.stats.pop(clientid, None)
        self._table.pop(clientid, None)

    def top(self, now: Optional[float] = None) -> List[dict]:
        """Slowest subscribers, expired entries pruned."""
        now = now if now is not None else time.time()
        for cid, (_, ts) in list(self._table.items()):
            if now - ts > self.expire_s:
                del self._table[cid]
        out = []
        for cid, (ema, ts) in sorted(self._table.items(), key=lambda kv: -kv[1][0]):
            st = self.stats.get(cid)
            out.append(
                {
                    "clientid": cid,
                    "ema_ms": round(ema, 3),
                    "peak_ms": round(st.peak_ms, 3) if st else None,
                    "last_update": ts,
                }
            )
        return out
