"""Alarms — `emqx_alarm` analog.

activate/deactivate named alarms with details; deactivated alarms keep
a bounded history; transitions publish to
`$SYS/brokers/<node>/alarms/activate|deactivate` so subscribed ops
tooling sees them (the reference publishes the same topics).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Alarm:
    name: str
    details: dict = field(default_factory=dict)
    message: str = ""
    activated_at: float = field(default_factory=time.time)
    deactivated_at: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "details": self.details,
            "message": self.message,
            "activated_at": self.activated_at,
            "deactivated_at": self.deactivated_at,
        }


class AlarmManager:
    def __init__(self, broker=None, node: str = "emqx_tpu", history_size: int = 1000):
        self.broker = broker
        self.node = node
        self.history_size = history_size
        self.active: Dict[str, Alarm] = {}
        self.history: List[Alarm] = []

    def activate(self, name: str, details: Optional[dict] = None, message: str = "") -> bool:
        """Returns False if already active (`{error, already_existed}`)."""
        if name in self.active:
            return False
        alarm = Alarm(name=name, details=details or {}, message=message or name)
        self.active[name] = alarm
        self._publish("activate", alarm)
        return True

    def deactivate(self, name: str) -> bool:
        alarm = self.active.pop(name, None)
        if alarm is None:
            return False
        alarm.deactivated_at = time.time()
        self.history.append(alarm)
        del self.history[: -self.history_size]
        self._publish("deactivate", alarm)
        return True

    def is_active(self, name: str) -> bool:
        return name in self.active

    def delete_all_deactivated(self) -> None:
        self.history.clear()

    def _publish(self, kind: str, alarm: Alarm) -> None:
        if self.broker is None:
            return
        from ..broker.message import Message

        self.broker.publish(
            Message(
                topic=f"$SYS/brokers/{self.node}/alarms/{kind}",
                payload=json.dumps(alarm.to_dict()).encode(),
            )
        )
