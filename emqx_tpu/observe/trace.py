"""Managed trace sessions — `emqx_trace`/`emqx_trace_handler` analog.

A trace spec filters by clientid, topic filter, or peer IP and streams
matching broker events (publish/subscribe/connect/deliver...) to its
own log file, with start/stop lifecycle and bounded concurrent traces —
the reference installs per-trace OTP logger handlers with the same
three filter kinds (`emqx_trace_handler.erl:34-36,63-90`).

Wired in as hook callbacks, so it sees exactly what extensions see.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..broker import topic as topiclib


@dataclass
class TraceSpec:
    name: str
    kind: str  # clientid | topic | ip
    value: str
    path: str
    start_at: float = field(default_factory=time.time)
    end_at: Optional[float] = None

    def matches(self, clientid: str, topic: Optional[str], ip: Optional[str]) -> bool:
        if self.kind == "clientid":
            return clientid == self.value
        if self.kind == "topic":
            return topic is not None and topiclib.match(topic, self.value)
        if self.kind == "ip":
            return ip == self.value
        return False


class TraceManager:
    MAX_TRACES = 30  # reference caps concurrent traces

    def __init__(self, hooks, directory: str = "trace"):
        self.hooks = hooks
        self.dir = directory
        self.traces: Dict[str, TraceSpec] = {}
        self._files: Dict[str, object] = {}
        self._installed = False

    # ----------------------------------------------------------- lifecycle

    def start_trace(
        self, name: str, kind: str, value: str, end_at: Optional[float] = None
    ) -> TraceSpec:
        if name in self.traces:
            raise ValueError(f"trace {name!r} already exists")
        if len(self.traces) >= self.MAX_TRACES:
            raise ValueError("too many traces")
        if kind not in ("clientid", "topic", "ip"):
            raise ValueError(f"bad trace kind {kind!r}")
        os.makedirs(self.dir, exist_ok=True)
        path = os.path.join(self.dir, f"trace_{name}.log")
        spec = TraceSpec(name=name, kind=kind, value=value, path=path, end_at=end_at)
        self.traces[name] = spec
        self._files[name] = open(path, "a", buffering=1)
        self._ensure_hooks()
        return spec

    def stop_trace(self, name: str) -> bool:
        spec = self.traces.pop(name, None)
        f = self._files.pop(name, None)
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        if not self.traces:
            self._release_hooks()
        return spec is not None

    def list_traces(self) -> List[TraceSpec]:
        return list(self.traces.values())

    def stop_all(self) -> None:
        for name in list(self.traces):
            self.stop_trace(name)

    # -------------------------------------------------------------- events

    def _ensure_hooks(self) -> None:
        if self._installed:
            return
        self.hooks.put("message.publish", self._on_publish, priority=-500)
        self.hooks.put("session.subscribed", self._on_subscribed, priority=-500)
        self.hooks.put("session.unsubscribed", self._on_unsubscribed, priority=-500)
        self.hooks.put("client.connected", self._on_connected, priority=-500)
        self.hooks.put("message.delivered", self._on_delivered, priority=-500)
        self._installed = True

    def _release_hooks(self) -> None:
        """Mirror of _ensure_hooks: the last trace stopping removes the
        tracer from every hook chain, so an idle tracer costs the
        publish/deliver paths nothing."""
        if not self._installed:
            return
        self.hooks.delete("message.publish", self._on_publish)
        self.hooks.delete("session.subscribed", self._on_subscribed)
        self.hooks.delete("session.unsubscribed", self._on_unsubscribed)
        self.hooks.delete("client.connected", self._on_connected)
        self.hooks.delete("message.delivered", self._on_delivered)
        self._installed = False

    def _emit(self, event: str, clientid: str, topic: Optional[str],
              ip: Optional[str], extra: dict) -> None:
        now = time.time()
        for name, spec in list(self.traces.items()):
            if spec.end_at is not None and now >= spec.end_at:
                self.stop_trace(name)
                continue
            if not spec.matches(clientid, topic, ip):
                continue
            rec = {"ts": round(now, 6), "event": event, "clientid": clientid}
            if topic is not None:
                rec["topic"] = topic
            rec.update(extra)
            f = self._files.get(name)
            if f is not None:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def _on_publish(self, msg):
        ip = msg.headers.get("peername") if isinstance(msg.headers, dict) else None
        self._emit(
            "PUBLISH", msg.from_client, msg.topic, ip,
            {"qos": msg.qos, "retain": msg.retain, "payload_len": len(msg.payload)},
        )
        return None  # fold passthrough

    def _on_subscribed(self, clientid, filt, *a):
        self._emit("SUBSCRIBE", clientid, filt, None, {})

    def _on_unsubscribed(self, clientid, filt, *a):
        self._emit("UNSUBSCRIBE", clientid, filt, None, {})

    def _on_connected(self, clientinfo, *a):
        ip = getattr(clientinfo, "peername", None)
        self._emit("CONNECTED", clientinfo.clientid, None, ip, {})

    def _on_delivered(self, clientid, msg):
        self._emit("DELIVER", clientid, msg.topic, None, {"qos": msg.qos})
