"""Structured trace points + causal trace assertions — snabbkaffe analog.

The reference compiles `?tp(kind, #{...})` probes into prod code and
asserts on the causal event stream in tests via `?check_trace` /
`?strict_causality` (snabbkaffe 0.16.0; tracepoints in `emqx_cm.erl:129`,
`emqx_connection.erl`, `emqx_persistent_session.erl`, consumed by
`emqx_broker_SUITE`, `emqx_takeover_SUITE`, ... — SURVEY.md §4).

Here `tp(kind, **fields)` is a near-zero-cost call (one global check)
that records into the active collectors.  Tests wrap scenarios in
`check_trace()` and assert on the ordered event list:

    with check_trace() as t:
        ...drive the broker...
    t.assert_seen("session_takeover_begin", clientid="c1")
    t.strict_causality("publish_enter", "dispatch_done",
                       key=lambda e: e["msg_id"])

Events double as production tracing: a long-running collector can be
installed and drained (the `?tp` kinds also flow to logger in the
reference via the snk_kind compile flag).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

_collectors: List["TraceCollector"] = []
_lock = threading.Lock()
_active = False  # fast-path gate: tp() is one bool test when tracing is off

# Every tp("<kind>", ...) emitted from production code (emqx_tpu/**) MUST
# be registered here — dashboards and trace consumers key on these names,
# and an unregistered kind is an event nobody can subscribe to by
# contract.  The static-analysis gate (`tools/analysis/registry.py`)
# lints call sites against this registry in BOTH directions — emitted
# kinds must be registered, registrations must be emitted — (tests may
# emit ad-hoc kinds; only the package is linted).
KNOWN_KINDS: Dict[str, str] = {
    # broker publish path
    "publish_enter": "message accepted into the publish pipeline",
    "dispatch_done": "per-message dispatch finished (receivers counted)",
    # delivery plane (broker/delivery.py worker pool + listener.py
    # vectored transport flush)
    "deliver.batch": "one connection's per-tick delivery batch drained "
                     "by its shard worker",
    "deliver.backpressure": "a delivery shard (queue depth) or a slow "
                            "consumer (transport backlog) pushed back",
    "deliver.flush": "multi-frame action batch flushed to the "
                     "transport as one vectored write",
    # session lifecycle (emqx_cm analog)
    "session_created": "new session bound to a clientid",
    "session_resumed": "clean_start=false reattached to a parked session",
    "session_takeover_begin": "live session stolen by a new connection",
    "session_takeover_end": "takeover handshake finished",
    "session_discarded": "session dropped (clean start or kick)",
    # engine flight recorder (hybrid match arbitration)
    "engine.tick": "one match tick collected (path/reason/latency)",
    "engine.flip": "arbitration switched serving path (host<->device)",
    "engine.probe": "device warm-keeping probe dispatched or harvested",
    "engine.stall": "device fetch exceeded its timeout budget",
    "engine.churn": "one apply_churn batch applied to host truth",
    "engine.churn.shed": "churn ops shed: demand exceeded apply capacity",
    "engine.pipeline": "dispatch-window event (drain / window-full / "
                       "prep-degrade)",
    "engine.kcap": "adaptive compact-return cap shrank toward traffic",
    # fused prep pipeline (ops/prep.py + parallel/sharded.py): per-tick
    # sub-stage attribution of the formerly opaque prep phase
    "engine.prep.hash": "fused prep split+hash+memo+dedup sub-stage",
    "engine.prep.pack": "fused prep staging-buffer gather+pad sub-stage",
    "engine.prep.submit": "packed batch handed to the mesh dispatch "
                          "(group assembly + device_put; group = "
                          "coalesced prep-ahead ticks in one dispatch)",
    # table checkpoint & warm restart (checkpoint/ subsystem)
    "engine.ckpt.save": "table snapshot persisted; WAL acked to watermark",
    "engine.ckpt.restore": "warm restart: snapshot loaded + WAL tail replayed",
    "engine.ckpt.fallback": "newest snapshot corrupt; older one restored",
    "engine.ckpt.wal": "churn record appended to the write-ahead log",
    # durable message log (ds/ subsystem: sharded streams + cursors)
    "ds.append": "message appended to a shard's durable topic stream",
    "ds.flush": "write-behind buffer flushed + fsync'd (bytes watermark "
                "or interval)",
    "ds.replay": "session resume rebuilt its mqueue from the log cursor",
    "ds.gc": "retention GC dropped one sealed generation (forced = past "
             "a lagging cursor; replay reports the gap)",
    # ds append replication (ds/repl.py + cluster/node.py takeover)
    "ds.repl.ship": "leader shipped one flushed range; the follower's "
                    "ack advanced the replicated watermark",
    "ds.repl.mirror": "follower appended a replicated range to its "
                      "mirror shard log (fsync'd before the ack left)",
    "ds.repl.degrade": "shard replication degraded to leader-only "
                       "appends, or healed (state field)",
    "ds.repl.catchup": "heal-time catch-up re-shipped a range read "
                       "back from the leader's own durable log",
    "ds.repl.handoff": "cross-node takeover served/imported in cursor-"
                       "handoff form — session + unreplicated tail, "
                       "never a materialized queue",
    # retained device index (models/retained.py + broker/retainer.py):
    # bucketed name index probed by batched compact dispatches, trie/
    # index arbitration mirroring the publish engine
    "retained.lookup": "one batched retained-index dispatch collected "
                       "(filters/latency/wire bytes)",
    "retained.shape": "wildcard shape registered into (or rejected "
                      "from) the retained key plane",
    "retained.merge": "retained entry tail merged into the sorted main "
                      "(or zombie compaction)",
    "retained.kcap": "retained candidate-window cap shrank toward "
                     "observed fan-in",
    "retained.flip": "retainer arbitration switched serving path "
                     "(trie<->index)",
    "retained.probe": "retained-index warm-keeping probe dispatched or "
                      "harvested",
    # fault injection + self-healing (fault/, cluster data plane, engine)
    "fault.inject": "a configured fault fired at a registered site",
    "cluster.peer.miss": "heartbeat ping to a peer went unanswered",
    "cluster.peer.health": "peer health transition (up/degraded/down, "
                           "incl. link breaker open/close)",
    "cluster.forward.spool": "QoS>=1 forward queued in the replay spool",
    "cluster.forward.replay": "spooled forwards replayed after a heal",
    "engine.breaker": "device-path circuit breaker opened or closed",
    # process-sharded wire plane (emqx_tpu/wire/ supervisor + the
    # accept-path limiter in broker/listener.py)
    "olp.accept.shed": "accept-rate bucket refused a new socket before "
                       "any protocol work (wire.max_conn_rate)",
    "wire.worker.spawn": "wire-worker process spawned (or respawned "
                         "after a crash, with backoff)",
    "wire.worker.exit": "wire-worker process exited; sessions park and "
                        "QoS>=1 forwards spool until the respawn heals "
                        "the IPC link",
    # shared-memory match plane (emqx_tpu/shm/)
    "shm.degrade": "worker's shm client changed serving state "
                   "(hub-down/hub-up on heartbeat age, or a tick "
                   "timed out to the local trie)",
    "shm.reregister": "worker re-registered with the hub after a hub "
                      "generation bump (rings reset, filters replayed)",
    "shm.reclaim": "hub dropped a dead worker incarnation's filters "
                   "(worker generation bump or fresh HELLO)",
    "shm.churn": "hub applied a worker churn record to the shared "
                 "engine (registry-of-record write)",
    "shm.group": "hub fused match ticks from multiple worker lanes "
                 "into one device dispatch",
    "shm.hub_stale": "hub heartbeat went stale: the worker fell back "
                     "to all-local matching (shm_hub_degraded alarm "
                     "raises off the same observation)",
    "shm.ack_shed": "hub shed queued churn acks for a worker whose "
                    "result ring stayed full past 4x ring depth (the "
                    "stuck-worker tell before its eventual "
                    "re-register)",
    "shm.credit": "a lane hit its per-pass drain credit "
                  "(shm.lane_credit) with records still queued; the "
                  "surplus carries over round-robin so siblings are "
                  "not starved",
    "shm.semq": "hub applied a worker semantic-query churn record to "
                "the shared query table (registry-of-record write, "
                "the K_SEMQ twin of shm.churn)",
    # semantic subscription plane (emqx_tpu/semantic/)
    "semantic.query": "a $semantic query entered or left the query "
                      "table (worker-local plane or hub registry)",
    "semantic.degrade": "a publish was matched by the exact host path "
                        "because the device/hub path was unavailable",
    "semantic.flip": "the semantic arbiter switched serving path "
                     "(device top-k <-> exact host) on EWMA rates",
    "semantic.probe": "idle-path re-measure dispatched by the "
                      "semantic arbiter (doubles as device warm-keep)",
    "semantic.refetch": "device top-k overflowed threshold at kcap; "
                        "dense re-fetch served the tick and kcap "
                        "widened",
    "semantic.forward": "origin broker forwarded a publish to a "
                        "remote node's semantic subscribers by hub "
                        "query id",
    # ds append replication mirror retention (ds/repl.py)
    "ds.repl.mirror_gc": "follower dropped sealed mirror generations "
                         "wholly below the leader's retention floor "
                         "(bounded-disk contract)",
}


def tp(kind: str, **fields: Any) -> None:
    """Emit a structured trace event (no-op unless a collector is active)."""
    if not _active:
        return
    evt = {"kind": kind, "ts": time.monotonic(), **fields}
    with _lock:
        for c in _collectors:
            c._events.append(evt)


class TraceAssertionError(AssertionError):
    pass


class TraceCollector:
    def __init__(self):
        self._events: List[Dict[str, Any]] = []

    # ------------------------------------------------------------- capture

    def __enter__(self) -> "TraceCollector":
        global _active
        with _lock:
            _collectors.append(self)
            _active = True
        return self

    def __exit__(self, *exc) -> None:
        global _active
        with _lock:
            if self in _collectors:
                _collectors.remove(self)
            _active = bool(_collectors)

    @property
    def events(self) -> List[Dict[str, Any]]:
        with _lock:
            return list(self._events)

    def drain(self) -> List[Dict[str, Any]]:
        with _lock:
            out, self._events = self._events, []
            return out

    # ------------------------------------------------------------- queries

    def of_kind(self, kind: str) -> List[Dict[str, Any]]:
        return [e for e in self.events if e["kind"] == kind]

    def find(self, kind: str, **match: Any) -> List[Dict[str, Any]]:
        out = []
        for e in self.of_kind(kind):
            if all(e.get(k) == v for k, v in match.items()):
                out.append(e)
        return out

    # ---------------------------------------------------------- assertions

    def assert_seen(self, kind: str, n: Optional[int] = None, **match: Any):
        got = self.find(kind, **match)
        if not got or (n is not None and len(got) != n):
            raise TraceAssertionError(
                f"expected {'%d×' % n if n is not None else ''} {kind!r} "
                f"matching {match}, saw {len(got)} "
                f"(kinds present: {sorted({e['kind'] for e in self.events})})")
        return got

    def assert_not_seen(self, kind: str, **match: Any) -> None:
        got = self.find(kind, **match)
        if got:
            raise TraceAssertionError(f"unexpected {kind!r} events: {got[:3]}")

    def assert_order(self, *kinds: str) -> None:
        """The FIRST occurrence of each kind appears in the given order."""
        firsts = []
        for k in kinds:
            evs = self.of_kind(k)
            if not evs:
                raise TraceAssertionError(f"kind {k!r} never seen")
            firsts.append(evs[0]["ts"])
        if firsts != sorted(firsts):
            raise TraceAssertionError(
                f"order violated: {list(zip(kinds, firsts))}")

    def strict_causality(self, cause: str, effect: str,
                         key: Callable[[Dict[str, Any]], Any]) -> None:
        """?strict_causality: every `cause` has a LATER matching `effect`,
        and no effect without a cause (matched by `key`)."""
        causes: Dict[Any, float] = {}
        for e in self.of_kind(cause):
            causes.setdefault(key(e), e["ts"])
        effects: Dict[Any, float] = {}
        for e in self.of_kind(effect):
            effects.setdefault(key(e), e["ts"])
        for k, ts in causes.items():
            if k not in effects:
                raise TraceAssertionError(
                    f"cause {cause!r} key={k!r} has no {effect!r}")
            if effects[k] < ts:
                raise TraceAssertionError(
                    f"effect {effect!r} key={k!r} precedes its cause")
        orphans = set(effects) - set(causes)
        if orphans:
            raise TraceAssertionError(
                f"{effect!r} without {cause!r}: keys {sorted(orphans)[:5]}")

    def pairs(self, open_kind: str, close_kind: str,
              key: Callable[[Dict[str, Any]], Any]) -> None:
        """Balanced open/close pairs (e.g. lock acquire/release)."""
        depth: Dict[Any, int] = {}
        for e in self.events:
            if e["kind"] == open_kind:
                depth[key(e)] = depth.get(key(e), 0) + 1
            elif e["kind"] == close_kind:
                k = key(e)
                if depth.get(k, 0) <= 0:
                    raise TraceAssertionError(
                        f"{close_kind!r} key={k!r} without open")
                depth[k] -= 1
        bad = {k: d for k, d in depth.items() if d != 0}
        if bad:
            raise TraceAssertionError(f"unbalanced pairs: {bad}")


def check_trace() -> TraceCollector:
    """`?check_trace` entry point for tests."""
    return TraceCollector()
