"""Versioned RPC contracts — the BPAPI analog.

The reference pins every cross-node call behind a versioned api module
(`apps/emqx/src/proto/emqx_broker_proto_v1.erl`) and statically checks
call sites (`apps/emqx/src/bpapi/emqx_bpapi_static_checks.erl`), so a
rolling upgrade never sends a node an RPC it cannot serve.

Here the contract table IS the registry: every cluster-visible method
declares the versions this node can SERVE and the minimum it may CALL.
Nodes exchange their tables in the HELLO and each side computes the
negotiated version per method; calling a method the peer cannot serve
fails loudly at call time instead of as an opaque remote error.

`check_handlers` is the static-check analog: it verifies at startup
that every method this node claims to serve has a registered handler.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .transport import RpcError

#: method -> (min_version, max_version) this build can SERVE.
#: Bump max when a method's semantics/shape change; keep serving old
#: versions until every deployment has crossed the boundary.
CONTRACTS: Dict[str, Tuple[int, int]] = {
    "publish": (1, 1),          # management publish proxy
    "remote_snapshot": (1, 1),  # core-mirrored route snapshot
    "cluster_commit": (1, 1),   # cluster_rpc MFA log commit
    "cluster_apply": (1, 1),
    "cluster_catchup": (1, 1),
    "lock_acquire": (1, 1),     # distributed locker (cluster/locker.py)
    "lock_release": (1, 1),
    # cross-node session migration; v2 adds the cursor-handoff form:
    # the caller offers its ds mirror coverage and the origin may
    # answer with session + unreplicated tail instead of a
    # materialized queue (ds/repl.py)
    "session_takeover": (1, 2),
}


def announce() -> Dict[str, List[int]]:
    """The HELLO payload: method -> [min, max] served versions.

    The table is static per release, like the reference's bpapi modules:
    wiring order (ClusterRpc may attach after links come up) must not
    change what a node advertises.  A declared-but-unwired method fails
    at the remote as a plain RpcError, which every fan-out caller
    already skips per-peer; `check_handlers` warns at startup."""
    return {m: [lo, hi] for m, (lo, hi) in CONTRACTS.items()}


def negotiate(peer_table: Optional[Dict[str, List[int]]]
              ) -> Dict[str, int]:
    """Per-method negotiated version against a peer's announcement.

    A legacy peer that announced nothing is assumed to serve v1 of
    everything (the pre-bpapi wire); methods with no version overlap are
    omitted — `version_for` then refuses the call.
    """
    if not peer_table:
        return {m: 1 for m in CONTRACTS}
    out: Dict[str, int] = {}
    for method, (lo, hi) in CONTRACTS.items():
        peer = peer_table.get(method)
        if peer is None:
            continue  # peer cannot serve it at all
        plo, phi = int(peer[0]), int(peer[1])
        best = min(hi, phi)
        if best >= max(lo, plo):
            out[method] = best
    return out


class IncompatiblePeer(RpcError):
    """Subclasses RpcError so per-peer `except RpcError` skip paths
    (cluster_rpc multicall fan-out, catch-up) treat a version-skewed
    peer like an unreachable one instead of aborting the whole round."""


def version_for(negotiated: Dict[str, int], method: str) -> int:
    v = negotiated.get(method)
    if v is None:
        raise IncompatiblePeer(
            f"peer cannot serve rpc {method!r} at any compatible version"
        )
    return v


def check_handlers(rpc_handlers: Dict[str, object]) -> List[str]:
    """Static-check analog: every served contract needs a handler.
    Returns the list of missing handlers (callers decide to raise/log)."""
    return sorted(m for m in CONTRACTS if m not in rpc_handlers)
