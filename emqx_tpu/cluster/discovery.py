"""Cluster discovery strategies — the ekka autocluster analog.

The reference picks peers via `cluster.discovery_strategy`:
static | mcast | dns | etcd | k8s (`emqx_conf_schema.erl:148-230`).
Here a strategy is anything with `discover() -> Dict[name, (host, port)]`;
`ClusterNode` polls it and joins newly seen peers.  DNS resolution and
the etcd/k8s HTTP fetches are injectable for tests and for hosts where
the backing service exists.
"""

from __future__ import annotations

import json
import logging
import socket
from typing import Callable, Dict, List, Optional, Tuple

log = logging.getLogger("emqx_tpu.cluster.discovery")

Addr = Tuple[str, int]


class StaticDiscovery:
    """Fixed seed list (`discovery_strategy = static`)."""

    def __init__(self, seeds: Dict[str, Addr]):
        self.seeds = dict(seeds)

    def discover(self) -> Dict[str, Addr]:
        return dict(self.seeds)


class DnsDiscovery:
    """A/AAAA record discovery (`discovery_strategy = dns`): every
    address behind `name` is a cluster node listening on `port`.  Node
    names follow the reference's `<app>@<ip>` convention."""

    def __init__(
        self,
        name: str,
        port: int,
        app: str = "emqx_tpu",
        resolver: Optional[Callable[[str], List[str]]] = None,
    ):
        self.name = name
        self.port = port
        self.app = app
        self.resolver = resolver or self._system_resolve

    @staticmethod
    def _system_resolve(name: str) -> List[str]:
        try:
            infos = socket.getaddrinfo(name, None, type=socket.SOCK_STREAM)
        except OSError as e:
            log.info("dns discovery: %s: %s", name, e)
            return []
        return sorted({i[4][0] for i in infos})

    def discover(self) -> Dict[str, Addr]:
        return {
            f"{self.app}@{ip}": (ip, self.port)
            for ip in self.resolver(self.name)
        }


class HttpKvDiscovery:
    """etcd/k8s-style discovery: GET a url returning a JSON object of
    node -> [host, port] (the etcd prefix scan / k8s endpoints shape,
    `emqx_conf_schema.erl:190-230`).  The fetcher is injectable; the
    default uses urllib so a real etcd/k8s proxy endpoint works when
    reachable."""

    def __init__(self, url: str, fetch: Optional[Callable[[str], bytes]] = None,
                 timeout: float = 5.0):
        self.url = url
        self.timeout = timeout
        self.fetch = fetch or self._http_get

    def _http_get(self, url: str) -> bytes:
        import urllib.request

        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            return r.read()

    def discover(self) -> Dict[str, Addr]:
        try:
            obj = json.loads(self.fetch(self.url))
        except Exception as e:
            log.info("kv discovery %s failed: %s", self.url, e)
            return {}
        out: Dict[str, Addr] = {}
        for name, addr in (obj or {}).items():
            try:
                out[str(name)] = (str(addr[0]), int(addr[1]))
            except (TypeError, ValueError, IndexError):
                continue
        return out


def make_discovery(kind: str, **cfg):
    if kind == "static":
        seeds = {
            name: (a[0], int(a[1]))
            for name, a in (cfg.get("seeds") or {}).items()
        }
        return StaticDiscovery(seeds)
    if kind == "dns":
        return DnsDiscovery(cfg["name"], int(cfg["port"]),
                            app=cfg.get("app", "emqx_tpu"))
    if kind in ("etcd", "k8s", "http"):
        return HttpKvDiscovery(cfg["url"])
    raise ValueError(f"unknown discovery strategy {kind!r}")
