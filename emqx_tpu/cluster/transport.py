"""Inter-node transport: framed asyncio TCP with RPC + push channels.

The gen_rpc analog (reference `emqx_rpc.erl`, gen_rpc dep — SURVEY.md
§1.8): every node runs one TCP server; for each peer it also dials ONE
outbound link used for all of its originated traffic (route ops, pings,
forwards, rpc requests).  Responses ride back on the same socket, so a
pair of nodes uses two sockets total — one per direction — and there is
no head-of-line blocking between control RPC and the forward data plane
beyond the socket itself (frames are small and length-prefixed).

Frame layout:  u32 len | u8 type | body
  JSON frames: body = utf-8 JSON
  FORWARD:     body = u16 hlen | JSON header | raw payload bytes

Addressing: a peer address is either a ("host", port) TCP endpoint or a
("unix", path) UNIX-domain endpoint.  The unix variant carries the
process-sharded wire plane (emqx_tpu/wire/): co-hosted wire workers are
zero-latency peers, and a local socketpair hop must not pay the TCP
loopback tax (checksum, nagle, conntrack).  Everything above the dial —
HELLO auth, frames, RPC matching, reconnect/breaker — is shared.

The FORWARD header is an open JSON map; optional fields ride end to
end through relays and the forward spool without a frame-format bump —
`relay_to` (core relay target), `shared_group`/`shared_filt` (targeted
shared delivery), `replay` (spool-replay dedup hint), and `span_t0`
(message-lifecycle span context: origin publish-ingress wall clock, so
the remote broker closes and reports the cross-node latency leg —
observe/spans.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import itertools
import json
import logging
import os
import random
import struct
from typing import Awaitable, Callable, Dict, Optional, Tuple

from .. import fault as _fault
from ..observe.tracepoints import tp

log = logging.getLogger("emqx_tpu.cluster.transport")

# frame types
HELLO = 1
PING = 2
PONG = 3
ROUTE_OP = 4
SNAPSHOT_REQ = 5
SNAPSHOT = 6
FORWARD = 7
FORWARD_ACK = 8
RPC_REQ = 9
RPC_RESP = 10
REPL = 11
REPL_ACK = 12

MAX_FRAME = 64 * 1024 * 1024


class RpcError(Exception):
    pass


def is_unix_addr(addr) -> bool:
    """("unix", <path>) peer addresses dial a UNIX-domain socket."""
    return (
        isinstance(addr, (tuple, list))
        and len(addr) == 2
        and addr[0] == "unix"
    )


def check_addr(addr) -> Tuple[str, object]:
    """Normalize a configured peer address: ("unix", path) stays as-is,
    anything else must coerce to (host, int port)."""
    if is_unix_addr(addr):
        return ("unix", str(addr[1]))
    return (str(addr[0]), int(addr[1]))


async def dial(addr) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    if is_unix_addr(addr):
        return await asyncio.open_unix_connection(addr[1])
    return await asyncio.open_connection(*addr)


def hello_auth(cookie: str, node: str, incarnation, nonce: str) -> str:
    """Keyed proof of the shared cluster cookie for the HELLO exchange.

    The reference gates node joins on the Erlang distribution cookie;
    here the cookie never crosses the wire — each side proves it with
    HMAC(cookie, node:incarnation:peer_nonce).  Binding to the PEER's
    fresh nonce makes a captured frame worthless for replay.
    """
    return hmac.new(
        cookie.encode(),
        f"{node}:{incarnation}:{nonce}".encode(),
        hashlib.sha256,
    ).hexdigest()


def check_hello_auth(cookie: str, obj: dict, nonce: str) -> bool:
    want = hello_auth(
        cookie, obj.get("node", "?"), obj.get("incarnation"), nonce
    )
    return hmac.compare_digest(want, obj.get("auth") or "")


def _pack(ftype: int, body: bytes) -> bytes:
    return struct.pack("!IB", len(body) + 1, ftype) + body


def pack_json(ftype: int, obj: dict) -> bytes:
    return _pack(ftype, json.dumps(obj, separators=(",", ":")).encode())


def pack_forward_body(header: dict, payload: bytes) -> bytes:
    """FORWARD frame body (no length/type prefix) — also the forward
    spool's on-queue record format (cluster/node.py)."""
    h = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack("!H", len(h)) + h + payload


def pack_forward(header: dict, payload: bytes) -> bytes:
    return _pack(FORWARD, pack_forward_body(header, payload))


def pack_repl(header: dict, payload: bytes) -> bytes:
    """REPL frame: one ds append-replication range (FORWARD body layout —
    u16 hlen | JSON header | raw record blob; see ds/repl.py)."""
    return _pack(REPL, pack_forward_body(header, payload))


def unpack_forward(body: bytes) -> Tuple[dict, bytes]:
    (hlen,) = struct.unpack_from("!H", body)
    header = json.loads(body[2 : 2 + hlen])
    return header, body[2 + hlen :]


async def read_frame(reader: asyncio.StreamReader) -> Tuple[int, bytes]:
    hdr = await reader.readexactly(4)
    (n,) = struct.unpack("!I", hdr)
    if not 1 <= n <= MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    body = await reader.readexactly(n)
    return body[0], body[1:]


class PeerLink:
    """Outbound connection to one peer; owns reconnect + request matching.

    Reconnects use jittered exponential backoff (`reconnect_ivl` base
    doubling to `reconnect_max`, ±50% jitter so a cluster-wide restart
    does not produce synchronized dial storms) instead of the old fixed
    0.5 s hammer.  `fails` counts consecutive connect/connection
    failures; at `breaker_threshold` the link's circuit breaker is open
    (`health` == "down") — dials continue at the max backoff as the
    half-open probe, and the first successful HELLO closes it."""

    def __init__(
        self,
        self_node: str,
        peer: str,
        addr: Tuple[str, int],
        incarnation: int,
        on_up: Callable[["PeerLink", dict], None],
        on_down: Callable[["PeerLink"], None],
        reconnect_ivl: float = 0.5,
        cookie: str = "",
        extra_hello: Optional[dict] = None,  # role/addr advertisement
        reconnect_max: float = 15.0,
        breaker_threshold: int = 5,
    ):
        self.self_node = self_node
        self.peer = peer
        self.addr = addr
        self.incarnation = incarnation
        self.on_up = on_up
        self.on_down = on_down
        self.reconnect_ivl = reconnect_ivl
        self.reconnect_max = reconnect_max
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.cookie = cookie
        self.extra_hello = dict(extra_hello or {})
        self._auth_warned = False
        self.connected = False
        self.fails = 0  # consecutive dial/connection failures
        self.peer_hello: dict = {}
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reqs: Dict[int, asyncio.Future] = {}
        self._req_id = itertools.count(1)
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    @property
    def breaker_open(self) -> bool:
        return not self.connected and self.fails >= self.breaker_threshold

    @property
    def health(self) -> str:
        """up (connected) | degraded (reconnecting, breaker closed) |
        down (breaker open)."""
        if self.connected:
            return "up"
        return "down" if self.fails >= self.breaker_threshold else "degraded"

    def _backoff(self) -> float:
        """Jittered exponential reconnect delay for the current streak."""
        d = min(
            self.reconnect_ivl * (2 ** max(self.fails - 1, 0)),
            self.reconnect_max,
        )
        return d * (0.5 + random.random())

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
        self._teardown()

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await _fault.ainject("transport.dial", err=ConnectionError)
                reader, writer = await dial(self.addr)
                self._writer = writer
                # 1. server opens with HELLO{"challenge": nonce}
                ftype, body = await read_frame(reader)
                if ftype != HELLO:
                    raise ConnectionError("expected server challenge")
                server_nonce = json.loads(body).get("challenge", "")
                # 2. our HELLO proves the cookie against the server nonce
                #    and carries our own nonce for the server's proof
                my_nonce = os.urandom(16).hex()
                my_hello = {
                    "node": self.self_node,
                    "incarnation": self.incarnation,
                    "challenge": my_nonce,
                    **self.extra_hello,
                }
                if self.cookie:
                    my_hello["auth"] = hello_auth(
                        self.cookie, self.self_node, self.incarnation,
                        server_nonce,
                    )
                writer.write(pack_json(HELLO, my_hello))
                await writer.drain()
                # 3. greeting proves the server's cookie against our nonce
                ftype, body = await read_frame(reader)
                if ftype != HELLO:
                    raise ConnectionError("expected HELLO")
                greeting = json.loads(body)
                if greeting.get("error"):
                    if not self._auth_warned:
                        self._auth_warned = True
                        log.warning(
                            "peer %s rejected hello: %s",
                            self.peer,
                            greeting["error"],
                        )
                    raise ConnectionError(f"hello rejected: {greeting['error']}")
                if self.cookie and not check_hello_auth(
                    self.cookie, greeting, my_nonce
                ):
                    if not self._auth_warned:
                        self._auth_warned = True
                        log.warning(
                            "peer %s failed cookie verification", self.peer
                        )
                    raise ConnectionError("peer failed cookie verification")
                self.peer_hello = greeting
                self.connected = True
                if self.fails >= self.breaker_threshold:
                    tp("cluster.peer.health", peer=self.peer, state="up",
                       breaker="closed", fails=self.fails)
                self.fails = 0
                self.on_up(self, self.peer_hello)
                await self._read_loop(reader)
            except asyncio.CancelledError:
                raise  # stop() cancelled us: propagate, don't reconnect
            except Exception:
                pass
            was_up = self.connected
            self._teardown()
            self.fails += 1
            if self.fails == self.breaker_threshold:
                tp("cluster.peer.health", peer=self.peer, state="down",
                   breaker="open", fails=self.fails)
            if was_up:
                self.on_down(self)
            if not self._stopped:
                await asyncio.sleep(self._backoff())

    def _teardown(self) -> None:
        self.connected = False
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass
            self._writer = None
        for fut in self._reqs.values():
            if not fut.done():
                fut.set_exception(RpcError(f"link to {self.peer} lost"))
        self._reqs.clear()

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        while True:
            ftype, body = await read_frame(reader)
            if _fault.enabled():
                a = await _fault.ainject("transport.recv", err=ConnectionError)
                if a is not None and a.kind in ("drop", "corrupt"):
                    continue  # frame lost on the floor
            if ftype in (PONG, RPC_RESP, SNAPSHOT, FORWARD_ACK, REPL_ACK):
                obj = json.loads(body)
                fut = self._reqs.pop(obj.get("id", -1), None)
                if fut is not None and not fut.done():
                    if obj.get("error"):
                        fut.set_exception(RpcError(obj["error"]))
                    else:
                        fut.set_result(obj)

    # ------------------------------------------------------------ sending

    def send_nowait(self, frame: bytes) -> bool:
        """Fire-and-forget (async forward mode). False if link is down
        or the socket queue refuses the frame — callers must COUNT or
        SPOOL a False, never ignore it."""
        if not self.connected or self._writer is None:
            return False
        if _fault.enabled():
            a = _fault.inject("transport.send", err=ConnectionError)
            if a is not None:
                if a.kind == "drop":
                    return False
                if a.kind == "corrupt":
                    frame = a.corrupt(frame)
        try:
            self._writer.write(frame)
            return True
        except Exception:
            return False

    async def request(self, ftype: int, obj: dict, timeout: float = 5.0) -> dict:
        """Send a JSON frame and await the matching response by id."""
        if not self.connected or self._writer is None:
            raise RpcError(f"link to {self.peer} down")
        rid = next(self._req_id)
        obj = dict(obj, id=rid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._reqs[rid] = fut
        dropped = None
        if _fault.enabled():
            dropped = _fault.inject("transport.send", err=False)
        if dropped is None or dropped.kind not in ("drop", "error"):
            # a dropped request frame is simply never written: the
            # matching response never arrives and the timeout below
            # surfaces it as an RpcError, exactly like real frame loss
            self._writer.write(pack_json(ftype, obj))
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._reqs.pop(rid, None)
            raise RpcError(f"timeout waiting on {self.peer}")

    async def rpc(self, method: str, params: dict, timeout: float = 5.0) -> dict:
        resp = await self.request(
            RPC_REQ, {"method": method, "params": params}, timeout
        )
        return resp.get("result", {})

    async def forward_request(
        self, header: dict, payload: bytes, timeout: float = 5.0
    ) -> Optional[dict]:
        """Acked (sync-mode) forward; None if the link was down."""
        if not self.connected or self._writer is None:
            return None
        rid = next(self._req_id)
        header = dict(header, id=rid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._reqs[rid] = fut
        if not self.send_nowait(pack_forward(header, payload)):
            self._reqs.pop(rid, None)
            return None
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._reqs.pop(rid, None)
            raise RpcError(f"forward timeout on {self.peer}")

    async def repl_request(
        self, header: dict, payload: bytes, timeout: float = 5.0
    ) -> Optional[dict]:
        """Ship one ds replication range and await the follower's
        durable ack (ds/repl.py); None if the link was down."""
        if not self.connected or self._writer is None:
            return None
        rid = next(self._req_id)
        header = dict(header, id=rid)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._reqs[rid] = fut
        if not self.send_nowait(pack_repl(header, payload)):
            self._reqs.pop(rid, None)
            return None
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            self._reqs.pop(rid, None)
            raise RpcError(f"repl ack timeout on {self.peer}")


class Transport:
    """Server side: accepts inbound links, dispatches frames to handlers.

    Handlers (set by ClusterNode):
      on_hello(peer_name, hello) -> dict          greeting response fields
      on_route_op(peer_name, obj)
      on_snapshot_req(peer_name, obj) -> dict
      on_forward(peer_name, header, payload) -> Optional[dict]  ack fields
      on_repl(peer_name, header, payload) -> Optional[dict]     ack fields
      rpc_handlers[method](peer_name, params) -> dict | Awaitable[dict]
    """

    def __init__(self, node: str, host: str = "127.0.0.1", port: int = 0,
                 cookie: str = "", unix_path: Optional[str] = None):
        self.node = node
        self.host = host
        self.port = port
        # optional UNIX-domain server alongside the TCP one (wire-plane
        # IPC): same _handle, same frames — a local peer just dials the
        # path instead of the port
        self.unix_path = unix_path
        self.cookie = cookie
        self.on_hello: Callable[[str, dict], dict] = lambda p, h: {}
        self.on_route_op: Callable[[str, dict], None] = lambda p, o: None
        self.on_snapshot_req: Callable[[str, dict], dict] = lambda p, o: {}
        self.on_forward: Callable[[str, dict, bytes], Optional[dict]] = (
            lambda p, h, b: None
        )
        # ds append replication (ds/repl.py mirror appends); the default
        # never acks, so a leader shipping at a node with no replicator
        # times out and degrades instead of wedging
        self.on_repl: Callable[[str, dict, bytes], Optional[dict]] = (
            lambda p, h, b: None
        )
        self.rpc_handlers: Dict[str, Callable] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._unix_server: Optional[asyncio.base_events.Server] = None
        self._inbound: set = set()  # live inbound writers, closed on stop
        # inbound RPCs run on a bounded pool, keyed by peer so one node's
        # requests execute in order (the gen_server serialization the
        # reference gets for free) and a flood cannot spawn unbounded
        # tasks (emqx_pool analog)
        self._rpc_pool: Optional["WorkerPool"] = None

    async def start(self) -> None:
        from ..utils.pool import WorkerPool

        self._rpc_pool = WorkerPool(
            size=4, queue_size=1000, name=f"rpc@{self.node}"
        ).start()
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.unix_path:
            # a stale socket file from a kill -9'd predecessor refuses
            # the bind; the supervisor guarantees single ownership of
            # the path, so unlink-then-bind is safe here
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
            self._unix_server = await asyncio.start_unix_server(
                self._handle, path=self.unix_path
            )

    async def stop(self) -> None:
        if self._server is not None or self._unix_server is not None:
            for w in list(self._inbound):
                try:
                    w.close()
                except Exception:
                    pass
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._unix_server is not None:
            self._unix_server.close()
            await self._unix_server.wait_closed()
            self._unix_server = None
            try:
                os.unlink(self.unix_path)
            except OSError:
                pass
        if self._rpc_pool is not None:
            await self._rpc_pool.stop(drain=False)
            self._rpc_pool = None

    async def _handle(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer_name = "?"
        self._inbound.add(writer)
        rpc_tasks: set = set()
        # RPC handlers may themselves RPC back over other links (e.g.
        # cluster_commit -> cluster_apply -> cluster_catchup), so they run
        # as tasks — the read loop keeps draining PING/FORWARD/ROUTE_OP
        # frames meanwhile; wlock serializes interleaved response writes
        wlock = asyncio.Lock()

        async def run_rpc_bg(obj: dict) -> None:
            resp = await self._run_rpc(peer_name, obj)
            try:
                async with wlock:
                    writer.write(pack_json(RPC_RESP, resp))
                    await writer.drain()
            except (ConnectionError, RuntimeError):
                pass  # peer gone before the response could be written

        try:
            # 1. open with a fresh challenge; the peer's cookie proof must
            #    be bound to it (replayed HELLOs verify against a stale
            #    nonce and fail)
            my_nonce = os.urandom(16).hex()
            writer.write(pack_json(HELLO, {"challenge": my_nonce}))
            await writer.drain()
            ftype, body = await read_frame(reader)
            if ftype != HELLO:
                return
            hello = json.loads(body)
            peer_name = hello.get("node", "?")
            if self.cookie and not check_hello_auth(
                self.cookie, hello, my_nonce
            ):
                log.warning(
                    "rejecting link from %s: bad cluster cookie", peer_name
                )
                writer.write(pack_json(HELLO, {"error": "bad_cookie"}))
                await writer.drain()
                return
            greeting = {"node": self.node}
            greeting.update(self.on_hello(peer_name, hello) or {})
            if self.cookie:
                greeting["auth"] = hello_auth(
                    self.cookie,
                    self.node,
                    greeting.get("incarnation"),
                    hello.get("challenge", ""),
                )
            writer.write(pack_json(HELLO, greeting))
            await writer.drain()
            while True:
                ftype, body = await read_frame(reader)
                if _fault.enabled():
                    a = await _fault.ainject(
                        "transport.recv", err=ConnectionError
                    )
                    if a is not None and a.kind in ("drop", "corrupt"):
                        continue  # inbound frame lost on the floor
                if ftype == RPC_REQ:
                    obj = json.loads(body)
                    pool = self._rpc_pool
                    if pool is None:
                        await run_rpc_bg(obj)  # stopping: best effort
                    else:
                        # bounded backpressure: when the worker queue is
                        # full this awaits ADMISSION (one queued item
                        # draining), not a handler's full runtime — so a
                        # flood stalls this peer's reads briefly without
                        # starving PING/FORWARD for seconds or spawning
                        # unbounded tasks
                        await pool.submit_to_wait(
                            peer_name, lambda o=obj: run_rpc_bg(o)
                        )
                    continue
                async with wlock:
                    if ftype == PING:
                        obj = json.loads(body)
                        writer.write(pack_json(PONG, {"id": obj.get("id")}))
                    elif ftype == ROUTE_OP:
                        self.on_route_op(peer_name, json.loads(body))
                    elif ftype == SNAPSHOT_REQ:
                        obj = json.loads(body)
                        resp = self.on_snapshot_req(peer_name, obj)
                        resp["id"] = obj.get("id")
                        writer.write(pack_json(SNAPSHOT, resp))
                    elif ftype == FORWARD:
                        header, payload = unpack_forward(body)
                        ack = self.on_forward(peer_name, header, payload)
                        if ack is not None and header.get("id") is not None:
                            ack["id"] = header["id"]
                            writer.write(pack_json(FORWARD_ACK, ack))
                    elif ftype == REPL:
                        header, payload = unpack_forward(body)
                        ack = self.on_repl(peer_name, header, payload)
                        if ack is not None and header.get("id") is not None:
                            ack["id"] = header["id"]
                            writer.write(pack_json(REPL_ACK, ack))
                    await writer.drain()
        except asyncio.CancelledError:
            raise  # server shutdown cancels handlers; finally cleans up
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            for t in rpc_tasks:
                t.cancel()
            self._inbound.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _run_rpc(self, peer_name: str, obj: dict) -> dict:
        method = obj.get("method", "")
        handler = self.rpc_handlers.get(method)
        if handler is None:
            return {"id": obj.get("id"), "error": f"no such method {method!r}"}
        try:
            result = handler(peer_name, obj.get("params") or {})
            if isinstance(result, Awaitable):
                result = await result
            return {"id": obj.get("id"), "result": result or {}}
        except Exception as e:  # rpc errors propagate to the caller
            return {"id": obj.get("id"), "error": f"{type(e).__name__}: {e}"}
