"""Cluster-wide locks — the ekka_locker / emqx_cm_locker analog.

The reference serializes session takeover per clientid with a
distributed lock (`emqx_cm_locker:trans`, `emqx_cm.erl:225` open_session
path).  Here lock state lives on ONE deterministic authority — the
lexicographically-smallest live core node — and every node acquires by
RPC (`lock_acquire` / `lock_release`, versioned in bpapi.py).  Leases
bound the damage of a crashed holder: an expired lock is simply granted
to the next caller, matching ekka_locker's best-effort semantics (locks
do not survive an authority failover either — they guard short critical
sections, not durable state).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Tuple

from .transport import RpcError

DEFAULT_LEASE_S = 15.0


class DistLocker:
    def __init__(self, node, default_lease: float = DEFAULT_LEASE_S):
        self.node = node
        self.default_lease = default_lease
        # authority-side table: key -> (owner_node, expires_at)
        self._held: Dict[str, Tuple[str, float]] = {}
        node.transport.rpc_handlers["lock_acquire"] = self._rpc_acquire
        node.transport.rpc_handlers["lock_release"] = self._rpc_release

    # ---------------------------------------------------------- authority

    def authority(self) -> Optional[str]:
        """Smallest live core node name (self counts when core).

        None when no core is visible — a partitioned replicant must
        fail closed rather than self-grant, or two partitioned nodes
        would both 'hold' the same takeover lock."""
        cands = [
            p for p in self.node.up_peers()
            if self.node._roles.get(p, "core") == "core"
        ]
        if self.node.role == "core":
            cands.append(self.node.name)
        return min(cands) if cands else None

    def _grant(self, key: str, owner: str, lease_s: float) -> bool:
        now = time.monotonic()
        cur = self._held.get(key)
        if cur is not None and cur[1] > now and cur[0] != owner:
            return False
        self._held[key] = (owner, now + lease_s)
        return True

    def _rpc_acquire(self, peer: str, params: dict) -> dict:
        ok = self._grant(
            str(params.get("key", "")),
            params.get("owner", peer),
            float(params.get("lease_s", self.default_lease)),
        )
        return {"ok": ok}

    def _rpc_release(self, peer: str, params: dict) -> dict:
        key = str(params.get("key", ""))
        owner = params.get("owner", peer)
        cur = self._held.get(key)
        if cur is not None and cur[0] == owner:
            del self._held[key]
            return {"ok": True}
        return {"ok": False}

    # -------------------------------------------------------------- client

    async def acquire(self, key: str, lease_s: Optional[float] = None,
                      retries: int = 0, retry_ivl: float = 0.1) -> bool:
        lease = lease_s if lease_s is not None else self.default_lease
        for attempt in range(retries + 1):
            auth = self.authority()
            if auth is None:
                ok = False  # no visible core: fail closed
            elif auth == self.node.name:
                ok = self._grant(key, self.node.name, lease)
            else:
                try:
                    resp = await self.node.call(
                        auth, "lock_acquire",
                        {"key": key, "owner": self.node.name,
                         "lease_s": lease},
                    )
                    ok = bool(resp.get("ok"))
                except (RpcError, asyncio.TimeoutError):
                    ok = False  # authority unreachable: fail closed
            if ok:
                return True
            if attempt < retries:
                await asyncio.sleep(retry_ivl)
        return False

    async def release(self, key: str) -> bool:
        auth = self.authority()
        if auth is None:
            return False  # lease expiry reclaims it on the authority
        if auth == self.node.name:
            cur = self._held.get(key)
            if cur is not None and cur[0] == self.node.name:
                del self._held[key]
                return True
            return False
        try:
            resp = await self.node.call(
                auth, "lock_release", {"key": key, "owner": self.node.name}
            )
            return bool(resp.get("ok"))
        except (RpcError, asyncio.TimeoutError):
            return False

    async def trans(self, key: str, fn, lease_s: Optional[float] = None,
                    retries: int = 20):
        """`emqx_cm_locker:trans` analog: run `fn` under the lock.
        Raises TimeoutError when the lock cannot be had."""
        if not await self.acquire(key, lease_s, retries=retries):
            raise TimeoutError(f"could not acquire cluster lock {key!r}")
        try:
            r = fn()
            if asyncio.iscoroutine(r):
                r = await r
            return r
        finally:
            await self.release(key)
