"""Replicated remote-route table — the mria rlog analog.

Reference: `emqx_router.erl` keeps a global mria `emqx_route` bag
(topic -> node) replicated to every core node, with wildcard filters
additionally indexed in the mnesia trie (SURVEY.md §1.7-1.8).

TPU redesign: each node is the single writer for its OWN route set and
broadcasts a per-node monotonically-sequenced oplog (add/del filter).
Receivers mirror each peer's set into ONE shared `TopicMatchEngine`
(fid -> node set), so remote matching for a publish batch is the same
batched device kernel as local matching.  Gaps or peer restarts
(incarnation change) trigger a full snapshot fetch — the rlog
"bootstrap then replay" recovery, with the engine as the HBM cache of
host truth (SURVEY.md §5.4 failure model).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

from ..models.engine import TopicMatchEngine


class RemoteRoutes:
    def __init__(self, engine: TopicMatchEngine | None = None):
        self.engine = engine or TopicMatchEngine()
        # fid -> set of node names holding that filter
        self._nodes_of: Dict[int, Set[str]] = {}
        # node -> its filter set (host truth for purge/snapshot diff)
        self._filters_of: Dict[str, Set[str]] = {}
        # node -> (incarnation, last applied oplog seq)
        self.applied: Dict[str, Tuple[int, int]] = {}

    # ----------------------------------------------------------- mutation

    def add(self, node: str, filt: str) -> None:
        filters = self._filters_of.setdefault(node, set())
        if filt in filters:
            return
        filters.add(filt)
        fid = self.engine.add_filter(filt)
        self._nodes_of.setdefault(fid, set()).add(node)

    def delete(self, node: str, filt: str) -> None:
        filters = self._filters_of.get(node)
        if filters is None or filt not in filters:
            return
        filters.discard(filt)
        fid = self.engine.fid_of(filt)
        self.engine.remove_filter(filt)
        if fid is not None:
            nodes = self._nodes_of.get(fid)
            if nodes is not None:
                nodes.discard(node)
                if not nodes:
                    del self._nodes_of[fid]

    def purge_node(self, node: str) -> int:
        """Drop all routes of a dead node (`emqx_router_helper` cleanup)."""
        filters = list(self._filters_of.get(node, set()))
        for filt in filters:
            self.delete(node, filt)
        self._filters_of.pop(node, None)
        self.applied.pop(node, None)
        return len(filters)

    def load_snapshot(
        self, node: str, incarnation: int, seq: int, filters: Sequence[str]
    ) -> None:
        """Replace a peer's mirrored set wholesale (bootstrap/catch-up)."""
        old = self._filters_of.get(node, set())
        new = set(filters)
        for filt in old - new:
            self.delete(node, filt)
        for filt in new - old:
            self.add(node, filt)
        self.applied[node] = (incarnation, seq)

    def apply_op(self, node: str, incarnation: int, seq: int, op: str, filt: str) -> bool:
        """Apply one oplog entry; False => gap/restart, caller must resync."""
        inc, applied = self.applied.get(node, (None, None))
        if inc == incarnation and applied is not None and seq <= applied:
            # duplicate: the same op arrives directly AND via a core
            # relay (replicant fan-out) — already applied, not a gap
            return True
        if inc != incarnation or applied is None or seq != applied + 1:
            return False
        if op == "add":
            self.add(node, filt)
        else:
            self.delete(node, filt)
        self.applied[node] = (incarnation, seq)
        return True

    # ------------------------------------------------------------ queries

    def match(self, topics: Sequence[str]) -> List[Set[str]]:
        """Batched device match -> set of remote nodes per topic."""
        out: List[Set[str]] = [set() for _ in topics]
        if not self._nodes_of:
            return out
        for i, fids in enumerate(self.engine.match(list(topics))):
            for fid in fids:
                out[i] |= self._nodes_of.get(fid, set())
        return out

    def filters_of(self, node: str) -> Set[str]:
        return set(self._filters_of.get(node, set()))

    def nodes(self) -> List[str]:
        return [n for n, f in self._filters_of.items() if f]

    @property
    def route_count(self) -> int:
        return sum(len(f) for f in self._filters_of.values())

    def topics(self) -> Dict[str, Set[str]]:
        """filter -> node set (REST /routes view)."""
        out: Dict[str, Set[str]] = {}
        for node, filters in self._filters_of.items():
            for filt in filters:
                out.setdefault(filt, set()).add(node)
        return out
