"""Replicated remote-route table — the mria rlog analog.

Reference: `emqx_router.erl` keeps a global mria `emqx_route` bag
(topic -> node) replicated to every core node, with wildcard filters
additionally indexed in the mnesia trie (SURVEY.md §1.7-1.8).

TPU redesign: each node is the single writer for its OWN route set and
broadcasts a per-node monotonically-sequenced oplog (add/del filter).
Receivers mirror each peer's set into ONE shared `TopicMatchEngine`
(fid -> node set), so remote matching for a publish batch is the same
batched device kernel as local matching.  Gaps or peer restarts
(incarnation change) trigger a full snapshot fetch — the rlog
"bootstrap then replay" recovery, with the engine as the HBM cache of
host truth (SURVEY.md §5.4 failure model).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

from ..models.engine import TopicMatchEngine


class RemoteRoutes:
    def __init__(self, engine: TopicMatchEngine | None = None):
        self.engine = engine or TopicMatchEngine()
        # fid -> set of node names holding that filter
        self._nodes_of: Dict[int, Set[str]] = {}
        # node -> its filter set (host truth for purge/snapshot diff)
        self._filters_of: Dict[str, Set[str]] = {}
        # node -> (incarnation, last applied oplog seq)
        self.applied: Dict[str, Tuple[int, int]] = {}
        # shared-group membership mirror (mria shared_sub table analog):
        # (group, filt) -> nodes with members; host trie for topic match
        from ..models.reference import CpuTrieIndex

        self._shared: Dict[Tuple[str, str], Set[str]] = {}
        self._shared_of: Dict[str, Set[Tuple[str, str]]] = {}
        self._shared_trie = CpuTrieIndex()
        self._shared_fids: Dict[str, int] = {}  # filt -> trie id
        self._sid_back: Dict[int, str] = {}  # trie id -> filt
        self._shared_groups_of: Dict[str, Set[str]] = {}  # filt -> groups
        self._next_sid = 0

    # ----------------------------------------------------------- mutation

    def add(self, node: str, filt: str) -> None:
        filters = self._filters_of.setdefault(node, set())
        if filt in filters:
            return
        filters.add(filt)
        fid = self.engine.add_filter(filt)
        self._nodes_of.setdefault(fid, set()).add(node)

    def delete(self, node: str, filt: str) -> None:
        filters = self._filters_of.get(node)
        if filters is None or filt not in filters:
            return
        filters.discard(filt)
        fid = self.engine.fid_of(filt)
        self.engine.remove_filter(filt)
        if fid is not None:
            nodes = self._nodes_of.get(fid)
            if nodes is not None:
                nodes.discard(node)
                if not nodes:
                    del self._nodes_of[fid]

    def add_shared(self, node: str, group: str, filt: str) -> None:
        key = (group, filt)
        entries = self._shared_of.setdefault(node, set())
        if key in entries:
            return
        entries.add(key)
        self._shared.setdefault(key, set()).add(node)
        groups = self._shared_groups_of.setdefault(filt, set())
        groups.add(group)
        if filt not in self._shared_fids:
            sid = self._next_sid
            self._next_sid += 1
            self._shared_fids[filt] = sid
            self._sid_back[sid] = filt
            self._shared_trie.insert(filt, sid)

    def del_shared(self, node: str, group: str, filt: str) -> None:
        key = (group, filt)
        entries = self._shared_of.get(node)
        if entries is None or key not in entries:
            return
        entries.discard(key)
        nodes = self._shared.get(key)
        if nodes is not None:
            nodes.discard(node)
            if not nodes:
                del self._shared[key]
                groups = self._shared_groups_of.get(filt)
                if groups is not None:
                    groups.discard(group)
                    if not groups:
                        del self._shared_groups_of[filt]
                        sid = self._shared_fids.pop(filt)
                        del self._sid_back[sid]
                        self._shared_trie.delete(filt, sid)

    def shared_nodes(self, group: str, filt: str) -> Set[str]:
        return set(self._shared.get((group, filt), ()))

    def shared_of(self, node: str) -> List[Tuple[str, str]]:
        return sorted(self._shared_of.get(node, set()))

    def match_shared(self, topic: str) -> List[Tuple[str, str]]:
        """(group, filter) pairs with remote members matching `topic`."""
        out: List[Tuple[str, str]] = []
        if not self._shared:
            return out
        for sid in self._shared_trie.match(topic):
            filt = self._sid_back[sid]
            for group in self._shared_groups_of.get(filt, ()):
                out.append((group, filt))
        return out

    def purge_node(self, node: str) -> int:
        """Drop all routes of a dead node (`emqx_router_helper` cleanup)."""
        filters = list(self._filters_of.get(node, set()))
        for filt in filters:
            self.delete(node, filt)
        for group, filt in list(self._shared_of.get(node, set())):
            self.del_shared(node, group, filt)
        self._filters_of.pop(node, None)
        self._shared_of.pop(node, None)
        self.applied.pop(node, None)
        return len(filters)

    def load_snapshot(
        self, node: str, incarnation: int, seq: int, filters: Sequence[str],
        shared: Sequence[Sequence[str]] = (),
    ) -> None:
        """Replace a peer's mirrored set wholesale (bootstrap/catch-up)."""
        old = self._filters_of.get(node, set())
        new = set(filters)
        for filt in old - new:
            self.delete(node, filt)
        for filt in new - old:
            self.add(node, filt)
        old_sh = self._shared_of.get(node, set())
        new_sh = {(g, f) for g, f in shared}
        for g, f in old_sh - new_sh:
            self.del_shared(node, g, f)
        for g, f in new_sh - old_sh:
            self.add_shared(node, g, f)
        self.applied[node] = (incarnation, seq)

    def apply_op(
        self, node: str, incarnation: int, seq: int, op: str, filt: str,
        group: str = "",
    ) -> bool:
        """Apply one oplog entry; False => gap/restart, caller must resync."""
        inc, applied = self.applied.get(node, (None, None))
        if inc == incarnation and applied is not None and seq <= applied:
            # duplicate: the same op arrives directly AND via a core
            # relay (replicant fan-out) — already applied, not a gap
            return True
        if inc != incarnation or applied is None or seq != applied + 1:
            return False
        if op == "add":
            self.add(node, filt)
        elif op == "del":
            self.delete(node, filt)
        elif op == "adds":  # shared-group membership appears on `node`
            self.add_shared(node, group, filt)
        elif op == "dels":
            self.del_shared(node, group, filt)
        self.applied[node] = (incarnation, seq)
        return True

    # ------------------------------------------------------------ queries

    def match(self, topics: Sequence[str]) -> List[Set[str]]:
        """Batched device match -> set of remote nodes per topic."""
        out: List[Set[str]] = [set() for _ in topics]
        if not self._nodes_of:
            return out
        for i, fids in enumerate(self.engine.match(list(topics))):
            for fid in fids:
                out[i] |= self._nodes_of.get(fid, set())
        return out

    def filters_of(self, node: str) -> Set[str]:
        return set(self._filters_of.get(node, set()))

    def nodes(self) -> List[str]:
        return [n for n, f in self._filters_of.items() if f]

    @property
    def route_count(self) -> int:
        return sum(len(f) for f in self._filters_of.values())

    def topics(self) -> Dict[str, Set[str]]:
        """filter -> node set (REST /routes view)."""
        out: Dict[str, Set[str]] = {}
        for node, filters in self._filters_of.items():
            for filt in filters:
                out.setdefault(filt, set()).add(node)
        return out
