"""Cluster layer: membership, replicated routes, message forwarding.

TPU-native redesign of the reference's three distribution planes
(SURVEY.md §1.8, §5.8):

1. Erlang distribution (control)  -> asyncio TCP peer links + RPC
   (`emqx_tpu.cluster.transport`);
2. gen_rpc data plane (forwards)  -> binary FORWARD frames, sync/async
   modes (`emqx_tpu.cluster.node.ClusterNode.forward*`);
3. mria rlog table replication    -> per-node sequenced route oplog with
   snapshot catch-up (`emqx_tpu.cluster.routes`).

Rather than a global mnesia trie, every node keeps TWO match engines:
its local subscription engine (the Broker's) and a second
`TopicMatchEngine` holding *remote* filters mapped to node sets — both
run the same batched TPU match kernel, so a publish batch resolves local
deliveries and remote forwards in two device calls.
"""

from .node import ClusterBroker, ClusterNode
from .routes import RemoteRoutes
from .transport import PeerLink, RpcError, Transport

__all__ = [
    "ClusterBroker",
    "ClusterNode",
    "RemoteRoutes",
    "PeerLink",
    "RpcError",
    "Transport",
]
