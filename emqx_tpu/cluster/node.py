"""ClusterNode: membership + route replication + publish forwarding.

Reference behavior being matched (SURVEY.md §1.8, §3.3):
  * static-seed membership with heartbeat failure detection (ekka
    static discovery + `monitor_node`);
  * route table replication (`emqx_router:do_add_route` ->
    `?ROUTE_SHARD` rlog) — here a per-owner sequenced oplog with
    snapshot bootstrap (`RemoteRoutes`);
  * publish forwarding to nodes holding matching routes
    (`emqx_broker:forward`, gen_rpc sync/async modes) — here binary
    FORWARD frames, fire-and-forget by default, awaitable acks in
    "sync" mode;
  * route purge on nodedown (`emqx_router_helper:cleanup_routes`).

Topology is a full mesh over the configured peer map — the reference's
static cluster discovery (`emqx_conf_schema.erl:148-230`).
"""

from __future__ import annotations

import asyncio
import base64
import logging
import os
import random
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import fault as _fault
from ..broker.broker import Broker
from ..broker.message import Message
from ..observe import spans as _spans
from ..observe.tracepoints import tp as tracept
from ..utils.replayq import ReplayQ
from . import bpapi
from . import transport as tp
from .routes import RemoteRoutes
from .transport import PeerLink, RpcError, Transport

log = logging.getLogger("emqx_tpu.cluster")

# receiver-side forward dedup window: (mid, group, filt) keys of the
# most recent dispatched QoS>=1 forwards.  Replayed/retried frames
# (header "replay": true) that hit the window are acked but not
# re-dispatched, so at-least-once spool replay turns into exactly-once
# delivery at the receiving broker.
DEDUP_WINDOW = 8192

# Route-snapshot responses at or above this many filters ship a packed
# zlib blob (checkpoint/store.py pack_filter_blob) instead of a JSON
# string array — the cluster fast-bootstrap path: a peer that is far
# behind (restart, long partition) receives one compressed table image
# rather than a per-filter op replay's worth of JSON.  Below it the
# plain list is cheaper than the compress+base64 round trip.
SNAPSHOT_BLOB_MIN = 512


def _snapshot_filters(resp: dict) -> List[str]:
    """Filters from a snapshot response — JSON list or packed blob."""
    filters = resp.get("filters")
    if filters is None and resp.get("blob") is not None:
        from ..checkpoint.store import unpack_filter_blob

        filters = unpack_filter_blob(base64.b64decode(resp["blob"]))
    return list(filters or ())


def _pack_snapshot_filters(resp: dict, filters: List[str]) -> dict:
    """Attach a filter list to a snapshot response, blob-packed when a
    peer is far enough behind that a wholesale image beats op replay."""
    if len(filters) >= SNAPSHOT_BLOB_MIN:
        from ..checkpoint.store import pack_filter_blob

        resp["blob"] = base64.b64encode(
            pack_filter_blob(filters)
        ).decode("ascii")
        resp["n"] = len(filters)
    else:
        resp["filters"] = filters
    return resp


class ClusterBroker(Broker):
    """Broker whose publish path also forwards to matching peers."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.cluster: Optional[ClusterNode] = None

    def _pre_match(self, todo) -> None:
        # between accept and match (rides publish_submit, so the batcher's
        # pipelined path forwards exactly like the synchronous one)
        if self.cluster is not None and todo:
            accepted = [m for _, m in todo]
            self.cluster.forward_publish(accepted)
            # shared groups with members ONLY on peers: targeted forward
            # (exactly one delivery per group cluster-wide)
            self.cluster.dispatch_remote_shared(accepted)

    def dispatch_forwarded(self, msg: Message) -> int:
        """Receiving side of a remote forward: local match+dispatch of
        DIRECT subscriptions only — shared groups are the origin node's
        responsibility (targeted forwards), so a generic forward must
        never trigger a second group pick here.  No 'message.publish'
        hooks, no retain, no re-forward (those ran on the origin;
        mirrors `emqx_broker:dispatch/2` on the target)."""
        fids = self.engine.match([msg.topic])[0]
        n = self._dispatch(msg, fids, include_shared=False)
        self.metrics.inc("messages.forward.in")
        return n


def message_to_wire(msg: Message) -> Tuple[dict, bytes]:
    header = {
        "topic": msg.topic,
        "qos": msg.qos,
        "retain": msg.retain,
        "dup": msg.dup,
        "from": msg.from_client,
        "username": msg.from_username,
        "mid": msg.mid.hex(),
        "ts": msg.timestamp,
        "props": {str(k): v for k, v in msg.properties.items()
                  if isinstance(v, (int, str, float, bool))},
    }
    if _spans.enabled():
        # sampled message-lifecycle span: carry the origin's ingress
        # wall-clock so the REMOTE broker can close the cross-node
        # forward leg (observe/spans.py; survives relays and the spool
        # since it rides the frame header)
        ctx = msg.headers.get("__span")
        if ctx is not None:
            header["span_t0"] = ctx.wall0
    return header, msg.payload


def message_from_wire(header: dict, payload: bytes) -> Message:
    props = {}
    for k, v in (header.get("props") or {}).items():
        try:
            props[int(k)] = v
        except ValueError:
            props[k] = v
    return Message(
        topic=header["topic"],
        payload=payload,
        qos=header.get("qos", 0),
        retain=header.get("retain", False),
        dup=header.get("dup", False),
        from_client=header.get("from", ""),
        from_username=header.get("username"),
        mid=bytes.fromhex(header["mid"]) if header.get("mid") else b"",
        timestamp=header.get("ts", 0),
        properties=props,
    )


class ClusterNode:
    def __init__(
        self,
        name: str,
        broker: ClusterBroker,
        host: str = "127.0.0.1",
        port: int = 0,
        peers: Optional[Dict[str, Tuple[str, int]]] = None,
        heartbeat_ivl: float = 1.0,
        miss_limit: int = 3,
        rpc_mode: str = "async",  # forward mode: async | sync
        cookie: str = "",  # shared secret gating peer links ("" = open)
        unix_path: Optional[str] = None,  # serve peer links on a UNIX
        # socket too (wire-plane IPC: co-hosted workers dial the path)
        role: str = "core",  # core | replicant (mria topology analog)
        discovery=None,  # strategy with discover() -> {name: (host, port)}
        discovery_ivl: float = 5.0,
        advertise_host: Optional[str] = None,  # dial-back address when
        # the bind host (e.g. 0.0.0.0) is not routable from peers
        route_hold: float = 5.0,  # keep a down peer's routes this long
        # before purging (transient flaps spool + replay instead of
        # losing QoS>=1 forwards to a purged route table)
        spool_max_bytes: int = 8 << 20,  # per-peer forward-spool bound
        reconnect_ivl: float = 0.5,  # PeerLink backoff base
        reconnect_max: float = 15.0,  # PeerLink backoff ceiling
    ):
        assert role in ("core", "replicant"), role
        self.advertise_host = advertise_host
        self.name = name
        self.broker = broker
        broker.cluster = self
        self.incarnation = time.time_ns()
        self.cookie = cookie
        self.role = role
        self.discovery = discovery
        self.discovery_ivl = discovery_ivl
        self.transport = Transport(name, host, port, cookie=cookie,
                                   unix_path=unix_path)
        self.remote = RemoteRoutes()
        self.peers_cfg: Dict[str, Tuple[str, int]] = {
            n: tp.check_addr(a) for n, a in (peers or {}).items()
        }
        self.links: Dict[str, PeerLink] = {}
        self.heartbeat_ivl = heartbeat_ivl
        self.miss_limit = miss_limit
        self.rpc_mode = rpc_mode
        self.route_hold = float(route_hold)
        self.spool_max_bytes = int(spool_max_bytes)
        self.reconnect_ivl = float(reconnect_ivl)
        self.reconnect_max = float(reconnect_max)

        # per-peer forward spool (replayq-backed): QoS>=1 forwards that
        # could not ride the wire wait here, bounded by spool_max_bytes
        # with drop-oldest overflow, and replay (acked, msgid-deduped on
        # the receiver) when the peer heals
        self._spools: Dict[str, ReplayQ] = {}
        self._spool_bytes: Dict[str, int] = {}
        self.spool_dropped = 0  # records lost to the overflow bound
        self.replay_timeout = 5.0  # per-record ack wait during replay
        self._replay_tasks: Dict[str, asyncio.Task] = {}
        self._purge_tasks: Dict[str, asyncio.Task] = {}
        self._stopping = False
        self._seen_fwd: "OrderedDict[Tuple[str, str, str], bool]" = (
            OrderedDict()
        )

        # local route oplog (this node is its single writer)
        self.seq = 0
        self._local_filters: Set[str] = set()
        self._shared_rng = random.Random()
        # pre-seed CONFIGURED peers as down so readiness (`/status`
        # `ready`: all peer links up) is never vacuously true on a node
        # whose links are all inbound — the mesh shows as forming, not
        # formed, until every configured peer's hello lands
        self._status: Dict[str, str] = dict.fromkeys(self.peers_cfg, "down")
        self._resyncing: Set[str] = set()
        self._hb_task: Optional[asyncio.Task] = None
        self._disc_task: Optional[asyncio.Task] = None
        # one-shot background work (link teardown, resyncs, remote
        # sweeps): retained here so the GC cannot drop a running task
        # and stop() can cancel the stragglers; done tasks self-evict
        self._bg_tasks: Set[asyncio.Task] = set()
        self._misses: Dict[str, int] = {}
        self._roles: Dict[str, str] = {}  # peer -> core|replicant

        broker.on_route_added = self._route_added
        broker.on_route_removed = self._route_removed
        # cluster-wide shared-subscription dispatch (one delivery per
        # group across the cluster): membership rides the same oplog;
        # shared messages use TARGETED forwards, never the generic one
        broker.on_shared_added = self._shared_added
        broker.on_shared_removed = self._shared_removed
        broker.shared_remote_nodes = lambda g, f: self.remote.shared_nodes(g, f)
        broker.forward_shared = self.forward_shared
        self._local_shared: Set[Tuple[str, str]] = set()
        t = self.transport
        t.on_hello = self._on_hello
        t.on_route_op = self._on_route_op
        t.on_snapshot_req = self._on_snapshot_req
        t.on_forward = self._on_forward
        t.rpc_handlers["publish"] = self._rpc_publish
        t.rpc_handlers["remote_snapshot"] = self._rpc_remote_snapshot
        t.rpc_handlers["session_takeover"] = self._rpc_session_takeover
        # distributed locks (ekka_locker analog) + per-peer negotiated
        # rpc versions (bpapi analog; filled at link-up)
        from .locker import DistLocker

        self.locker = DistLocker(self)
        self.peer_bpapi: Dict[str, Dict[str, int]] = {}
        # ds append-replication plane (ds/repl.py), wired by
        # attach_ds_repl; enables the v2 cursor-handoff takeover form
        self.ds_repl = None

    def attach_ds_repl(self, repl) -> None:
        """Wire the ds replication plane: inbound REPL frames land on
        the replicator's mirror appends, and takeover calls negotiate
        the cursor-handoff form against its mirror coverage."""
        self.ds_repl = repl
        self.transport.on_repl = repl.handle_repl

    # ------------------------------------------------------------- lifecycle

    async def start(self) -> None:
        # bpapi static check: contracts are per-release and announced in
        # full; warn when a declared method has no handler wired yet
        # (e.g. ClusterRpc not constructed) — its callers degrade to the
        # same per-peer RpcError skip as an unreachable node
        missing = bpapi.check_handlers(self.transport.rpc_handlers)
        if missing:
            log.warning("%s: declared rpc contracts without handlers: %s",
                        self.name, missing)
        await self.transport.start()
        for peer, addr in self.peers_cfg.items():
            self._add_link(peer, addr)
        self._hb_task = asyncio.get_running_loop().create_task(self._heartbeat())
        if self.discovery is not None:
            self._disc_task = asyncio.get_running_loop().create_task(
                self._discovery_loop()
            )

    def _spawn_bg(self, coro) -> asyncio.Task:
        """Run a one-shot background coroutine, retained + reaped: the
        task registry keeps a strong reference until completion and
        surfaces unexpected failures instead of dropping them."""
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._reap_bg)
        return task

    def _reap_bg(self, task: asyncio.Task) -> None:
        self._bg_tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.warning("%s: background task failed: %r", self.name, exc)

    async def stop(self) -> None:
        self._stopping = True
        tasks = [self._hb_task, self._disc_task]
        tasks += list(self._purge_tasks.values())
        tasks += list(self._replay_tasks.values())
        tasks += list(self._bg_tasks)
        self._purge_tasks.clear()
        self._replay_tasks.clear()
        for task in tasks:
            if task:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        for link in self.links.values():
            await link.stop()
        await self.transport.stop()
        for q in self._spools.values():
            q.close()

    def join(self, peer: str, addr: Tuple[str, int]) -> None:
        """Add a peer at runtime (manual `cluster join`).  A changed
        address (peer restarted elsewhere, k8s pod move) replaces the
        old link so reconnects chase the live endpoint."""
        addr = tp.check_addr(addr)
        self.peers_cfg[peer] = addr
        old = self.links.get(peer)
        if old is not None and old.addr != tuple(addr):
            self.links.pop(peer, None)
            self._spawn_bg(old.stop())
        if peer not in self.links:
            self._add_link(peer, addr)

    def leave(self, peer: str) -> None:
        self.peers_cfg.pop(peer, None)
        link = self.links.pop(peer, None)
        if link is not None:
            self._spawn_bg(link.stop())
        # explicit leave: no transient-flap grace, purge immediately
        self._node_down(peer, purge=True)

    def _add_link(self, peer: str, addr: Tuple[str, int]) -> None:
        link = PeerLink(
            self.name,
            peer,
            addr,
            self.incarnation,
            on_up=self._link_up,
            on_down=lambda l: self._node_down(l.peer),
            cookie=self.cookie,
            extra_hello=self._hello_extra(),
            reconnect_ivl=self.reconnect_ivl,
            reconnect_max=self.reconnect_max,
        )
        self.links[peer] = link
        self._status.setdefault(peer, "down")
        link.start()

    def _hello_extra(self) -> dict:
        extra = {"role": self.role, "bpapi": bpapi.announce()}
        if self.transport.unix_path:
            # co-hosted peers (wire workers) dial back over the unix
            # path — cheaper than loopback TCP and valid even when the
            # TCP bind is a wildcard
            extra["uaddr"] = ["unix", self.transport.unix_path]
        host = self.advertise_host or self.transport.host
        if host not in ("0.0.0.0", "::"):
            # a wildcard bind with no advertise_host is not dialable;
            # omit addr so peers skip dial-back instead of dialing junk
            extra["addr"] = [host, self.transport.port]
        elif not self.transport.unix_path:
            log.warning(
                "node %s binds %s without advertise_host: peers cannot "
                "dial back", self.name, host,
            )
        return extra

    async def _discovery_loop(self) -> None:
        """Poll the discovery strategy; join newly seen peers.  Cores
        join every discovered node; replicants join cores only — their
        links to other nodes come from cores dialing back."""
        # `not self._stopping` guards against a swallowed cancellation
        # (see _heartbeat) leaving stop() awaiting this loop forever
        while not self._stopping:
            try:
                found = await asyncio.to_thread(self.discovery.discover)
            except Exception:
                log.exception("%s: discovery poll failed", self.name)
                found = {}
            for peer, addr in (found or {}).items():
                if peer == self.name:
                    continue
                if self.role == "replicant" and (
                    self._roles.get(peer) == "replicant"
                ):
                    continue
                try:
                    self.join(peer, (str(addr[0]), int(addr[1])))
                except (ValueError, TypeError, IndexError):
                    log.warning(
                        "%s: discovery entry %r -> %r unusable",
                        self.name, peer, addr,
                    )
            await asyncio.sleep(self.discovery_ivl)

    # ----------------------------------------------------------- membership

    def _link_up(self, link: PeerLink, hello: dict) -> None:
        peer_role = hello.get("role", "core")
        self._roles[link.peer] = peer_role
        self.peer_bpapi[link.peer] = bpapi.negotiate(hello.get("bpapi"))
        if self.role == "replicant" and peer_role == "replicant":
            # replicants never mesh with each other (mria topology) —
            # discovery could not know the role before dialing; now we
            # do, so tear the link down and remember not to redial
            log.info("%s: dropping replicant<->replicant link to %s",
                     self.name, link.peer)
            self.links.pop(link.peer, None)
            self.peers_cfg.pop(link.peer, None)
            self._status.pop(link.peer, None)
            self._spawn_bg(link.stop())
            return
        self._cancel_purge(link.peer)
        self._status[link.peer] = "up"
        self._misses[link.peer] = 0
        tracept("cluster.peer.health", peer=link.peer, state="up")
        self.broker.hooks.run("node.up", (link.peer,))
        # bootstrap that peer's routes, then drain the forward spool
        self._spawn_bg(self._resync(link.peer))
        self._kick_replay(link.peer)

    def _node_down(self, peer: str, purge: bool = False) -> None:
        """Mark a peer down.  Routes are NOT purged immediately: a
        transient flap (redial window, brief partition) keeps the routes
        so QoS>=1 forwards spool instead of un-matching; only after
        `route_hold` seconds continuously down — or an explicit
        `purge=True` (leave, takeover) — does the purge run.  The
        'node.down' hook fires at purge time with the purged count, same
        contract as before, just `route_hold` later for flaps."""
        prev = self._status.get(peer)
        if prev == "down" and not purge:
            return
        if prev != "down":
            self._status[peer] = "down"
            tracept("cluster.peer.health", peer=peer, state="down")
        if purge:
            self._cancel_purge(peer)
            self._purge_routes(peer)
        elif self._stopping:
            pass  # links tearing down with the node: no purge timers
        elif peer not in self._purge_tasks:
            self._purge_tasks[peer] = asyncio.get_running_loop().create_task(
                self._purge_after_hold(peer)
            )

    def _purge_routes(self, peer: str) -> None:
        purged = self.remote.purge_node(peer)
        self.broker.hooks.run("node.down", (peer, purged))

    async def _purge_after_hold(self, peer: str) -> None:
        try:
            await asyncio.sleep(self.route_hold)
            if self._status.get(peer) == "down":
                self._purge_routes(peer)
        finally:
            self._purge_tasks.pop(peer, None)

    def _cancel_purge(self, peer: str) -> None:
        t = self._purge_tasks.pop(peer, None)
        if t is not None:
            t.cancel()

    def _peer_recovered(self, peer: str) -> None:
        """A down peer answered a ping on a still-connected link (paused
        process, healed partition — no TCP reset, so no _link_up fires):
        cancel the pending purge, resync its routes (they may have been
        purged already if the outage outlived route_hold) and drain the
        spool."""
        self._cancel_purge(peer)
        self._status[peer] = "up"
        tracept("cluster.peer.health", peer=peer, state="up")
        self._spawn_bg(self._resync(peer))
        self._kick_replay(peer)

    async def _heartbeat(self) -> None:
        # `not self._stopping`, not `True`: py3.10 asyncio.wait_for can
        # swallow a cancellation delivered in the same tick the awaited
        # future completes (bpo-37658) — inside link.request that turns
        # stop()'s cancel into a normal PING return and `await task`
        # would hang forever on a loop that never exits
        while not self._stopping:
            await asyncio.sleep(self.heartbeat_ivl)
            for peer, link in list(self.links.items()):
                if not link.connected:
                    continue
                # the heartbeat task is bare (no supervisor): any
                # exception besides the expected ping failures — e.g. a
                # bug in the degraded/recovered bookkeeping — must
                # degrade to a logged skipped beat, not silently kill
                # peer-health detection for the node's lifetime
                try:
                    try:
                        await link.request(
                            tp.PING, {}, timeout=self.heartbeat_ivl * 2
                        )
                    except (RpcError, OSError) as e:
                        # RpcError: timeout / link raced down; OSError:
                        # the write itself failed on a dying socket
                        misses = self._misses[peer] = (
                            self._misses.get(peer, 0) + 1
                        )
                        tracept("cluster.peer.miss", peer=peer,
                                misses=misses,
                                error=str(e) or type(e).__name__)
                        if misses >= self.miss_limit:
                            self._node_down(peer)
                        elif self._status.get(peer) == "up":
                            self._status[peer] = "degraded"
                            tracept("cluster.peer.health", peer=peer,
                                    state="degraded")
                        continue
                    self._misses[peer] = 0
                    st = self._status.get(peer)
                    if st == "degraded":
                        self._status[peer] = "up"
                        tracept("cluster.peer.health", peer=peer,
                                state="up")
                    elif st == "down":
                        self._peer_recovered(peer)
                    elif self.spool_pending(peer):
                        # link healthy but spooled backlog remains (e.g.
                        # the last replay aborted mid-fault): keep
                        # draining
                        self._kick_replay(peer)
                except Exception:
                    log.exception(
                        "heartbeat: bookkeeping for peer %s failed", peer
                    )

    def status(self) -> Dict[str, str]:
        return dict(self._status)

    def up_peers(self) -> List[str]:
        return [p for p, s in self._status.items() if s == "up"]

    # -------------------------------------------------------- route oplog

    def _route_added(self, filt: str) -> None:
        self._local_filters.add(filt)
        self.seq += 1
        self._broadcast_op("add", filt)

    def _route_removed(self, filt: str) -> None:
        self._local_filters.discard(filt)
        self.seq += 1
        self._broadcast_op("del", filt)

    def _shared_added(self, group: str, filt: str) -> None:
        self._local_shared.add((group, filt))
        self.seq += 1
        self._broadcast_op("adds", filt, group)

    def _shared_removed(self, group: str, filt: str) -> None:
        self._local_shared.discard((group, filt))
        self.seq += 1
        self._broadcast_op("dels", filt, group)

    def _broadcast_op(self, op: str, filt: str, group: str = "") -> None:
        frame = tp.pack_json(
            tp.ROUTE_OP,
            {
                "node": self.name,
                "incarnation": self.incarnation,
                "seq": self.seq,
                "op": op,
                "filt": filt,
                **({"group": group} if group else {}),
            },
        )
        for link in self.links.values():
            link.send_nowait(frame)

    def _on_route_op(self, peer: str, obj: dict) -> None:
        ok = self.remote.apply_op(
            obj["node"], obj["incarnation"], obj["seq"], obj["op"],
            obj["filt"], obj.get("group", ""),
        )
        if not ok:
            self._spawn_bg(self._resync(obj["node"]))
        # cores relay first-hop ops so nodes without a direct link to the
        # origin (replicant<->replicant) still converge (rlog fan-out)
        if (
            self.role == "core"
            and not obj.get("relayed")
            and obj.get("node") == peer
        ):
            frame = tp.pack_json(tp.ROUTE_OP, {**obj, "relayed": True})
            for name, link in self.links.items():
                if name != peer:
                    link.send_nowait(frame)

    async def _resync(self, peer: str) -> None:
        """Fetch a full route snapshot from a peer (rlog bootstrap).

        Without a direct link to `peer` (replicant<->replicant), the
        snapshot is served from a core's mirror instead."""
        if peer in self._resyncing:
            return
        link = self.links.get(peer)
        if link is None or not link.connected:
            await self._resync_via_core(peer)
            return
        self._resyncing.add(peer)
        try:
            resp = None
            for attempt in range(3):
                try:
                    resp = await link.request(
                        tp.SNAPSHOT_REQ, {"node": self.name}
                    )
                    break
                except RpcError:
                    # idempotent read: a lost frame mid-heal is worth a
                    # couple of backed-off retries before the next
                    # route-op gap triggers resync again
                    if attempt == 2:
                        raise
                    await asyncio.sleep(
                        0.2 * (2 ** attempt)
                        * (0.5 + self._shared_rng.random())
                    )
            self.remote.load_snapshot(
                peer, resp["incarnation"], resp["seq"],
                _snapshot_filters(resp),
                [tuple(x) for x in resp.get("shared", ())],
            )
            if self._status.get(peer) != "up":
                self._status[peer] = "up"
        except (RpcError, Exception):
            pass
        finally:
            self._resyncing.discard(peer)

    def _on_hello(self, peer: str, hello: dict) -> dict:
        self._roles[peer] = hello.get("role", "core")
        self.peer_bpapi[peer] = bpapi.negotiate(hello.get("bpapi"))
        # dial back a peer we have no outbound link to (replicants dial
        # cores; the core's return link is how forwards/relays reach
        # them — mria's replicant attach).  A unix dial-back address
        # wins over TCP when the path exists here — same-host peer,
        # no loopback tax.
        addr = hello.get("addr")
        uaddr = hello.get("uaddr")
        if (
            isinstance(uaddr, (list, tuple))
            and tp.is_unix_addr(uaddr)
            and os.path.exists(str(uaddr[1]))
        ):
            addr = uaddr
        if (
            peer not in self.links
            and isinstance(addr, (list, tuple))
            and not (
                self.role == "replicant"
                and hello.get("role", "core") == "replicant"
            )
        ):
            try:
                self.join(peer, addr)
            except (ValueError, TypeError):
                pass
        return {
            "incarnation": self.incarnation,
            "role": self.role,
            "bpapi": bpapi.announce(),
        }

    async def _resync_via_core(self, origin: str) -> None:
        """Ask an up core for its mirror of `origin`'s routes."""
        key = f"{origin}/via-core"
        if key in self._resyncing:
            return
        self._resyncing.add(key)
        try:
            for peer, link in list(self.links.items()):
                if (
                    self._roles.get(peer) != "core"
                    or not link.connected
                    or peer == origin
                ):
                    continue
                try:
                    resp = await self.call_retry(
                        peer, "remote_snapshot", {"node": origin},
                        timeout=5.0, retries=2,
                    )
                except (RpcError, Exception):
                    continue
                if resp.get("known"):
                    self.remote.load_snapshot(
                        origin,
                        resp["incarnation"],
                        resp["seq"],
                        _snapshot_filters(resp),
                        [tuple(x) for x in resp.get("shared", ())],
                    )
                    return
        finally:
            self._resyncing.discard(key)

    def _rpc_remote_snapshot(self, peer: str, params: dict) -> dict:
        """Serve this core's mirror of another node's routes."""
        node = params.get("node", "")
        inc_seq = self.remote.applied.get(node)
        if inc_seq is None:
            return {"known": False}
        return _pack_snapshot_filters(
            {
                "known": True,
                "incarnation": inc_seq[0],
                "seq": inc_seq[1],
                "shared": self.remote.shared_of(node),
            },
            sorted(self.remote.filters_of(node)),
        )

    def _on_snapshot_req(self, peer: str, obj: dict) -> dict:
        return _pack_snapshot_filters(
            {
                "incarnation": self.incarnation,
                "seq": self.seq,
                "shared": sorted(self._local_shared),
            },
            sorted(self._local_filters),
        )

    # -------------------------------------------------------- forward spool

    def spool_pending(self, node: Optional[str] = None) -> int:
        """Spooled-but-undelivered forward records (one node or all)."""
        if node is not None:
            q = self._spools.get(node)
            return q.pending_count() if q is not None else 0
        return sum(q.pending_count() for q in self._spools.values())

    def _spool_put(self, node: str, header: dict, payload: bytes) -> None:
        """Queue one QoS>=1 forward for replay, bounded drop-oldest."""
        q = self._spools.get(node)
        if q is None:
            q = self._spools[node] = ReplayQ()
            self._spool_bytes[node] = 0
        body = tp.pack_forward_body(header, payload)
        # drop_oldest (NOT pop+ack) so an overflow during an in-flight
        # replay batch cannot ack past the replayer's popped-unacked
        # window — those records stay requeue-able on a mid-replay
        # failure.  With the whole queue in flight (count()==0) the
        # bound is exceeded by at most one replay batch.
        while (
            self._spool_bytes[node] + len(body) > self.spool_max_bytes
            and q.count()
        ):
            items = q.drop_oldest(1)
            if not items:
                break
            lost = len(items)
            self.spool_dropped += lost
            self._spool_bytes[node] -= sum(len(i) for i in items)
            self.broker.metrics.inc("messages.forward.spool_dropped", lost)
            self.broker.metrics.inc("messages.forward.dropped", lost)
        q.append(body)
        self._spool_bytes[node] += len(body)
        self.broker.metrics.inc("messages.forward.spooled")
        tracept("cluster.forward.spool", node=node, pending=q.count())
        # link up (queue-full / fault blip rather than a dead peer):
        # start draining right away instead of waiting for a heal event
        link = self.links.get(node)
        if link is not None and link.connected \
                and self._status.get(node) == "up":
            self._kick_replay(node)

    def _kick_replay(self, peer: str) -> None:
        if self._stopping:
            return
        if self.spool_pending(peer) and peer not in self._replay_tasks:
            self._replay_tasks[peer] = asyncio.get_running_loop().create_task(
                self._replay_spool(peer)
            )

    async def _replay_spool(self, peer: str) -> None:
        """Drain one peer's spool over the healed link.  Every record is
        an ACKED forward (the receiver dedups by msgid, so a retry after
        a lost ack cannot double-deliver); the queue is only acked past
        records the peer confirmed, so a mid-replay link loss replays
        the unconfirmed tail on the next heal."""
        sent = 0
        try:
            q = self._spools.get(peer)
            while q is not None and q.count():
                link = self.links.get(peer)
                if link is None or not link.connected:
                    return
                ref, items = q.pop(16)
                if not items:
                    return
                try:
                    for body in items:
                        header, payload = tp.unpack_forward(body)
                        header["replay"] = True
                        ack = await link.forward_request(
                            header, payload, timeout=self.replay_timeout
                        )
                        if ack is None:
                            raise RpcError(f"link to {peer} down mid-replay")
                except (RpcError, ConnectionError, OSError):
                    q.requeue(ref, items)
                    return
                q.ack(ref)
                sent += len(items)
                self._spool_bytes[peer] -= sum(len(i) for i in items)
                await asyncio.sleep(0)  # yield between batches
        finally:
            self._replay_tasks.pop(peer, None)
            if sent:
                self.broker.metrics.inc("messages.forward.replayed", sent)
                tracept("cluster.forward.replay", node=peer, n=sent,
                        drained=self.spool_pending(peer) == 0)

    # ----------------------------------------------------------- forwarding

    def forward_publish(self, msgs: Sequence[Message]) -> int:
        """Async-mode forward of a publish batch (one remote match kernel).

        Fire-and-forget like `forward_async` (`emqx_broker.erl:277-292`);
        for acked forwarding use `forward_publish_sync`.  A failed send
        is never silent: QoS>=1 messages spool for replay on heal when
        a PeerLink to the node exists; everything else (QoS0, or an
        unlinked peer whose relay failed) lands in
        `messages.forward.dropped`.
        """
        per_node = self._match_remote(msgs)
        n = 0
        metrics = self.broker.metrics
        for node, node_msgs in per_node.items():
            link = self.links.get(node)
            # a peer whose heartbeats are missing ("down") may still hold
            # a live TCP link (paused process, one-way partition): stop
            # trusting it — spool instead of queueing into a black hole
            direct = (
                link is not None
                and link.connected
                and self._status.get(node) != "down"
            )
            relay = None if direct else self._up_core_link(exclude=node)
            blocked = _fault.inject("cluster.forward", err=False) is not None \
                if _fault.enabled() else False
            for msg in node_msgs:
                header, payload = message_to_wire(msg)
                sent = False
                if blocked:
                    pass
                elif direct:
                    sent = link.send_nowait(tp.pack_forward(header, payload))
                elif msg.qos >= 1 and link is not None:
                    # down direct link: the spool's acked replay is the
                    # reliable path — an unacked core relay could not be
                    # deduped against it
                    pass
                elif relay is not None:
                    # no direct link (replicant->replicant), or QoS0 with
                    # the direct link down: ride via a core
                    h2 = dict(header, relay_to=node)
                    sent = relay.send_nowait(tp.pack_forward(h2, payload))
                if sent:
                    n += 1
                elif msg.qos >= 1 and link is not None:
                    self._spool_put(node, header, payload)
                else:
                    # QoS0, or a peer we hold no PeerLink for (replicant->
                    # replicant) whose core relay failed: replay needs a
                    # direct link, so a spool record for an unlinked peer
                    # would sit forever — count the loss instead
                    metrics.inc("messages.forward.dropped")
        if n:
            metrics.inc("messages.forward.out", n)
        return n

    def _up_core_link(self, exclude: str = ""):
        for peer, link in self.links.items():
            if (
                peer != exclude
                and link.connected
                and self._roles.get(peer) == "core"
            ):
                return link
        return None

    async def forward_publish_sync(self, msgs: Sequence[Message]) -> int:
        """Sync-mode forward: awaits per-message dispatch acks, with a
        bounded backoff retry per message instead of giving up on the
        first RpcError (the retry is marked as a replay so the receiver
        dedups a delivered-but-ack-lost first attempt)."""
        per_node = self._match_remote(msgs)
        delivered = 0
        for node, node_msgs in per_node.items():
            link = self.links.get(node)
            if link is None:
                # sync mode has no relay/spool path for unlinked peers:
                # make the loss visible instead of skipping silently
                self.broker.metrics.inc(
                    "messages.forward.dropped", len(node_msgs)
                )
                continue
            for msg in node_msgs:
                header, payload = message_to_wire(msg)
                ack = None
                for attempt in range(3):
                    try:
                        h = dict(header, replay=True) if attempt else header
                        ack = await link.forward_request(h, payload)
                        break
                    except RpcError:
                        if attempt == 2:
                            break
                        await asyncio.sleep(
                            0.1 * (2 ** attempt)
                            * (0.5 + self._shared_rng.random())
                        )
                if ack is not None:
                    delivered += ack.get("n", 0)
                elif msg.qos >= 1:
                    self._spool_put(node, header, payload)
        if delivered:
            self.broker.metrics.inc("messages.forward.out", delivered)
        return delivered

    def _match_remote(
        self, msgs: Sequence[Message]
    ) -> Dict[str, List[Message]]:
        per_node: Dict[str, List[Message]] = {}
        for msg, nodes in zip(msgs, self.remote.match([m.topic for m in msgs])):
            for node in nodes:
                per_node.setdefault(node, []).append(msg)
        return per_node

    def forward_shared(self, node: str, msg: Message, group: str,
                       filt: str) -> bool:
        """Targeted one-way forward: `node` delivers to ONE local member
        of (group, filt).  Rides the forward frame with a shared tag, so
        relaying through a core works unchanged."""
        header, payload = message_to_wire(msg)
        header["shared_group"] = group
        header["shared_filt"] = filt
        link = self.links.get(node)
        ok = False
        direct = (
            link is not None
            and link.connected
            and self._status.get(node) != "down"
        )
        if direct:
            ok = link.send_nowait(tp.pack_forward(header, payload))
        elif link is None:
            relay = self._up_core_link(exclude=node)
            if relay is not None:
                h2 = dict(header, relay_to=node)
                ok = relay.send_nowait(tp.pack_forward(h2, payload))
        if ok:
            self.broker.metrics.inc("messages.forward.shared")
        elif msg.qos >= 1 and link is not None:
            # accept responsibility: spool for replay on heal (returning
            # False would make the caller pick ANOTHER node, and the
            # replay would then double-deliver to the group)
            self._spool_put(node, header, payload)
            self.broker.metrics.inc("messages.forward.shared")
            ok = True
        else:
            # QoS0, or an unlinked peer (replicant->replicant) with the
            # relay down: no spool-replay path exists for it, so report
            # the failure honestly — the caller may repick another
            # member node (no double-delivery risk: nothing was queued)
            self.broker.metrics.inc("messages.forward.dropped")
        return bool(ok)

    def forward_semantic(self, node: str, msg: Message,
                         qids: Sequence[int]) -> bool:
        """Targeted semantic forward: `node` owns hub queries `qids`
        that matched this publish (the hub's K_SEM_RES "rem" section).
        The FULL message rides a forward frame tagged with the qids —
        the receiver maps hub->local and fans out; the hub itself only
        ever saw the embed prefix.  Same send/relay/spool ladder as
        :meth:`forward_shared`."""
        header, payload = message_to_wire(msg)
        header["sem_qids"] = [int(q) for q in qids]
        link = self.links.get(node)
        ok = False
        direct = (
            link is not None
            and link.connected
            and self._status.get(node) != "down"
        )
        if direct:
            ok = link.send_nowait(tp.pack_forward(header, payload))
        elif link is None:
            relay = self._up_core_link(exclude=node)
            if relay is not None:
                h2 = dict(header, relay_to=node)
                ok = relay.send_nowait(tp.pack_forward(h2, payload))
        if ok:
            self.broker.metrics.inc("messages.forward.semantic")
            tracept("semantic.forward", node=node, n=len(qids))
        elif msg.qos >= 1 and link is not None:
            self._spool_put(node, header, payload)
            self.broker.metrics.inc("messages.forward.semantic")
            ok = True
        else:
            self.broker.metrics.inc("messages.forward.dropped")
        return bool(ok)

    def dispatch_remote_shared(self, msgs: Sequence[Message]) -> int:
        """Origin-side dispatch for shared groups that have NO local
        member: pick one member-holding peer per (group, filt) and send
        a targeted forward (groups with local members were already
        served by the local dispatch, which itself falls back to
        forward_shared when every local member fails)."""
        n = 0
        for msg in msgs:
            for group, filt in self.remote.match_shared(msg.topic):
                if self.broker.shared.members(group, filt):
                    continue  # local dispatch owns this group
                nodes = sorted(self.remote.shared_nodes(group, filt))
                if not nodes:
                    continue
                # forward_shared returns False only when it accepted NO
                # delivery responsibility (nothing sent, nothing
                # spooled), so trying the next candidate cannot
                # double-deliver to the group
                start = self._shared_rng.randrange(len(nodes))
                for i in range(len(nodes)):
                    node = nodes[(start + i) % len(nodes)]
                    if self.forward_shared(node, msg, group, filt):
                        n += 1
                        break
        return n

    def _on_forward(self, peer: str, header: dict, payload: bytes):
        relay_to = header.pop("relay_to", None)
        if relay_to and relay_to != self.name:
            # core relaying a forward between two unlinked nodes
            link = self.links.get(relay_to)
            if (
                link is not None
                and link.connected
                and link.send_nowait(tp.pack_forward(header, payload))
            ):
                self.broker.metrics.inc("messages.forward.relayed")
            else:
                self.broker.metrics.inc("messages.forward.dropped")
            return None
        group = header.pop("shared_group", None)
        filt = header.pop("shared_filt", None)
        sem_qids = header.pop("sem_qids", None)
        replay = header.pop("replay", None)
        span_t0 = header.pop("span_t0", None)
        mid = header.get("mid")
        if mid and header.get("qos", 0) >= 1:
            # exactly-once at this broker across spool replays/retries:
            # (mid, group, filt) — a generic forward, a targeted shared
            # forward, and a semantic forward of the SAME message are
            # distinct deliveries
            key = (mid, group or "",
                   filt or ("$semantic" if sem_qids is not None else ""))
            seen = self._seen_fwd
            if key in seen:
                seen.move_to_end(key)
                if replay:
                    self.broker.metrics.inc("messages.forward.dup_dropped")
                    return (
                        {"n": 0} if header.get("id") is not None else None
                    )
            else:
                seen[key] = True
                if len(seen) > DEDUP_WINDOW:
                    seen.popitem(last=False)
        msg = message_from_wire(header, payload)
        if sem_qids is not None:
            # targeted semantic delivery: this node owns the matched
            # hub queries (the origin never learns the query texts)
            n = self.broker.dispatch_semantic_forwarded(msg, sem_qids)
        elif group is not None:
            # targeted shared delivery: local members only (the origin
            # already owns cluster-wide responsibility for this copy)
            n = self.broker.dispatch_shared_forwarded(msg, group, filt)
        else:
            n = self.broker.dispatch_forwarded(msg)
        if span_t0 is not None and _spans.enabled():
            # close + report the cross-node leg HERE, exactly once per
            # forwarded copy: dedup-dropped replays returned above, so
            # an at-least-once spool replay still reports one leg
            _spans.close_remote(span_t0, topic=msg.topic,
                                mid=header.get("mid") or "",
                                origin=peer, node=self.name)
        return {"n": n} if header.get("id") is not None else None

    # ------------------------------------------------------------ rpc plane

    async def call(self, peer: str, method: str, params: dict, timeout: float = 5.0) -> dict:
        link = self.links.get(peer)
        if link is None:
            raise RpcError(f"unknown peer {peer!r}")
        if _fault.enabled():
            a = await _fault.ainject("cluster.rpc", err=RpcError)
            if a is not None and a.kind == "drop":
                raise RpcError(f"rpc to {peer} dropped (fault)")
        # bpapi gate: refuse calls the peer announced it cannot serve
        if method in bpapi.CONTRACTS:
            negotiated = self.peer_bpapi.get(peer)
            if negotiated is not None:
                params = dict(params)
                params["_v"] = bpapi.version_for(negotiated, method)
        return await link.rpc(method, params, timeout)

    async def call_retry(
        self,
        peer: str,
        method: str,
        params: dict,
        timeout: float = 5.0,
        retries: int = 3,
        backoff: float = 0.2,
    ) -> dict:
        """Bounded jittered-backoff retry wrapper for IDEMPOTENT RPCs
        (snapshot reads, catch-up fetches).  Never use it for state-
        moving calls like session_takeover: a retry after a lost
        response would re-execute the move."""
        for attempt in range(retries + 1):
            try:
                return await self.call(peer, method, params, timeout=timeout)
            except RpcError:
                if attempt == retries:
                    raise
                await asyncio.sleep(
                    backoff * (2 ** attempt)
                    * (0.5 + self._shared_rng.random())
                )
        raise RpcError("unreachable")  # pragma: no cover

    def _rpc_session_takeover(self, peer: str, params: dict) -> dict:
        """Hand a locally-held session (live or parked) to the peer.

        The serving half of cross-node takeover (`emqx_cm:takeover_session`
        rpc, `emqx_cm.erl:320-361`): a live channel is kicked with
        SESSION_TAKEN_OVER, the session state ships wholesale
        (subscriptions + mqueue + inflight), and this node's routes for
        the client are retracted so publishes chase the new owner."""
        from ..broker.packet import ReasonCode
        from ..broker.persist import session_to_dict

        cid = str(params.get("clientid", ""))
        cm = self.broker.cm
        ch = cm.channels.get(cid)
        if ch is not None and getattr(ch, "session", None) is not None:
            session = ch.session
            cm._kick(ch, ReasonCode.SESSION_TAKEN_OVER)
            # a live session ships with a real deadline (expiry, or a
            # short handoff grace for expiry-0 sessions) so an importer
            # that dies mid-handshake cannot strand it forever
            exp = session.expiry_interval
            expire_at = time.time() + (exp if exp > 0 else 30.0)
            data = session_to_dict(session, expire_at)
            self.broker.client_down(cid, list(session.subscriptions))
            return {"found": True, "live": True, "session": data}
        ent = cm.pending.pop(cid, None)
        if ent is not None:
            session, expire_at = ent
            # the session resumes on the peer: its delayed will must NOT
            # publish here (MQTT-3.1.3-9, same as the local resume path)
            cm.cancel_will(cid)
            cursor = getattr(session, "ds_cursor", None)
            ds = getattr(self.broker, "ds", None)
            if (int(params.get("_v", 1)) >= 2
                    and params.get("mirror") is not None
                    and ds is not None and cursor is not None
                    and getattr(session, "ds_cursor_node", None) is None):
                # v2 cursor handoff (ds/repl.py): ship the session
                # record + only the tail the taker's mirror lacks —
                # O(replication lag), never the materialized queue.
                # (A cursor already pointing at a THIRD node falls
                # through to materialization: the taker's mirror of
                # this node cannot resolve it.)
                resp = self._handoff_session(
                    cid, session, expire_at, cursor, ds,
                    {int(k): (int(v[0]), int(v[1]))
                     for k, v in params["mirror"].items()},
                )
                self.broker.client_down(cid, list(session.subscriptions))
                return resp
            if cm.on_resume:
                # persistence hook: the on-disc copy must die with the
                # handoff or a restart would resurrect a stale duplicate.
                # Passing the session also replays the durable log into
                # its mqueue (logs are node-local; the peer gets the
                # messages wholesale, not an unreadable cursor)
                cm.on_resume(cid, session)
            data = session_to_dict(session, expire_at)
            self.broker.client_down(cid, list(session.subscriptions))
            return {"found": True, "live": False, "session": data}
        return {"found": False}

    def _handoff_session(
        self, cid: str, session, expire_at: float, cursor: dict, ds,
        mirror: Dict[int, Tuple[int, int]],
    ) -> dict:
        """Serving half of the v2 cursor-handoff takeover: per shard,
        ship only `[max(cursor, mirror_end), durable_end)` — the range
        the taker's mirror does not already hold.  With replication
        healthy the tail is empty and the response is O(session
        record)."""
        from ..broker.persist import session_to_dict

        ds.flush_all()  # the tail read below must see every append
        tail: Dict[str, dict] = {}
        shipped = 0
        for shard, cur in cursor.items():
            coff = int(cur[1])
            shard_log = ds.logs[shard]
            end = shard_log.next_offset
            mbase, mend = mirror.get(shard, (end, end))
            # the mirror only helps if it reaches back to the cursor
            lo = max(coff, mend) if mbase <= coff else coff
            if lo >= end:
                continue
            records: List[str] = []
            gap = 0
            first = lo
            off = lo
            while off < end:
                got, off, g = shard_log.read_from(off, 512)
                gap += g
                if not got:
                    break
                if not records:
                    first = got[0][0]
                records.extend(
                    base64.b64encode(p).decode("ascii") for _o, p in got
                )
            if records or gap:
                tail[str(shard)] = {
                    "first": first, "records": records, "gap": gap,
                }
                shipped += len(records)
        data = session_to_dict(session, expire_at, cursor=cursor)
        data["cursor_node"] = self.name
        p = getattr(self.broker, "persistence", None)
        if p is not None:
            # the on-disc copy dies with the handoff (a restart must
            # not resurrect a duplicate) — but WITHOUT the replay half
            # of on_resume; not materializing is the point
            p.on_handoff(cid)
        tracept("ds.repl.handoff", clientid=cid, side="serve",
                shards=len(cursor), tail_records=shipped)
        self.broker.metrics.inc("ds.repl.handoffs")
        return {"found": True, "live": False, "handoff": True,
                "session": data, "tail": tail}

    async def import_session(self, clientid: str) -> bool:
        """Pull `clientid`'s session from whichever peer holds it.

        The calling half of cross-node takeover: runs under the cluster
        lock (duplicate simultaneous reconnects race for it; the loser
        finds the session already local).  Instead of a replicated
        clientid->node registry (`emqx_cm_registry`'s mria table), the
        owner is found by fan-out query — at broker cluster sizes the
        connect-time RPC round is cheaper than replicating every session
        movement into all nodes.  Returns True when a session is local
        (imported now or already here)."""
        from ..broker.persist import session_from_dict

        cm = self.broker.cm
        if clientid in cm.channels or clientid in cm.pending:
            # local copy wins; still sweep remote duplicates in the
            # background — a partition-degraded takeover can leave a
            # second live copy elsewhere, and single-session-per-clientid
            # must converge (registry-based emqx kicks cluster-wide)
            self._spawn_bg(self.discard_remote(clientid))
            return True

        async def attempt() -> bool:
            if clientid in cm.channels or clientid in cm.pending:
                return True
            resp = await self._query_takeover(clientid)
            if resp is None:
                return False
            data = resp["session"]
            session = session_from_dict(data)
            if resp.get("handoff"):
                # cursor-handoff form: fold the shipped tail into our
                # mirror where contiguous (durable before the client
                # resumes); the leftovers replay from RAM at resume
                origin = data.get("cursor_node") or ""
                tail = {int(k): v
                        for k, v in (resp.get("tail") or {}).items()}
                if self.ds_repl is not None and tail:
                    tail = self.ds_repl.absorb_tail(origin, tail)
                session.ds_handoff_tail = tail or None
                tracept("ds.repl.handoff", clientid=clientid,
                        side="import", origin=origin,
                        tail_shards=len(tail))
            exp = data.get("expire_at")
            cm.pending[clientid] = (
                session, exp if exp is not None else float("inf")
            )
            for f, opts in session.subscriptions.items():
                self.broker.subscribe(clientid, f, opts)
            return True

        try:
            return await self.locker.trans(
                f"takeover:{clientid}", attempt, retries=10
            )
        except TimeoutError:
            # lock unavailable (authority partitioned): best effort, like
            # ekka_locker degrading rather than refusing connects
            return await attempt()

    async def _query_takeover(self, clientid: str):
        """Concurrent per-peer takeover query; first found wins (any
        second copy is already removed at its origin by the RPC itself,
        which also makes duplicates self-heal).  Returns the full found
        response ({"session": ..., optionally "handoff"/"tail"}).  Each
        peer is offered this node's ds-mirror coverage OF THAT PEER, so
        an origin with a replicated log can answer in cursor-handoff
        form instead of materializing the queue."""
        peers = self.up_peers()
        if not peers:
            return None

        def params_for(peer: str) -> dict:
            d: dict = {"clientid": clientid}
            if self.ds_repl is not None:
                d["mirror"] = {
                    str(k): [lo, hi]
                    for k, (lo, hi)
                    in self.ds_repl.mirror_state(peer).items()
                }
            return d

        results = await asyncio.gather(
            *(
                self.call(p, "session_takeover", params_for(p), timeout=3.0)
                for p in peers
            ),
            return_exceptions=True,
        )
        found = None
        for resp in results:
            if isinstance(resp, dict) and resp.get("found"):
                if found is None:
                    found = resp
        return found

    async def discard_remote(self, clientid: str) -> None:
        """clean_start: purge any remote copy of the session so a later
        clean_start=false reconnect cannot resurrect stale state (the
        reference's open_session discards cluster-wide via the registry).
        Reuses the takeover RPC — the origin retracts routes and drops
        the session; the pulled state is simply discarded.  Queries run
        concurrently so one slow peer does not stall CONNACK."""
        await self._query_takeover(clientid)

    def _rpc_publish(self, peer: str, params: dict) -> dict:
        """Remote-origin publish (management API proxying)."""
        msg = Message(
            topic=params["topic"],
            payload=params.get("payload", "").encode(),
            qos=params.get("qos", 0),
            retain=params.get("retain", False),
        )
        return {"n": self.broker.publish(msg)}
