"""Cluster-wide serialized operations — the `emqx_cluster_rpc` analog.

Reference (`apps/emqx_conf/src/emqx_cluster_rpc.erl`, SURVEY.md §5.6):
cluster config mutations are serialized through a replicated MFA log
with a per-node commit cursor and catch-up recovery.

Redesign: a deterministic coordinator (lowest node name among up peers,
self included) assigns sequence numbers.  `multicall(op, params)` sends
the op to the coordinator, which appends it to its log and broadcasts
`cluster_apply`; every node applies ops strictly in order through its
registered handler table and keeps a cursor.  A node that detects a gap
pulls the log tail from the coordinator (`cluster_catchup`).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .node import ClusterNode
from .transport import RpcError


class ClusterRpc:
    def __init__(self, node: ClusterNode):
        self.node = node
        self.handlers: Dict[str, Callable[[dict], None]] = {}
        # full replicated log: every node appends entries as it applies
        # them, so any node can take over as coordinator with history
        # intact (the reference keeps the MFA log in a replicated mnesia
        # table for the same reason)
        self.log: List[Tuple[int, str, dict]] = []
        self.cursor = 0  # last applied seq
        node.transport.rpc_handlers["cluster_commit"] = self._rpc_commit
        node.transport.rpc_handlers["cluster_apply"] = self._rpc_apply
        node.transport.rpc_handlers["cluster_catchup"] = self._rpc_catchup

    def register(self, op: str, handler: Callable[[dict], None]) -> None:
        self.handlers[op] = handler

    def coordinator(self) -> str:
        return min([self.node.name] + self.node.up_peers())

    async def multicall(self, op: str, params: dict) -> int:
        """Commit one op cluster-wide; returns its sequence number."""
        coord = self.coordinator()
        if coord == self.node.name:
            return await self._commit(op, params)
        resp = await self.node.call(coord, "cluster_commit", {"op": op, "params": params})
        return resp["seq"]

    async def _commit(self, op: str, params: dict) -> int:
        seq = self.cursor + 1
        self._apply_entry(seq, op, params)
        entry = {"seq": seq, "op": op, "params": params}
        for peer in self.node.up_peers():
            try:
                await self.node.call(peer, "cluster_apply", entry)
            except RpcError:
                pass  # the peer catches up on its next gap detection
        return seq

    def _apply_entry(self, seq: int, op: str, params: dict) -> bool:
        if seq != self.cursor + 1:
            return False
        handler = self.handlers.get(op)
        if handler is not None:
            try:
                handler(params)
            except Exception:
                pass  # handler failure must not wedge the log cursor
        self.log.append((seq, op, params))
        self.cursor = seq
        return True

    # --------------------------------------------------------- rpc handlers

    async def _rpc_commit(self, peer: str, params: dict) -> dict:
        if self.coordinator() != self.node.name:
            raise RpcError("not the coordinator")
        seq = await self._commit(params["op"], params["params"])
        return {"seq": seq}

    async def _rpc_apply(self, peer: str, entry: dict) -> dict:
        ok = self._apply_entry(entry["seq"], entry["op"], entry["params"])
        if not ok and entry["seq"] > self.cursor:
            await self.catchup(peer)
        return {"cursor": self.cursor}

    async def catchup(self, coord: Optional[str] = None) -> None:
        coord = coord or self.coordinator()
        if coord == self.node.name:
            return
        try:
            resp = await self.node.call(
                coord, "cluster_catchup", {"from": self.cursor}
            )
        except RpcError:
            return
        for seq, op, params in resp.get("entries", []):
            self._apply_entry(seq, op, params)

    def _rpc_catchup(self, peer: str, params: dict) -> dict:
        frm = params.get("from", 0)
        return {"entries": [e for e in self.log if e[0] > frm]}
