"""bcrypt ($2b$) password hashing over the native EksBlowfish core.

The reference pulls bcrypt in as a C NIF (`mix.exs` bcrypt_dep;
`emqx_passwd.erl` hash verification).  Here the hot loop lives in
`native/bcrypt.cc`; this wrapper supplies

* the Blowfish initial state, derived at first use from pi's fractional
  hex expansion (Machin arctan series over Python bigints — the
  canonical constants, computed rather than copied);
* the `$2b$` wire format: bcrypt's nonstandard base64 alphabet, salt
  generation, constant-time verification.

API mirrors the familiar bcrypt package: gensalt / hashpw / checkpw.
"""

from __future__ import annotations

import ctypes
import hmac
import os
import threading

from .ops import native

_ALPHABET = "./ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"
_B64_INV = {c: i for i, c in enumerate(_ALPHABET)}

_N_WORDS = 18 + 4 * 256  # P-array + S-boxes

_init_lock = threading.Lock()
_initialized = False


# ------------------------------------------------------------------ pi

def _pi_fraction_words(n_words: int) -> list:
    """First `n_words` 32-bit words of pi's fractional part in hex.

    Machin's formula pi = 16*atan(1/5) - 4*atan(1/239) evaluated in
    fixed-point integer arithmetic with guard bits.  Word 0 is
    0x243F6A88 — the universally known leading digits 3.243F6A88...
    """
    bits = 32 * n_words + 64  # guard bits
    one = 1 << bits

    def atan_inv(x: int) -> int:
        # atan(1/x) * 2^bits, alternating series over integers
        total = 0
        term = one // x
        x2 = x * x
        k = 0
        while term:
            total += term // (2 * k + 1) if k % 2 == 0 else -(term // (2 * k + 1))
            term //= x2
            k += 1
        return total

    pi = 16 * atan_inv(5) - 4 * atan_inv(239)
    frac = pi - 3 * one  # fractional part, bits of precision
    words = []
    for i in range(n_words):
        shift = bits - 32 * (i + 1)
        words.append((frac >> shift) & 0xFFFFFFFF)
    return words


def _ensure_init() -> ctypes.CDLL:
    global _initialized
    lib = native.get_lib()
    if lib is None:
        raise RuntimeError(
            "bcrypt requires the native library (native/bcrypt.cc); "
            "g++ build failed or unavailable"
        )
    if not _initialized:
        with _init_lock:
            if not _initialized:
                words = _pi_fraction_words(_N_WORDS)
                assert words[0] == 0x243F6A88, hex(words[0])  # pi sanity
                arr = (ctypes.c_uint32 * _N_WORDS)(*words)
                lib.etpu_bcrypt_init(arr)
                _initialized = True
    return lib


# ------------------------------------------------------------- base64

def _b64_encode(data: bytes) -> str:
    out = []
    i = 0
    while i < len(data):
        c1 = data[i]
        out.append(_ALPHABET[c1 >> 2])
        c1 = (c1 & 0x03) << 4
        if i + 1 >= len(data):
            out.append(_ALPHABET[c1])
            break
        c2 = data[i + 1]
        c1 |= c2 >> 4
        out.append(_ALPHABET[c1])
        c1 = (c2 & 0x0F) << 2
        if i + 2 >= len(data):
            out.append(_ALPHABET[c1])
            break
        c3 = data[i + 2]
        c1 |= c3 >> 6
        out.append(_ALPHABET[c1])
        out.append(_ALPHABET[c3 & 0x3F])
        i += 3
    return "".join(out)


def _b64_decode(s: str, n_bytes: int) -> bytes:
    bits = 0
    acc = 0
    out = bytearray()
    for ch in s:
        v = _B64_INV.get(ch)
        if v is None:
            raise ValueError(f"invalid bcrypt base64 char {ch!r}")
        acc = (acc << 6) | v
        bits += 6
        if bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    return bytes(out[:n_bytes])


# ----------------------------------------------------------------- api

def gensalt(rounds: int = 12) -> str:
    if not 4 <= rounds <= 31:
        raise ValueError("bcrypt cost must be in [4, 31]")
    return f"$2b$" + f"{rounds:02d}$" + _b64_encode(os.urandom(16))


def _parse(salt_or_hash: str):
    parts = salt_or_hash.split("$")
    if len(parts) < 4 or parts[1] not in ("2b", "2a", "2y") or len(parts[3]) < 22:
        raise ValueError("malformed bcrypt salt/hash")
    rounds = int(parts[2])
    salt = _b64_decode(parts[3][:22], 16)
    return parts[1], rounds, salt


def hashpw(password: bytes, salt: str) -> str:
    """Hash `password` with a `$2b$NN$...` salt (or full hash) string."""
    if isinstance(password, str):
        password = password.encode("utf-8")
    variant, rounds, salt_raw = _parse(salt)
    lib = _ensure_init()
    key = password[:72] + b"\x00"  # $2b$: cap, then trailing NUL
    out = (ctypes.c_uint8 * 24)()
    rc = lib.etpu_bcrypt_hash(
        (ctypes.c_uint8 * len(key)).from_buffer_copy(key),
        len(key),
        (ctypes.c_uint8 * 16).from_buffer_copy(salt_raw),
        rounds,
        out,
    )
    if rc != 0:
        raise RuntimeError("bcrypt native core rejected input")
    digest = bytes(out)[:23]
    return f"${variant}${rounds:02d}$" + _b64_encode(salt_raw)[:22] + _b64_encode(digest)


def checkpw(password: bytes, hashed: str) -> bool:
    try:
        return hmac.compare_digest(hashpw(password, hashed), hashed)
    except (ValueError, RuntimeError):
        return False


def available() -> bool:
    try:
        _ensure_init()
        return True
    except RuntimeError:
        return False
