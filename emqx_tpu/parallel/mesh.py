"""Device-mesh helpers.

The reference scales routing state by replicating mria tables to every core
node and sharding fan-out into buckets (SURVEY.md §2.4).  The TPU-native
design instead *partitions the filter table across chips* on a 1-D mesh:
each chip owns 1/D of the filters (disjoint), matches the full publish batch
against its local shard, and the per-subscriber-shard hit counts are merged
with `psum_scatter` over ICI.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FILTER_AXIS = "filters"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(devs, axis_names=(FILTER_AXIS,))


def shard_leading(mesh: Mesh) -> NamedSharding:
    """Shard a stacked [D, ...] array along its leading axis."""
    return NamedSharding(mesh, P(FILTER_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
