"""Multi-chip sharding: device mesh, sharded match, collective merges."""
