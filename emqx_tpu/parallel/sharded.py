"""Filter-sharded multi-chip match engine.

Design (BASELINE.json north star, SURVEY.md §5.7/§5.8):

* the filter population is partitioned across the mesh's ``filters`` axis —
  chip ``d`` owns the hash-table shard for filters with ``fid % D == d``
  (disjoint, so cross-chip merge is a plain sum);
* a publish batch is replicated to every chip; each chip matches it against
  its local table with the same static-shape kernel as single-chip;
* THE DISPATCH CONTRACT is the compact fid return
  (`sharded_match_compact` / `sharded_step_compact`): filter partitions
  are disjoint, so the host-side union of per-chip top-k blocks is the
  exact matched-fid set, which the broker expands to receivers through
  `SubscriberShards` — the multi-chip analog of
  `emqx_broker:dispatch`'s shard-bucket fold (`emqx_broker.erl:520-524`).
  Per-topic *counts* cannot identify receivers, so the collective-merge
  path below is deliberately NOT the delivery path;
* the ``psum_scatter`` merge (`sharded_match_counts` / `sharded_step`):
  matched fids map to *subscriber shards* (the reference's fan-out
  buckets, `emqx_broker_helper.erl:82-91`) via a replicated ``dest``
  array and per-(topic, subscriber-shard) hit counts merge over ICI,
  leaving each chip its 1/D fan-out slice.  This is the fan-out
  ACCOUNTING plane — per-topic fan-out metrics, overload decisions on
  huge fan-outs, and the mesh "training step" the driver dry-runs —
  kept off the broker's delivery path by design;
* subscription churn reaches the device as per-shard scatter deltas
  (`sharded_apply_delta`) or fused into the match dispatch
  (`sharded_step_compact_packed` on the broker path, `sharded_step` on
  the counts path) — no re-upload, mirroring `emqx_router:do_add_route`'s
  incremental trie mutation;
* THE DISPATCH IS PIPELINED: up to ``engine.pipeline_depth`` ticks may
  be submitted-but-unresolved at once, sharing the stacked tables
  through non-donating dispatches; churn-fused ticks donate the table
  buffers after a window drain.  See ShardedMatchEngine.match_submit
  and README "Sharded dispatch pipeline".

Everything is jit-compiled over a `jax.sharding.Mesh`; tested on a virtual
8-device CPU mesh, deployed unchanged on a v5e-8.
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from .. import fault as _fault
from ..broker import topic as topiclib
from ..models.reference import CpuTrieIndex
from ..observe.flight import (
    FlightRecorder,
    LatencyHistogram,
    PATH_DEVICE,
    R_FORCED,
)
from ..observe import tracepoints as _tps
from ..observe.tracepoints import tp
from ..ops import hashing
from ..ops.match import (
    DeviceTables,
    TopicBatch,
    apply_delta_impl,
    match_batch,
    next_pow2,
    unpack_topic_batch,
)
from ..ops.prep import PrepStage, PrepTicket, TopicPrep
from ..ops.tables import MatchTables
from .mesh import FILTER_AXIS, make_mesh


def _count_and_merge(
    t: DeviceTables, b: TopicBatch, dest: jax.Array, n_sub: int
) -> jax.Array:
    """Local match -> per-subscriber-shard counts -> psum_scatter merge.

    Runs inside shard_map. Returns this chip's [B, n_sub/D] slice.
    """
    matched = match_batch(t, b)  # [B, M] global fids or -1
    ok = matched >= 0
    fids = jnp.where(ok, matched, 0)
    sub = jnp.where(ok, jnp.take(dest, fids, mode="clip"), n_sub)  # n_sub drops
    counts = jnp.zeros((matched.shape[0], n_sub), dtype=jnp.int32)
    counts = jax.vmap(lambda c, i: c.at[i].add(1, mode="drop"))(counts, sub)
    # Disjoint filter partitions -> counts add exactly across chips.
    return jax.lax.psum_scatter(counts, FILTER_AXIS, scatter_dimension=1, tiled=True)


def _unstack(st: DeviceTables) -> DeviceTables:
    """Drop the leading per-device dim inside shard_map."""
    return jax.tree.map(lambda a: a[0], st)


@functools.partial(jax.jit, static_argnames=("mesh", "n_sub"))
def sharded_match_counts(
    stacked: DeviceTables,  # arrays stacked [D, ...], sharded on axis 0
    batch: TopicBatch,  # replicated
    dest: jax.Array,  # [Fcap] i32 fid -> subscriber shard, replicated
    *,
    mesh: Mesh,
    n_sub: int,
) -> jax.Array:
    """Returns hit counts [B, n_sub], sharded over n_sub along the mesh."""

    def local(st, b, d):
        return _count_and_merge(_unstack(st), b, d, n_sub)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS), P(), P()),
        out_specs=P(None, FILTER_AXIS),
    )(stacked, batch, dest)


@functools.partial(jax.jit, static_argnames=("mesh",), donate_argnums=(0,))
def sharded_apply_delta(
    stacked: DeviceTables,
    delta_slots: jax.Array,  # [D, K] i32, -1 padded
    delta_ka: jax.Array,  # [D, K] u32
    delta_kb: jax.Array,  # [D, K] u32
    delta_val: jax.Array,  # [D, K] i32
    *,
    mesh: Mesh,
) -> DeviceTables:
    """Scatter per-shard churn deltas into the sharded tables (donated)."""

    def local(st, sl, ka, kb, vv):
        t = apply_delta_impl(_unstack(st), sl[0], ka[0], kb[0], vv[0])
        return jax.tree.map(lambda a: a[None], t)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS),) * 5,
        out_specs=P(FILTER_AXIS),
    )(stacked, delta_slots, delta_ka, delta_kb, delta_val)


@functools.partial(jax.jit, static_argnames=("mesh", "n_sub"), donate_argnums=(0,))
def sharded_step(
    stacked: DeviceTables,  # [D, ...] sharded, donated
    delta_slots: jax.Array,  # [D, K] i32, -1 padded; per-shard table writes
    delta_ka: jax.Array,  # [D, K] u32
    delta_kb: jax.Array,  # [D, K] u32
    delta_val: jax.Array,  # [D, K] i32
    batch: TopicBatch,  # replicated
    dest: jax.Array,  # [Fcap] replicated
    *,
    mesh: Mesh,
    n_sub: int,
) -> Tuple[DeviceTables, jax.Array]:
    """One full engine step: apply subscription churn, then match + merge.

    This is the flagship "training step" — route-table mutation (the
    reference's `emqx_router:do_add_route`) fused with the publish hot path
    (`emqx_broker:publish` -> match -> dispatch), executed as one jit over
    the mesh with donated table buffers.
    """

    def local(st, sl, ka, kb, vv, b, d):
        t = apply_delta_impl(_unstack(st), sl[0], ka[0], kb[0], vv[0])
        counts = _count_and_merge(t, b, d, n_sub)
        return jax.tree.map(lambda a: a[None], t), counts

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS),) * 5 + (P(), P()),
        out_specs=(P(FILTER_AXIS), P(None, FILTER_AXIS)),
    )(stacked, delta_slots, delta_ka, delta_kb, delta_val, batch, dest)


@functools.partial(jax.jit, static_argnames=("mesh", "kcap"))
def sharded_match_compact(
    stacked: DeviceTables,
    batch: TopicBatch,
    *,
    mesh: Mesh,
    kcap: int,
) -> Tuple[jax.Array, jax.Array]:
    """Dispatch-oriented device->host return: compact matched pairs.

    Each chip compacts its local [B, M] shape-hit row to its top
    ``min(kcap, M)`` fids (filter partitions are disjoint, so the union
    across chips is exact), plus a per-topic local hit count so the host
    can detect the rare per-chip overflow and fall back to the full
    return.  Transfers [D, B, k] + [D, B] instead of [D, B, M] — the
    contract `emqx_broker:dispatch` needs (matched fids), at a size the
    tunnel can afford.
    """
    M = stacked.k_a.shape[-1]
    k = min(kcap, M)

    def local(st, b):
        matched = match_batch(_unstack(st), b)  # [B, M]
        counts = jnp.sum(matched >= 0, axis=-1, dtype=jnp.int32)
        top, _ = jax.lax.top_k(matched, k)  # sorted desc; -1 pads
        return top[None], counts[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS), P()),
        out_specs=(P(FILTER_AXIS), P(FILTER_AXIS)),
    )(stacked, batch)


# NOT buffer-donating: pipelined pendings hold the pre-step table
# version for the overflow refetch (same reasoning as the single-chip
# fused_step_sparse; the non-donated scatter costs one on-device copy).
@functools.partial(jax.jit, static_argnames=("mesh", "kcap"))
def sharded_step_compact(
    stacked: DeviceTables,  # [D, ...] sharded
    delta_slots: jax.Array,  # [D, K] i32, -1 padded
    delta_ka: jax.Array,  # [D, K] u32
    delta_kb: jax.Array,  # [D, K] u32
    delta_val: jax.Array,  # [D, K] i32
    batch: TopicBatch,  # replicated
    *,
    mesh: Mesh,
    kcap: int,
) -> Tuple[DeviceTables, jax.Array, jax.Array]:
    """Broker-facing flagship step: per-shard churn scatter fused with
    the compact match in ONE dispatch over the mesh — the multi-chip
    twin of the single-chip `ops.match.fused_step_sparse`, so a churn
    tick costs the same round trip as a pure match tick (round-3 verdict
    weak #3; the mutation+match transaction unity of
    `emqx_router.erl:117-120`).  Returns (tables, top [D,B,k], counts)."""
    M = stacked.k_a.shape[-1]
    k = min(kcap, M)

    def local(st, sl, ka, kb, vv, b):
        t = apply_delta_impl(_unstack(st), sl[0], ka[0], kb[0], vv[0])
        matched = match_batch(t, b)  # [B, M]
        counts = jnp.sum(matched >= 0, axis=-1, dtype=jnp.int32)
        top, _ = jax.lax.top_k(matched, k)
        return jax.tree.map(lambda a: a[None], t), top[None], counts[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS),) * 5 + (P(),),
        out_specs=(P(FILTER_AXIS), P(FILTER_AXIS), P(FILTER_AXIS)),
    )(stacked, delta_slots, delta_ka, delta_kb, delta_val, batch)


def _compact_topk(matched: jax.Array, k: int) -> jax.Array:
    """[B, M] shape-hit rows -> the k largest fids per row, descending,
    -1 padded — k iterative max+mask passes instead of `jax.lax.top_k`.

    Each shape hits at most one fid (one masked hash per shape), so rows
    are duplicate-free and the iterative max is exactly top_k.  On the
    CPU mesh the sort-based `top_k` was ~40% of the whole dispatch
    (measured: 9.5 ms -> 5.7 ms per 512-topic tick at M=32); with the
    adaptive kcap keeping k small (4-8 covers steady traffic) the k
    passes are O(k*B*M) elementwise ops, no sort anywhere."""
    outs = []
    m = matched
    idx = jnp.arange(m.shape[-1], dtype=jnp.int32)[None, :]
    for _ in range(k):
        mx = jnp.max(m, axis=-1)
        outs.append(mx)
        am = jnp.argmax(m, axis=-1).astype(jnp.int32)
        m = jnp.where(idx == am[:, None], -1, m)
    return jnp.stack(outs, axis=-1)  # [B, k]


@functools.partial(jax.jit, static_argnames=("mesh", "kcap"))
def sharded_match_compact_packed(
    stacked: DeviceTables,
    pbatch: jax.Array,  # [B, 2L+2] u32 packed topic batch, replicated
    *,
    mesh: Mesh,
    kcap: int,
) -> Tuple[jax.Array, jax.Array]:
    """Pipelined-dispatch flavor of `sharded_match_compact`:

    * the topic batch arrives as ONE packed u32 array (one host->device
      transfer instead of four; `ops.match.pack_topic_batch_np` layout),
    * per-topic counts come back as u16 (saturated at 0xFFFF -> host
      refetch), halving the counts leg of `bytes_down`,
    * compaction is the iterative `_compact_topk`, not a sort.

    NOT buffer-donating: up to `engine.pipeline_depth` in-flight ticks
    share the same stacked tables — donation happens only on churn-fused
    ticks, after a window drain (`sharded_step_compact_packed`)."""
    M = stacked.k_a.shape[-1]
    k = min(kcap, M)

    def local(st, pb):
        matched = match_batch(_unstack(st), unpack_topic_batch(pb))
        counts = jnp.minimum(
            jnp.sum(matched >= 0, axis=-1, dtype=jnp.int32), 0xFFFF
        ).astype(jnp.uint16)
        return _compact_topk(matched, k)[None], counts[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS), P()),
        out_specs=(P(FILTER_AXIS), P(FILTER_AXIS)),
    )(stacked, pbatch)


# Donating: churn-fused ticks run with the in-flight window DRAINED
# (match_submit), so no pending holds the pre-step table version and the
# scatter can reuse the table buffers in place instead of paying an
# on-device copy per churn tick.
@functools.partial(
    jax.jit, static_argnames=("mesh", "kcap"), donate_argnums=(0,)
)
def sharded_step_compact_packed(
    stacked: DeviceTables,  # [D, ...] sharded, donated
    delta_slots: jax.Array,  # [D, K] i32, -1 padded
    delta_ka: jax.Array,  # [D, K] u32
    delta_kb: jax.Array,  # [D, K] u32
    delta_val: jax.Array,  # [D, K] i32
    pbatch: jax.Array,  # [B, 2L+2] u32, replicated
    *,
    mesh: Mesh,
    kcap: int,
) -> Tuple[DeviceTables, jax.Array, jax.Array]:
    """Churn scatter fused with the packed compact match in ONE mesh
    dispatch (`sharded_step_compact` with the pipelined wire format)."""
    M = stacked.k_a.shape[-1]
    k = min(kcap, M)

    def local(st, sl, ka, kb, vv, pb):
        t = apply_delta_impl(_unstack(st), sl[0], ka[0], kb[0], vv[0])
        matched = match_batch(t, unpack_topic_batch(pb))
        counts = jnp.minimum(
            jnp.sum(matched >= 0, axis=-1, dtype=jnp.int32), 0xFFFF
        ).astype(jnp.uint16)
        top = _compact_topk(matched, k)
        return jax.tree.map(lambda a: a[None], t), top[None], counts[None]

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(FILTER_AXIS),) * 5 + (P(),),
        out_specs=(P(FILTER_AXIS), P(FILTER_AXIS), P(FILTER_AXIS)),
    )(stacked, delta_slots, delta_ka, delta_kb, delta_val, pbatch)


@functools.partial(jax.jit, static_argnames=("rows",))
def _slice_live(hits: jax.Array, counts: jax.Array, *, rows: int):
    """Device-side row slice: fetch only the live topic rows of the
    padded batch (the padded tail can never match — length -1)."""
    return hits[:, :rows], counts[:, :rows]


def _round_up(n: int, g: int) -> int:
    return ((n + g - 1) // g) * g


@functools.partial(jax.jit, static_argnames=("mesh",))
def sharded_match_fids(
    stacked: DeviceTables,
    batch: TopicBatch,
    *,
    mesh: Mesh,
) -> jax.Array:
    """Returns matched fids [D, B, M] (−1 padded), sharded over D."""

    def local(st, b):
        return match_batch(_unstack(st), b)[None]

    return shard_map(
        local, mesh=mesh, in_specs=(P(FILTER_AXIS), P()), out_specs=P(FILTER_AXIS)
    )(stacked, batch)


class ShardedMatchEngine:
    """Host frontend over the sharded device tables.

    The host keeps canonical truth (global filter registry + per-shard
    `MatchTables`); device arrays are patched incrementally from the per-shard
    delta logs, with full re-stack only after capacity growth.  Filters
    deeper than the device level cap go to a host-side trie fallback, as in
    `TopicMatchEngine`.
    """

    def __init__(
        self,
        mesh: Optional[Mesh] = None,
        space: Optional[hashing.HashSpace] = None,
        n_sub_shards: int = 1024,
        min_batch: int = 64,
        kcap: int = 128,
        use_churn_plane: Optional[bool] = None,
        churn_shards: int = 16,
    ):
        self.mesh = mesh or make_mesh()
        self.space = space or hashing.HashSpace()
        self.D = self.mesh.devices.size
        if n_sub_shards % self.D:
            n_sub_shards += self.D - n_sub_shards % self.D
        self.n_sub = n_sub_shards
        self.min_batch = min_batch
        self.kcap = kcap  # per-chip compact-return cap (match())

        self.shards = [MatchTables(self.space) for _ in range(self.D)]
        self._fids: Dict[str, int] = {}
        self._refs: Dict[int, int] = {}
        self._words: Dict[int, List[str]] = {}
        self._fbytes: Dict[int, bytes] = {}
        # single-mutator contract (same as TopicMatchEngine / ops/
        # tables.py): runtime churn is serialized on the event loop,
        # boot warm-restore runs on the pre-serving to_thread worker;
        # collect threads only read, and mid-grow array swaps hand them
        # the intact old array (benign-dirty-read model, PR 6)
        self._next_fid = 0  # analysis: owner=loop
        self._free_fids: List[int] = []

        # checkpoint WAL hook (checkpoint/manager.py), same contract as
        # the single-chip engine: (adds, removes) per committed mutation
        self.on_churn = None

        # exact-match guarantee (same contract as TopicMatchEngine)
        self.verify_matches = True
        self.collision_count = 0
        self.on_collision = None
        self._dest_cap = 1024
        self._dest = np.zeros(self._dest_cap, dtype=np.int32)  # analysis: owner=loop
        self._dest_dirty = True

        self._deep = CpuTrieIndex()
        self._deep_fids: Set[int] = set()

        # native fid -> filter-string registry (same contract as the
        # single-chip engine): registry-backed device-hit verification,
        # no per-batch Python blob assembly; None without the native lib
        from ..ops import native as _native

        self._reg = _native.make_registry()

        # parallel churn plane (native/churn.cc, same contract as the
        # single-chip engine): sharded filter -> (fid, refcount, key)
        # truth mutated GIL-free on the worker pool.  The plane runs
        # WITHOUT table placement here — new keys land per DEVICE shard
        # through churn_insert_keys so deltas stay per-shard for the
        # fused mesh dispatch.
        self._plane = None
        if use_churn_plane is None:
            use_churn_plane = True
        if use_churn_plane and self._reg is not None:
            self._plane = _native.make_churn_plane(self.space, churn_shards)

        # churn shed-load visibility (note_churn_shed, same contract as
        # the single-chip engine)
        self.churn_shed = 0
        self._churn_shed_rec = 0

        self._stacked: Optional[DeviceTables] = None
        self._dest_dev: Optional[jax.Array] = None

        # fused prep front (ops/prep.py): split + hash + two-generation
        # topic memo + in-tick dedup + bucket-padded pack in ONE native
        # pass (`native/prep.cc`, GIL-released, worker-pool parallel;
        # pure-Python fallback when the lib is absent).  The memo arrays
        # live behind the native boundary (C++-owned, the ChurnPlane
        # discipline) and the staging-buffer pool rides inside it —
        # persistent per-(B, L) buffers recycled across ticks.
        self._prep = TopicPrep(self.space, min_batch=min_batch)
        # prep-ahead pipeline stage (lazily started; see prep_submit):
        # a persistent worker preps tick N+1..N+depth while tick N's
        # dispatch is in flight; a stalled worker degrades to inline
        # prep at match_submit (fault site engine.prep)
        self._prep_stage: Optional[PrepStage] = None  # analysis: owner=loop
        self.prep_timeout = 0.25  # claim wait before the inline degrade
        self.prep_degraded = 0  # stalled/mismatched tickets served inline
        # registry mutation generation: a coalesced pre-dispatched tick
        # is claimable only while the tables it matched against are
        # still current (any churn bumps this and the drain resolves it)
        self._mut_gen = 0  # analysis: owner=loop

        # ---- pipelined dispatch window (engine.pipeline_depth) --------
        # Up to `pipeline_depth` submitted-but-unresolved ticks share the
        # same (non-donated) stacked tables, so host prep of tick N+1
        # overlaps device compute of tick N and the async fetch of tick
        # N-1.  Churn-fused ticks DONATE the tables (no on-device copy),
        # which requires draining the window first — see match_submit.
        self.pipeline_depth = 4
        self._inflight: List["_ShardedPending"] = []
        # adaptive window clamp: depth N must never underperform depth 1
        # (BENCH_TABLE mesh w5/w3 regression).  Two signals drive the
        # EFFECTIVE window: (1) churn-fused ticks drain the window at
        # submit, so when (nearly) every tick fuses churn the window
        # never fills and deep submits only add bookkeeping — an EWMA of
        # the drain fraction clamps to 1 past `drain_clamp`; (2) a
        # measured A/B cost controller (median submit-to-submit interval
        # per mode; deep serves only when it measures a real win past
        # `depth_margin` — real hardware's overlap win clears it, a
        # serialized host's bookkeeping overhead never does) re-probes
        # the losing mode every `depth_probe_interval` ticks.
        self._eff_depth = self.pipeline_depth
        self.drain_clamp = 0.5  # churn-drain EWMA above this -> eff 1
        self._drain_ewma = 0.0
        self.depth_probe_interval = 64  # ticks between loser re-probes
        # (64: a stuck verdict re-probes within ~1.5 bench windows —
        # the coalesced group dispatch only shows its win while deep
        # actually serves, so the idle mode must get its chance often)
        self.depth_probe_len = 6  # submit-interval samples per verdict
        self.depth_margin = 0.05  # deep must win by this to serve
        self.depth_win_streak = 2  # consecutive winning verdicts needed
        self._dw_streak = 0
        self._dw_deep = True  # current A/B mode (deep = configured)
        self._dw_last: Optional[float] = None  # prior submit timestamp
        self._dw_samples: List[float] = []
        self._dw_cost: Dict[bool, Optional[float]] = {True: None,
                                                      False: None}
        self._dw_age: Dict[bool, int] = {True: 0, False: 0}
        # (the per-(B, L) staging-buffer pool lives in self._prep —
        # recycled at resolve so pipelined ticks never rewrite a buffer
        # a still-running device_put may alias)
        # adaptive per-chip compact-return cap: k tracks the OBSERVED
        # per-chip hit maximum (shrinks toward it every
        # kcap_adapt_interval ticks, regrows on overflow), cutting the
        # [D, B, k] fetch leg to what traffic actually needs.  kcap from
        # the constructor stays the steady-state ceiling.
        self._kcap_ceil = next_pow2(max(1, kcap))
        self._kcap_floor = min(4, self._kcap_ceil)
        self._kcap_dyn = min(8, self._kcap_ceil)
        self._kpeak = 0
        self._kticks = 0
        self.kcap_adapt_interval = 64

        # flight recorder + histograms (observe/flight.py — same plane as
        # the single-chip engine; the mesh path is always device-served,
        # so records explain latency/bytes, not arbitration)
        self.flight: Optional[FlightRecorder] = FlightRecorder()
        self.hist_tick = LatencyHistogram()
        self.hist_churn = LatencyHistogram()
        self._churn_lag = 0.0

    # ----------------------------------------------------------- mutation

    def fid_of(self, filt: str) -> Optional[int]:
        if self._plane is not None:
            return self._plane.lookup(filt)
        return self._fids.get(filt)

    def fid_map(self) -> Dict[str, int]:
        """filter -> fid copy (tests/introspection; O(n))."""
        if self._plane is not None:
            return self._plane.fid_map()
        return dict(self._fids)

    def free_fid_count(self) -> int:
        if self._plane is not None:
            return self._plane.free_count()
        return len(self._free_fids)

    def refcount_of(self, filt: str) -> int:
        if self._plane is not None:
            return self._plane.refcount(filt)
        fid = self._fids.get(filt)
        return 0 if fid is None else self._refs[fid]

    def note_churn_shed(self, n: int) -> None:
        """Count churn ops shed upstream (demand exceeded apply
        capacity) — see TopicMatchEngine.note_churn_shed."""
        if n <= 0:
            return
        self.churn_shed += n
        tp("engine.churn.shed", shed=n, total=self.churn_shed)

    # ---- churn-plane fast paths (native/churn.cc; see __init__) -------

    def _plane_deep(self, res, adds, removes) -> None:
        """Deep entries -> the host-trie fallback (the plane owns their
        fid/refcount; _words/_fbytes own their verify strings)."""
        if res.new_deep.any():
            for k in np.nonzero(res.new_deep)[0].tolist():
                filt = adds[int(res.new_aidx[k])]
                fid = int(res.new_fid[k])
                self._words[fid] = topiclib.words(filt)
                self._fbytes[fid] = filt.encode("utf-8")
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
        if res.dead_deep.any():
            for k in np.nonzero(res.dead_deep)[0].tolist():
                filt = removes[int(res.dead_ridx[k])]
                fid = int(res.dead_fid[k])
                self._deep_fids.discard(fid)
                self._deep.delete(filt, fid)
                self._words.pop(fid, None)
                self._fbytes.pop(fid, None)

    def _plane_apply(self, adds, removes, bulk: bool = False):
        """One plane tick routed to the DEVICE shards: the plane does
        bookkeeping + keys GIL-free (no placement — tables are
        per-shard here); deads tombstone via each shard's vectorized
        delete_batch, news land via churn_insert_keys (or
        bulk_insert_keys at bootstrap scale) grouped by fid % D.
        Callers own the on_churn hook calls."""
        res = self._plane.apply(adds, removes, reg=self._reg, place=False)
        self._plane_deep(res, adds, removes)
        if len(res.dead_fid):
            dk = ~res.dead_deep
            dead = res.dead_fid[dk]
            if len(dead):
                dsh = dead % self.D
                for d in range(self.D):
                    part = dead[dsh == d]
                    if len(part):
                        self.shards[d].delete_batch(part)
        if len(res.new_fid):
            nk = ~res.new_deep
            nf = res.new_fid[nk]
            if len(nf):
                ha, hb = res.new_ha[nk], res.new_hb[nk]
                plen, mask = res.new_plen[nk], res.new_mask[nk]
                hsh = res.new_hash[nk]
                nsh = nf % self.D
                for d in range(self.D):
                    m = nsh == d
                    if m.any():
                        ins = (self.shards[d].bulk_insert_keys if bulk
                               else self.shards[d].churn_insert_keys)
                        ins(nf[m], ha[m], hb[m], plen[m], mask[m], hsh[m])
            # dest rows for every new fid (incl. deep): fid % n_sub
            top = int(res.new_fid.max())
            if top >= self._dest_cap:
                while self._dest_cap <= top:
                    self._dest_cap *= 2
                nd = np.zeros(self._dest_cap, dtype=np.int32)
                nd[: len(self._dest)] = self._dest
                self._dest = nd
            self._dest[res.new_fid] = res.new_fid % self.n_sub
            self._dest_dirty = True
        return res

    def add_filter(self, filt: str, sub_shard: Optional[int] = None) -> int:
        self._mut_gen += 1  # pre-dispatched prepped ticks go stale
        if self._plane is not None:
            res = self._plane_apply([filt], [])
            fid = int(res.fids[0])
            if sub_shard is not None:
                self._dest[fid] = sub_shard
                self._dest_dirty = True
            if self.on_churn is not None:
                self.on_churn([filt], [])
            return fid
        fid = self._fids.get(filt)
        if fid is not None:
            self._refs[fid] += 1
            if self.on_churn is not None:
                self.on_churn([filt], [])  # refcount bumps reach the WAL
            return fid
        fid = self._free_fids[-1] if self._free_fids else self._next_fid
        ws = topiclib.words(filt)
        deep = self.space.shape_of(ws).plen > self.space.max_levels
        if deep:
            self._deep.insert(filt, fid)
            self._deep_fids.add(fid)
        else:
            self.shards[fid % self.D].insert(ws, fid)
        # registry updated only after a successful insert
        if self._free_fids:
            self._free_fids.pop()
        else:
            self._next_fid += 1
        self._fids[filt] = fid
        self._refs[fid] = 1
        if deep or self._reg is None:
            self._words[fid] = ws
            self._fbytes[fid] = filt.encode("utf-8")
        else:
            self._reg.set_bulk([fid], [filt.encode("utf-8")])
        if fid >= self._dest_cap:
            self._dest_cap *= 2
            nd = np.zeros(self._dest_cap, dtype=np.int32)
            nd[: len(self._dest)] = self._dest
            self._dest = nd
        self._dest[fid] = sub_shard if sub_shard is not None else fid % self.n_sub
        self._dest_dirty = True
        if self.on_churn is not None:
            self.on_churn([filt], [])
        return fid

    def add_filters(
        self, filts: Sequence[str], churn: bool = False
    ) -> List[int]:
        """Bulk add: one native key pass per SHARD instead of per-filter
        inserts (the mesh analog of TopicMatchEngine.add_filters; fids
        round-robin over shards so partitions stay balanced).

        ``churn=True`` places into the live shard arrays incrementally
        (`churn_insert`: slot deltas ride the next fused dispatch) —
        the default ``bulk_insert`` REBUILDS each touched shard, which
        is right for bootstrap but forces a full mirror re-upload per
        churn tick (measured: the sharded config-5 p99 driver).

        Same commit discipline as add_filter: shard table inserts happen
        BEFORE any registry state is written, so a failed insert leaves
        the engine exactly as it was (only the fid allocator is rolled
        back)."""
        self._mut_gen += 1  # pre-dispatched prepped ticks go stale
        if self._plane is not None:
            if not isinstance(filts, list):
                filts = list(filts)
            res = self._plane_apply(filts, [], bulk=not churn)
            if self.on_churn is not None:
                self.on_churn(list(filts), [])
            return res.fids.tolist()
        # plan: dedup against the live registry AND within the batch,
        # allocating fids but committing nothing yet
        fids: List[int] = []
        local: Dict[str, int] = {}
        local_refs: Dict[int, int] = {}
        plan: List[Tuple[str, int, List[str], bool]] = []
        popped: List[int] = []
        next_mark = self._next_fid
        for filt in filts:
            fid = self._fids.get(filt)
            if fid is not None:
                self._refs[fid] += 1  # safe: no insert involved
                fids.append(fid)
                continue
            fid = local.get(filt)
            if fid is not None:
                local_refs[fid] += 1
                fids.append(fid)
                continue
            if self._free_fids:
                fid = self._free_fids.pop()
                popped.append(fid)
            else:
                fid = self._next_fid
                self._next_fid += 1
            ws = topiclib.words(filt)
            deep = self.space.shape_of(ws).plen > self.space.max_levels
            local[filt] = fid
            local_refs[fid] = 1
            plan.append((filt, fid, ws, deep))
            fids.append(fid)
        by_shard_strs: List[List[str]] = [[] for _ in range(self.D)]
        by_shard_fids: List[List[int]] = [[] for _ in range(self.D)]
        for filt, fid, ws, deep in plan:
            if not deep:
                by_shard_strs[fid % self.D].append(filt)
                by_shard_fids[fid % self.D].append(fid)
        done = 0
        try:
            for d in range(self.D):
                if by_shard_strs[d]:
                    if churn:
                        self.shards[d].churn_insert(
                            by_shard_strs[d], by_shard_fids[d]
                        )
                    else:
                        self.shards[d].bulk_insert(
                            by_shard_strs[d], by_shard_fids[d]
                        )
                done = d + 1
        except BaseException:
            for dd in range(done):  # unwind shards already inserted
                for fid in by_shard_fids[dd]:
                    try:
                        self.shards[dd].delete(fid)
                    except KeyError:  # pragma: no cover
                        pass
            self._free_fids.extend(reversed(popped))
            self._next_fid = next_mark
            raise
        # commit
        reg_fids: List[int] = []
        reg_blobs: List[bytes] = []
        for filt, fid, ws, deep in plan:
            self._fids[filt] = fid
            self._refs[fid] = local_refs[fid]
            if deep or self._reg is None:
                self._words[fid] = ws
                self._fbytes[fid] = filt.encode("utf-8")
            else:
                reg_fids.append(fid)
                reg_blobs.append(filt.encode("utf-8"))
            if deep:
                self._deep.insert(filt, fid)
                self._deep_fids.add(fid)
            if fid >= self._dest_cap:
                while self._dest_cap <= fid:
                    self._dest_cap *= 2
                nd = np.zeros(self._dest_cap, dtype=np.int32)
                nd[: len(self._dest)] = self._dest
                self._dest = nd
            self._dest[fid] = fid % self.n_sub
        if reg_fids:
            self._reg.set_bulk(reg_fids, reg_blobs)
        if plan:
            self._dest_dirty = True
        if self.on_churn is not None:
            self.on_churn(list(filts), [])
        return fids

    def apply_churn(
        self, adds: Sequence[str], removes: Sequence[str]
    ) -> List[int]:
        """One churn tick: batched unsubscribes + subscribes.  Removes
        are grouped per shard and tombstoned in one vectorized
        `delete_batch` pass each (+ one registry del_bulk) — per-op
        remove_filter measured ~15k ops/s, an order short of config 5's
        churn rate.  Shard deltas accumulate and ride the next fused
        dispatch (`sharded_step_compact`), same as the single-chip
        engine's fused churn+match contract.  With the churn plane the
        whole tick's bookkeeping runs sharded and GIL-free; the hook
        stream keeps the same two-record framing as the fallback."""
        import time

        self._mut_gen += 1  # pre-dispatched prepped ticks go stale

        if self._plane is not None:
            t0 = time.monotonic()
            if not isinstance(adds, list):
                adds = list(adds)
            if not isinstance(removes, list):
                removes = list(removes)
            res = self._plane_apply(adds, removes)
            if self.on_churn is not None and removes:
                self.on_churn([], list(removes))
            if self.on_churn is not None:
                self.on_churn(list(adds), [])
            dt = time.monotonic() - t0
            self._churn_lag = dt
            self.hist_churn.observe(dt)
            tp("engine.churn", adds=len(adds), removes=len(removes),
               dt_ms=dt * 1e3)
            return res.fids.tolist()

        t0 = time.monotonic()
        dead_by_shard: List[List[int]] = [[] for _ in range(self.D)]
        refs = self._refs
        _fids = self._fids
        # uniq first-occurrence walk with counted decrements — the same
        # discipline as the single-chip engine (and the churn plane), so
        # fid-reuse ORDER is identical across all three paths
        uniq_rem = dict.fromkeys(removes)
        rem_counts = None
        if len(uniq_rem) != len(removes):
            from collections import Counter

            rem_counts = Counter(removes)
        for filt in uniq_rem:
            fid = _fids.get(filt)
            if fid is None:
                continue
            dec = rem_counts[filt] if rem_counts is not None else 1
            rc = refs[fid]
            if rc > dec:
                refs[fid] = rc - dec
                continue
            del refs[fid]
            del _fids[filt]
            self._words.pop(fid, None)
            self._fbytes.pop(fid, None)
            if fid in self._deep_fids:
                self._deep_fids.discard(fid)
                self._deep.delete(filt, fid)
            else:
                dead_by_shard[fid % self.D].append(fid)
            self._free_fids.append(fid)
        dead_all: List[int] = []
        for d, fl in enumerate(dead_by_shard):
            if fl:
                self.shards[d].delete_batch(fl)
                dead_all.extend(fl)
        if dead_all and self._reg is not None:
            self._reg.del_bulk(dead_all)
        if self.on_churn is not None and removes:
            # the adds side is logged by add_filters below; removes are
            # applied inline above, so log them first (apply order)
            self.on_churn([], list(removes))
        out = self.add_filters(adds, churn=True)
        dt = time.monotonic() - t0
        self._churn_lag = dt
        self.hist_churn.observe(dt)
        tp("engine.churn", adds=len(adds), removes=len(removes),
           dt_ms=dt * 1e3)
        return out

    def remove_filter(self, filt: str) -> Optional[int]:
        self._mut_gen += 1  # pre-dispatched prepped ticks go stale
        if self._plane is not None:
            if self._plane.lookup(filt) is None:
                return None  # unknown filter: no mutation, no hook
            res = self._plane_apply([], [filt])
            if self.on_churn is not None:
                self.on_churn([], [filt])
            return int(res.dead_fid[0]) if len(res.dead_fid) else None
        fid = self._fids.get(filt)
        if fid is None:
            return None
        self._refs[fid] -= 1
        if self._refs[fid] > 0:
            if self.on_churn is not None:
                self.on_churn([], [filt])  # log the refcount decrement
            return None
        del self._refs[fid]
        del self._fids[filt]
        self._words.pop(fid, None)
        self._fbytes.pop(fid, None)
        if fid in self._deep_fids:
            self._deep_fids.discard(fid)
            self._deep.delete(filt, fid)
        else:
            self.shards[fid % self.D].delete(fid)
            if self._reg is not None:
                self._reg.del_bulk([fid])
        self._free_fids.append(fid)
        if self.on_churn is not None:
            self.on_churn([], [filt])
        return fid

    @property
    def n_filters(self) -> int:
        if self._plane is not None:
            return self._plane.count()
        return len(self._fids)

    # --------------------------------------------------------- checkpoint

    def ref_snapshot(self) -> Dict[str, int]:
        """filter -> refcount copy (checkpoint reconcile, tests)."""
        if self._plane is not None:
            buf, offs, _fids, rcs, _dp, _fr, _nx = self._plane.export()
            data = buf.tobytes()
            ol = offs.tolist()
            return {
                data[ol[i]:ol[i + 1]].decode("utf-8"): int(rc)
                for i, rc in enumerate(rcs.tolist())
            }
        refs = self._refs
        return {f: refs[fid] for f, fid in self._fids.items()}

    def export_checkpoint(self):
        """Host truth as (named arrays, meta): one per-shard table block
        each (`tab<d>/...`) plus the global registry + dest map — one
        snapshot file carries every shard, restored as a unit."""
        from ..checkpoint.store import pack_nul_list, packed_to_nul

        arrays: Dict[str, np.ndarray] = {}
        shard_metas = []
        for d, t in enumerate(self.shards):
            t_arr, t_meta = t.export_state()
            for k, v in t_arr.items():
                arrays[f"tab{d}/{k}"] = v
            shard_metas.append(t_meta)
        if self._plane is not None:
            buf, offs, pfids, prefs, pdeep, pfree, next_fid = (
                self._plane.export()
            )
            n = len(pfids)
            arrays.update({
                "reg/nul": packed_to_nul(buf, offs, n),
                "reg/fid": pfids.astype(np.int64),
                "reg/ref": prefs,
                "reg/deep": pdeep,
                "reg/free": pfree.astype(np.int64),
                "reg/dest": self._dest.copy(),
            })
        else:
            filts = list(self._fids)
            n = len(filts)
            fids = np.fromiter(
                (self._fids[f] for f in filts), dtype=np.int64, count=n
            )
            refs = np.fromiter(
                (self._refs[int(i)] for i in fids), dtype=np.int64,
                count=n,
            )
            deep = np.fromiter(
                (int(i) in self._deep_fids for i in fids), dtype=bool,
                count=n,
            )
            arrays.update({
                "reg/nul": pack_nul_list(filts), "reg/fid": fids,
                "reg/ref": refs, "reg/deep": deep,
                "reg/free": np.asarray(self._free_fids, dtype=np.int64),
                "reg/dest": self._dest.copy(),
            })
            next_fid = self._next_fid
        meta = {
            "kind": "sharded",
            "n_devices": self.D,
            "n_sub": self.n_sub,
            "shards": shard_metas,
            "max_levels": self.space.max_levels,
            "next_fid": next_fid,
            "n_filters": n,
        }
        return arrays, meta

    def restore_checkpoint(self, arrays, meta) -> int:
        """Adopt a sharded snapshot wholesale; the stacked device mirror
        is dropped so the next dispatch restacks from the restored
        shards in one upload."""
        self._mut_gen += 1  # pre-dispatched prepped ticks go stale
        from ..checkpoint.store import nul_to_packed, unpack_nul_list
        from ..ops import native as _native

        if meta.get("kind") != "sharded":
            raise ValueError(f"snapshot kind {meta.get('kind')!r} is not "
                             "a sharded engine checkpoint")
        if int(meta["n_devices"]) != self.D:
            raise ValueError(
                "snapshot has %s shards, mesh has %d — fid %% D "
                "partitioning is not portable" % (meta["n_devices"], self.D)
            )
        shards = [
            MatchTables.from_state(
                self.space,
                {k.split("/", 1)[1]: v for k, v in arrays.items()
                 if k.startswith(f"tab{d}/")},
                meta["shards"][d],
            )
            for d in range(self.D)
        ]
        n_filts = int(meta["n_filters"])
        deep = arrays["reg/deep"]
        self.shards = shards
        self.n_sub = int(meta["n_sub"])
        self._dest = arrays["reg/dest"]
        self._dest_cap = len(self._dest)
        self._dest_dirty = True
        self._words = {}
        self._fbytes = {}
        self._deep = CpuTrieIndex()
        self._deep_fids = set()
        self._reg = _native.make_registry()  # fresh: drop stale entries
        if self._plane is not None:
            self._plane = _native.make_churn_plane(
                self.space, self._plane.n_shards()
            )
            buf, offs = nul_to_packed(arrays["reg/nul"], n_filts)
            fid_arr = arrays["reg/fid"]
            self._plane.ingest(buf, offs, fid_arr, arrays["reg/ref"],
                               arrays["reg/free"], int(meta["next_fid"]))
            self._fids = {}
            self._refs = {}
            self._next_fid = int(meta["next_fid"])
            self._free_fids = []
            if deep.any():
                filts = unpack_nul_list(arrays["reg/nul"], n_filts)
                fids_l = fid_arr.tolist()
                for k in np.nonzero(deep)[0].tolist():
                    filt, fid = filts[k], int(fids_l[k])
                    self._words[fid] = topiclib.words(filt)
                    self._fbytes[fid] = filt.encode("utf-8")
                    self._deep.insert(filt, fid)
                    self._deep_fids.add(fid)
                shallow = np.nonzero(~deep)[0].tolist()
                self._reg.set_bulk(
                    [fids_l[k] for k in shallow],
                    [filts[k].encode("utf-8") for k in shallow],
                )
            elif n_filts:
                self._reg.set_bulk_packed(fid_arr, buf, offs)
            self._stacked = None  # restack from restored shards
            self._dest_dev = None
            self._inflight = []
            self._prep.reset_buffers()
            return n_filts
        filts = unpack_nul_list(arrays["reg/nul"], n_filts)
        fids = arrays["reg/fid"].tolist()
        refs = arrays["reg/ref"].tolist()
        self._fids = dict(zip(filts, fids))
        self._refs = dict(zip(fids, refs))
        self._next_fid = int(meta["next_fid"])
        self._free_fids = arrays["reg/free"].tolist()
        if not deep.any() and self._reg is not None:
            if n_filts:
                buf, offs = nul_to_packed(arrays["reg/nul"], n_filts)
                self._reg.set_bulk_packed(fids, buf, offs)
        else:
            reg_fids: List[int] = []
            reg_blobs: List[bytes] = []
            for k, (filt, fid) in enumerate(zip(filts, fids)):
                if bool(deep[k]):
                    self._words[fid] = topiclib.words(filt)
                    self._fbytes[fid] = filt.encode("utf-8")
                    self._deep.insert(filt, fid)
                    self._deep_fids.add(fid)
                elif self._reg is not None:
                    reg_fids.append(fid)
                    reg_blobs.append(filt.encode("utf-8"))
                else:
                    self._words[fid] = topiclib.words(filt)
                    self._fbytes[fid] = filt.encode("utf-8")
            if self._reg is not None and reg_fids:
                self._reg.set_bulk(reg_fids, reg_blobs)
        self._stacked = None  # restack from restored shards on next sync
        self._dest_dev = None
        self._inflight = []
        self._prep.reset_buffers()
        return len(filts)

    # --------------------------------------------------------------- sync

    def _uniform_caps(self) -> bool:
        """Grow shards until all agree on capacities (growth may overshoot)."""
        grew = False
        while True:
            log2cap = max(t.log2cap for t in self.shards)
            desc_cap = max(t.desc_cap for t in self.shards)
            if all(
                t.log2cap == log2cap and t.desc_cap == desc_cap
                for t in self.shards
            ):
                return grew
            for t in self.shards:
                t.ensure_caps(log2cap, desc_cap)
            grew = True

    def _shard0(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(FILTER_AXIS))

    def _repl(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def _full_restack(self) -> None:
        for t in self.shards:
            t.drain_delta()
        stacked_np = {
            k: np.stack([t.device_arrays()[k] for t in self.shards])
            for k in self.shards[0].device_arrays()
        }
        self._stacked = DeviceTables(
            **{k: jax.device_put(v, self._shard0()) for k, v in stacked_np.items()}
        )

    def _pre_step_sync(self):
        """Restack if needed; push descriptor updates; return slot deltas.

        Returns padded per-shard slot deltas (slots, ka, kb, vv) not yet
        applied on device, or all-None if none are pending.  Also refreshes
        the replicated dest array.
        """
        grew = self._uniform_caps()
        deltas = [t.delta for t in self.shards]
        if self._stacked is None or grew or any(d.rebuilt for d in deltas):
            self._full_restack()
            out = (None, None, None, None)
        else:
            if any(d.desc_dirty for d in deltas):
                put = lambda a: jax.device_put(np.stack(a), self._shard0())
                arrs = [t.device_arrays() for t in self.shards]
                self._stacked = self._stacked._replace(
                    incl=put([a["incl"] for a in arrs]),
                    k_a=put([a["k_a"] for a in arrs]),
                    k_b=put([a["k_b"] for a in arrs]),
                    min_len=put([a["min_len"] for a in arrs]),
                    max_len=put([a["max_len"] for a in arrs]),
                    wild_root=put([a["wild_root"] for a in arrs]),
                    valid=put([a["valid"] for a in arrs]),
                )
            out = self._drain_slot_deltas()
        if self._dest_dirty or self._dest_dev is None:
            self._dest_dev = jax.device_put(self._dest, self._repl())
            self._dest_dirty = False
        return out

    def sync_device(self) -> Tuple[DeviceTables, jax.Array]:
        slots, ka, kb, vv = self._pre_step_sync()
        if slots is not None:
            # the delta scatter donates the stacked tables: every
            # in-flight pending still references them (overflow refetch)
            self._drain_window("sync-donate")
            put = lambda a: jax.device_put(a, self._shard0())
            self._stacked = sharded_apply_delta(
                self._stacked, put(slots), put(ka), put(kb), put(vv), mesh=self.mesh
            )
        return self._stacked, self._dest_dev

    def _drain_slot_deltas(self):
        """Per-shard slot deltas as padded [D, K] arrays (or all-None)."""
        ds = [t.drain_delta() for t in self.shards]
        kmax = max((len(d.slots) for d in ds), default=0)
        if kmax == 0:
            return None, None, None, None
        K = next_pow2(max(kmax, 16))
        slots = np.full((self.D, K), -1, dtype=np.int32)
        ka = np.zeros((self.D, K), dtype=np.uint32)
        kb = np.zeros((self.D, K), dtype=np.uint32)
        vv = np.zeros((self.D, K), dtype=np.int32)
        for i, d in enumerate(ds):
            n = len(d.slots)
            slots[i, :n] = d.slots
            ka[i, :n] = d.key_a
            kb[i, :n] = d.key_b
            vv[i, :n] = d.val
        return slots, ka, kb, vv

    def _prep_batch(self, topics: Sequence[str]) -> Tuple[TopicBatch, int]:
        # native split+hash fast path (same as the single-chip engine):
        # the pure-Python words()+hash loop measured 11 us/topic — the
        # single biggest sharded-tick phase before the dispatch itself
        from ..ops.match import prepare_topics_raw

        nb, n = prepare_topics_raw(self.space, list(topics), self.min_batch)
        repl = self._repl()
        return TopicBatch(*(jax.device_put(a, repl) for a in nb)), n

    # ------------------------------------------------- pipelined prep/fetch

    def _acquire_staging(self, key: Tuple[int, int]) -> np.ndarray:
        return self._prep.acquire(key)

    def _release_staging(self, pending: "_ShardedPending") -> None:
        buf, key = pending.buf, pending.bufkey
        pending.buf = None
        self._prep.release(buf, key)

    # ---- topic-memo telemetry/compat (the memo itself lives in the
    # fused prep plane, ops/prep.py — C++-owned when the lib is present)

    @property
    def memo_hits(self) -> int:
        return self._prep.hits

    @property
    def memo_misses(self) -> int:
        return self._prep.misses

    @property
    def topic_memo_cap(self) -> int:
        return self._prep.cap

    @topic_memo_cap.setter
    def topic_memo_cap(self, v: int) -> None:
        self._prep.cap = v

    def _hash_topics_memo(self, topics: List[str]):
        """Memoized batch split+hash, full-width rows (tests/TopicBatch
        path) — delegates to the fused prep front."""
        return self._prep.hash_rows(list(topics))

    def _prep_packed(self, topics: Sequence[str]):
        """Fused prep + upload of a publish batch: ONE replicated
        [B, 2L+2] u32 `device_put` from a pooled staging buffer
        (`ops.prep.TopicPrep.pack`).  Returns (pbatch, n, B, L, buf,
        key)."""
        res = self._prep.pack(list(topics))
        return (jax.device_put(res.buf, self._repl()), res.n, res.B,
                res.L, res.buf, res.key)

    def _fetch_rows(self, n: int, B: int) -> int:
        """Live rows to fetch for an n-topic tick in a B bucket, rounded
        so the slice jit compiles at most ~8 variants per bucket."""
        return min(B, _round_up(max(n, 1), max(self.min_batch, B // 8)))

    def _note_kmax(self, maxc: int) -> None:
        """Adaptive kcap bookkeeping (see __init__): track the per-chip
        hit peak; shrink k toward it every kcap_adapt_interval ticks."""
        if maxc > self._kpeak:
            self._kpeak = maxc
        self._kticks += 1
        if self._kticks >= self.kcap_adapt_interval:
            tgt = min(
                self._kcap_ceil,
                max(self._kcap_floor, next_pow2(max(1, 2 * self._kpeak))),
            )
            if tgt < self._kcap_dyn:
                self._kcap_dyn = tgt
                tp("engine.kcap", kcap=tgt, peak=self._kpeak)
            self._kpeak = 0
            self._kticks = 0

    # ------------------------------------------------- in-flight window

    @property
    def inflight_ticks(self) -> int:
        return len(self._inflight)

    @property
    def delta_backlog(self) -> int:
        """Churn-delta slots awaiting the next device sync, summed over
        the device shards (contention telemetry: churn backlog gauge —
        same contract as the single-chip engine's property)."""
        return sum(len(s.delta.slots) for s in self.shards)

    @property
    def effective_depth(self) -> int:
        """The adaptively clamped in-flight window bound (<= the
        configured pipeline_depth)."""
        return self._eff_depth

    def _depth_window(self, now: float, fused: bool) -> int:
        """Effective window bound for this tick (see the __init__
        comment): churn-drain EWMA clamps to 1 when the window can't
        fill; otherwise a measured A/B over submit-to-submit intervals
        picks deep vs shallow, deep favored inside depth_margin."""
        depth = self.pipeline_depth
        if depth <= 1:
            self._eff_depth = depth
            return depth
        self._drain_ewma += 0.125 * (
            (1.0 if fused else 0.0) - self._drain_ewma
        )
        if self._drain_ewma >= self.drain_clamp:
            # the drain serializes every tick regardless of the window;
            # interval samples here would measure churn, not the window
            self._dw_last = None
            self._dw_samples.clear()
            if self._eff_depth != 1:
                self._eff_depth = 1
                if _tps._active:
                    tp("engine.pipeline", event="clamp",
                       reason="churn-drain", eff=1, depth=depth)
            return 1
        last, self._dw_last = self._dw_last, now
        if last is not None:
            self._dw_samples.append(now - last)
            self._dw_age[not self._dw_deep] += 1
            if len(self._dw_samples) >= self.depth_probe_len:
                self._dw_cost[self._dw_deep] = float(
                    np.median(self._dw_samples)
                )
                self._dw_samples.clear()
                self._dw_age[self._dw_deep] = 0
                other = not self._dw_deep
                if (
                    self._dw_cost[other] is None
                    or self._dw_age[other] > self.depth_probe_interval
                ):
                    self._dw_deep = other  # probe the stale mode
                else:
                    # both measurements fresh: deep serves only when it
                    # measures a REAL win (the overlap on parallel
                    # hardware) on `depth_win_streak` consecutive
                    # verdicts — on a serialized host the window only
                    # adds bookkeeping and noisy phantom wins don't
                    # repeat, so ties clamp to 1 and depth N can never
                    # underperform depth 1
                    win = (
                        self._dw_cost[True]
                        < self._dw_cost[False] * (1.0 - self.depth_margin)
                    )
                    if self._dw_deep or not win:
                        # count only independent wins (deep cost just
                        # refreshed); a stale deep cost can lose but
                        # never score
                        self._dw_streak = self._dw_streak + 1 if win else 0
                    deep = self._dw_streak >= self.depth_win_streak
                    if deep != self._dw_deep and _tps._active:
                        tp("engine.pipeline", event="clamp",
                           reason="measured", eff=depth if deep else 1,
                           depth=depth,
                           cost_deep=self._dw_cost[True],
                           cost_shallow=self._dw_cost[False])
                    self._dw_deep = deep
        eff = depth if self._dw_deep else 1
        self._eff_depth = eff
        return eff

    def _drain_window(self, reason: str = "drain") -> None:
        """Resolve every in-flight tick (device fetch + overflow refetch
        against its own table version).  Must run before any dispatch
        that DONATES the stacked tables: a donated buffer would yank the
        table snapshot out from under the pending refetches."""
        drained = 0
        while self._inflight:
            self._resolve(self._inflight[0])
            drained += 1
        if drained and _tps._active:
            tp("engine.pipeline", event="drain", reason=reason, n=drained)

    def _resolve(self, pending: "_ShardedPending", blocking: bool = True) -> bool:
        """Fetch a pending tick's device results to host (idempotent,
        thread-safe): the [D, rows, k] hits + u16 counts, plus the rare
        per-chip-overflow refetch against THIS tick's table snapshot.
        After resolve the pending holds only numpy data — collect just
        verifies, and the tick no longer pins device buffers or its
        staging buffer.  `blocking=False` skips (returns False) when
        another thread is already resolving this pending."""
        lk = pending.lock
        if not lk.acquire(blocking=blocking):
            return False
        try:
            if pending.resolved:
                return True
            if _fault.enabled():
                # delay-only site (no host fallback on the mesh path):
                # models a slow collect leg for pipeline-pressure soaks
                _fault.inject("sharded.collect", err=False)
            g = pending.group
            if g is not None:
                # group-shared dispatch: the device->host materialize
                # happens ONCE per group (idempotent under the group
                # lock); each member slices its own row segment
                pending.bytes_down += g.fetch(self._prep)
                n, off = pending.n, pending.row_off
                hits = g.hits_np[:, off:off + n, :]  # [D, n, k]
                counts = g.counts_np[:, off:off + n].astype(np.int32)
                k = hits.shape[2]
                self._note_kmax(int(counts.max(initial=0)))
                over = (counts > k).any(axis=0)
                if over.any():
                    hits = (
                        self._refetch_overflow_foreign(
                            pending, hits, counts, over
                        )
                        if pending.foreign_rows is not None
                        else self._refetch_overflow(
                            pending, hits, counts, over
                        )
                    )
                pending.hits_np = hits
                pending.counts_np = counts
                pending.group = None
            pending.snap = None
            self._release_staging(pending)
            pending.resolved = True
            try:
                self._inflight.remove(pending)
            except ValueError:
                pass
            return True
        finally:
            lk.release()

    def _refetch_overflow(
        self,
        pending: "_ShardedPending",
        hits: np.ndarray,
        counts: np.ndarray,
        over: np.ndarray,
    ) -> np.ndarray:
        """Per-chip compact-return overflow: refetch ONLY the overflowing
        topics with k widened to the observed max (pow2-rounded so the
        kcap-static jit compiles a bounded variant set) against THIS
        tick's table version — a [D, B_over, k2] transfer instead of
        [D, B, M].  Both transfer legs land in the pending's wire-byte
        accounting (the BENCH wire floor reads them)."""
        k = hits.shape[2]
        snap = pending.snap if pending.snap is not None else self._stacked
        M = int(snap.k_a.shape[-1])
        over_idx = np.nonzero(over)[0]
        sub_topics = [pending.topics[i] for i in over_idx.tolist()]
        maxc = int(counts[:, over].max())
        if maxc >= 0xFFFF:  # u16-saturated: the true count is unknown
            maxc = M
        k2 = next_pow2(min(max(maxc, k + 1), M))
        pb, n_sub, B2, _L2, buf2, key2 = self._prep_packed(sub_topics)
        pending.bytes_up += buf2.nbytes
        sub_hits, _sub_counts = sharded_match_compact_packed(
            snap, pb, mesh=self.mesh, kcap=k2
        )
        rows = self._fetch_rows(n_sub, B2)
        if rows < B2:
            sub_hits, _sub_counts = _slice_live(
                sub_hits, _sub_counts, rows=rows
            )
        pending.bytes_down += int(sub_hits.nbytes)
        sub = np.asarray(sub_hits)[:, :n_sub, :]
        self._prep.release(buf2, key2)
        k2 = sub.shape[2]  # min(k2, M) inside the kernel
        grown = np.concatenate(
            [hits, np.full(hits.shape[:2] + (k2 - k,), -1, dtype=hits.dtype)],
            axis=2,
        )
        grown[:, over_idx, :] = sub
        # regrow the steady-state cap toward the observed demand
        self._kcap_dyn = min(max(self._kcap_dyn, k2), self._kcap_ceil)
        return grown

    def _refetch_overflow_foreign(
        self,
        pending: "_ShardedPending",
        hits: np.ndarray,
        counts: np.ndarray,
        over: np.ndarray,
    ) -> np.ndarray:
        """Overflow refetch for a FOREIGN (shm-plane) tick: there are no
        topic strings to re-prep, so the sub-batch is assembled straight
        from the member's stored packed rows (`foreign_rows`), padded to
        a fresh pow2 bucket with never-match length sentinels."""
        k = hits.shape[2]
        snap = pending.snap if pending.snap is not None else self._stacked
        M = int(snap.k_a.shape[-1])
        over_idx = np.nonzero(over)[0]
        maxc = int(counts[:, over].max())
        if maxc >= 0xFFFF:  # u16-saturated: the true count is unknown
            maxc = M
        k2 = next_pow2(min(max(maxc, k + 1), M))
        rows_src = pending.foreign_rows
        W = rows_src.shape[1]  # 2L+2
        n_sub = int(over_idx.size)
        B2 = max(self._prep.min_batch, next_pow2(n_sub))
        buf2 = np.empty((B2, W), dtype=np.uint32)
        buf2[:n_sub] = rows_src[over_idx]
        if n_sub < B2:
            buf2[n_sub:, W - 2] = np.uint32(0xFFFFFFFF)  # never match
        pending.bytes_up += buf2.nbytes
        sub_hits, _sub_counts = sharded_match_compact_packed(
            snap, jax.device_put(buf2, self._repl()),
            mesh=self.mesh, kcap=k2,
        )
        rows = self._fetch_rows(n_sub, B2)
        if rows < B2:
            sub_hits, _sub_counts = _slice_live(
                sub_hits, _sub_counts, rows=rows
            )
        pending.bytes_down += int(sub_hits.nbytes)
        sub = np.asarray(sub_hits)[:, :n_sub, :]
        k2 = sub.shape[2]  # min(k2, M) inside the kernel
        grown = np.concatenate(
            [hits, np.full(hits.shape[:2] + (k2 - k,), -1, dtype=hits.dtype)],
            axis=2,
        )
        grown[:, over_idx, :] = sub
        self._kcap_dyn = min(max(self._kcap_dyn, k2), self._kcap_ceil)
        return grown

    # -------------------------------------------------------------- match

    def match_counts(self, topics: Sequence[str]) -> np.ndarray:
        """[len(topics), n_sub] per-subscriber-shard hit counts."""
        stacked, dest = self.sync_device()
        batch, n = self._prep_batch(topics)
        out = sharded_match_counts(
            stacked, batch, dest, mesh=self.mesh, n_sub=self.n_sub
        )
        counts = np.array(out)[:n]  # copy: deep-filter merge mutates
        if self._deep_fids:
            for i, t in enumerate(topics):
                for fid in self._deep.match(t) & self._deep_fids:
                    counts[i, self._dest[fid]] += 1
        return counts

    def step(self, topics: Sequence[str]) -> np.ndarray:
        """Fused churn-apply + match + merge (the flagship step).

        Donates the current device tables to `sharded_step` and adopts the
        returned ones, so the cached mirror is never left dangling.
        """
        slots, ka, kb, vv = self._pre_step_sync()
        self._drain_window("step-donate")
        if slots is None:
            K = 16
            slots = np.full((self.D, K), -1, dtype=np.int32)
            ka = np.zeros((self.D, K), dtype=np.uint32)
            kb = np.zeros((self.D, K), dtype=np.uint32)
            vv = np.zeros((self.D, K), dtype=np.int32)
        batch, n = self._prep_batch(topics)
        put = lambda a: jax.device_put(a, self._shard0())
        self._stacked, out = sharded_step(
            self._stacked,
            put(slots),
            put(ka),
            put(kb),
            put(vv),
            batch,
            self._dest_dev,
            mesh=self.mesh,
            n_sub=self.n_sub,
        )
        counts = np.array(out)[:n]  # copy: deep-filter merge mutates
        if self._deep_fids:
            for i, t in enumerate(topics):
                for fid in self._deep.match(t) & self._deep_fids:
                    counts[i, self._dest[fid]] += 1
        return counts

    def match(self, topics: Sequence[str]) -> List[Set[int]]:
        """Broker-facing match: verified fid sets per topic."""
        return self.match_collect(self.match_submit(topics))

    # --------------------------------------------------- prep-ahead stage

    def prep_submit(self, topics: Sequence[str]) -> PrepTicket:
        """Stage prep for a FUTURE tick on the prep-ahead worker: the
        packed staging buffer for tick N+k is built (fused native op,
        GIL-released) while tick N's dispatch is in flight.  Hand the
        ticket to ``match_submit(topics, prep=ticket)``; a stalled
        worker degrades to inline prep there (``prep_timeout``), never
        freezing the dispatch window — the fault site ``engine.prep``
        exercises exactly that path."""
        st = self._prep_stage
        if st is None:
            st = self._prep_stage = PrepStage(self._prep)
        return st.submit(list(topics))

    @property
    def prep_ready(self) -> int:
        """Tickets prepped-ahead and not yet dispatched (occupancy
        telemetry for the bench's prep-ahead column)."""
        st = self._prep_stage
        return 0 if st is None else st.ready_count

    def close(self) -> None:
        """Tear down the prep-ahead stage: worker joined via the queue
        sentinel, undispatched ticket buffers recycled (PR 10 lifecycle
        discipline).  Idempotent; the stage restarts lazily on the next
        prep_submit."""
        st, self._prep_stage = self._prep_stage, None
        if st is not None:
            st.close()

    def prep_discard(self, ticket: PrepTicket) -> None:
        """Abandon a staged ticket whose tick never materialized (e.g.
        every message of the batch was hook-dropped): the worker's
        buffer — if it got that far — recycles into the pool."""
        st = self._prep_stage
        if st is not None:
            st.consume(ticket)
        r = ticket.abandon()
        if r is not None:
            self._prep.release(r.buf, r.key)

    def _claim_ticket(self, ticket: PrepTicket, topics: List[str]):
        """Claim a prep-ahead ticket's result for THIS tick; None means
        degrade to inline prep (stalled worker / failed pack / topics
        mismatch).  The ticket is consumed from the stage either way."""
        st = self._prep_stage
        if st is not None:
            st.consume(ticket)
        r = ticket.claim(self.prep_timeout)
        if r is not None and ticket.topics == topics:
            return r
        if r is not None:  # mismatched topics: recycle the buffer
            self._prep.release(r.buf, r.key)
        self.prep_degraded += 1
        if _tps._active:
            tp("engine.pipeline", event="prep-degrade",
               reason="stall" if r is None else "mismatch")
        return None

    # -------------------------------------------------------------- submit

    def match_submit(
        self, topics: Sequence[str], prep: Optional[PrepTicket] = None
    ) -> "_ShardedPending":
        """Dispatch the sharded match WITHOUT blocking (three-phase
        publish contract, broker.publish_submit).  ALL engine-state
        mutation (delta drain, restack, dest refresh) happens here on
        the caller's thread; collect only fetches + verifies, so it is
        executor-safe — the same contract as the single-chip engine.

        PIPELINED: up to ``pipeline_depth`` submitted-but-unresolved
        ticks may be in flight at once, all sharing the same stacked
        tables through the NON-donating packed match.  Past the window
        the oldest tick is force-resolved (its compute is ≥depth ticks
        old, so the fetch is ~a memcpy).

        PREP-AHEAD + COALESCED DISPATCH: with ``prep`` (a ticket from
        :meth:`prep_submit`) the packed upload buffer was built by the
        prep-ahead worker while earlier dispatches were in flight; when
        several consecutive tickets are already prepped in the same
        (B, L) bucket and the window has room, they ride ONE mesh
        dispatch (rows concatenated, group sizes 1/2/4 to bound the jit
        variant set) — the per-dispatch overhead a serialized host pays
        per tick amortizes over the group, which is the depth-N win the
        A/B controller cashes in.  Members are pre-dispatched: their
        later ``match_submit(prep=ticket)`` call returns the already
        in-flight pending, valid only while the registry generation is
        unchanged (any churn bumps it; the drain already resolved the
        group, and the claim falls back to a fresh dispatch).

        Pending subscription churn is FUSED into the dispatch
        (`sharded_step_compact_packed`, never coalesced), donating the
        table buffers after a window drain, as before.  The rare
        per-chip overflow refetches just the overflowing topics at
        resolve time against THIS tick's tables."""
        import time

        t0 = time.monotonic()
        topics = list(topics)
        ticket = prep
        if ticket is not None and ticket.pending is not None:
            # pre-dispatched member of an earlier coalesced group
            p = ticket.pending
            st = self._prep_stage
            if st is not None:
                st.consume(ticket)
            if p.mut_gen == self._mut_gen and ticket.topics == topics:
                self._depth_window(t0, False)  # keep the A/B sampled
                return p
            # stale (registry mutated since the group dispatch — the
            # churn drain already resolved it) or mismatched topics:
            # fall through to a fresh dispatch with inline prep
            ticket = None
        deep = (
            [self._deep.match(t) & self._deep_fids for t in topics]
            if self._deep_fids
            else None
        )  # snapshotted at submit: collect may run on an executor thread
        if not any(t.n_entries for t in self.shards):
            if ticket is not None:
                st = self._prep_stage
                if st is not None:
                    st.consume(ticket)
                r = ticket.abandon()
                if r is not None:
                    self._prep.release(r.buf, r.key)
            p = _ShardedPending(None, 0, topics, deep, t0=t0)
            p.resolved = True
            return p
        slots, ka, kb, vv = self._pre_step_sync()
        churn_slots = int((slots >= 0).sum()) if slots is not None else 0
        eff_depth = self._depth_window(t0, slots is not None)
        if slots is not None:
            # donation below invalidates the tables every in-flight tick
            # still snapshots (overflow refetch): drain the window first
            self._drain_window("churn-fuse")
        # ---- prep: claim the prep-ahead ticket, else pack inline ------
        res = None
        ahead = False
        if ticket is not None:
            res = self._claim_ticket(ticket, topics)
            ahead = res is not None
        if res is None:
            res = self._prep.pack(topics)
        n, B, L, key = res.n, res.B, res.L, res.key
        # ---- coalesce: fold following already-prepped tickets into
        # this dispatch (pure-match ticks only; group size bounded by
        # the effective window and rounded down to 1/2/4)
        extras: List[Tuple[PrepTicket, "PrepResult"]] = []
        st = self._prep_stage
        if slots is None and ahead and st is not None and eff_depth > 1:
            # group members share ONE dispatch's device buffers, so the
            # group is bounded by the window depth itself (they are the
            # next ticks' pendings either way); a 2x-occupancy guard
            # keeps a slow collector from ballooning the in-flight set
            avail = (max(eff_depth - 1, 0)
                     if len(self._inflight) < 2 * eff_depth else 0)
            cand = st.ready_group(key, min(avail, 3))
            k_total = 1 + len(cand)
            k_total = 4 if k_total >= 4 else (2 if k_total >= 2 else 1)
            for t in cand[: k_total - 1]:
                st.consume(t)
                r = t.claim(0)  # prepped by construction (peeked)
                if r is None:  # pragma: no cover - defensive
                    break
                extras.append((t, r))
        K = 1 + len(extras)
        kc = self._kcap_dyn
        t_asm = time.perf_counter()
        if K > 1:
            # one [K*B, 2L+2] upload for the whole group, assembled in a
            # pooled buffer; member buffers recycle immediately (copied)
            gkey = (K * B, L)
            big = self._prep.acquire(gkey)
            big[0:B] = res.buf
            self._prep.release(res.buf, key)
            for j, (_t, r) in enumerate(extras):
                big[(j + 1) * B:(j + 2) * B] = r.buf
                self._prep.release(r.buf, key)
            pbatch = jax.device_put(big, self._repl())
        else:
            big, gkey = None, None
            pbatch = jax.device_put(res.buf, self._repl())
        put_s = time.perf_counter() - t_asm
        # wire-byte accounting (flight recorder): the packed topic batch
        # is the upload payload (counted once — replication is the mesh
        # fabric's job, not the host link's), plus churn deltas
        if slots is not None:
            bytes_up = res.buf.nbytes + (
                slots.nbytes + ka.nbytes + kb.nbytes + vv.nbytes
            )
            put = lambda a: jax.device_put(a, self._shard0())
            self._stacked, hits, counts = sharded_step_compact_packed(
                self._stacked, put(slots), put(ka), put(kb), put(vv),
                pbatch, mesh=self.mesh, kcap=kc,
            )
        else:
            bytes_up = B * (2 * L + 2) * 4
            hits, counts = sharded_match_compact_packed(
                self._stacked, pbatch, mesh=self.mesh, kcap=kc
            )
        # fetch slimming: transfer only the live topic rows of the
        # padded bucket (worth a slice dispatch past ~25% padding).
        # For a group, rows 0..(K-1)*B are earlier members (kept whole);
        # only the LAST member's padding can be trimmed.
        n_last = extras[-1][1].n if extras else n
        rows = (K - 1) * B + self._fetch_rows(n_last, B)
        if rows < K * B and K * B - rows >= (K * B) // 4:
            hits, counts = _slice_live(hits, counts, rows=rows)
        try:  # start the device->host copy NOW; resolve overlaps it
            hits.copy_to_host_async()
            counts.copy_to_host_async()
        except AttributeError:  # pragma: no cover - older jax
            pass
        group = _ShardedGroup(hits, counts, K, host_buf=big, buf_key=gkey)
        p = _ShardedPending(
            self._stacked, n, topics, deep, t0=t0, bytes_up=bytes_up,
        )
        p.group = group
        p.mut_gen = self._mut_gen
        p.churn_slots = churn_slots
        if K == 1:
            p.buf, p.bufkey = res.buf, key  # recycled at resolve
        p.prep_hash_s = res.hash_s
        p.prep_pack_s = res.pack_s
        p.prep_put_s = put_s / K
        p.memo_hits_tick = res.hits
        p.prep_group = K
        members = [p]
        for j, (t, r) in enumerate(extras):
            mdeep = (
                [self._deep.match(tt) & self._deep_fids
                 for tt in t.topics]
                if self._deep_fids else None
            )
            mp = _ShardedPending(
                self._stacked, r.n, list(t.topics), mdeep,
                t0=t0, bytes_up=B * (2 * L + 2) * 4,
            )
            mp.group = group
            mp.mut_gen = self._mut_gen
            mp.row_off = (j + 1) * B
            mp.prep_hash_s = r.hash_s
            mp.prep_pack_s = r.pack_s
            mp.prep_put_s = put_s / K
            mp.memo_hits_tick = r.hits
            mp.prep_group = K
            t.pending = mp
            members.append(mp)
        for mp in members:
            self._inflight.append(mp)
            mp.pipe_occ = len(self._inflight)
            mp.pipe_depth = self.pipeline_depth
        if _tps._active:
            tp("engine.prep.hash", ms=res.hash_s * 1e3, n=n)
            tp("engine.prep.pack", ms=res.pack_s * 1e3, B=B, L=L)
            tp("engine.prep.submit", ms=put_s * 1e3, group=K, ahead=ahead)
        if len(self._inflight) > eff_depth:
            # bound the window (at the adaptively clamped effective
            # depth): resolve the oldest tick, but ONLY if its device
            # result is already materialized — the submit thread is the
            # broker's event loop, and a stalled device must not freeze
            # it (test_pipeline.py's guarantee).  Past a 4x hard ceiling
            # (of the CONFIGURED depth) memory safety wins and the
            # resolve blocks (OLP has shed load long before that point).
            oldest = self._inflight[0]
            force = len(self._inflight) > 4 * self.pipeline_depth
            if (force or self._tick_ready(oldest)) and self._resolve(
                oldest, blocking=force
            ) and _tps._active:
                tp("engine.pipeline", event="window-full",
                   occ=p.pipe_occ, depth=self.pipeline_depth)
        return p

    @staticmethod
    def _tick_ready(pending: "_ShardedPending") -> bool:
        g = pending.group
        out = g.hits if g is not None else None
        if out is None:
            return True
        try:
            return bool(out.is_ready())
        except AttributeError:  # pragma: no cover - older jax
            return True

    def match_collect(self, pending: "_ShardedPending") -> List[Set[int]]:
        return [set(x) for x in self.match_collect_raw(pending)]

    def match_collect_raw(self, pending: "_ShardedPending") -> List[List[int]]:
        """Block on a submitted sharded match; verified fid lists.
        Records one flight-recorder row per tick (always device-path on
        the mesh: host arbitration does not apply across shards), with
        the pipeline occupancy this tick saw at submit and the churn
        slots THIS tick's fused dispatch actually shipped (the live
        delta backlog belongs to the NEXT tick after the submit-time
        drain)."""
        import time

        colls0 = self.collision_count
        out = self._collect_serve(pending)
        t1 = time.monotonic()
        lat = max(t1 - (pending.t0 if pending.t0 is not None else t1), 0.0)
        self.hist_tick.observe(lat)
        fl = self.flight
        if fl is not None:
            shed = self.churn_shed - self._churn_shed_rec
            self._churn_shed_rec = self.churn_shed
            fl.record(
                n_topics=len(pending.topics), n_unique=len(pending.topics),
                path=PATH_DEVICE, reason=R_FORCED,
                rate_host=None, rate_dev=None,
                bytes_up=pending.bytes_up, bytes_down=pending.bytes_down,
                verify_fail=self.collision_count - colls0,
                churn_slots=pending.churn_slots,
                lat_s=lat, churn_lag_s=self._churn_lag,
                pipe_occ=pending.pipe_occ, pipe_depth=pending.pipe_depth,
                churn_shed=shed,
                prep_hash_s=pending.prep_hash_s,
                prep_pack_s=pending.prep_pack_s,
                prep_submit_s=pending.prep_put_s,
                memo_hits=pending.memo_hits_tick,
                prep_group=pending.prep_group,
            )
        if _tps._active:  # gate: skip kwarg evaluation when tracing is off
            tp("engine.tick", path="device", n=len(pending.topics),
               lat_ms=lat * 1e3, reason="forced")
        return out

    def _collect_serve(self, pending: "_ShardedPending") -> List[List[int]]:
        topics = pending.topics
        out: List[List[int]] = [[] for _ in topics]
        if not pending.resolved:
            # blocking resolve: waits out a concurrent resolver, then
            # returns with hits_np populated (or None for an empty tick)
            self._resolve(pending)
        hits = pending.hits_np  # [D, n, k], overflow already widened
        if hits is not None:
            from ..models.engine import verify_pairs_into

            _d, bb, jj = np.nonzero(hits >= 0)
            if bb.size:
                fids = hits[_d, bb, jj]
                verified = False
                if self.verify_matches and self._reg is not None:
                    from ..ops import native

                    tbuf, toffs = native.pack_strs(topics)
                    ok = native.verify_pairs_reg(
                        self._reg, tbuf, toffs,
                        bb.astype(np.int32), fids,
                    )
                    if ok is not None:
                        for i, f, good in zip(
                            bb.tolist(), fids.tolist(), ok.tolist()
                        ):
                            if good:
                                out[i].append(int(f))
                            else:
                                self._collide(topics[i], int(f))
                        verified = True
                if not verified:
                    if self.verify_matches:
                        tmp: List[Set[int]] = [set() for _ in topics]
                        verify_pairs_into(
                            topics, bb, fids, self._words, self._fbytes,
                            tmp, self._collide,
                        )
                        for o, s in zip(out, tmp):
                            o.extend(s)
                    else:
                        for i, f in zip(bb.tolist(), fids.tolist()):
                            out[i].append(int(f))
        if pending.deep is not None:
            for o, hits_i in zip(out, pending.deep):
                o.extend(hits_i)
        return out

    def match_one(self, name: str) -> Set[int]:
        return self.match([name])[0]

    def _collide(self, topic: str, fid: int) -> None:
        self.collision_count += 1
        if self.on_collision is not None:
            self.on_collision(topic, fid)

    # --------------------------------------------- foreign ticket intake
    # (shm match plane: pre-packed ticks from wire workers, no topic
    # strings — verify and deep serving stay worker-side, the mesh
    # returns raw hash-match runs)

    def foreign_submit(self, reqs) -> List["_ShardedPending"]:
        """Dispatch K same-(B, L) PRE-PACKED foreign ticks as ONE mesh
        call.  Each req is ``(buf, n_live)`` with buf a `[B, 2L+2]` u32
        staging array packed by a wire worker's own TopicPrep — the
        coalesced-group machinery now fusing ticks from DIFFERENT
        processes (the flight `grp` column).  Pending churn fuses into
        the dispatch exactly like the native submit path; members carry
        their packed rows (`foreign_rows`) so the overflow refetch
        works without topic strings."""
        import time

        t0 = time.monotonic()
        K = len(reqs)
        B = int(reqs[0][0].shape[0])
        L = (int(reqs[0][0].shape[1]) - 2) // 2
        if any(r[0].shape != reqs[0][0].shape for r in reqs[1:]):
            raise ValueError(
                "foreign group members must share one (B, L) bucket: "
                + ", ".join(str(tuple(r[0].shape)) for r in reqs)
            )
        if not any(t.n_entries for t in self.shards):
            members = []
            for _buf, n in reqs:
                p = _ShardedPending(None, int(n), None, None, t0=t0)
                p.resolved = True
                members.append(p)
            return members
        slots, ka, kb, vv = self._pre_step_sync()
        churn_slots = int((slots >= 0).sum()) if slots is not None else 0
        if slots is not None:
            # donation below invalidates the tables every in-flight tick
            # still snapshots (overflow refetch): drain the window first
            self._drain_window("churn-fuse")
        kc = self._kcap_dyn
        if K > 1:
            # one [K*B, 2L+2] upload for the whole group, assembled in a
            # pooled buffer (the member bufs are the service's copies)
            gkey = (K * B, L)
            big = self._prep.acquire(gkey)
            for j, (buf, _n) in enumerate(reqs):
                big[j * B:(j + 1) * B] = buf
            pbatch = jax.device_put(big, self._repl())
        else:
            big, gkey = None, None
            pbatch = jax.device_put(reqs[0][0], self._repl())
        if slots is not None:
            bytes_up0 = reqs[0][0].nbytes + (
                slots.nbytes + ka.nbytes + kb.nbytes + vv.nbytes
            )
            put = lambda a: jax.device_put(a, self._shard0())
            self._stacked, hits, counts = sharded_step_compact_packed(
                self._stacked, put(slots), put(ka), put(kb), put(vv),
                pbatch, mesh=self.mesh, kcap=kc,
            )
        else:
            bytes_up0 = B * (2 * L + 2) * 4
            hits, counts = sharded_match_compact_packed(
                self._stacked, pbatch, mesh=self.mesh, kcap=kc
            )
        # fetch slimming: only the LAST member's padding can be trimmed
        n_last = int(reqs[-1][1])
        rows = (K - 1) * B + self._fetch_rows(n_last, B)
        if rows < K * B and K * B - rows >= (K * B) // 4:
            hits, counts = _slice_live(hits, counts, rows=rows)
        try:  # start the device->host copy NOW; resolve overlaps it
            hits.copy_to_host_async()
            counts.copy_to_host_async()
        except AttributeError:  # pragma: no cover - older jax
            pass
        group = _ShardedGroup(hits, counts, K, host_buf=big, buf_key=gkey)
        members = []
        for j, (buf, n) in enumerate(reqs):
            p = _ShardedPending(
                self._stacked, int(n), None, None, t0=t0,
                bytes_up=bytes_up0 if j == 0 else B * (2 * L + 2) * 4,
            )
            p.group = group
            p.row_off = j * B
            p.foreign_rows = buf
            p.mut_gen = self._mut_gen
            p.prep_group = K
            if j == 0:
                p.churn_slots = churn_slots
            members.append(p)
            self._inflight.append(p)
            p.pipe_occ = len(self._inflight)
            p.pipe_depth = self.pipeline_depth
        return members

    def foreign_collect(self, members: List["_ShardedPending"]):
        """Block on a foreign group; returns ``[(counts, fids)]`` per
        member in submit order (counts int64[n_j], fids i32 grouped per
        topic row) — UNVERIFIED hash runs, the owning worker verifies
        against its own filter words."""
        import time

        results = []
        for p in members:
            if not p.resolved:
                self._resolve(p)
            lat = max(time.monotonic() - (p.t0 or 0.0), 0.0)
            self.hist_tick.observe(lat)
            if p.hits_np is None:
                results.append(
                    (np.zeros(p.n, np.int64), np.empty(0, np.int32))
                )
            else:
                h2 = p.hits_np.transpose(1, 0, 2)  # [n, D, k]
                m2 = h2 >= 0
                results.append((
                    m2.sum(axis=(1, 2)).astype(np.int64),
                    h2[m2].astype(np.int32),  # row-major: per-topic runs
                ))
            fl = self.flight
            if fl is not None:
                fl.record(
                    n_topics=p.n, n_unique=p.n,
                    path=PATH_DEVICE, reason=R_FORCED,
                    rate_host=None, rate_dev=None,
                    bytes_up=p.bytes_up, bytes_down=p.bytes_down,
                    verify_fail=0, churn_slots=p.churn_slots,
                    lat_s=lat, churn_lag_s=self._churn_lag,
                    pipe_occ=p.pipe_occ, pipe_depth=p.pipe_depth,
                    prep_group=p.prep_group,
                )
        return results

    def match_fids(self, topics: Sequence[str]) -> List[Set[int]]:
        """Full unverified [D, B, M] fid sets (tests/debug)."""
        stacked, _ = self.sync_device()
        batch, n = self._prep_batch(topics)
        out = np.asarray(sharded_match_fids(stacked, batch, mesh=self.mesh))
        res: List[Set[int]] = []
        for b in range(n):
            col = out[:, b, :]
            res.append({int(x) for x in col[col >= 0]})
        if self._deep_fids:
            for i, t in enumerate(topics):
                res[i] |= self._deep.match(t) & self._deep_fids
        return res


class _ShardedGroup:
    """One mesh dispatch shared by K >= 1 in-flight ticks.

    Prep-ahead coalescing (ShardedMatchEngine.match_submit): up to
    `effective_depth` consecutive prepped ticks ride ONE
    `sharded_match_compact_packed` call with their rows concatenated;
    each member `_ShardedPending` slices its own [row_off, row_off + n)
    segment at resolve.  The device->host materialize happens once,
    under the group lock (members may race from collect threads)."""

    __slots__ = ("hits", "counts", "k", "lock", "hits_np", "counts_np",
                 "host_buf", "buf_key", "_share")

    def __init__(self, hits, counts, k, host_buf=None, buf_key=None):
        self.hits = hits  # device [D, rows, k] until fetched
        self.counts = counts  # device [D, rows] u16 until fetched
        self.k = k  # member count (1 = uncoalesced dispatch)
        self.lock = threading.Lock()
        self.hits_np = None
        self.counts_np = None
        # the coalesced [K*B, 2L+2] upload buffer (K>1 only): device_put
        # may alias it on the CPU backend, so it recycles only once the
        # dispatch outputs have materialized (fetch)
        self.host_buf = host_buf
        self.buf_key = buf_key
        self._share = 0

    def fetch(self, prep) -> int:
        """Materialize the dispatch outputs to host ONCE (idempotent,
        thread-safe); returns each member's wire-byte share of the
        download leg."""
        with self.lock:
            if self.hits_np is None:
                total = int(self.hits.nbytes) + int(self.counts.nbytes)
                self._share = total // self.k
                self.hits_np = np.asarray(self.hits)
                self.counts_np = np.asarray(self.counts)
                self.hits = self.counts = None
                if self.host_buf is not None:
                    prep.release(self.host_buf, self.buf_key)
                    self.host_buf = None
            return self._share


class _ShardedPending:
    """An in-flight sharded match (see ShardedMatchEngine.match_submit).

    Lives in the engine's pipeline window until `_resolve` fetches its
    device results to `hits_np`/`counts_np` (idempotent under `lock`;
    collect, a window drain, or a window-full force-resolve may race to
    do it).  The device outputs live on the shared `_ShardedGroup` (a
    group of 1 for uncoalesced dispatches); after resolve the pending
    holds numpy data only — no device buffers, no table snapshot, no
    staging buffer."""

    __slots__ = (
        "group", "row_off", "snap", "n", "topics", "deep", "t0",
        "bytes_up", "bytes_down", "churn_slots", "pipe_occ", "pipe_depth",
        "lock", "resolved", "hits_np", "counts_np", "buf", "bufkey",
        "mut_gen", "prep_hash_s", "prep_pack_s", "prep_put_s",
        "memo_hits_tick", "prep_group", "foreign_rows",
    )

    def __init__(self, snap, n, topics, deep=None, t0=None, bytes_up=0):
        self.group = None  # shared dispatch handle (None = empty tick)
        self.row_off = 0  # this tick's first row in the group batch
        self.snap = snap  # stacked tables of THIS tick (overflow refetch)
        self.n = n
        self.topics = topics
        self.t0 = t0
        self.bytes_up = bytes_up
        self.bytes_down = 0
        self.deep = deep  # deep-filter hits, snapshotted at submit
        self.churn_slots = 0  # delta slots THIS tick's dispatch shipped
        self.pipe_occ = 0  # in-flight ticks at submit (incl. this one)
        self.pipe_depth = 0  # engine.pipeline_depth at submit
        self.lock = threading.Lock()
        self.resolved = False
        self.hits_np = None  # [D, n, k] after resolve (overflow widened)
        self.counts_np = None  # [D, n] i32 after resolve
        self.buf = None  # staging buffer to recycle at resolve
        self.bufkey = None
        self.mut_gen = -1  # registry generation this tick matched against
        self.prep_hash_s = 0.0  # prep sub-stages (flight tick columns)
        self.prep_pack_s = 0.0
        self.prep_put_s = 0.0
        self.memo_hits_tick = 0  # topic-memo hits within this tick
        self.prep_group = 1  # coalesced dispatch group size
        self.foreign_rows = None  # packed rows of a foreign (shm) tick
