"""External plugin packages — `apps/emqx_plugins` analog.

The reference installs `.tar.gz` packages (name-vsn dirs with a
`release.json` manifest) into an install dir, keeps an *ordered* enabled
list in config, and starts/stops the contained apps
(`emqx_plugins.erl`: ensure_installed/uninstalled/enabled/disabled/
started/stopped).

Here a package is `<name>-<vsn>.tar.gz` containing::

    <name>-<vsn>/release.json    {"name": ..., "rel_vsn": ..., ...}
    <name>-<vsn>/<name>.py       module with on_load(ctx) / on_unload(ctx)

`on_load` receives a `PluginContext` exposing the broker facade (hooks,
publish, subscribe) — the same surface reference plugins get via the
emqx application.  State transitions mirror the reference: a plugin must
be installed to be enabled, and uninstall refuses while running.
"""

from __future__ import annotations

import importlib.util
import json
import logging
import os
import tarfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("emqx_tpu.plugins")


class PluginError(Exception):
    pass


@dataclass
class PluginContext:
    """What a plugin sees (`emqx.erl` facade subset)."""

    broker: object
    config: dict = field(default_factory=dict)

    @property
    def hooks(self):
        return self.broker.hooks


@dataclass
class PluginState:
    name_vsn: str
    manifest: dict
    enabled: bool = False
    running: bool = False
    module: Optional[object] = None


def _split_name_vsn(name_vsn: str):
    name, sep, vsn = name_vsn.rpartition("-")
    if not sep or not name:
        raise PluginError(f"bad name-vsn {name_vsn!r}")
    return name, vsn


class PluginManager:
    def __init__(self, broker, install_dir: str):
        self.broker = broker
        self.install_dir = install_dir
        os.makedirs(install_dir, exist_ok=True)
        self._plugins: Dict[str, PluginState] = {}
        # ordered enabled list, persisted like the reference's config entry
        self._state_path = os.path.join(install_dir, "plugins_state.json")
        self._enabled_order: List[str] = []
        self._load_state()
        self._scan_installed()

    # ---------------------------------------------------------- persistence

    def _load_state(self) -> None:
        if os.path.exists(self._state_path):
            with open(self._state_path, "r", encoding="utf-8") as f:
                self._enabled_order = json.load(f).get("enabled", [])

    def _save_state(self) -> None:
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"enabled": self._enabled_order}, f)
        os.replace(tmp, self._state_path)

    def _scan_installed(self) -> None:
        for entry in sorted(os.listdir(self.install_dir)):
            manifest = os.path.join(self.install_dir, entry, "release.json")
            if os.path.isfile(manifest):
                with open(manifest, "r", encoding="utf-8") as f:
                    self._plugins[entry] = PluginState(entry, json.load(f))
        for nv in self._enabled_order:
            if nv in self._plugins:
                self._plugins[nv].enabled = True

    # --------------------------------------------------------------- install

    def ensure_installed(self, name_vsn: str) -> PluginState:
        """Extract `<name_vsn>.tar.gz` from install_dir (`emqx_plugins.erl`
        do_ensure_installed)."""
        if name_vsn in self._plugins:
            return self._plugins[name_vsn]
        tar_path = os.path.join(self.install_dir, name_vsn + ".tar.gz")
        if not os.path.exists(tar_path):
            raise PluginError(f"package not found: {tar_path}")
        with tarfile.open(tar_path, "r:gz") as tf:
            root = os.path.realpath(self.install_dir)
            for m in tf.getmembers():  # refuse path escapes
                dest = os.path.realpath(os.path.join(root, m.name))
                if not dest.startswith(root + os.sep):
                    raise PluginError(f"unsafe member path {m.name!r}")
            tf.extractall(self.install_dir, filter="data")
        manifest_path = os.path.join(self.install_dir, name_vsn, "release.json")
        if not os.path.isfile(manifest_path):
            raise PluginError(f"package {name_vsn} lacks release.json")
        with open(manifest_path, "r", encoding="utf-8") as f:
            st = PluginState(name_vsn, json.load(f))
        self._plugins[name_vsn] = st
        return st

    def ensure_uninstalled(self, name_vsn: str) -> None:
        st = self._plugins.get(name_vsn)
        if st is None:
            return
        if st.running:
            raise PluginError(f"{name_vsn} is running; stop it first")
        if st.enabled:
            raise PluginError(f"{name_vsn} is enabled; disable it first")
        import shutil

        shutil.rmtree(os.path.join(self.install_dir, name_vsn),
                      ignore_errors=True)
        del self._plugins[name_vsn]

    # ---------------------------------------------------------- enable order

    def ensure_enabled(self, name_vsn: str, position: str = "rear") -> None:
        """position: 'front' | 'rear' | 'before:<name-vsn>'
        (`emqx_plugins:ensure_enabled/2`)."""
        if name_vsn not in self._plugins:
            raise PluginError(f"{name_vsn} not installed")
        if name_vsn in self._enabled_order:
            self._enabled_order.remove(name_vsn)
        if position == "front":
            self._enabled_order.insert(0, name_vsn)
        elif position == "rear":
            self._enabled_order.append(name_vsn)
        elif position.startswith("before:"):
            anchor = position.split(":", 1)[1]
            if anchor not in self._enabled_order:
                raise PluginError(f"anchor {anchor} not enabled")
            self._enabled_order.insert(self._enabled_order.index(anchor), name_vsn)
        else:
            raise PluginError(f"bad position {position!r}")
        self._plugins[name_vsn].enabled = True
        self._save_state()

    def ensure_disabled(self, name_vsn: str) -> None:
        if name_vsn in self._enabled_order:
            self._enabled_order.remove(name_vsn)
            self._save_state()
        if name_vsn in self._plugins:
            self._plugins[name_vsn].enabled = False

    # --------------------------------------------------------------- running

    def _load_module(self, st: PluginState):
        name, _vsn = _split_name_vsn(st.name_vsn)
        path = os.path.join(self.install_dir, st.name_vsn, f"{name}.py")
        if not os.path.isfile(path):
            raise PluginError(f"{st.name_vsn}: entry module {name}.py missing")
        spec = importlib.util.spec_from_file_location(
            f"emqx_tpu_plugin_{st.name_vsn.replace('-', '_').replace('.', '_')}",
            path,
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def ensure_started(self, name_vsn: Optional[str] = None) -> None:
        """Start one plugin, or every enabled plugin in configured order."""
        targets = [name_vsn] if name_vsn else list(self._enabled_order)
        for nv in targets:
            st = self._plugins.get(nv)
            if st is None:
                raise PluginError(f"{nv} not installed")
            if st.running:
                continue
            st.module = self._load_module(st)
            ctx = PluginContext(broker=self.broker,
                                config=st.manifest.get("config", {}))
            on_load = getattr(st.module, "on_load", None)
            if on_load is not None:
                on_load(ctx)
            st.running = True
            log.info("plugin started: %s", nv)

    def ensure_stopped(self, name_vsn: Optional[str] = None) -> None:
        targets = [name_vsn] if name_vsn else [
            nv for nv in reversed(self._enabled_order)
        ]
        for nv in targets:
            st = self._plugins.get(nv)
            if st is None or not st.running:
                continue
            on_unload = getattr(st.module, "on_unload", None)
            if on_unload is not None:
                try:
                    on_unload(PluginContext(broker=self.broker))
                except Exception:
                    log.exception("plugin %s on_unload failed", nv)
            st.running = False
            st.module = None
            log.info("plugin stopped: %s", nv)

    # ------------------------------------------------------------ inspection

    def list(self) -> List[dict]:
        out = []
        for nv, st in sorted(self._plugins.items()):
            out.append({
                "name_vsn": nv,
                "enabled": st.enabled,
                "running": st.running,
                **{k: st.manifest[k] for k in ("name", "rel_vsn", "description")
                   if k in st.manifest},
            })
        return out

    def get(self, name_vsn: str) -> Optional[PluginState]:
        return self._plugins.get(name_vsn)
