"""Named metric groups for plugins/bridges — `emqx_plugin_libs_metrics`.

The reference gives each resource/rule a counter group (matched,
success, failed, rate) registered under a namespace; this is the same
shape over the broker's Metrics store (or standalone), with the rate
computed over a sliding window like `emqx_plugin_libs_metrics:get_rate`.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple


class MetricsHelper:
    def __init__(self, namespace: str, metrics=None, window_s: float = 5.0):
        self.namespace = namespace
        self.metrics = metrics  # optional broker Metrics for mirroring
        self.window_s = window_s
        self._counters: Dict[str, int] = {}
        # name -> recent (ts, cumulative) samples for rate estimation
        self._hist: Dict[str, Deque[Tuple[float, int]]] = {}

    def inc(self, name: str, n: int = 1) -> None:
        cur = self._counters.get(name, 0) + n
        self._counters[name] = cur
        h = self._hist.setdefault(name, deque(maxlen=64))
        h.append((time.monotonic(), cur))
        if self.metrics is not None:
            self.metrics.inc(f"{self.namespace}.{name}", n)

    def get(self, name: str) -> int:
        return self._counters.get(name, 0)

    def rate(self, name: str, now: Optional[float] = None) -> float:
        """Events/sec over the sliding window."""
        h = self._hist.get(name)
        if not h:
            return 0.0
        now = time.monotonic() if now is None else now
        cutoff = now - self.window_s
        last_ts, last_val = h[-1]
        if last_ts < cutoff:
            return 0.0  # source idle: nothing inside the window
        base_ts, base_val = h[0]
        for ts, val in h:
            if ts >= cutoff:
                base_ts, base_val = ts, val
                break
        if last_ts <= base_ts:
            return 0.0
        return (last_val - base_val) / (last_ts - base_ts)

    def snapshot(self) -> Dict[str, int]:
        return dict(self._counters)

    def reset(self) -> None:
        self._counters.clear()
        self._hist.clear()
