"""Shared runtime utilities (the emqx_pool / emqx_plugin_libs analogs)."""

from .pool import WorkerPool
from .metrics_helper import MetricsHelper

__all__ = ["WorkerPool", "MetricsHelper"]
