"""Generic async task worker pool — the `emqx_pool` analog.

The reference runs a gproc pool of gen_servers and hash-dispatches work
(`emqx_pool:async_submit`, router/broker pools pick workers by
phash(topic)).  The asyncio equivalent: N worker tasks each draining a
bounded queue; `submit(fn)` round-robins, `submit_to(key, fn)` pins a
key to a worker so per-key ordering holds (the property the reference's
topic-hashed pools provide for route ops).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable, List

log = logging.getLogger("emqx_tpu.pool")


class WorkerPool:
    def __init__(self, size: int = 4, queue_size: int = 10_000,
                 name: str = "pool"):
        assert size >= 1
        self.size = size
        self.name = name
        self._queues: List[asyncio.Queue] = [
            asyncio.Queue(queue_size) for _ in range(size)
        ]
        self._tasks: List[asyncio.Task] = []
        self._rr = 0
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.dropped = 0

    def start(self) -> "WorkerPool":
        if not self._tasks:
            loop = asyncio.get_running_loop()
            self._tasks = [
                loop.create_task(self._worker(q)) for q in self._queues
            ]
        return self

    async def stop(self, drain: bool = True) -> None:
        if drain:
            await self.join()
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()

    async def _worker(self, q: asyncio.Queue) -> None:
        while True:
            fn, fut = await q.get()
            try:
                r = fn()
                if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
                    r = await r
                self.completed += 1
                if fut is not None and not fut.done():
                    fut.set_result(r)
            except Exception as e:
                self.failed += 1
                if fut is not None and not fut.done():
                    fut.set_exception(e)
                else:
                    log.exception("%s task failed", self.name)
            finally:
                q.task_done()

    # ------------------------------------------------------------ submit

    def submit(self, fn: Callable[[], Any]) -> bool:
        """Fire-and-forget on the next worker (async_submit)."""
        self._rr = (self._rr + 1) % self.size
        return self._enqueue(self._rr, fn, None)

    def submit_to(self, key: Any, fn: Callable[[], Any]) -> bool:
        """Fire-and-forget pinned to hash(key)'s worker: all work for a
        key runs on one worker in submission order."""
        return self._enqueue(hash(key) % self.size, fn, None)

    async def submit_to_wait(self, key: Any, fn: Callable[[], Any]) -> None:
        """Like submit_to, but awaits queue admission when the worker's
        queue is full — bounded backpressure (caller stalls only until
        one queued item drains, never for a handler's full runtime)."""
        i = hash(key) % self.size
        try:
            self._queues[i].put_nowait((fn, None))
        except asyncio.QueueFull:
            await self._queues[i].put((fn, None))
        self.submitted += 1

    def call(self, fn: Callable[[], Any]) -> "asyncio.Future":
        """Submit and get a future for the result (sync_submit analog)."""
        fut = asyncio.get_running_loop().create_future()
        self._rr = (self._rr + 1) % self.size
        if not self._enqueue(self._rr, fn, fut):
            fut.set_exception(RuntimeError(f"{self.name} queue full"))
        return fut

    def _enqueue(self, i: int, fn, fut) -> bool:
        try:
            self._queues[i].put_nowait((fn, fut))
        except asyncio.QueueFull:
            self.dropped += 1
            return False
        self.submitted += 1
        return True

    async def join(self) -> None:
        await asyncio.gather(*(q.join() for q in self._queues))

    @property
    def backlog(self) -> int:
        return sum(q.qsize() for q in self._queues)
