"""Small shared network helpers."""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple


def format_peername(addr: Tuple) -> str:
    """(host, port[, ...]) socket tuple → canonical peername string.
    IPv6 hosts get the bracket form so the port can be split back off
    unambiguously: ('::1', 1883) → '[::1]:1883'."""
    host, port = addr[0], addr[1]
    if ":" in str(host):
        return f"[{host}]:{port}"
    return f"{host}:{port}"


def peer_host(peername: Optional[str]) -> str:
    """Host part of a peername.  Handles '[v6]:port' (canonical),
    'v4:port', bare 'v4'/'v6' hosts (UDP gateways store addr[0] with
    no port), and legacy unbracketed 'v6:port' can't be split safely
    so it comes back whole."""
    if not peername:
        return ""
    if peername.startswith("["):
        end = peername.find("]")
        return peername[1:end] if end > 0 else peername
    if peername.count(":") > 1:
        return peername  # bare IPv6 (or unsplittable legacy v6:port)
    host, sep, port = peername.rpartition(":")
    if sep and port.isdigit():
        return host
    return peername


class UdpProtocolMixin:
    """Shared teardown for asyncio datagram protocols: transport
    close() only SCHEDULES the unbind, so an immediate restart races
    EADDRINUSE — `_close_transport` waits for connection_lost."""

    def connection_lost(self, exc) -> None:
        evt = getattr(self, "_closed_evt", None)
        if evt is not None:
            evt.set()

    async def _close_transport(self, transport,
                               timeout: float = 2.0) -> None:
        self._closed_evt = asyncio.Event()
        transport.close()
        try:
            await asyncio.wait_for(self._closed_evt.wait(), timeout)
        except asyncio.TimeoutError:
            pass
