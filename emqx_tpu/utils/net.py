"""Small shared network helpers."""

from __future__ import annotations

from typing import Optional


def peer_host(peername: Optional[str]) -> str:
    """Host part of a "host:port" peername, IPv6-safe: '::1:54321'
    splits on the LAST colon, so the address survives intact."""
    if not peername:
        return ""
    return peername.rsplit(":", 1)[0]
