"""Disk-backed replay queue — the replayq analog.

The reference buffers bridge traffic through replayq (`rebar.config`
replayq dep; SURVEY.md §2.3 "disk-backed queue (bridge buffering)"):
producers append items, a consumer pops a batch, and only an explicit
`ack` makes consumption durable — after a crash or restart every
popped-but-unacked item is replayed, so a bridge never loses messages
it has not confirmed delivered.

Same contract here, stdlib-only:

* ``append(item: bytes)`` — durable once the call returns (written +
  flushed to the current segment when a directory is configured);
* ``pop(count, bytes_limit) -> (ack_ref, items)`` — removes items from
  the in-memory queue but NOT from disk;
* ``ack(ack_ref)`` — commits the consumed prefix (atomic write of the
  commit cursor); fully-acked segments are deleted;
* reopen replays everything after the committed cursor, tolerating a
  torn tail record (a crash mid-append truncates to the last whole
  record, verified by per-record CRC32);
* ``max_total_bytes`` bounds disk use by dropping the OLDEST segment
  (the reference's default drop-oldest overflow policy).

Without a directory the queue is memory-only (replayq "mem_only"
mode) with the same API.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from collections import deque
from typing import Deque, List, Optional, Tuple

_REC_HDR = struct.Struct("<II")  # length, crc32


class ReplayQ:
    def __init__(
        self,
        dir: Optional[str] = None,
        seg_bytes: int = 4 * 1024 * 1024,
        max_total_bytes: int = 0,
    ):
        self.dir = dir
        self.seg_bytes = int(seg_bytes)
        self.max_total_bytes = int(max_total_bytes)
        # the churn WAL appends on the event loop while ack_through runs
        # inside the checkpoint worker's write(); bridges mix loop-side
        # appends with to_thread delivery pops — every cursor/segment
        # access is serialized here (reentrant: append -> _write ->
        # _enforce_bound nests)
        self._lock = threading.RLock()
        self.dropped = 0  # items lost to the overflow policy  # analysis: owner=any
        self._items: Deque[Tuple[int, bytes]] = deque()  # (seqno, item)
        self._next_seq = 1  # seqno of the next appended item
        self._acked = 0  # highest durably-consumed seqno
        self._popped = 0  # highest seqno handed out by pop()
        # seqnos evicted by drop_oldest() that the ack cursor has not
        # yet passed: they are gaps in the live seq space, subtracted
        # from pending_count() and absorbed as acks advance
        self._drop_gaps: Deque[int] = deque()
        self._segs: List[List] = []  # [first, last, path, nbytes]
        self._disk_bytes = 0  # all segments, tracked incrementally
        self._cur = None  # open segment file handle
        self._cur_first = 0
        self._cur_last = 0
        self._cur_bytes = 0
        if self.dir is not None:
            os.makedirs(self.dir, exist_ok=True)
            self._recover()

    # ---------------------------------------------------------- recovery

    def _commit_path(self) -> str:
        return os.path.join(self.dir, "commit")

    def _recover(self) -> None:
        # runs from __init__ only: construction-time replay, before the
        # queue is shared with any consumer thread
        with self._lock:
            try:
                with open(self._commit_path()) as f:
                    self._acked = int(f.read().strip() or 0)  # analysis: allow-blocking(construction-time recovery)
            except (OSError, ValueError):
                self._acked = 0
            self._popped = self._acked
            names = sorted(
                (n for n in os.listdir(self.dir)
                 if n.startswith("seg.") and n.endswith(".q")),
                key=lambda n: int(n.split(".")[1]),
            )
            seq = 0
            for name in names:
                first = int(name.split(".")[1])
                path = os.path.join(self.dir, name)
                seq = first - 1
                records = self._read_segment(path)
                for item in records:
                    seq += 1
                    if seq > self._acked:
                        self._items.append((seq, item))
                if seq <= self._acked:
                    os.unlink(path)  # fully consumed before the crash
                else:
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        size = 0
                    self._disk_bytes += size
                    self._segs.append([first, seq, path, size])
            self._next_seq = max(seq, self._acked) + 1

    @staticmethod
    def _read_segment(path: str) -> List[bytes]:
        """All intact records; a torn tail (crash mid-append) truncates
        the list at the last whole, CRC-valid record."""
        out: List[bytes] = []
        try:
            with open(path, "rb") as f:
                data = f.read()  # analysis: allow-blocking(construction-time recovery replay)
        except OSError:
            return out
        off = 0
        while off + _REC_HDR.size <= len(data):
            length, crc = _REC_HDR.unpack_from(data, off)
            end = off + _REC_HDR.size + length
            if end > len(data):
                break  # torn write
            body = data[off + _REC_HDR.size:end]
            if zlib.crc32(body) != crc:
                break  # torn/corrupt: stop at the damage
            out.append(body)
            off = end
        return out

    # ----------------------------------------------------------- append

    def append(self, item: bytes) -> int:
        """Queue one item; returns its seqno."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            self._items.append((seq, item))
            if self.dir is not None:
                self._write(seq, item)
            return seq

    def _write(self, seq: int, item: bytes) -> None:
        with self._lock:
            if self._cur is None or self._cur_bytes >= self.seg_bytes:
                self._rotate(seq)
            rec = _REC_HDR.pack(len(item), zlib.crc32(item)) + item
            # the replayq durability contract: durable-on-return means
            # one buffered write + flush into the page cache (NO fsync)
            # on the appender's thread — the docstring's at-least-once
            # reasoning depends on exactly this
            self._cur.write(rec)  # analysis: allow-blocking(replayq contract: page-cache write, no fsync)
            self._cur.flush()  # analysis: allow-blocking(replayq contract: page-cache flush, no fsync)
            self._cur_bytes += len(rec)
            self._cur_last = seq
            # refresh the open segment's span + size in _segs
            self._segs[-1][1] = seq
            self._segs[-1][3] += len(rec)
            self._disk_bytes += len(rec)
            if self.max_total_bytes:
                self._enforce_bound()

    def _rotate(self, first_seq: int) -> None:
        with self._lock:
            if self._cur is not None:
                self._cur.close()
            path = os.path.join(self.dir, f"seg.{first_seq}.q")
            self._cur = open(path, "ab")
            self._cur_first = first_seq
            self._cur_last = first_seq - 1
            self._cur_bytes = 0
            self._segs.append([first_seq, first_seq - 1, path, 0])

    def _enforce_bound(self) -> None:
        """Drop the oldest CLOSED segment while over budget (sizes are
        tracked incrementally — no per-append stat calls)."""
        with self._lock:
            while self._disk_bytes > self.max_total_bytes \
                    and len(self._segs) > 1:
                first, last, path, size = self._segs.pop(0)
                self._disk_bytes -= size
                try:
                    os.unlink(path)
                except OSError:
                    pass
                before = len(self._items)
                while self._items and self._items[0][0] <= last:
                    self._items.popleft()
                self.dropped += before - len(self._items)
                if self._acked < last:
                    self._acked = last
                if self._popped < last:
                    self._popped = last
                while self._drop_gaps and self._drop_gaps[0] <= self._acked:
                    self._drop_gaps.popleft()

    # -------------------------------------------------------------- pop

    def pop(self, count: int = 1, bytes_limit: Optional[int] = None
            ) -> Tuple[int, List[bytes]]:
        """Take up to `count` items (and at most `bytes_limit` payload
        bytes, always ≥1 item).  Returns (ack_ref, items); the items
        stay on disk until `ack(ack_ref)`."""
        with self._lock:
            items: List[bytes] = []
            taken = 0
            while self._items and len(items) < count:
                seq, item = self._items[0]
                if items and bytes_limit is not None and \
                        taken + len(item) > bytes_limit:
                    break
                self._items.popleft()
                items.append(item)
                taken += len(item)
                self._popped = seq
            return self._popped, items

    def requeue(self, ack_ref: int, items: List[bytes]) -> None:
        """Return a failed pop to the head of the queue (the items are
        still on disk; this only restores the in-memory view).  The
        items must be exactly one pop's batch, ending at ack_ref."""
        with self._lock:
            seq = ack_ref
            for item in reversed(items):
                if seq > self._acked:
                    self._items.appendleft((seq, item))
                seq -= 1
            self._popped = max(seq, self._acked)

    def drop_oldest(self, count: int = 1) -> List[bytes]:
        """Overflow eviction: remove up to `count` of the oldest UNPOPPED
        items and return them (caller accounting; they count toward
        `dropped`).  Unlike pop()+ack(), this never advances the ack
        cursor past a consumer's popped-but-unacked batch — an in-flight
        pop() window survives a concurrent eviction and can still be
        requeued and replayed.  The evicted seqnos become gaps that are
        absorbed lazily as the ack cursor reaches them (on disk, an
        unabsorbed gap may re-deliver after a crash — at-least-once,
        same as a lost ack writeback)."""
        with self._lock:
            out: List[bytes] = []
            while self._items and len(out) < count:
                seq, item = self._items.popleft()
                self._drop_gaps.append(seq)
                out.append(item)
            if not out:
                return out
            self.dropped += len(out)
            prev = self._acked
            self._absorb_drop_gaps()
            if self._acked != prev:
                self._persist_ack()
            return out

    def _absorb_drop_gaps(self) -> None:
        # with no in-flight pop window, the ack cursor may advance over
        # evicted seqnos adjacent to it (drops always come off the head,
        # so the gaps it meets are contiguous) — keeps pending_count()
        # honest and lets disk segments of dropped records be reclaimed
        with self._lock:
            while (
                self._popped == self._acked
                and self._drop_gaps
                and self._drop_gaps[0] == self._acked + 1
            ):
                self._drop_gaps.popleft()
                self._acked += 1
                self._popped = self._acked

    def ack(self, ack_ref: int) -> None:
        """Commit consumption up to ack_ref (a pop's returned ref)."""
        with self._lock:
            prev = self._acked
            if ack_ref > self._acked:
                self._acked = ack_ref
            while self._drop_gaps and self._drop_gaps[0] <= self._acked:
                self._drop_gaps.popleft()
            self._absorb_drop_gaps()
            if self._acked != prev:
                self._persist_ack()

    def _persist_ack(self) -> None:
        with self._lock:
            if self.dir is None:
                return
            tmp = self._commit_path() + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(self._acked))  # analysis: allow-blocking(replayq contract: tiny cursor writeback, no fsync)
            os.replace(tmp, self._commit_path())  # atomic; no fsync — the
            # queue is at-least-once (like replayq): a crash between ack
            # and writeback re-delivers a few confirmed items, never
            # loses unconfirmed ones, and the publish path never blocks
            # on disk
            # delete fully-acked segments (closing the current one first
            # if it is among them — a fresh segment opens on next append)
            while self._segs and self._segs[0][1] <= self._acked:
                _first, _last, path, size = self._segs.pop(0)
                self._disk_bytes -= size
                if self._cur is not None and not self._segs:
                    self._cur.close()
                    self._cur = None
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # ------------------------------------------------------------ state

    def count(self) -> int:
        with self._lock:
            return len(self._items)

    def pending_count(self) -> int:
        """Appended-but-unacked records (including popped-unacked ones,
        excluding drop_oldest() evictions) — the durable backlog a
        consumer still owes an ack for.  The churn WAL's snapshot
        threshold reads this (`checkpoint/manager.py`)."""
        with self._lock:
            return max(
                0,
                self._next_seq - 1 - self._acked - len(self._drop_gaps),
            )

    def pending_bytes(self) -> int:
        """Byte size of the unacked backlog.  Disk mode reports the live
        segment bytes (tracked incrementally; includes acked records in
        a partially-acked segment — an upper bound, which is the safe
        direction for a flush threshold).  Memory-only mode sums the
        queued payloads."""
        with self._lock:
            if self.dir is not None:
                return self._disk_bytes
            return sum(len(item) for _seq, item in self._items)

    def close(self) -> None:
        with self._lock:
            if self._cur is not None:
                self._cur.close()
                self._cur = None
