"""TLS-PSK identity store — `apps/emqx_psk` analog.

The reference keeps `psk_id -> shared_secret` entries in an mnesia table
(`emqx_psk.erl` #psk_entry record), bootstraps them from an init file of
`psk_id:secret` lines, and answers `on_psk_lookup` during the TLS
handshake (`emqx_tls_psk.erl`).  Here the store is the same shape:
in-memory dict + optional JSON snapshot persistence, file import with
the same line format, and a lookup callback shaped for
`ssl.SSLContext.set_psk_server_callback` (available from CPython 3.13;
on older runtimes the store still serves gateway/authn lookups).
"""

from __future__ import annotations

import json
import logging
import os
import ssl
from typing import Dict, Optional

log = logging.getLogger("emqx_tpu.psk")

SEPARATOR = ":"


class PskStore:
    def __init__(self, init_file: Optional[str] = None,
                 persist_path: Optional[str] = None, enable: bool = True):
        self.enable = enable
        self._entries: Dict[str, bytes] = {}
        self._persist_path = persist_path
        if persist_path and os.path.exists(persist_path):
            with open(persist_path, "r", encoding="utf-8") as f:
                self._entries = {
                    k: bytes.fromhex(v) for k, v in json.load(f).items()
                }
        if init_file:
            self.import_file(init_file)

    # ------------------------------------------------------------- access

    def lookup(self, psk_id: str) -> Optional[bytes]:
        """`on_psk_lookup` (`emqx_psk.erl`): None = unknown identity."""
        if not self.enable:
            return None
        return self._entries.get(psk_id)

    def insert(self, psk_id: str, secret: bytes) -> None:
        self._entries[psk_id] = secret
        self._save()

    def delete(self, psk_id: str) -> bool:
        existed = self._entries.pop(psk_id, None) is not None
        if existed:
            self._save()
        return existed

    def all_ids(self):
        return sorted(self._entries)

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------- import

    def import_file(self, path: str) -> int:
        """`psk_id:secret` per line, reference import format
        (`emqx_psk.erl` import/1).  Returns entries imported."""
        count = 0
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                psk_id, sep, secret = line.partition(SEPARATOR)
                if not sep or not psk_id:
                    log.warning("psk: skipping malformed line %r", line[:40])
                    continue
                self._entries[psk_id] = secret.encode("utf-8")
                count += 1
        self._save()
        return count

    def _save(self) -> None:
        if not self._persist_path:
            return
        tmp = self._persist_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({k: v.hex() for k, v in self._entries.items()}, f)
        os.replace(tmp, self._persist_path)

    # ----------------------------------------------------- TLS integration

    def ssl_callback(self):
        """Callback for `SSLContext.set_psk_server_callback`: returns the
        shared secret, or b"" to reject (per the ssl module contract)."""
        def cb(identity: Optional[str]) -> bytes:
            secret = self.lookup(identity or "")
            if secret is None:
                log.info("psk: unknown identity %r", identity)
                return b""
            return secret
        return cb

    def install(self, ctx: ssl.SSLContext) -> bool:
        """Attach to an SSLContext when the runtime supports server PSK."""
        setter = getattr(ctx, "set_psk_server_callback", None)
        if setter is None:
            log.warning("psk: ssl module lacks set_psk_server_callback "
                        "(needs CPython >= 3.13); store-only mode")
            return False
        setter(self.ssl_callback())
        return True
