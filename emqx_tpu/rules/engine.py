"""Rule engine runtime: events -> SQL eval -> outputs.

Analog of `emqx_rule_engine` (`emqx_rule_runtime.erl:48-143` apply_rules,
`emqx_rule_events.erl` event->topic mapping): rules select over broker
events; matching events are transformed by the SQL selection and fed to
outputs (republish, console, or arbitrary python callables — the bridge
integration point).

Event topics (reference-compatible):
    t/# ...                 -> 'message.publish' on matching topics
    $events/message_delivered, $events/message_acked,
    $events/message_dropped, $events/client_connected,
    $events/client_disconnected, $events/session_subscribed,
    $events/session_unsubscribed
"""

from __future__ import annotations

import fnmatch
import json
import logging
import time
from dataclasses import dataclass, field as dfield
from typing import Any, Callable, Dict, List, Optional

from ..broker import topic as topiclib
from ..broker.broker import Broker
from ..broker.message import Message
from .funcs import FUNCS, reset_proc_dict
from .sql import BinOp, Call, Case, Field, Lit, Not, Query, parse_sql

log = logging.getLogger("emqx_tpu.rules")

EVENT_TOPICS = {
    # explicit alias for the publish stream (plain topic filters in FROM
    # also select it); matches event_topic('message.publish')
    "$events/message_publish": "message.publish",
    "$events/message_delivered": "message.delivered",
    "$events/message_acked": "message.acked",
    "$events/message_dropped": "message.dropped",
    "$events/client_connected": "client.connected",
    "$events/client_disconnected": "client.disconnected",
    "$events/session_subscribed": "session.subscribed",
    "$events/session_unsubscribed": "session.unsubscribed",
}


# ------------------------------------------------------------- evaluation

class EvalError(Exception):
    pass


def _get_path(env: Dict[str, Any], path: List[str]) -> Any:
    cur: Any = env
    for i, seg in enumerate(path):
        if isinstance(cur, (bytes, str)) and i > 0:
            # auto-decode json payloads on nested access (reference behavior)
            try:
                cur = json.loads(cur if isinstance(cur, str) else cur.decode())
            except Exception:
                return None
        if isinstance(cur, dict):
            cur = cur.get(seg)
        elif isinstance(cur, list):
            try:
                cur = cur[int(seg)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    if isinstance(cur, bytes):
        try:
            cur = cur.decode("utf-8")
        except UnicodeDecodeError:
            pass
    return cur


def eval_expr(node: Any, env: Dict[str, Any]) -> Any:
    if isinstance(node, Lit):
        return node.value
    if isinstance(node, Field):
        return _get_path(env, node.path)
    if isinstance(node, Not):
        return not eval_expr(node.expr, env)
    if isinstance(node, Case):
        for cond, val in node.whens:
            if eval_expr(cond, env):
                return eval_expr(val, env)
        return eval_expr(node.default, env) if node.default is not None else None
    if isinstance(node, Call):
        if node.fn == "-":  # unary minus encoded as 0 - x (not in FUNCS)
            a, b = (eval_expr(x, env) for x in node.args)
            return a - b
        f = FUNCS.get(node.fn)
        if f is None:
            raise EvalError(f"unknown function {node.fn!r}")
        return f(*[eval_expr(a, env) for a in node.args])
    if isinstance(node, BinOp):
        op = node.op
        if op == "and":
            return bool(eval_expr(node.left, env)) and bool(eval_expr(node.right, env))
        if op == "or":
            return bool(eval_expr(node.left, env)) or bool(eval_expr(node.right, env))
        l = eval_expr(node.left, env)
        r = eval_expr(node.right, env)
        if op == "=":
            return _loose_eq(l, r)
        if op == "!=":
            return not _loose_eq(l, r)
        if op == "like":
            return fnmatch.fnmatch(str(l), str(r).replace("%", "*"))
        try:
            if op == ">":
                return l > r
            if op == "<":
                return l < r
            if op == ">=":
                return l >= r
            if op == "<=":
                return l <= r
            if op == "+":
                if isinstance(l, str) or isinstance(r, str):
                    return f"{l}{r}"
                return l + r
            if op == "-":
                return l - r
            if op == "*":
                return l * r
            if op == "/":
                return l / r
            if op == "div":
                return int(l) // int(r)
            if op == "mod":
                return int(l) % int(r)
        except TypeError:
            return None
        raise EvalError(f"unknown operator {op!r}")
    raise EvalError(f"bad AST node {node!r}")


def _loose_eq(l: Any, r: Any) -> bool:
    if isinstance(l, (int, float)) and isinstance(r, str):
        try:
            return float(r) == l
        except ValueError:
            return False
    if isinstance(r, (int, float)) and isinstance(l, str):
        try:
            return float(l) == r
        except ValueError:
            return False
    return l == r


def run_select(q: Query, env: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Apply WHERE + selection; returns the output map or None."""
    if q.where is not None and not eval_expr(q.where, env):
        return None
    if not q.selection:
        return {k: v for k, v in env.items() if not k.startswith("__")}
    out: Dict[str, Any] = {}
    for item in q.selection:
        val = eval_expr(item.expr, env)
        if item.alias:
            out[item.alias] = val
        elif isinstance(item.expr, Field):
            out[item.expr.path[-1]] = val
        else:
            out[f"col{len(out)}"] = val
    return out


# ----------------------------------------------------------------- outputs

@dataclass
class Republish:
    topic_template: str  # ${field} placeholders
    payload_template: str = "${payload}"
    qos: int = 0
    retain: bool = False

    def __call__(self, broker: Broker, selected: Dict[str, Any], env: Dict[str, Any]) -> None:
        topic = render_template(self.topic_template, selected, env)
        payload = render_template(self.payload_template, selected, env)
        broker.publish(
            Message(
                topic=topic,
                payload=payload.encode() if isinstance(payload, str) else payload,
                qos=self.qos,
                retain=self.retain,
                from_client="rule_engine",
                headers={"republish_by": "rule"},
            )
        )


@dataclass
class Console:
    sink: List = dfield(default_factory=list)

    def __call__(self, broker: Broker, selected: Dict[str, Any], env: Dict[str, Any]) -> None:
        self.sink.append(selected)
        log.info("[rule console] %s", selected)


@dataclass
class BridgeOutput:
    """Forward the selected output through a named data bridge — the
    `emqx_bridge:send_message(BridgeId, Selected)` rule output
    (`emqx_rule_runtime.erl:270`).  The manager is resolved at call
    time so rule and bridge construction order doesn't matter."""

    name: str
    manager_lookup: Callable[[], Any]

    def __call__(self, broker: Broker, selected: Dict[str, Any],
                 env: Dict[str, Any]) -> None:
        mgr = self.manager_lookup()
        if mgr is None:
            raise EvalError("no bridge manager configured")
        topic = str(selected.get("topic") or env.get("topic") or "")
        # SELECT * selections carry the raw payload bytes — serialize
        # them as text like render_template does for republish
        body = json.dumps(selected, default=_json_bytes)
        mgr.send_message(self.name, topic, body.encode("utf-8"))


def _json_bytes(v: Any) -> str:
    if isinstance(v, (bytes, bytearray)):
        return bytes(v).decode("utf-8", "replace")
    return str(v)


def build_outputs(defs, bridge_lookup: Optional[Callable] = None
                  ) -> List[Callable]:
    """Output definitions ({"type": "republish"|"console"|"bridge",
    ...}) -> output callables — shared by node-config and REST rule
    creation."""
    outs: List[Callable] = []
    for od in defs or [{"type": "console"}]:
        if not isinstance(od, dict):
            raise ValueError(f"output definition must be an object: {od!r}")
        if od.get("type") == "republish":
            if not od.get("topic"):
                raise ValueError("republish output requires 'topic'")
            try:
                qos = int(od.get("qos", 0))
            except (TypeError, ValueError):
                raise ValueError(f"republish qos must be an int: {od.get('qos')!r}")
            outs.append(
                Republish(
                    topic_template=od["topic"],
                    payload_template=od.get("payload", "${payload}"),
                    qos=qos,
                    retain=bool(od.get("retain", False)),
                )
            )
        elif od.get("type") == "bridge":
            if not od.get("name"):
                raise ValueError("bridge output requires 'name'")
            outs.append(BridgeOutput(od["name"],
                                     bridge_lookup or (lambda: None)))
        else:
            outs.append(Console())
    return outs


def render_template(tpl: str, selected: Dict[str, Any], env: Dict[str, Any]) -> str:
    """`${a.b}` placeholder substitution (emqx_placeholder analog)."""
    import re

    def sub(m):
        path = m.group(1).split(".")
        v = _get_path(selected, path)
        if v is None:
            v = _get_path(env, path)
        if v is None:
            return ""
        if isinstance(v, bytes):
            return v.decode("utf-8", "replace")
        if isinstance(v, (dict, list)):
            return json.dumps(v)
        return str(v)

    if tpl == "${.}":
        return json.dumps(selected)
    return re.sub(r"\$\{([^}]+)\}", sub, tpl)


# -------------------------------------------------------------------- rule

@dataclass
class Rule:
    rule_id: str
    sql: str
    outputs: List[Callable] = dfield(default_factory=list)
    enabled: bool = True
    description: str = ""
    query: Query = None  # parsed lazily
    metrics: Dict[str, int] = dfield(
        default_factory=lambda: {"matched": 0, "passed": 0, "failed": 0, "no_result": 0}
    )

    def __post_init__(self):
        if self.query is None:
            self.query = parse_sql(self.sql)


class RuleEngine:
    def __init__(self, broker: Broker):
        self.broker = broker
        self.rules: Dict[str, Rule] = {}
        self._installed = False

    # management ----------------------------------------------------------

    def create_rule(
        self,
        rule_id: str,
        sql: str,
        outputs: List[Callable],
        description: str = "",
    ) -> Rule:
        rule = Rule(rule_id=rule_id, sql=sql, outputs=outputs, description=description)
        self.rules[rule_id] = rule
        self._ensure_hooks()
        return rule

    def delete_rule(self, rule_id: str) -> bool:
        return self.rules.pop(rule_id, None) is not None

    def get_rule(self, rule_id: str) -> Optional[Rule]:
        return self.rules.get(rule_id)

    # hook plumbing -------------------------------------------------------

    def _ensure_hooks(self) -> None:
        if self._installed:
            return
        h = self.broker.hooks
        h.put("message.publish", self._on_publish, priority=-10)
        h.put("message.delivered", self._on_delivered)
        h.put("message.acked", self._on_acked)
        h.put("message.dropped", self._on_dropped)
        h.put("client.connected", self._on_connected)
        h.put("client.disconnected", self._on_disconnected)
        h.put("session.subscribed", self._on_subscribed)
        h.put("session.unsubscribed", self._on_unsubscribed)
        self._installed = True

    # event adapters ------------------------------------------------------

    def _msg_env(self, msg: Message, event: str) -> Dict[str, Any]:
        return {
            "event": event,
            "id": msg.mid.hex(),
            "topic": msg.topic,
            "payload": msg.payload,
            "qos": msg.qos,
            "retain": msg.retain,
            "clientid": msg.from_client,
            "username": msg.from_username,
            "flags": {"retain": msg.retain, "dup": msg.dup},
            "timestamp": msg.timestamp,
            "publish_received_at": msg.timestamp,
            "node": "local",
        }

    def _on_publish(self, msg):
        if (
            isinstance(msg, Message)
            and not msg.topic.startswith("$events/")
            # a rule's own republish must not re-trigger rules (loop guard,
            # mirrors the reference's republish flag check)
            and msg.headers.get("republish_by") != "rule"
        ):
            self._apply("message.publish", self._msg_env(msg, "message.publish"), msg.topic)
        return None

    def _on_delivered(self, clientid, msg):
        env = self._msg_env(msg, "message.delivered")
        env["to_clientid"] = clientid
        self._apply("message.delivered", env)

    def _on_acked(self, clientid, msg):
        env = self._msg_env(msg, "message.acked")
        env["to_clientid"] = clientid
        self._apply("message.acked", env)

    def _on_dropped(self, msg, reason):
        if msg is None:
            return
        env = self._msg_env(msg, "message.dropped")
        env["reason"] = reason
        self._apply("message.dropped", env)

    def _on_connected(self, clientinfo, *_):
        self._apply(
            "client.connected",
            {
                "event": "client.connected",
                "clientid": clientinfo.clientid,
                "username": clientinfo.username,
                "peerhost": clientinfo.peerhost,
                "proto_ver": clientinfo.proto_ver,
                "timestamp": int(time.time() * 1000),
                "node": "local",
            },
        )

    def _on_disconnected(self, clientinfo, normal=True, *_):
        self._apply(
            "client.disconnected",
            {
                "event": "client.disconnected",
                "clientid": clientinfo.clientid,
                "username": clientinfo.username,
                "reason": "normal" if normal else "abnormal",
                "timestamp": int(time.time() * 1000),
                "node": "local",
            },
        )

    def _on_subscribed(self, clientid, filt, opts):
        self._apply(
            "session.subscribed",
            {
                "event": "session.subscribed",
                "clientid": clientid,
                "topic": filt,
                "qos": getattr(opts, "qos", 0),
                "timestamp": int(time.time() * 1000),
                "node": "local",
            },
        )

    def _on_unsubscribed(self, clientid, filt):
        self._apply(
            "session.unsubscribed",
            {
                "event": "session.unsubscribed",
                "clientid": clientid,
                "topic": filt,
                "timestamp": int(time.time() * 1000),
                "node": "local",
            },
        )

    # core ----------------------------------------------------------------

    def _rule_matches_event(self, rule: Rule, event: str, topic: Optional[str]) -> bool:
        return topics_match_event(rule.query.topics, event, topic)

    def _apply(self, event: str, env: Dict[str, Any], topic: Optional[str] = None) -> None:
        for rule in self.rules.values():
            if not rule.enabled:
                continue
            if not self._rule_matches_event(rule, event, topic):
                continue
            rule.metrics["matched"] += 1
            try:
                reset_proc_dict()  # proc_dict_* scope = one application
                selected = run_select(rule.query, env)
            except Exception:
                rule.metrics["failed"] += 1
                log.exception("rule %s SQL failed", rule.rule_id)
                continue
            if selected is None:
                rule.metrics["no_result"] += 1
                continue
            rule.metrics["passed"] += 1
            for out in rule.outputs:
                try:
                    out(self.broker, selected, env)
                except Exception:
                    rule.metrics["failed"] += 1
                    log.exception("rule %s output failed", rule.rule_id)


def topics_match_event(topics, event: str,
                       topic: Optional[str]) -> bool:
    """FROM-clause match, shared by the live hook path and the SQL
    tester so they cannot diverge: event topics by name, plain filters
    against the message.publish topic."""
    for t in topics:
        mapped = EVENT_TOPICS.get(t)
        if mapped is not None:
            if mapped == event:
                return True
        elif event == "message.publish" and topic is not None:
            if topiclib.match(topic, t):
                return True
    return False


# ------------------------------------------------------------ SQL tester

class RuleTestNoMatch(Exception):
    """The FROM clause doesn't select the given event, or WHERE filtered
    it out — the reference's sqltester 412 'SQL Not Match' case."""


def rule_sql_test(sql: str, context: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Side-effect-free rule evaluation against a synthetic event — the
    `emqx_rule_sqltester:test/1` analog behind POST /rule_test.

    `context` carries `event_type` (message_publish, client_connected,
    ...) plus event fields; defaults mirror the reference's test
    defaults (topic "t/a", payload "{}")."""
    q = parse_sql(sql)  # SqlError propagates to the API layer (400)
    if context is not None and not isinstance(context, dict):
        raise ValueError("context must be an object")
    ctx = dict(context or {})
    event_type = str(ctx.pop("event_type", "message_publish"))
    event = event_type.replace("_", ".", 1)
    env: Dict[str, Any] = {
        "event": event,
        "topic": ctx.get("topic", "t/a"),
        "payload": ctx.get("payload", "{}"),
        "clientid": ctx.get("clientid", "c_emqx"),
        "username": ctx.get("username", "u_emqx"),
        "qos": ctx.get("qos", 1),
        "node": "local",
        "timestamp": int(time.time() * 1000),
    }
    env.update(ctx)
    if not topics_match_event(q.topics, event, str(env["topic"])):
        raise RuleTestNoMatch(
            f"SQL does not select event {event!r} topic {env['topic']!r}"
        )
    reset_proc_dict()
    selected = run_select(q, env)
    if selected is None:
        raise RuleTestNoMatch("WHERE clause did not match")
    return selected
