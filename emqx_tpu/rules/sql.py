"""SQL parser for rules — the `rulesql` dependency analog.

Grammar subset (mirrors the reference's rule SQL):

    SELECT <selection> FROM <topics> [WHERE <condition>]

    selection := * | expr [AS alias] {, expr [AS alias]}
    topics    := "str" {, "str"}
    expr      := literal | field path (payload.x.y, topic, clientid...)
               | fn(args...) | expr op expr | (expr)
    ops       := = != <> > < >= <= + - * / div mod and or not like

Produces an AST evaluated by `emqx_tpu.rules.engine` against event maps.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple


class SqlError(Exception):
    pass


# ------------------------------------------------------------------ lexer

TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<number>\d+\.\d+|\d+)
  | (?P<op><>|>=|<=|!=|=|>|<|\+|-|\*|/|\(|\)|,|\.)
  | (?P<name>[A-Za-z_$][A-Za-z0-9_$]*)
""",
    re.VERBOSE,
)

KEYWORDS = {"select", "from", "where", "as", "and", "or", "not", "div", "mod",
            "like", "in", "true", "false", "null", "case", "when", "then",
            "else", "end"}


@dataclass
class Tok:
    kind: str  # string|number|op|name|kw
    val: str


def tokenize(sql: str) -> List[Tok]:
    out: List[Tok] = []
    pos = 0
    while pos < len(sql):
        m = TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlError(f"bad character at {pos}: {sql[pos:pos+10]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        val = m.group()
        if kind == "name" and val.lower() in KEYWORDS:
            out.append(Tok("kw", val.lower()))
        else:
            out.append(Tok(kind, val))
    return out


# ------------------------------------------------------------------- AST

@dataclass
class Lit:
    value: Any


@dataclass
class Field:
    path: List[str]  # e.g. ["payload", "temp"]


@dataclass
class Call:
    fn: str
    args: List[Any]


@dataclass
class BinOp:
    op: str
    left: Any
    right: Any


@dataclass
class Not:
    expr: Any


@dataclass
class Case:
    whens: List[Tuple[Any, Any]]
    default: Optional[Any]


@dataclass
class SelectItem:
    expr: Any
    alias: Optional[str]  # None for '*'


@dataclass
class Query:
    selection: List[SelectItem]  # empty = SELECT *
    topics: List[str]
    where: Optional[Any]


# ----------------------------------------------------------------- parser

class _Parser:
    def __init__(self, toks: List[Tok]):
        self.toks = toks
        self.i = 0

    def peek(self) -> Optional[Tok]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> Tok:
        t = self.peek()
        if t is None:
            raise SqlError("unexpected end of SQL")
        self.i += 1
        return t

    def expect_kw(self, kw: str) -> None:
        t = self.next()
        if t.kind != "kw" or t.val != kw:
            raise SqlError(f"expected {kw.upper()}, got {t.val!r}")

    def accept_kw(self, kw: str) -> bool:
        t = self.peek()
        if t and t.kind == "kw" and t.val == kw:
            self.i += 1
            return True
        return False

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t and t.kind == "op" and t.val == op:
            self.i += 1
            return True
        return False

    # grammar ------------------------------------------------------------

    def parse_query(self) -> Query:
        self.expect_kw("select")
        selection = self.parse_selection()
        self.expect_kw("from")
        topics = self.parse_topics()
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        if self.peek() is not None:
            raise SqlError(f"trailing tokens at {self.peek().val!r}")
        return Query(selection, topics, where)

    def parse_selection(self) -> List[SelectItem]:
        if self.accept_op("*"):
            items: List[SelectItem] = []
            if self.accept_op(","):
                items = self.parse_select_items()
            return items  # [] = select-all
        return self.parse_select_items()

    def parse_select_items(self) -> List[SelectItem]:
        items = [self.parse_select_item()]
        while self.accept_op(","):
            if self.accept_op("*"):
                continue
            items.append(self.parse_select_item())
        return items

    def parse_select_item(self) -> SelectItem:
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            t = self.next()
            if t.kind not in ("name", "string"):
                raise SqlError(f"bad alias {t.val!r}")
            alias = _unquote(t.val) if t.kind == "string" else t.val
        return SelectItem(expr, alias)

    def parse_topics(self) -> List[str]:
        topics = []
        while True:
            t = self.next()
            if t.kind == "string":
                topics.append(_unquote(t.val))
            elif t.kind == "name":
                topics.append(t.val)
            else:
                raise SqlError(f"bad FROM topic {t.val!r}")
            if not self.accept_op(","):
                return topics

    # precedence: or < and < not < cmp < add < mul < unary < primary
    def parse_expr(self) -> Any:
        return self.parse_or()

    def parse_or(self) -> Any:
        e = self.parse_and()
        while self.accept_kw("or"):
            e = BinOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> Any:
        e = self.parse_not()
        while self.accept_kw("and"):
            e = BinOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> Any:
        if self.accept_kw("not"):
            return Not(self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self) -> Any:
        e = self.parse_add()
        t = self.peek()
        if t and t.kind == "op" and t.val in ("=", "!=", "<>", ">", "<", ">=", "<="):
            self.i += 1
            op = "!=" if t.val == "<>" else t.val
            return BinOp(op, e, self.parse_add())
        if t and t.kind == "kw" and t.val == "like":
            self.i += 1
            return BinOp("like", e, self.parse_add())
        if t and t.kind == "kw" and t.val == "in":
            self.i += 1
            if not self.accept_op("("):
                raise SqlError("expected ( after IN")
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            if not self.accept_op(")"):
                raise SqlError("expected ) after IN list")
            return Call("__in__", [e, *items])
        return e

    def parse_add(self) -> Any:
        e = self.parse_mul()
        while True:
            t = self.peek()
            if t and t.kind == "op" and t.val in ("+", "-"):
                self.i += 1
                e = BinOp(t.val, e, self.parse_mul())
            else:
                return e

    def parse_mul(self) -> Any:
        e = self.parse_unary()
        while True:
            t = self.peek()
            if t and ((t.kind == "op" and t.val in ("*", "/")) or (t.kind == "kw" and t.val in ("div", "mod"))):
                self.i += 1
                e = BinOp(t.val, e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> Any:
        if self.accept_op("-"):
            return Call("-", [Lit(0), self.parse_unary()])
        return self.parse_primary()

    def parse_primary(self) -> Any:
        t = self.next()
        if t.kind == "string":
            return Lit(_unquote(t.val))
        if t.kind == "number":
            return Lit(float(t.val) if "." in t.val else int(t.val))
        if t.kind == "kw":
            if t.val == "true":
                return Lit(True)
            if t.val == "false":
                return Lit(False)
            if t.val == "null":
                return Lit(None)
            if t.val == "case":
                return self.parse_case()
            nxt = self.peek()
            if nxt is not None and nxt.kind == "op" and nxt.val == "(":
                # keywords doubling as stdlib function names: mod(a,b),
                # div(a,b) work as calls like in the reference's rulesql
                self.next()
                args: List[Any] = []
                if not self.accept_op(")"):
                    while True:
                        args.append(self.parse_expr())
                        if self.accept_op(")"):
                            break
                        if not self.accept_op(","):
                            raise SqlError("expected , or ) in call")
                return Call(t.val, args)
            raise SqlError(f"unexpected keyword {t.val!r}")
        if t.kind == "op" and t.val == "(":
            e = self.parse_expr()
            if not self.accept_op(")"):
                raise SqlError("expected )")
            return e
        if t.kind == "name":
            # function call?
            if self.accept_op("("):
                args: List[Any] = []
                if not self.accept_op(")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                    if not self.accept_op(")"):
                        raise SqlError("expected ) after args")
                return Call(t.val, args)
            # dotted field path
            path = [t.val]
            while self.accept_op("."):
                nt = self.next()
                if nt.kind not in ("name", "number"):
                    raise SqlError(f"bad path segment {nt.val!r}")
                path.append(nt.val)
            return Field(path)
        raise SqlError(f"unexpected token {t.val!r}")

    def parse_case(self) -> Case:
        whens = []
        default = None
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            whens.append((cond, self.parse_expr()))
        if self.accept_kw("else"):
            default = self.parse_expr()
        self.expect_kw("end")
        return Case(whens, default)


def _unquote(s: str) -> str:
    body = s[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def parse_sql(sql: str) -> Query:
    return _Parser(tokenize(sql)).parse_query()
