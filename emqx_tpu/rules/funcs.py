"""SQL function stdlib for the rule engine (`emqx_rule_funcs.erl` analog)."""

from __future__ import annotations

import base64
import hashlib
import json
import time
import uuid
from typing import Any, Callable, Dict

from ..broker import topic as topiclib


def _num(x: Any) -> float:
    if isinstance(x, bool):
        return int(x)
    if isinstance(x, (int, float)):
        return x
    return float(x)


FUNCS: Dict[str, Callable] = {}


def fn(name):
    def deco(f):
        FUNCS[name] = f
        return f

    return deco


# strings ---------------------------------------------------------------
fn("upper")(lambda s: str(s).upper())
fn("lower")(lambda s: str(s).lower())
fn("trim")(lambda s: str(s).strip())
fn("ltrim")(lambda s: str(s).lstrip())
fn("rtrim")(lambda s: str(s).rstrip())
fn("reverse")(lambda s: str(s)[::-1])
fn("strlen")(lambda s: len(str(s)))
fn("concat")(lambda *a: "".join(str(x) for x in a))


@fn("substr")
def _substr(s, start, length=None):
    s = str(s)
    start = int(start)
    return s[start : start + int(length)] if length is not None else s[start:]


@fn("split")
def _split(s, sep=" ", index=None):
    parts = str(s).split(str(sep))
    return parts if index is None else parts[int(index)]


fn("replace")(lambda s, a, b: str(s).replace(str(a), str(b)))
fn("regex_match")(lambda s, p: __import__("re").search(p, str(s)) is not None)
fn("regex_replace")(lambda s, p, r: __import__("re").sub(p, r, str(s)))
fn("ascii")(lambda c: ord(str(c)[0]))
fn("find")(lambda s, sub: str(s).find(str(sub)))
fn("sprintf")(lambda f, *a: str(f) % a)

# numbers ---------------------------------------------------------------
fn("abs")(lambda x: abs(_num(x)))
fn("ceil")(lambda x: __import__("math").ceil(_num(x)))
fn("floor")(lambda x: __import__("math").floor(_num(x)))
fn("round")(lambda x: round(_num(x)))
fn("sqrt")(lambda x: __import__("math").sqrt(_num(x)))
fn("power")(lambda x, y: _num(x) ** _num(y))
fn("random")(lambda: __import__("random").random())
fn("range")(lambda a, b: list(range(int(a), int(b) + 1)))

# type conversion -------------------------------------------------------
fn("str")(lambda x: x.decode("utf-8", "replace") if isinstance(x, bytes) else str(x))
fn("int")(lambda x: int(_num(x)))
fn("float")(lambda x: float(_num(x)))
fn("bool")(lambda x: bool(x))
fn("is_null")(lambda x: x is None)
fn("is_not_null")(lambda x: x is not None)
fn("is_num")(lambda x: isinstance(x, (int, float)) and not isinstance(x, bool))
fn("is_str")(lambda x: isinstance(x, str))
fn("is_bool")(lambda x: isinstance(x, bool))
fn("is_map")(lambda x: isinstance(x, dict))
fn("is_array")(lambda x: isinstance(x, list))


@fn("coalesce")
def _coalesce(*args):
    for a in args:
        if a is not None and a != "":
            return a
    return None


# maps / arrays ---------------------------------------------------------
fn("map_get")(lambda k, m, default=None: (m or {}).get(k, default))
fn("map_put")(lambda k, v, m: {**(m or {}), k: v})
fn("map_keys")(lambda m: list((m or {}).keys()))
fn("map_values")(lambda m: list((m or {}).values()))
fn("contains")(lambda x, arr: x in (arr or []))
fn("nth")(lambda i, arr: (arr or [])[int(i) - 1])  # 1-indexed like the reference
fn("length")(lambda arr: len(arr or []))
fn("sublist")(lambda n, arr: (arr or [])[: int(n)])
fn("first")(lambda arr: (arr or [None])[0])
fn("last")(lambda arr: (arr or [None])[-1])

# json ------------------------------------------------------------------
fn("json_decode")(lambda s: json.loads(s if isinstance(s, str) else bytes(s).decode()))
fn("json_encode")(lambda x: json.dumps(x))

# hashing / encoding ----------------------------------------------------
def _to_bytes(x):
    return x if isinstance(x, bytes) else str(x).encode()

fn("md5")(lambda x: hashlib.md5(_to_bytes(x)).hexdigest())
fn("sha")(lambda x: hashlib.sha1(_to_bytes(x)).hexdigest())
fn("sha256")(lambda x: hashlib.sha256(_to_bytes(x)).hexdigest())
fn("base64_encode")(lambda x: base64.b64encode(_to_bytes(x)).decode())
fn("base64_decode")(lambda x: base64.b64decode(x))
fn("bin2hexstr")(lambda x: _to_bytes(x).hex())
fn("hexstr2bin")(lambda s: bytes.fromhex(str(s)))

# time / id -------------------------------------------------------------
fn("now_timestamp")(lambda unit="second": int(time.time() * (1000 if unit == "millisecond" else 1)))
fn("timezone_to_second")(lambda tz: 0)
fn("uuid_v4")(lambda: str(uuid.uuid4()))

# topic -----------------------------------------------------------------
fn("topic_match")(lambda name, filt: topiclib.match(str(name), str(filt)))


@fn("nth_topic_level")
def _nth_topic_level(i, topic):
    ws = topiclib.words(str(topic))
    i = int(i)
    return ws[i - 1] if 1 <= i <= len(ws) else None


# operators used internally --------------------------------------------
@fn("__in__")
def _in(x, *items):
    return x in items


# trigonometry / logs (emqx_rule_funcs.erl math section) ---------------
import math as _math

for _name in ("sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
              "tanh", "asinh", "acosh", "atanh", "exp", "log10", "log2"):
    fn(_name)(lambda x, _f=getattr(_math, _name): _f(_num(x)))
fn("log")(lambda x: _math.log(_num(x)))
fn("fmod")(lambda x, y: _math.fmod(_num(x), _num(y)))
fn("mod")(lambda x, y: int(_num(x)) % int(_num(y)))
fn("div")(lambda x, y: int(_num(x)) // int(_num(y)))
fn("exp2")(lambda x: 2.0 ** _num(x))

# bit operations --------------------------------------------------------
fn("bitand")(lambda a, b: int(_num(a)) & int(_num(b)))
fn("bitor")(lambda a, b: int(_num(a)) | int(_num(b)))
fn("bitxor")(lambda a, b: int(_num(a)) ^ int(_num(b)))
fn("bitnot")(lambda a: ~int(_num(a)))
fn("bitsl")(lambda a, n: int(_num(a)) << int(_num(n)))
fn("bitsr")(lambda a, n: int(_num(a)) >> int(_num(n)))
fn("bitsize")(lambda b: len(_to_bytes(b)) * 8)


@fn("subbits")
def _subbits(data, *args):
    """subbits(bytes[, len]) / subbits(bytes, start, len[, type,
    signedness, endianness]) — bit-addressed field extraction, the
    binary-payload decoder of `emqx_rule_funcs.erl` (do_get_subbits)."""
    raw = _to_bytes(data)
    if not args:
        start, length = 1, len(raw) * 8
        out_type, signed, endian = "integer", "unsigned", "big"
    elif len(args) == 1:
        start, length = 1, int(args[0])
        out_type, signed, endian = "integer", "unsigned", "big"
    else:
        start, length = int(args[0]), int(args[1])
        out_type = args[2] if len(args) > 2 else "integer"
        signed = args[3] if len(args) > 3 else "unsigned"
        endian = args[4] if len(args) > 4 else "big"
    total = int.from_bytes(raw, "big")
    nbits = len(raw) * 8
    end = start - 1 + length  # start is 1-based
    if end > nbits or start < 1:
        return None
    chunk = (total >> (nbits - end)) & ((1 << length) - 1)
    if out_type == "bits":
        nbytes = (length + 7) // 8
        return (chunk << (nbytes * 8 - length)).to_bytes(nbytes, "big")
    if endian == "little":
        nbytes = (length + 7) // 8
        chunk = int.from_bytes(chunk.to_bytes(nbytes, "big"), "little")
    if out_type == "float":
        import struct as _struct

        if length == 32:
            return _struct.unpack(">f", chunk.to_bytes(4, "big"))[0]
        if length == 64:
            return _struct.unpack(">d", chunk.to_bytes(8, "big"))[0]
        return None
    if signed == "signed" and chunk >= 1 << (length - 1):
        chunk -= 1 << length
    return chunk


FUNCS["get_subbits"] = _subbits

# time ------------------------------------------------------------------
_UNIT_MS = {"second": 1, "millisecond": 1_000, "microsecond": 1_000_000,
            "nanosecond": 1_000_000_000}


@fn("time_unit")
def _time_unit(val, from_unit, to_unit):
    return int(_num(val) * _UNIT_MS[str(to_unit)] / _UNIT_MS[str(from_unit)])


@fn("now_rfc3339")
def _now_rfc3339(unit="second"):
    return _unix_ts_to_rfc3339(time.time() * _UNIT_MS[str(unit)], unit)


@fn("unix_ts_to_rfc3339")
def _unix_ts_to_rfc3339(ts, unit="second"):
    import datetime as _dt

    secs = _num(ts) / _UNIT_MS[str(unit)]
    dt = _dt.datetime.fromtimestamp(secs, _dt.timezone.utc)
    if str(unit) == "second":
        return dt.strftime("%Y-%m-%dT%H:%M:%S+00:00")
    return dt.isoformat().replace("+00:00", "") + "+00:00"


@fn("rfc3339_to_unix_ts")
def _rfc3339_to_unix_ts(s, unit="second"):
    import datetime as _dt

    s = str(s)
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    dt = _dt.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return int(dt.timestamp() * _UNIT_MS[str(unit)])


# string extras ---------------------------------------------------------
@fn("tokens")
def _tokens(s, seps, nocrlf=None):
    s = str(s)
    if nocrlf == "nocrlf":
        s = s.replace("\r", "").replace("\n", "")
    out, cur = [], []
    sepset = set(str(seps))
    for ch in s:
        if ch in sepset:
            if cur:
                out.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


@fn("pad")
def _pad(s, n, direction="trailing", char=" "):
    s, n, char = str(s), int(n), str(char) or " "
    if direction == "leading":
        return s.rjust(n, char[0])
    if direction == "both":
        return s.center(n, char[0])
    return s.ljust(n, char[0])


@fn("sprintf_s")
def _sprintf_s(fmt, *args):
    """Erlang io_lib-style ~s/~p/~w/~b formatting; literal text (incl.
    braces) passes through untouched, ~~ escapes a tilde."""
    out = []
    ai = 0
    i = 0
    fmt = str(fmt)
    while i < len(fmt):
        ch = fmt[i]
        if ch == "~" and i + 1 < len(fmt):
            code = fmt[i + 1]
            i += 2
            if code == "~":
                out.append("~")
            elif code == "n":
                out.append("\n")
            elif code in ("s", "b"):
                out.append(str(args[ai]) if ai < len(args) else "")
                ai += 1
            elif code in ("p", "w"):
                out.append(repr(args[ai]) if ai < len(args) else "")
                ai += 1
            else:  # unknown directive: keep verbatim
                out.append("~" + code)
        else:
            out.append(ch)
            i += 1
    return "".join(out)
fn("str_utf8")(lambda x: x.decode("utf-8") if isinstance(x, (bytes, bytearray)) else str(x))
fn("float2str")(lambda x, prec=17: f"{float(_num(x)):.{int(prec)}g}")
fn("eq")(lambda a, b: a == b)


@fn("hash")
def _hash(alg, data):
    alg = str(alg).lower()
    h = hashlib.new("sha1" if alg == "sha" else alg)
    h.update(_to_bytes(data))
    return h.hexdigest()


# maps ------------------------------------------------------------------
fn("map_new")(lambda: {})


def _path_keys(k):
    return [p for p in str(k).replace("[", ".").replace("]", "").split(".") if p]


@fn("mget")
def _mget(k, m, default=None):
    cur = m or {}
    for part in _path_keys(k):
        if isinstance(cur, dict) and part in cur:
            cur = cur[part]
        elif isinstance(cur, list) and part.isdigit():
            i = int(part)
            if 1 <= i <= len(cur):
                cur = cur[i - 1]
            else:
                return default
        else:
            return default
    return cur


@fn("mput")
def _mput(k, v, m):
    parts = _path_keys(k)
    if not parts:
        return m
    root = dict(m or {})
    cur = root
    for part in parts[:-1]:
        # read the existing container at this step (1-based list index)
        if isinstance(cur, list):
            idx = int(part) - 1 if part.isdigit() else -1
            nxt = cur[idx] if 0 <= idx < len(cur) else None
        else:
            nxt = cur.get(part)
        # copy-on-write, preserving container kinds along the path
        if isinstance(nxt, list):
            nxt = list(nxt)
        elif isinstance(nxt, dict):
            nxt = dict(nxt)
        else:
            nxt = {}
        if isinstance(cur, list):
            if 0 <= idx < len(cur):
                cur[idx] = nxt
            else:
                return root  # out-of-range list step: no-op
        else:
            cur[part] = nxt
        cur = nxt
    last = parts[-1]
    if isinstance(cur, list) and last.isdigit() and 1 <= int(last) <= len(cur):
        cur[int(last) - 1] = v
    elif isinstance(cur, dict):
        cur[last] = v
    return root


FUNCS["map_path"] = _mget

# per-node kv store (kv_store_* of the reference; survives across rule
# evaluations, node-local like its ets table) ---------------------------
_KV_STORE: Dict[str, Any] = {}

fn("kv_store_put")(lambda k, v: (_KV_STORE.__setitem__(str(k), v), v)[1])
fn("kv_store_get")(lambda k, default=None: _KV_STORE.get(str(k), default))
fn("kv_store_del")(lambda k: _KV_STORE.pop(str(k), None))

# per-evaluation scratch dict (proc_dict_* — the reference's process
# dictionary scoped to one rule application; cleared by the engine) -----
_PROC_DICT: Dict[str, Any] = {}

fn("proc_dict_put")(lambda k, v: (_PROC_DICT.__setitem__(str(k), v), v)[1])
fn("proc_dict_get")(lambda k: _PROC_DICT.get(str(k)))
fn("proc_dict_del")(lambda k: _PROC_DICT.pop(str(k), None))


def reset_proc_dict() -> None:
    """Engine calls this around each rule application."""
    _PROC_DICT.clear()


# term encode/decode: the reference uses Erlang external term format;
# the portable analog here is canonical JSON bytes ----------------------
fn("term_encode")(lambda x: json.dumps(x, sort_keys=True).encode())
fn("term_decode")(lambda b: json.loads(_to_bytes(b).decode()))

# topic helpers ---------------------------------------------------------
# exact membership, unlike contains_topic_match's wildcard matching
fn("contains_topic")(lambda topics, t: str(t) in [str(x) for x in (topics or [])])


@fn("contains_topic_match")
def _contains_topic_match(filters, t):
    return any(topiclib.match(str(t), str(f)) for f in (filters or []))


@fn("find_topic_filter")
def _find_topic_filter(filters, t):
    for f in filters or []:
        if topiclib.match(str(t), str(f)):
            return f
    return None
