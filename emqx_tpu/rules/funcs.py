"""SQL function stdlib for the rule engine (`emqx_rule_funcs.erl` analog)."""

from __future__ import annotations

import base64
import fnmatch
import hashlib
import json
import time
import uuid
from typing import Any, Callable, Dict

from ..broker import topic as topiclib


def _num(x: Any) -> float:
    if isinstance(x, bool):
        return int(x)
    if isinstance(x, (int, float)):
        return x
    return float(x)


FUNCS: Dict[str, Callable] = {}


def fn(name):
    def deco(f):
        FUNCS[name] = f
        return f

    return deco


# strings ---------------------------------------------------------------
fn("upper")(lambda s: str(s).upper())
fn("lower")(lambda s: str(s).lower())
fn("trim")(lambda s: str(s).strip())
fn("ltrim")(lambda s: str(s).lstrip())
fn("rtrim")(lambda s: str(s).rstrip())
fn("reverse")(lambda s: str(s)[::-1])
fn("strlen")(lambda s: len(str(s)))
fn("concat")(lambda *a: "".join(str(x) for x in a))


@fn("substr")
def _substr(s, start, length=None):
    s = str(s)
    start = int(start)
    return s[start : start + int(length)] if length is not None else s[start:]


@fn("split")
def _split(s, sep=" ", index=None):
    parts = str(s).split(str(sep))
    return parts if index is None else parts[int(index)]


fn("replace")(lambda s, a, b: str(s).replace(str(a), str(b)))
fn("regex_match")(lambda s, p: __import__("re").search(p, str(s)) is not None)
fn("regex_replace")(lambda s, p, r: __import__("re").sub(p, r, str(s)))
fn("ascii")(lambda c: ord(str(c)[0]))
fn("find")(lambda s, sub: str(s).find(str(sub)))
fn("pad")(lambda s, n, c=" ": str(s).ljust(int(n), str(c)))
fn("sprintf")(lambda f, *a: str(f) % a)

# numbers ---------------------------------------------------------------
fn("abs")(lambda x: abs(_num(x)))
fn("ceil")(lambda x: __import__("math").ceil(_num(x)))
fn("floor")(lambda x: __import__("math").floor(_num(x)))
fn("round")(lambda x: round(_num(x)))
fn("sqrt")(lambda x: __import__("math").sqrt(_num(x)))
fn("power")(lambda x, y: _num(x) ** _num(y))
fn("random")(lambda: __import__("random").random())
fn("range")(lambda a, b: list(range(int(a), int(b) + 1)))

# type conversion -------------------------------------------------------
fn("str")(lambda x: x.decode("utf-8", "replace") if isinstance(x, bytes) else str(x))
fn("int")(lambda x: int(_num(x)))
fn("float")(lambda x: float(_num(x)))
fn("bool")(lambda x: bool(x))
fn("is_null")(lambda x: x is None)
fn("is_not_null")(lambda x: x is not None)
fn("is_num")(lambda x: isinstance(x, (int, float)) and not isinstance(x, bool))
fn("is_str")(lambda x: isinstance(x, str))
fn("is_bool")(lambda x: isinstance(x, bool))
fn("is_map")(lambda x: isinstance(x, dict))
fn("is_array")(lambda x: isinstance(x, list))


@fn("coalesce")
def _coalesce(*args):
    for a in args:
        if a is not None and a != "":
            return a
    return None


# maps / arrays ---------------------------------------------------------
fn("map_get")(lambda k, m, default=None: (m or {}).get(k, default))
fn("map_put")(lambda k, v, m: {**(m or {}), k: v})
fn("map_keys")(lambda m: list((m or {}).keys()))
fn("map_values")(lambda m: list((m or {}).values()))
fn("contains")(lambda x, arr: x in (arr or []))
fn("nth")(lambda i, arr: (arr or [])[int(i) - 1])  # 1-indexed like the reference
fn("length")(lambda arr: len(arr or []))
fn("sublist")(lambda n, arr: (arr or [])[: int(n)])
fn("first")(lambda arr: (arr or [None])[0])
fn("last")(lambda arr: (arr or [None])[-1])

# json ------------------------------------------------------------------
fn("json_decode")(lambda s: json.loads(s if isinstance(s, str) else bytes(s).decode()))
fn("json_encode")(lambda x: json.dumps(x))

# hashing / encoding ----------------------------------------------------
def _to_bytes(x):
    return x if isinstance(x, bytes) else str(x).encode()

fn("md5")(lambda x: hashlib.md5(_to_bytes(x)).hexdigest())
fn("sha")(lambda x: hashlib.sha1(_to_bytes(x)).hexdigest())
fn("sha256")(lambda x: hashlib.sha256(_to_bytes(x)).hexdigest())
fn("base64_encode")(lambda x: base64.b64encode(_to_bytes(x)).decode())
fn("base64_decode")(lambda x: base64.b64decode(x))
fn("bin2hexstr")(lambda x: _to_bytes(x).hex())
fn("hexstr2bin")(lambda s: bytes.fromhex(str(s)))

# time / id -------------------------------------------------------------
fn("now_timestamp")(lambda unit="second": int(time.time() * (1000 if unit == "millisecond" else 1)))
fn("timezone_to_second")(lambda tz: 0)
fn("uuid_v4")(lambda: str(uuid.uuid4()))

# topic -----------------------------------------------------------------
fn("topic_match")(lambda name, filt: topiclib.match(str(name), str(filt)))


@fn("nth_topic_level")
def _nth_topic_level(i, topic):
    ws = topiclib.words(str(topic))
    i = int(i)
    return ws[i - 1] if 1 <= i <= len(ws) else None


# operators used internally --------------------------------------------
@fn("__in__")
def _in(x, *items):
    return x in items
