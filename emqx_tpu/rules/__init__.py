"""Rule engine: SQL over broker event streams (apps/emqx_rule_engine analog)."""

from .engine import Rule, RuleEngine  # noqa: F401
from .sql import parse_sql, SqlError  # noqa: F401
