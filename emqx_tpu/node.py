"""Node boot orchestration — the `emqx_machine` analog.

The reference boots a node via `emqx_machine_boot:post_boot/0`
(`apps/emqx_machine/src/emqx_machine_boot.erl:29-47`): start all OTP apps
in dependency order, then kick autocluster; `emqx_sup` (one_for_all)
owns the kernel/router/broker/cm/sys trees (`emqx_sup.erl:64-80`).

`NodeRuntime` is the same composition root for the TPU-native stack: one
object builds config -> broker core (TPU match engine inside) ->
security chains -> modules -> observability -> listeners (tcp/ssl/ws/
wss) -> management REST -> cluster link-up, starts them in dependency
order, and stops them in reverse.  `python -m emqx_tpu --config
node.json` is the `bin/emqx start` equivalent.

Structured sections the typed schema does not model (lists of listener
blocks, cluster peer maps) ride in the same raw dict under "listeners" /
"cluster" and are validated here, the way the reference keeps listener
proplists outside the zone schema.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
from typing import Any, Dict, List, Optional

from .authn import AuthChain, BuiltInAuthenticator, JwtAuthenticator
from .authz import AuthzChain, BuiltInSource, ClientAclSource, FileSource
from .broker.banned import Banned, Flapping
from .broker.batcher import PublishBatcher
from .broker.broker import Broker
from .broker.limiter import Limiter, Olp
from .broker.listener import Listener
from .broker.persist import DiscBackend, RamBackend, SessionPersistence
from .broker.ws import WsListener
from .config.config import Config, ConfigError, channel_config_from
from .mgmt import HttpApi, ManagementApi, TokenStore
from .modules import AutoSubscribe, DelayedPublish, TopicMetrics, TopicRewrite
from .observe import AlarmManager, SlowSubs, Stats, TraceManager
from .observe.monitor import MonitorSampler
from .observe.sysmon import SysHeartbeat
from .psk import PskStore

log = logging.getLogger("emqx_tpu.node")


def poll_health_alarms(engine, cluster, alarms: AlarmManager,
                       ckpt=None, ds_repl=None) -> None:
    """Raise/clear the self-healing alarms from observed state.

    Polled (node ticker, chaos soak) rather than pushed so the alarm
    publish — itself a broker publish — never re-enters the engine from
    a collect thread.  `engine_device_degraded` tracks the device
    breaker; `cluster_forward_spool_overflow` raises when the bounded
    forward spool dropped records and clears once the spool has fully
    drained after a heal."""
    if getattr(engine, "breaker_open", False):
        alarms.activate(
            "engine_device_degraded",
            details={
                "consec_timeouts": getattr(engine, "consec_dev_timeouts", 0),
                "trips": getattr(engine, "breaker_trips", 0),
            },
            message="engine device path tripped to host-only serving",
        )
    elif alarms.is_active("engine_device_degraded"):
        alarms.deactivate("engine_device_degraded")
    # shm plane (wire workers): the client's silent fallback to local
    # matching on a stale hub heartbeat becomes an operator-visible
    # alarm; clears itself once the heartbeat freshens
    if getattr(engine, "hub_down", False):
        alarms.activate(
            "shm_hub_degraded",
            details={
                "degraded_ticks": getattr(engine, "shm_degraded", 0),
                "local_serves": getattr(engine, "shm_local", 0),
            },
            message="shm hub heartbeat stale: matching locally",
        )
    elif alarms.is_active("shm_hub_degraded"):
        alarms.deactivate("shm_hub_degraded")
    if ckpt is not None:
        # checkpoint write()/restore() run on worker threads and only
        # RECORD alarm transitions; the publish happens here, on-loop
        ckpt.poll_alarm()
    # ds replication (ds/repl.py): degraded shards append leader-only
    # until the follower hop heals; appends never block on this
    if ds_repl is not None:
        if ds_repl.degraded:
            alarms.activate(
                "ds_repl_degraded",
                details={
                    "shards": ds_repl.degraded_shards(),
                    "lag": ds_repl.lag(),
                },
                message="ds replication degraded: appends are "
                        "leader-only until the follower hop heals",
            )
        elif alarms.is_active("ds_repl_degraded"):
            alarms.deactivate("ds_repl_degraded")
    if cluster is None:
        return
    dropped = getattr(cluster, "spool_dropped", 0)
    if alarms.is_active("cluster_forward_spool_overflow"):
        if cluster.spool_pending() == 0:
            alarms.deactivate("cluster_forward_spool_overflow")
            cluster._spool_alarm_mark = dropped
    elif dropped > getattr(cluster, "_spool_alarm_mark", 0):
        alarms.activate(
            "cluster_forward_spool_overflow",
            details={"dropped": dropped},
            message="forward spool overflow: QoS>=1 forwards dropped",
        )


def _tls_from_dict(d: Dict[str, Any]):
    from .broker.tls import TlsConfig

    sni = {
        name: _tls_from_dict(sub) for name, sub in (d.get("sni_hosts") or {}).items()
    }
    kw = {k: v for k, v in d.items() if k != "sni_hosts"}
    return TlsConfig(sni_hosts=sni, **kw)


class NodeRuntime:
    """Composition root + ordered lifecycle for one broker node."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None):
        raw = raw or {}
        self.conf = Config(raw)
        self.raw = raw
        self.node_name = self.conf.get("node.name")
        # fault-injection plane (chaos testing): armed before any
        # component wires up so even boot-path IO sees the schedule
        if self.conf.get("fault.enable"):
            from . import fault

            fault.configure(
                self.conf.get("fault.spec") or {},
                seed=int(self.conf.get("fault.seed")),
            )
        # process-global GC tuning at end of boot; opted in by __main__
        # (dedicated broker process) only — see start()
        self.gc_tune_after_boot = False

        # ---- broker core (layer 1.7 + device engine) ------------------
        from .broker.retainer import Retainer

        retain_store = None
        if self.conf.get("retainer.backend") == "disc":
            from .broker.retain_store import DiscRetainStore

            retain_store = DiscRetainStore(
                os.path.join(self.conf.get("node.data_dir"), "retained.log")
            )
        retain_index = None
        if self.conf.get("retainer.device_index"):
            from .models.retained import RetainedDeviceIndex

            retain_index = RetainedDeviceIndex(
                fanin_max=self.conf.get("retainer.index_fanin_max"),
                max_shapes=self.conf.get("retainer.index_max_shapes"),
            )
        retainer = Retainer(
            max_retained=self.conf.get("retainer.max_retained_messages"),
            max_payload=self.conf.get("retainer.max_payload_size"),
            enable=self.conf.get("retainer.enable"),
            store=retain_store,
            device_index=retain_index,
            probe_interval=self.conf.get("retainer.probe_interval"),
        )
        # engine choice: single-chip TopicMatchEngine (default) or the
        # mesh-sharded engine over every visible device (the v5e-8 path)
        from .ops.hashing import HashSpace

        space = HashSpace(max_levels=self.conf.get("engine.max_levels"))
        self._engine_kind = self.conf.get("broker.engine")
        if self._engine_kind == "shm" and not self.conf.get("shm.region"):
            # "shm" is meaningful only with a slab to attach (the wire
            # supervisor injects shm.region into worker configs); a hub
            # or standalone node falls back to its own engine
            self._engine_kind = "single"
        if self._engine_kind == "sharded":
            from .parallel.sharded import ShardedMatchEngine

            engine = ShardedMatchEngine(
                space=space,
                n_sub_shards=self.conf.get("engine.n_sub_shards"),
                min_batch=self.conf.get("engine.min_batch"),
            )
        elif self._engine_kind == "shm":
            # shared-memory match plane (emqx_tpu/shm/): this process
            # owns NO device planes — ticks ride the hub's engine over
            # the per-worker rings, O(own subs) memory stays here
            from .shm.client import ShmMatchEngine

            engine = ShmMatchEngine(
                space=space,
                region=self.conf.get("shm.region"),
                slots=int(self.conf.get("shm.slots")),
                slot_bytes=int(self.conf.get("shm.slot_bytes")),
                timeout=float(self.conf.get("shm.timeout")),
                min_batch=self.conf.get("engine.min_batch"),
                doorbell_fd=int(self.conf.get("shm.doorbell_fd")),
                pin_core=int(self.conf.get("shm.pin_core")),
            )
        else:
            from .models.engine import TopicMatchEngine

            engine = TopicMatchEngine(
                space=space, min_batch=self.conf.get("engine.min_batch")
            )
            # hybrid host/device arbitration (broker.hybrid, default on):
            # never lose to an in-node matcher when the device link is
            # degraded (the reference matches in-node, emqx_router.erl:127)
            engine.hybrid = bool(self.conf.get("broker.hybrid"))
        # match-dispatch pipeline window (engine.pipeline_depth): both
        # engines bound their submitted-but-uncollected ticks by it, and
        # the publish batcher's in-flight ceiling is raised to match
        engine.pipeline_depth = int(self.conf.get("engine.pipeline_depth"))
        # flight recorder ring (engine.flight_ring; 0 = ring off, the
        # latency histograms stay — they are one bucket add per tick)
        ring = int(self.conf.get("engine.flight_ring"))
        if ring:
            from .observe.flight import FlightRecorder

            engine.flight = FlightRecorder(ring)
        else:
            engine.flight = None
        from .broker.shared_sub import SharedSub

        shared = SharedSub(
            strategy=self.conf.get("broker.shared_subscription_strategy"),
            group_strategies=self.conf.get(
                "broker.shared_subscription_group_strategies"
            ),
        )
        cluster_cfg = self.conf.get("cluster") or {}
        self.cluster = None
        # process-sharded wire plane (emqx_tpu/wire/): wire.workers > 0
        # makes this node the HUB of a worker pool — the cluster
        # machinery must exist (workers are peers over unix sockets)
        # even when no TCP cluster is configured
        _wk = self.conf.get("wire.workers")
        if _wk == "auto":
            # one core stays with the hub (event loop + device planes);
            # the clamp keeps a many-core host from forking a full
            # broker plane per core by default
            _wk = min(
                max(1, (os.cpu_count() or 2) - 1),
                int(self.conf.get("wire.max_workers")),
            )
        self._wire_workers = int(_wk)
        self.wire = None
        wire_unix = None
        if self._wire_workers > 0:
            wire_unix = os.path.join(
                self.conf.get("wire.ipc_dir")
                or os.path.join(self.conf.get("node.data_dir"), "wire"),
                "hub.sock",
            )
            os.makedirs(os.path.dirname(wire_unix), exist_ok=True)
        if cluster_cfg.get("enable") or self._wire_workers > 0:
            from .cluster.node import ClusterBroker, ClusterNode
            from .cluster.transport import check_addr

            self.broker: Broker = ClusterBroker(engine=engine, retainer=retainer, shared=shared)
            peers = {
                name: check_addr(addr)
                for name, addr in (cluster_cfg.get("peers") or {}).items()
            }
            discovery = None
            discovery_ivl = 5.0
            disc_cfg = cluster_cfg.get("discovery")
            if disc_cfg:
                from .cluster.discovery import make_discovery

                discovery_ivl = float(disc_cfg.get("interval", 5.0))
                discovery = make_discovery(
                    disc_cfg.get("strategy", "static"),
                    **{
                        k: v
                        for k, v in disc_cfg.items()
                        if k not in ("strategy", "interval")
                    },
                )
            # wire hub links heal on the worker-boot timescale (a few
            # seconds), not the cross-host partition timescale: the
            # hub's OUTBOUND link is the forward path INTO a worker, so
            # its reconnect ceiling stays short unless configured
            from .wire.supervisor import (HUB_RECONNECT_IVL,
                                          HUB_RECONNECT_MAX)

            default_ivl, default_max = (
                (HUB_RECONNECT_IVL, HUB_RECONNECT_MAX)
                if self._wire_workers > 0 and not cluster_cfg.get("enable")
                else (0.5, 15.0)
            )
            self.cluster = ClusterNode(
                self.node_name,
                self.broker,
                host=cluster_cfg.get("host", "127.0.0.1"),
                port=int(cluster_cfg.get("port", 0)),
                peers=peers,
                rpc_mode=cluster_cfg.get("rpc_mode", "async"),
                cookie=self.conf.get("node.cookie"),
                role=cluster_cfg.get("role", "core"),
                discovery=discovery,
                discovery_ivl=discovery_ivl,
                advertise_host=cluster_cfg.get("advertise_host"),
                route_hold=float(cluster_cfg.get("route_hold", 5.0)),
                spool_max_bytes=int(
                    cluster_cfg.get("spool_max_bytes", 8 << 20)
                ),
                unix_path=cluster_cfg.get("unix_path") or wire_unix,
                reconnect_ivl=float(
                    cluster_cfg.get("reconnect_ivl", default_ivl)
                ),
                reconnect_max=float(
                    cluster_cfg.get("reconnect_max", default_max)
                ),
            )
            from .cluster.cluster_rpc import ClusterRpc

            # cluster-wide config mutation log (emqx_conf/emqx_cluster_rpc)
            self.cluster_rpc = ClusterRpc(self.cluster)
        else:
            self.broker = Broker(engine=engine, retainer=retainer, shared=shared)

        # ---- semantic subscription plane (emqx_tpu/semantic/) ----------
        # `$semantic/<query>` filters match publishes on MEANING: the
        # subscribe path classifies them into this plane ($share-style),
        # never the trie/churn plane.  A wire worker runs the shm
        # backend (payload ticks ride K_SEM to the hub's one table);
        # everything else owns a device-resident SemanticEngine.
        self.semantic = None
        if self.conf.get("semantic.enable"):
            from .semantic.plane import SemanticPlane

            _sdim = int(self.conf.get("semantic.dim"))
            _stopk = int(self.conf.get("semantic.topk"))
            if self._engine_kind == "shm":
                engine.sem_node = self.node_name
                self.semantic = SemanticPlane(
                    shm=engine, dim=_sdim, topk=_stopk
                )
            else:
                from .semantic.engine import SemanticEngine

                self.semantic = SemanticPlane(engine=SemanticEngine(
                    dim=_sdim,
                    max_queries=int(
                        self.conf.get("semantic.max_queries")
                    ),
                    topk=_stopk,
                    probe_interval=float(
                        self.conf.get("semantic.probe_interval")
                    ),
                ))
            self.broker.semantic = self.semantic
            if self.cluster is not None:
                # cross-worker hits ride FORWARD frames to the owning
                # node (the $share forward discipline, qid-addressed)
                self.broker.forward_semantic = self.cluster.forward_semantic

        # ---- durable message log (ds/) ---------------------------------
        # parked persistent sessions replay QoS>=1 offline traffic from
        # a shared, sharded append-only log instead of per-session
        # mqueue snapshots; wired BEFORE persistence so restore() can
        # run the one-shot legacy-snapshot migration through it
        self.ds = None
        if self.conf.get("ds.enable"):
            from .ds.manager import DsManager

            ddir = self.conf.get("ds.dir") or os.path.join(
                self.conf.get("node.data_dir"), "ds"
            )
            self.ds = DsManager(
                self.broker, ddir, self.conf, metrics=self.broker.metrics
            )
            self.broker.ds = self.ds

        # ---- ds append replication (ds/repl.py) ------------------------
        # leader->follower shipment of flushed ranges + mirror serving;
        # construction wires the flush hooks and the REPL frame handler,
        # the drain task starts after cluster.start()
        self.ds_repl = None
        if (self.ds is not None and self.cluster is not None
                and self.conf.get("ds.repl.enable")):
            from .ds.repl import DsReplicator

            self.ds_repl = DsReplicator(
                self.cluster, self.ds, self.conf,
                metrics=self.broker.metrics,
            )

        # ---- persistence (5.4 checkpoint/resume) -----------------------
        self.persistence = None
        if self.conf.get("persistent_session_store.enable"):
            if self.conf.get("persistent_session_store.on_disc"):
                pdir = os.path.join(self.conf.get("node.data_dir"), "persist")
                backend = DiscBackend(pdir)
            else:
                backend = RamBackend()
            self.persistence = SessionPersistence(self.broker, backend)

        # ---- security chains (1.11) ------------------------------------
        self.banned = Banned()
        self.banned.install(self.broker.hooks)
        self.flapping = None
        if self.conf.get("flapping_detect.enable"):
            self.flapping = Flapping(
                self.banned,
                max_count=self.conf.get("flapping_detect.max_count"),
                window=self.conf.get("flapping_detect.window_time"),
                ban_duration=self.conf.get("flapping_detect.ban_time"),
            )
            self.flapping.install(self.broker.hooks)
        self._db_drivers: List[Any] = []  # pooled DB clients we own
        self.authn = None
        self.scram = None
        if self.conf.get("authn.enable"):
            self.authn = AuthChain(
                allow_anonymous=self.conf.get("authn.allow_anonymous")
            )
            self._build_authenticators(self.conf.get("authentication") or [])
            self.authn.install(self.broker.hooks)
        self.authz = None
        if self.conf.get("authz.enable"):
            self.authz = AuthzChain(default=self.conf.get("authz.no_match"))
            self._build_authz_sources(self.conf.get("authorization") or [])
            self.authz.install(self.broker.hooks)
        # shared access-control facade: channels inherit the configured
        # verdict-cache sizing and authz.deny_action (ignore|disconnect)
        from .broker.access_control import AccessControl

        self.broker.force_shutdown = (
            bool(self.conf.get("force_shutdown.enable")),
            int(self.conf.get("force_shutdown.max_message_queue_len")),
        )
        self.broker.access_control = AccessControl(
            self.broker.hooks,
            cache_size=self.conf.get("authz.cache_max_size"),
            cache_ttl=self.conf.get("authz.cache_ttl"),
            cache_enable=self.conf.get("authz.cache_enable"),
            deny_action=self.conf.get("authz.deny_action"),
        )

        # ---- modules (emqx_modules) ------------------------------------
        delayed_store = None
        if self.conf.get("delayed.persist"):
            os.makedirs(self.conf.get("node.data_dir"), exist_ok=True)
            delayed_store = os.path.join(
                self.conf.get("node.data_dir"), "delayed.log"
            )
        self.delayed = DelayedPublish(
            self.broker,
            enable=self.conf.get("delayed.enable"),
            max_delayed_messages=self.conf.get(
                "delayed.max_delayed_messages"
            ),
            store_path=delayed_store,
        )
        self.delayed.install(self.broker.hooks)
        from .broker.packet import SubOpts
        from .modules import RewriteRule

        self.rewrite = TopicRewrite(
            [
                RewriteRule(
                    action=r.get("action", "all"),
                    source=r["source_topic"],
                    regex=r["re"],
                    dest=r["dest_topic"],
                )
                for r in self.conf.get("rewrite") or []
            ]
        )
        self.rewrite.install(self.broker.hooks)
        self.auto_subscribe = AutoSubscribe(
            self.broker,
            [
                (t["topic"], SubOpts(qos=int(t.get("qos", 0))))
                for t in self.conf.get("auto_subscribe") or []
            ],
        )
        self.auto_subscribe.install(self.broker.hooks)
        self.topic_metrics = TopicMetrics()
        self.topic_metrics.install(self.broker.hooks)
        from .modules import EventMessage

        ev_conf = {
            k: self.conf.get(f"event_message.{k}")
            for k in EventMessage.TOPICS
        }
        self.event_message = None
        if any(ev_conf.values()):
            self.event_message = EventMessage(self.broker, ev_conf)
            self.event_message.install(self.broker.hooks)

        # ---- observability (1.13) ---------------------------------------
        # message-lifecycle span plane (observe/spans.py): head-sampled
        # per-plane latency attribution, armed process-wide like the
        # fault plane (observe.span_sample=0 disarms every boundary)
        from .observe import spans as _spans

        _spans.configure(
            sample=int(self.conf.get("observe.span_sample")),
            keep=int(self.conf.get("observe.span_keep")),
        )
        # contention telemetry (observe/contention.py): loop-lag probe +
        # GC pause tracking + queue-depth gauges, started with the node
        from .observe.contention import ContentionMonitor

        self.contention = ContentionMonitor(
            interval=float(self.conf.get("observe.loop_probe_interval"))
        )
        self.stats = Stats(self.broker,
                           enable=bool(self.conf.get("stats.enable")))
        self.alarms = AlarmManager(self.broker, node=self.node_name)
        self.slow_subs = SlowSubs()
        self.slow_subs.install(self.broker.hooks)
        # per-tick p99 comes from the engine histogram, not a second
        # wall-clock sampling path (observe/slow_subs.py docstring)
        self.slow_subs.attach_tick_hist(self.broker.engine.hist_tick)
        trace_dir = os.path.join(self.conf.get("node.data_dir"), "trace")
        self.traces = TraceManager(self.broker.hooks, directory=trace_dir)
        self.sys_heartbeat = SysHeartbeat(
            self.broker, stats=self.stats, node=self.node_name
        )
        self.monitor = MonitorSampler(self.broker)
        # dashboard series get the loop-lag level alongside engine p99
        self.monitor.contention = self.contention
        from .observe.exporters import ExporterRuntime

        self.exporters = ExporterRuntime(
            metrics_fn=self._metrics_table,
            stats_fn=lambda: self.stats.collect(),
            hists_fn=self._engine_histograms,
            prometheus={
                "enable": self.conf.get("prometheus.enable"),
                "push_gateway_server": self.conf.get(
                    "prometheus.push_gateway_server"),
                "interval": self.conf.get("prometheus.interval"),
            },
            statsd={
                "enable": self.conf.get("statsd.enable"),
                "server": self.conf.get("statsd.server"),
                "flush_time_interval": self.conf.get(
                    "statsd.flush_time_interval"),
            },
        )

        # ---- table checkpoint & warm restart (checkpoint/) ---------------
        # periodic binary snapshots of the engine's table state + a churn
        # WAL; boot restores the newest valid snapshot and replays the
        # WAL tail instead of replaying every filter through add_filters
        self.ckpt = None
        # shm-engine processes have no table state to snapshot: the hub
        # is registry-of-record (its own ckpt covers the union)
        if self.conf.get("engine.ckpt.enable") \
                and self._engine_kind != "shm":
            from .checkpoint.manager import CheckpointManager

            cdir = self.conf.get("engine.ckpt.dir") or os.path.join(
                self.conf.get("node.data_dir"), "ckpt"
            )
            self.ckpt = CheckpointManager(
                self.broker.engine,
                cdir,
                interval=self.conf.get("engine.ckpt.interval"),
                wal_max_bytes=self.conf.get("engine.ckpt.wal_max_bytes"),
                keep=self.conf.get("engine.ckpt.keep"),
                wal_seg_bytes=self.conf.get("engine.ckpt.wal_seg_bytes"),
                retained_index=retain_index,
                metrics=self.broker.metrics,
                alarms=self.alarms,
            )

        # ---- rule engine (emqx_rule_engine) ------------------------------
        from .rules.engine import RuleEngine, build_outputs

        # always present so the REST API can create rules at runtime;
        # bridge outputs resolve the manager lazily (bridges are built
        # after rules, and REST can add either at any time)
        self.rule_engine = RuleEngine(self.broker)
        bridge_lookup = lambda: self.bridges  # noqa: E731
        for idx, rd in enumerate(self.conf.get("rules") or []):
            self.rule_engine.create_rule(
                rd.get("id", f"rule{idx}"),
                rd["sql"],
                build_outputs(rd.get("outputs"), bridge_lookup),
                description=rd.get("description", ""),
            )

        # ---- exhook (out-of-process providers, gRPC or framed JSON) ------
        self.exhook = None
        self._exhook_defs = list(self.conf.get("exhook") or [])
        if self._exhook_defs:
            from .exhook import ExhookManager

            self.exhook = ExhookManager(self.broker.hooks, self.broker.metrics)

        # ---- flow control ------------------------------------------------
        self.limiter = self._build_limiter()
        self.olp = Olp()
        self.psk = PskStore()

        # ---- listeners (1.3) ---------------------------------------------
        self.batcher = PublishBatcher(
            self.broker,
            max_batch=self.conf.get("broker.batch_max"),
            max_delay=self.conf.get("broker.batch_delay"),
            # the tick queue must be able to fill the engine's dispatch
            # window (engine.pipeline_depth), or the pipeline starves
            max_inflight=max(
                32, int(self.conf.get("engine.pipeline_depth"))
            ),
        )
        # the pipelined publish path keeps the loop responsive even when
        # the device falls behind, so loop-lag-based OLP alone can't see
        # that overload — feed tick depth into the same shed decision
        self.olp.pressure_fn = lambda: self.batcher.inflight_ticks >= 8
        # sharded delivery-worker pool: broadcast fan-out drains off the
        # dispatch call stack, partitioned by connection shard
        self.delivery_pool = None
        if int(self.conf.get("broker.delivery_workers")) > 0:
            from .broker.delivery import DeliveryPool

            self.delivery_pool = DeliveryPool(
                self.broker,
                workers=int(self.conf.get("broker.delivery_workers")),
                queue_max=int(self.conf.get("broker.delivery_queue_max")),
                backpressure_bytes=int(
                    self.conf.get("broker.delivery_backpressure_bytes")
                ),
            )
            self.broker.delivery = self.delivery_pool
        self.listeners: List[Listener] = []
        for ldef in self.conf.get("listeners") or [{"type": "tcp", "port": 1883}]:
            self.listeners.append(self._build_listener(ldef))
        if self._wire_workers > 0:
            # the worker pool serves the listeners; this node keeps the
            # defs (REST /listeners reflects the configured ports) but
            # never binds them itself
            from .wire.supervisor import WireSupervisor

            self.wire = WireSupervisor(self)

        # ---- gateways (1.10) ----------------------------------------------
        from .gateway.core import GatewayRegistry

        self.gateways = GatewayRegistry()
        for gd in self.conf.get("gateways") or []:
            self.gateways.register(
                gd.get("name", gd["type"]), self._build_gateway(gd)
            )

        # ---- data bridges (1.9, emqx_bridge analog) -----------------------
        self.bridges = None
        bridge_defs = list(self.conf.get("bridges") or [])
        if bridge_defs:
            from .bridges.manager import BridgeManager

            self.bridges = BridgeManager(
                self.broker,
                data_dir=self.conf.get("node.data_dir"),
                definitions=bridge_defs,
            )

        # ---- management REST (1.12) ---------------------------------------
        from .mgmt.token import ApiKeyStore

        self.api_keys = ApiKeyStore()
        self.tokens = TokenStore(
            ttl_s=self.conf.get("dashboard.token_expired_time")
        )
        self.tokens.add_admin(
            self.conf.get("dashboard.default_username"),
            self.conf.get("dashboard.default_password"),
        )
        self.api = ManagementApi(
            self.broker,
            node=self.node_name,
            tokens=self.tokens,
            stats=self.stats,
            alarms=self.alarms,
            traces=self.traces,
            slow_subs=self.slow_subs,
            banned=self.banned,
            config=self.conf,
            cluster=self.cluster,
            listeners=self.listeners,
            sys_heartbeat=self.sys_heartbeat,
            psk=self.psk,
            monitor=self.monitor,
            rule_engine=self.rule_engine,
            authn=self.authn,
            authz=self.authz,
            gateways=self.gateways,
            bridges=self.bridges,
            olp=self.olp,
            delayed=self.delayed,
            exporters=self.exporters,
            api_keys=self.api_keys,
            ds=self.ds,
        )
        self.http = HttpApi(
            port=self.conf.get("dashboard.listen_port"),
            auth=self.api.auth_check,
        )
        self.api.install(self.http)

        self._tick_task: Optional[asyncio.Task] = None
        self._exporter_task: Optional[asyncio.Task] = None
        self._stop_evt: Optional[asyncio.Event] = None
        self.started = False

    # ------------------------------------------------------------ builders

    def _metrics_table(self) -> Dict[str, float]:
        """Exporter counter source: engine telemetry synced first so
        Prometheus/StatsD see current engine.* counters."""
        self.broker.sync_engine_metrics()
        return self.broker.metrics.all()

    def _engine_histograms(self) -> Dict[str, Any]:
        """Prometheus histogram table (observe/flight.py log2 buckets):
        engine latencies + per-stage span histograms + contention
        probes, all through the same NaN-skip exposition path."""
        from .observe import spans as _spans

        e = self.broker.engine
        out: Dict[str, Any] = {}
        for name, attr in (
            ("engine_tick_latency", "hist_tick"),
            ("engine_probe_latency", "hist_probe"),
            ("engine_churn_apply_latency", "hist_churn"),
        ):
            h = getattr(e, attr, None)
            if h is not None:
                out[name] = h
        for stage, h in _spans.stage_histograms().items():
            out[f"span_stage_{stage}_latency"] = h
        out.update(self.contention.histograms())
        # shm plane: worker side exports its stamped ring round-trip;
        # the hub side its drain-cycle gap + the fleet-merged worker
        # histograms scraped over wire_stats (fleet_* series)
        h = getattr(e, "hist_ring", None)
        if h is not None and h.count:
            out["shm_ring_roundtrip"] = h
        if self.wire is not None:
            if self.wire.service is not None \
                    and self.wire.service.hist_drain.count:
                out["shm_drain_cycle"] = self.wire.service.hist_drain
            out.update(self.wire.fleet_histograms())
        return out

    def _build_limiter(self) -> Optional[Limiter]:
        rates = {}
        for kind in Limiter.KINDS:
            r = self.conf.get(f"limiter.{kind}_rate")
            if r and r > 0:
                rates[kind] = {"rate": r, "burst": r}
        return Limiter(**rates) if rates else None

    def _build_listener(self, ldef: Dict[str, Any]) -> Listener:
        kind = ldef.get("type", "tcp")
        zone = ldef.get("zone")
        chan_cfg = channel_config_from(self.conf, zone=zone)
        chan_cfg.mountpoint = ldef.get("mountpoint")
        common = dict(
            host=ldef.get("host", "0.0.0.0"),
            port=int(ldef.get("port", 1883)),
            config=chan_cfg,
            max_connections=int(ldef.get("max_connections", 0)),
            batcher=self.batcher,
            limiter=self.limiter,
            olp=self.olp,
            # wire plane: workers bind the shared port via SO_REUSEPORT
            # (or adopt the supervisor-bound fd), and every listener
            # carries the accept-rate shed bucket when configured
            reuse_port=bool(ldef.get("reuseport")),
            sock_fd=ldef.get("sock_fd"),
            max_conn_rate=float(self.conf.get("wire.max_conn_rate")),
        )
        tls = None
        if kind in ("ssl", "wss") or ldef.get("ssl"):
            ssl_block = ldef.get("ssl")
            if not ssl_block:
                raise ConfigError(
                    f"listener type {kind!r} requires an 'ssl' block"
                )
            tls = _tls_from_dict(ssl_block)
        if kind in ("tcp", "ssl"):
            return Listener(self.broker, tls=tls, psk_store=self.psk, **common)
        if kind in ("ws", "wss"):
            return WsListener(
                self.broker,
                path=ldef.get("path", "/mqtt"),
                tls=tls,
                psk_store=self.psk,
                **common,
            )
        if kind == "quic":
            # the reference itself makes QUIC optional (BUILD_WITHOUT_QUIC,
            # rebar.config.erl:55-56); no MsQuic binding exists in this
            # environment, so the listener type is declared, not served
            raise ConfigError(
                "quic listener not available in this build (the reference "
                "gates it behind BUILD_WITHOUT_QUIC as well); use tcp/ssl/"
                "ws/wss"
            )
        raise ConfigError(f"unknown listener type {kind!r}")

    def _build_gateway(self, gd: Dict[str, Any]):
        kind = gd["type"]
        kw = dict(
            host=gd.get("host", "127.0.0.1"), port=int(gd.get("port", 0))
        )
        if kind == "mqttsn":
            from .gateway.mqttsn import MqttSnGateway

            return MqttSnGateway(
                self.broker,
                gateway_id=int(gd.get("gateway_id", 1)),
                predefined={
                    int(k): v
                    for k, v in (gd.get("predefined") or {}).items()
                },
                **kw,
            )
        if kind == "stomp":
            from .gateway.stomp import StompGateway

            return StompGateway(self.broker, **kw)
        if kind == "coap":
            from .gateway.coap import CoapGateway

            return CoapGateway(self.broker, **kw)
        if kind == "lwm2m":
            from .gateway.lwm2m import Lwm2mGateway

            return Lwm2mGateway(self.broker, **kw)
        if kind == "exproto":
            from .gateway.exproto import ExProtoGateway

            return ExProtoGateway(
                self.broker,
                handler_port=int(gd.get("handler_port", 0)),
                **kw,
            )
        raise ConfigError(f"unknown gateway type {kind!r}")

    def _build_authenticators(self, defs: List[Dict[str, Any]]) -> None:
        from . import drivers as drivers_mod

        for d in defs:
            mech = d.get("mechanism", "password_based")
            backend = d.get("backend", "built_in_database")
            if mech == "scram" or backend == "scram":
                # enhanced auth rides its own hookpoints, not the chain
                from .scram import ScramAuthenticator

                s = ScramAuthenticator(
                    iterations=int(d.get("iterations", 4096))
                )
                for u in d.get("users") or []:
                    s.add_user(
                        u["user_id"],
                        u["password"],
                        is_superuser=bool(u.get("is_superuser")),
                    )
                s.install(self.broker.hooks)
                self.scram = s
                continue
            if backend == "built_in_database":
                a = BuiltInAuthenticator(
                    user_id_type=d.get("user_id_type", "username")
                )
                for u in d.get("users") or []:
                    a.add_user(
                        u["user_id"],
                        u["password"],
                        is_superuser=bool(u.get("is_superuser")),
                        algorithm=d.get("password_hash_algorithm",
                                        "pbkdf2_sha256"),
                    )
            elif backend == "jwt" or mech == "jwt":
                a = JwtAuthenticator(secret=(d.get("secret") or "").encode())
            elif backend in drivers_mod.DB_KINDS:
                from .authn import DbAuthenticator

                driver_cfg = {
                    k: v
                    for k, v in d.items()
                    if k not in ("mechanism", "backend", "query",
                                 "password_hash_algorithm", "iterations",
                                 "user_id_type", "users")
                }
                a = DbAuthenticator(
                    backend,
                    d.get("query", ""),
                    algorithm=d.get("password_hash_algorithm",
                                    "pbkdf2_sha256"),
                    iterations=int(d.get("iterations", 10_000)),
                    **driver_cfg,
                )
                self._db_drivers.append(a.driver)
            else:
                raise ConfigError(f"unsupported authenticator backend {backend!r}")
            self.authn.add(a)

    def _build_authz_sources(self, defs: List[Dict[str, Any]]) -> None:
        from . import drivers as drivers_mod
        from .authz import DbSource, Rule

        for d in defs:
            t = d.get("type", "built_in_database")
            if t in drivers_mod.DB_KINDS:
                cfg = {k: v for k, v in d.items() if k not in ("type", "query")}
                src = DbSource(t, d.get("query", ""), **cfg)
                self._db_drivers.append(src.driver)
                self.authz.add(src)
            elif t == "built_in_database":
                self.authz.add(BuiltInSource())
            elif t == "client_acl":
                self.authz.add(ClientAclSource())
            elif t == "file":
                rules = [
                    Rule(
                        permission=r.get("permission", "allow"),
                        who=tuple(r["who"]) if isinstance(r.get("who"), list) else r.get("who", "all"),
                        action=r.get("action", "all"),
                        topics=list(r.get("topics") or []),
                    )
                    for r in d.get("rules") or []
                ]
                self.authz.add(FileSource(rules))
            else:
                raise ConfigError(f"unsupported authz source {t!r}")

    # ------------------------------------------------------------ lifecycle

    async def start(self) -> None:
        """Ordered startup.  A component failure tears down everything
        started so far before re-raising — no leaked sockets/tasks."""
        log.info("node %s booting", self.node_name)
        try:
            # pooled DB clients first: misconfiguration (bad host/AUTH)
            # must fail the boot loudly, not degrade authn/authz to
            # silent per-request fallthrough
            for drv in self._db_drivers:
                fn = getattr(drv, "start", None)
                if fn is not None:
                    await asyncio.to_thread(fn)
            if self.exhook is not None:
                from .exhook import ExhookServerConfig

                for d in self._exhook_defs:
                    if not d.get("enable", True):
                        continue
                    await asyncio.to_thread(
                        self.exhook.load_server,
                        ExhookServerConfig(
                            name=d.get("name", "default"),
                            host=d.get("host", "127.0.0.1"),
                            port=int(d.get("port", 9000)),
                            driver=d.get("driver", "grpc"),
                            pool_size=int(d.get("pool_size", 4)),
                            request_timeout=float(d.get("request_timeout", 5.0)),
                            failed_action=d.get("failed_action", "deny"),
                        ),
                    )
            # warm the engine's jit before serving: the first match pays
            # XLA compilation (hundreds of ms), which would otherwise
            # stall the event loop mid-traffic and trip the OLP shed
            # (one compile per batch-size bucket; the min_batch bucket
            # covers interactive publishes, bigger buckets compile lazily)
            def _warm():
                import jax

                try:
                    # persistent XLA cache: restarts (and every node
                    # sharing the cache dir) skip recompilation entirely
                    cache = self.conf.get("node.xla_cache_dir") or \
                        os.path.join(self.conf.get("node.data_dir"),
                                     "xla_cache")
                    jax.config.update("jax_compilation_cache_dir", cache)
                except Exception:
                    pass
                eng = self.broker.engine
                # restore-before-warmup: adopt the newest table snapshot
                # + WAL tail FIRST, so the warmup matches below ship the
                # restored tables to the device as ONE bulk upload (the
                # cold path replays every filter via add_filters instead)
                if self.ckpt is not None:
                    n_restored = self.ckpt.restore()
                    if n_restored:
                        log.info(
                            "engine warm restart: %d filters", n_restored
                        )
                # warm the DEVICE kernels even when hybrid arbitration
                # would route these matches host-side
                hybrid = getattr(eng, "hybrid", False)
                eng.hybrid = False
                eng.add_filter("$boot/warmup/+")
                eng.add_filter("$boot/warmup/#")
                try:
                    # first match has the add_filter delta pending ->
                    # compiles the FUSED churn+match kernel; the second
                    # has none -> compiles the pure-match kernel.  Warm
                    # both even-depth buckets common traffic hits
                    # (deeper buckets compile lazily; the persistent
                    # XLA cache makes this a first-boot-only cost).
                    eng.match(["$boot/warmup/x"])      # fused, bucket 4
                    eng.match(["$boot/warmup/x"])      # pure, bucket 4
                    eng.match(["warm"])                # pure, bucket 2
                finally:
                    # remove ONE of the two so entries remain: the
                    # match still dispatches and warms the fused
                    # REMOVE path (n_entries==0 would skip the device)
                    eng.remove_filter("$boot/warmup/#")
                    eng.match(["$boot/warmup/x"])
                    eng.remove_filter("$boot/warmup/+")
                    eng.hybrid = hybrid

            await asyncio.to_thread(_warm)
            if self.persistence is not None:
                # reload parked sessions (+ their routes) before serving;
                # expired entries are GC'd by restore().  With warm
                # tables every re-subscribe is a refcount bump, not a
                # hash+placement.
                n = self.persistence.restore()
                if n:
                    log.info("restored %d persistent sessions", n)
                if self.ckpt is not None:
                    # sessions are the authority on which subscriptions
                    # still exist: release the checkpoint's references
                    # (filters whose sessions expired while down drop
                    # out of the table; re-subscribed ones keep exactly
                    # their session refs)
                    await asyncio.to_thread(self.ckpt.reconcile_sessions)
            if self.cluster is not None:
                await self.cluster.start()
            if self.ds_repl is not None:
                # drain task needs the running loop; the PeerLinks it
                # ships over exist once cluster.start() returned
                self.ds_repl.start()
            if self.bridges is not None:
                # a down endpoint is DISCONNECTED + retried, not a boot
                # failure (reference bridges start async the same way)
                await self.bridges.start()
            if self.delivery_pool is not None:
                self.delivery_pool.start()
            if self.wire is not None:
                # process-sharded wire plane: the worker pool binds the
                # configured listeners (reuseport / inherited fd); the
                # hub serves no MQTT socket of its own
                await self.wire.start()
            else:
                for lst in self.listeners:
                    await lst.start()
            for name in self.gateways.list():
                await self.gateways.lookup(name).start()
            await self.http.start()
            # contention probes: loop-lag task + gc.callbacks tracker
            self.contention.start()
            self._stop_evt = asyncio.Event()
            self._tick_task = asyncio.create_task(self._ticker())
            # separate task: a hung pushgateway (5s timeouts) must not
            # stall delayed publish / retainer flush / heartbeats
            self._exporter_task = asyncio.create_task(
                self._exporter_loop()
            )
        except BaseException:
            await self._shutdown()
            raise
        if self.gc_tune_after_boot:
            # Dedicated-process GC tuning (opted in by __main__): the
            # boot-time object graph — route tables, restored sessions —
            # holds millions of long-lived objects, and cyclic-GC gen-2
            # sweeps over them cost tens of ms per pause on the match
            # hot path (measured: p99 9 ms -> 77 ms at 100k routes).
            # Freeze it out of collection and raise the gen0 threshold;
            # the BEAM analog is per-process heaps that never scan the
            # route tables at all.
            import gc

            gc.collect()
            gc.freeze()
            _g0, g1, g2 = gc.get_threshold()
            gc.set_threshold(50_000, g1, g2)
        self.started = True
        log.info(
            "node %s up: %s, dashboard :%d",
            self.node_name,
            ", ".join(
                f"{type(l).__name__.lower()}:{l.port}" for l in self.listeners
            ),
            self.http.port,
        )

    async def stop(self) -> None:
        """Reverse-order shutdown (`emqx_machine_terminator` analog)."""
        if not self.started:
            return
        self.started = False
        await self._shutdown()
        log.info("node %s stopped", self.node_name)

    async def _shutdown(self) -> None:
        """Stop every component that is running; safe on partial starts
        (each component's stop() tolerates never-started state)."""
        for task in (self._tick_task, self._exporter_task):
            if task:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):
                    pass
        self._tick_task = None
        self._exporter_task = None
        await self.contention.stop()
        await self.http.stop()
        for name in self.gateways.list():
            try:
                await self.gateways.lookup(name).stop()
            except Exception:
                log.exception("stopping gateway %s", name)
        if self.wire is not None:
            try:
                await self.wire.stop()
            except Exception:
                log.exception("stopping wire supervisor")
        else:
            for lst in reversed(self.listeners):
                try:
                    await lst.stop()
                except Exception:
                    log.exception("stopping listener on port %s", lst.port)
        if self.delivery_pool is not None:
            try:
                await self.delivery_pool.stop()
            except Exception:
                log.exception("stopping delivery pool")
        if self.ds_repl is not None:
            try:
                await self.ds_repl.stop()  # before the links it ships over
            except Exception:
                log.exception("stopping ds replicator")
        if self.cluster is not None:
            await self.cluster.stop()
        if self.bridges is not None:
            try:
                await self.bridges.stop()
            except Exception:
                log.exception("stopping bridges")
        if self.exhook is not None:
            await asyncio.to_thread(self.exhook.stop)
        if self.persistence is not None:
            self.persistence.tick()  # final dirty-page flush
        if self.ds is not None:
            try:
                self.ds.close()  # final log flush: clean durable handoff
            except Exception:
                log.exception("closing durable message log")
        if self.ckpt is not None:
            try:
                self.ckpt.checkpoint()  # final snapshot: clean WAL handoff
            except Exception:
                log.exception("final engine checkpoint")
            self.ckpt.close()
        if self.broker.retainer.store is not None:
            self.broker.retainer.store.close()
        eng_close = getattr(self.broker.engine, "close", None)
        if eng_close is not None:
            eng_close()  # prep-ahead stage: worker joined, buffers freed
        self.delayed.close()
        for drv in self._db_drivers:
            fn = getattr(drv, "stop", None)
            if fn is not None:
                try:
                    await asyncio.to_thread(fn)
                except Exception:
                    log.exception("stopping db driver %r", drv)
        self.traces.stop_all()

    async def _exporter_loop(self) -> None:
        """Prometheus/StatsD export cadence, isolated from the node
        ticker (pushes can block for their full network timeout)."""
        while True:
            await asyncio.sleep(1.0)
            if not self.exporters.active:
                continue  # both disabled: skip the thread hop
            try:
                now = asyncio.get_running_loop().time()
                await asyncio.to_thread(self.exporters.tick, now)
            except Exception:
                log.exception("exporter tick")

    async def _ticker(self) -> None:
        """Node-level periodic work: $SYS heartbeats, dashboard sampler,
        delayed-publish scheduler, stats gauges.  (Connection-level timers
        live in the listener housekeeping loop.)"""
        hb_ivl = self.conf.get("broker.sys_heartbeat_interval")
        msg_ivl = self.conf.get("broker.sys_msg_interval")
        last_hb = last_msg = 0.0
        while True:
            await asyncio.sleep(1.0)
            try:
                now = asyncio.get_running_loop().time()
                self.delayed.tick()
                # queue-depth / loop-lag / gc gauges land in the
                # metrics table before the monitor samples them
                self.contention.sample(
                    self.broker, delivery=self.delivery_pool,
                    batcher=self.batcher,
                )
                self.monitor.tick()
                self._refresh_stats()
                self._poll_health_alarms()
                if self.broker.retainer.store is not None:
                    # buffered-append flush can stall on disk pressure:
                    # keep it off the loop like ds.flush_all/ckpt.write
                    await asyncio.to_thread(self.broker.retainer.store.flush)
                if self.ds is not None:
                    # only the fsync-heavy flush leaves the loop; GC +
                    # min-cursor + gauges stay ON the loop so the walk
                    # over cm.pending is serialized with resumes (an
                    # off-loop min-cursor can miss a session mid-resume
                    # and GC the generation it is replaying)
                    if self.ds.flush_due(now):
                        await asyncio.to_thread(self.ds.flush_all)
                    self.ds.tick_gc(now)
                if now - last_hb >= hb_ivl:
                    last_hb = now
                    self.sys_heartbeat.tick()
                if now - last_msg >= msg_ivl:
                    last_msg = now
                    self.sys_heartbeat.tick_msgs()
                if self.ckpt is not None and self.ckpt.due():
                    # capture on the loop (serialized with engine
                    # mutations); serialize + fsync on a worker thread
                    payload = self.ckpt.capture()
                    await asyncio.to_thread(self.ckpt.write, payload)
            except Exception:
                log.exception("node ticker")

    def _poll_health_alarms(self) -> None:
        """Self-healing alarms, polled from the ticker so alarm publish
        (itself a broker publish) never runs on an engine collect
        thread: the device breaker and the forward-spool overflow."""
        poll_health_alarms(self.broker.engine, self.cluster, self.alarms,
                           ckpt=self.ckpt, ds_repl=self.ds_repl)

    def _refresh_stats(self) -> None:
        """Periodic gauges (`emqx_stats` setstat points).  `stats.enable`
        turns the sampling off wholesale (the reference's emqx_stats
        enable flag; Stats.collect honors the same switch) — dashboards
        then show the boot-time zeros."""
        if not self.stats.enable:
            return
        b = self.broker
        self.stats.setstat("connections.count", len(b.cm.channels))
        self.stats.setstat(
            "sessions.count", len(b.cm.channels) + len(b.cm.pending)
        )
        self.stats.setstat("subscriptions.count", b.subscription_count)
        self.stats.setstat("topics.count", b.route_count)
        self.stats.setstat("retained.count", b.retainer.count)

    # ------------------------------------------------------------ run-until

    async def run_forever(self) -> None:
        """Start, then block until SIGINT/SIGTERM (bin/emqx foreground)."""
        await self.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        try:
            await stop.wait()
        finally:
            await self.stop()
