"""Typed config system: schema, store, zones, env overrides."""

from .config import Config, ConfigError, SCHEMA  # noqa: F401
