"""Config schema + store.

Analog of `emqx_config.erl` + `emqx_schema.erl` + zones (SURVEY.md §5.6):

* a typed schema tree (field name -> Field(type, default, validator));
* `Config.load(dict)` checks/translates raw config against the schema;
* environment overrides: `EMQX_TPU__MQTT__MAX_PACKET_SIZE=2097152`
  (double-underscore path separator, mirroring EMQX_<PATH> env overrides);
* dotted-path get/put with change-handler callbacks
  (`emqx_config_handler` analog);
* zones: named overlays over the `mqtt` namespace applied per listener
  (`emqx_config.erl:61-66`, `emqx_zone_schema.erl`).

The same schema drives the REST API's config endpoints and their OpenAPI
description (`emqx_dashboard_swagger.erl:57-76` single-source-of-truth).
"""

from __future__ import annotations

import copy
import json
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Union


class ConfigError(Exception):
    pass


@dataclass
class Field:
    type: str  # int | float | bool | str | enum | map | list | duration | bytesize
    default: Any = None
    enum: Optional[List[str]] = None
    min: Optional[float] = None
    max: Optional[float] = None
    desc: str = ""

    def check(self, path: str, value: Any) -> Any:
        t = self.type
        try:
            if t == "int":
                if isinstance(value, str):
                    value = int(value)
                if isinstance(value, bool) or not isinstance(value, int):
                    raise ConfigError(f"{path}: expected int, got {value!r}")
            elif t == "int_or_auto":
                # sized-at-boot fields (wire.workers): "auto" resolves
                # against the host at startup, any int pins it
                if isinstance(value, str):
                    if value.lower() == "auto":
                        return "auto"
                    value = int(value)
                if isinstance(value, bool) or not isinstance(value, int):
                    raise ConfigError(
                        f"{path}: expected int or \"auto\", got {value!r}"
                    )
            elif t == "float":
                value = float(value)
            elif t == "bool":
                if isinstance(value, str):
                    value = value.lower() in ("true", "1", "on", "yes")
                value = bool(value)
            elif t == "str":
                value = str(value)
            elif t == "enum":
                value = str(value)
                if self.enum and value not in self.enum:
                    raise ConfigError(f"{path}: {value!r} not in {self.enum}")
            elif t == "duration":  # "30s" / "5m" / "1h" -> seconds
                value = parse_duration(value)
            elif t == "bytesize":  # "1MB" -> bytes
                value = parse_bytesize(value)
            elif t == "map":
                if isinstance(value, str):
                    value = json.loads(value)
                if not isinstance(value, dict):
                    raise ConfigError(f"{path}: expected map")
            elif t == "list":
                if isinstance(value, str):
                    value = json.loads(value)
                if not isinstance(value, list):
                    raise ConfigError(f"{path}: expected list")
        except (ValueError, json.JSONDecodeError) as e:
            raise ConfigError(f"{path}: {e}")
        if self.min is not None and value < self.min:
            raise ConfigError(f"{path}: {value} < min {self.min}")
        if self.max is not None and value > self.max:
            raise ConfigError(f"{path}: {value} > max {self.max}")
        return value

    def to_openapi(self) -> Dict[str, Any]:
        """OpenAPI schema object for this field — generated from the SAME
        definition that validates config, so the REST doc and the
        validator cannot disagree (`emqx_dashboard_swagger.erl:57-76`
        single-source-of-truth)."""
        kinds = {
            "int": {"type": "integer"},
            "int_or_auto": {
                "oneOf": [{"type": "integer"},
                          {"type": "string", "enum": ["auto"]}],
                "x-format": "integer or \"auto\" (sized at boot)",
            },
            "float": {"type": "number"},
            "bool": {"type": "boolean"},
            "str": {"type": "string"},
            "enum": {"type": "string"},
            "map": {"type": "object"},
            "list": {"type": "array", "items": {}},
            "duration": {
                "oneOf": [{"type": "string"}, {"type": "number"}],
                "x-format": "duration (\"30s\", \"5m\", \"1h\" or seconds)",
            },
            "bytesize": {
                "oneOf": [{"type": "string"}, {"type": "integer"}],
                "x-format": "bytesize (\"1MB\", \"512KB\" or bytes)",
            },
        }
        out: Dict[str, Any] = dict(kinds[self.type])
        if self.enum:
            out["enum"] = list(self.enum)
        if self.min is not None:
            out["minimum"] = self.min
        if self.max is not None:
            out["maximum"] = self.max
        if self.default is not None:
            out["default"] = self.default
        if self.desc:
            out["description"] = self.desc
        return out


@dataclass
class Struct:
    """A nested object schema (listener blocks, cluster section, ...).

    ``open=True`` permits unknown keys (driver/TLS passthrough blocks),
    mirroring how the reference keeps connector-specific config outside
    the core schema."""

    fields: Dict[str, Any]  # name -> Field | Struct | ListOf
    desc: str = ""
    open: bool = False

    def check(self, path: str, value: Any) -> Any:
        if not isinstance(value, dict):
            raise ConfigError(f"{path}: expected object")
        if not self.open:
            unknown = set(value) - set(self.fields)
            if unknown:
                raise ConfigError(f"{path}: unknown keys {sorted(unknown)}")
        for name, f in self.fields.items():
            if name in value:
                value[name] = f.check(f"{path}.{name}", value[name])
        return value

    def to_openapi(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "type": "object",
            "properties": {
                n: f.to_openapi() for n, f in self.fields.items()
            },
            # closed structs reject unknown keys at load — the doc must
            # say so or doc and validator disagree
            "additionalProperties": self.open,
        }
        if self.desc:
            out["description"] = self.desc
        return out


@dataclass
class ListOf:
    """A list-of-objects schema (listeners, authentication chain, ...)."""

    item: Any  # Field | Struct
    desc: str = ""

    def check(self, path: str, value: Any) -> Any:
        if not isinstance(value, list):
            raise ConfigError(f"{path}: expected list")
        return [
            self.item.check(f"{path}[{i}]", v) for i, v in enumerate(value)
        ]

    def to_openapi(self) -> Dict[str, Any]:
        out = {"type": "array", "items": self.item.to_openapi()}
        if self.desc:
            out["description"] = self.desc
        return out


def parse_duration(v: Union[str, int, float]) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    units = {"ms": 0.001, "s": 1, "m": 60, "h": 3600, "d": 86400}
    for suffix in sorted(units, key=len, reverse=True):
        if v.endswith(suffix):
            return float(v[: -len(suffix)]) * units[suffix]
    return float(v)


def parse_bytesize(v: Union[str, int]) -> int:
    if isinstance(v, int):
        return v
    units = {"KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30, "B": 1}
    up = v.upper()
    for suffix in ("KB", "MB", "GB", "B"):
        if up.endswith(suffix):
            return int(float(up[: -len(suffix)]) * units[suffix])
    return int(v)


# ------------------------------------------------------------------ schema

SCHEMA: Dict[str, Dict[str, Field]] = {
    "mqtt": {
        "max_packet_size": Field("bytesize", 1 << 20, desc="max MQTT packet size"),
        "max_clientid_len": Field("int", 65535, min=23),
        "max_topic_levels": Field("int", 128, min=1),
        "max_qos_allowed": Field("int", 2, min=0, max=2),
        "max_topic_alias": Field("int", 65535, min=0),
        "retain_available": Field("bool", True),
        "wildcard_subscription": Field("bool", True),
        "shared_subscription": Field("bool", True),
        "max_inflight": Field("int", 32, min=1),
        "max_mqueue_len": Field("int", 1000, min=0),
        "mqueue_store_qos0": Field("bool", True),
        "upgrade_qos": Field("bool", False),
        "retry_interval": Field("duration", 30.0),
        "max_awaiting_rel": Field("int", 100, min=0),
        "await_rel_timeout": Field("duration", 300.0),
        "session_expiry_interval": Field("duration", 7200.0),
        "keepalive_multiplier": Field(
            "float", 1.5, min=1.0,
            desc="silence window = keepalive * multiplier (the deprecated emqx keepalive_backoff=0.75 meant the SAME 1.5x window via 2*backoff)"),
        "server_keepalive": Field("int", 0, min=0, desc="0 = client value"),
        "idle_timeout": Field("duration", 15.0),
    },
    "broker": {
        "engine": Field(
            "enum",
            "single",
            enum=["single", "sharded", "shm"],
            desc="match engine: single-chip (with hybrid host/device "
                 "arbitration, see broker.hybrid) or mesh-sharded — the "
                 "multi-chip deployment for real ICI meshes, where the "
                 "device path wins and host arbitration does not apply",
        ),
        "shared_subscription_strategy": Field(
            "enum",
            "random",
            enum=["random", "round_robin", "sticky", "hash_clientid",
                  "hash_topic", "local"],
        ),
        "shared_subscription_group_strategies": Field(
            "map", {}, desc="per-group strategy overrides (group -> strategy)"
        ),
        "batch_max": Field("int", 4096, min=1, desc="publish batch tick size"),
        "batch_delay": Field("duration", 0.002),
        "delivery_workers": Field(
            "int", 4, min=0, max=64,
            desc="sharded asyncio delivery-worker pool: broadcast "
                 "fan-out is partitioned by connection shard "
                 "(subscriber-uid % workers) and drained concurrently "
                 "so one stalled socket cannot head-of-line-block a "
                 "broadcast (esockd conn-sup analog); 0 = deliver "
                 "inline on the dispatch path"),
        "delivery_queue_max": Field(
            "int", 4096, min=1,
            desc="per-shard delivery queue depth; past it the dispatch "
                 "path delivers the batch inline (counted "
                 "deliver.shard.backpressure) instead of growing the "
                 "queue without bound"),
        "delivery_backpressure_bytes": Field(
            "bytesize", 1 << 20,
            desc="slow-consumer watermark: a connection whose unflushed "
                 "transport backlog exceeds this is counted + traced "
                 "(deliver.backpressure) and skipped past, never "
                 "awaited — force_shutdown reaps the extreme cases"),
        "hybrid": Field(
            "bool", True,
            desc="hybrid host/device match arbitration: serve matches from "
                 "the native host probe whenever the measured device "
                 "round-trip is slower (degraded link), keeping the HBM "
                 "mirror warm; false = always device",
        ),
        "sys_msg_interval": Field("duration", 60.0),
        "sys_heartbeat_interval": Field("duration", 30.0),
    },
    "engine": {
        "max_levels": Field("int", 16, min=4, max=32, desc="device trie level cap"),
        "min_batch": Field("int", 64, min=1),
        "n_sub_shards": Field("int", 1024, min=8),
        "flight_ring": Field(
            "int", 4096, min=0,
            desc="flight-recorder ring size in ticks (one ~60 B struct "
                 "per match tick: path, arbitration reason, EWMA rates, "
                 "wire bytes, verify mismatches, churn lag, pipeline "
                 "occupancy); 0 disables the ring (latency histograms "
                 "stay on)"),
        "pipeline_depth": Field(
            "int", 4, min=1, max=64,
            desc="match-dispatch pipeline window: submitted-but-"
                 "uncollected ticks allowed in flight, so host prep of "
                 "tick N+1 overlaps device compute of tick N and the "
                 "async fetch of tick N-1 (churn-fused ticks drain the "
                 "window and donate the table buffers); 1 = lock-step"),
        # table checkpoint & warm restart (checkpoint/ subsystem)
        "ckpt.enable": Field(
            "bool", False,
            desc="periodic binary snapshots of the match-table state + a "
                 "churn write-ahead log; boot restores the newest valid "
                 "snapshot and replays the WAL tail instead of replaying "
                 "every filter through add_filters"),
        "ckpt.dir": Field(
            "str", "",
            desc="checkpoint directory (snap/ + wal/); empty = "
                 "<node.data_dir>/ckpt"),
        "ckpt.interval": Field(
            "duration", 60.0,
            desc="snapshot cadence; a snapshot also fires early when the "
                 "WAL backlog crosses ckpt.wal_max_bytes"),
        "ckpt.wal_max_bytes": Field(
            "bytesize", 64 << 20,
            desc="WAL-backlog threshold that forces a snapshot between "
                 "intervals"),
        "ckpt.keep": Field(
            "int", 3, min=1,
            desc="snapshots retained; restore falls back to an older one "
                 "when the newest fails its CRC frame"),
        "ckpt.wal_seg_bytes": Field(
            "bytesize", 4 << 20, desc="WAL segment rotation size"),
    },
    "ds": {
        # durable message log (emqx_tpu/ds/ — emqx_durable_storage
        # analog): parked persistent sessions replay QoS>=1 offline
        # traffic from a shared, sharded append-only log instead of
        # per-session mqueue snapshots
        "enable": Field(
            "bool", False,
            desc="append QoS>=1 publishes that match a parked "
                 "persistent-session subscription to a sharded durable "
                 "log; parked sessions persist only (subscriptions, "
                 "inflight, dedup, cursor) and rebuild their mqueue by "
                 "replaying the log on resume"),
        "dir": Field(
            "str", "",
            desc="log directory (shard-<k>/ segment chains); empty = "
                 "<node.data_dir>/ds"),
        "shards": Field(
            "int", 4, min=1, max=1024,
            desc="stream shards; shard = matchhash(topic) % shards"),
        "seg_bytes": Field(
            "bytesize", 4 << 20,
            desc="segment roll size; retention GC drops whole sealed "
                 "segments"),
        "flush_interval": Field(
            "duration", 1.0,
            desc="write-behind fsync cadence (node ticker)"),
        "flush_bytes": Field(
            "bytesize", 256 << 10,
            desc="per-shard buffered-bytes watermark that forces an "
                 "inline fsync — the documented crash-loss window, in "
                 "bytes"),
        "gc_interval": Field(
            "duration", 30.0,
            desc="retention GC cadence (node ticker)"),
        "retention_bytes": Field(
            "bytesize", 256 << 20,
            desc="per-shard on-disk cap; sealed generations behind the "
                 "session min-cursor drop first, then oldest-first "
                 "(forced; replay reports the gap)"),
        "retention": Field(
            "duration", 604800.0,  # 7 days
            desc="hard message age bound (duration, bare numbers are "
                 "seconds), even ahead of a lagging cursor"),
        # leader->follower append replication (ds/repl.py)
        "repl.enable": Field(
            "bool", False,
            desc="replicate each shard's flushed append ranges to an "
                 "elected follower peer over the cluster PeerLinks; "
                 "cross-node takeover then resumes from the follower's "
                 "mirror (cursor handoff) instead of materializing the "
                 "queue, and node loss preserves everything at/below "
                 "the replicated watermark"),
        "repl.ack_timeout": Field(
            "duration", 2.0,
            desc="follower-ack wait per shipped range; a timeout "
                 "degrades that shard to leader-only appends "
                 "(ds_repl_degraded alarm) without ever blocking the "
                 "flush path"),
        "repl.retry_interval": Field(
            "duration", 1.0,
            desc="degraded-shard heal probe cadence; catch-up re-ships "
                 "from the replicated watermark once the follower link "
                 "returns"),
        "repl.queue_max": Field(
            "int", 256, min=1,
            desc="flushed-but-unshipped ranges buffered per shard; "
                 "overflow drops the RAM backlog (records stay durable "
                 "locally) and falls back to a heal-time catch-up read"),
        "repl.catchup_batch": Field(
            "int", 512, min=1,
            desc="records per catch-up read+ship batch after a heal"),
    },
    "retainer": {
        "enable": Field("bool", True),
        "max_retained_messages": Field("int", 0, min=0),
        "max_payload_size": Field("bytesize", 1 << 20),
        "backend": Field("enum", "ram", enum=["ram", "disc"],
                         desc="disc = retained messages survive restart"),
        "device_index": Field(
            "bool", False,
            desc="index retained topic names in HBM: subscribe-time "
                 "wildcard fan-in becomes one device dispatch (host trie "
                 "remains canonical truth + verify oracle)"),
        "probe_interval": Field(
            "duration", 10.0,
            desc="while one retained path (trie/device index) serves, "
                 "re-measure the other at most this often; index probes "
                 "double as device-mirror warm-keeping"),
        "index_fanin_max": Field(
            "int", 4096, min=1,
            desc="retained filters matching more stored names than this "
                 "are trie-served (output-proportional enumeration)"),
        "index_max_shapes": Field(
            "int", 64, min=1,
            desc="wildcard shape registry cap of the retained device "
                 "index; shapes past the cap are trie-served"),
        "flow_control_batch": Field(
            "int", 1000, min=1,
            desc="retained re-delivery batch size on subscribe"),
        "flow_control_interval": Field(
            "duration", 0.05,
            desc="pause between retained re-delivery batches"),
    },
    "delayed": {
        "enable": Field("bool", True),
        "max_delayed_messages": Field("int", 0, min=0,
                                      desc="0 = unlimited"),
        "persist": Field("bool", False,
                         desc="survive restarts (disc mnesia analog); "
                              "opt-in like retainer.backend=disc"),
    },
    "authn": {"enable": Field("bool", False), "allow_anonymous": Field("bool", True)},
    "authz": {
        "enable": Field("bool", False),
        "no_match": Field("enum", "allow", enum=["allow", "deny"]),
        "deny_action": Field("enum", "ignore", enum=["ignore", "disconnect"]),
        "cache_enable": Field("bool", True),
        "cache_max_size": Field("int", 32, min=1),
        "cache_ttl": Field("duration", 60.0),
    },
    "fault": {
        # seeded fault-injection plane (emqx_tpu/fault/) — chaos testing
        # only; zero overhead and zero behavior change while disabled
        "enable": Field("bool", False,
                        desc="arm the fault-injection plane from "
                             "fault.spec at boot"),
        "seed": Field("int", 0,
                      desc="global fault seed; each site derives its own "
                           "deterministic PRNG from (seed, site)"),
        "spec": Field(
            "map", {},
            desc="site -> action spec, e.g. {\"transport.send\": "
                 "{\"action\": \"drop\", \"p\": 0.3}}; sites must be "
                 "registered in emqx_tpu/fault/sites.py (actions: "
                 "delay|drop|error|corrupt; fields: p, delay, times, "
                 "after)"),
    },
    "observe": {
        # message-lifecycle span plane + contention telemetry
        # (observe/spans.py, observe/contention.py)
        "span_sample": Field(
            "int", 64, min=0,
            desc="head-sampling rate for message-lifecycle spans: 1/N "
                 "publishes carry a span context stamped at every plane "
                 "boundary (hooks/submit/collect/enqueue/wire + the "
                 "cross-node forward and durable-log ds legs), deltas "
                 "into mergeable log2 histograms with bucket-derived "
                 "p50/p99/p999; 0 disarms the plane (every boundary "
                 "back to one bool test, fault-plane discipline)"),
        "span_keep": Field(
            "int", 64, min=1,
            desc="slowest-K completed span records kept (full per-stage "
                 "waterfall) for tools/span_dump.py"),
        "loop_probe_interval": Field(
            "duration", 1.0,
            desc="event-loop lag probe cadence: scheduled-vs-actual "
                 "wakeup delta into an EWMA gauge + histogram "
                 "(contention telemetry; GC pauses and queue-depth "
                 "gauges ride the same monitor)"),
    },
    "prometheus": {
        "enable": Field("bool", False),
        "push_gateway_server": Field("str", ""),
        "interval": Field("duration", 15.0),
    },
    "statsd": {
        "enable": Field("bool", False),
        "server": Field("str", "127.0.0.1:8125"),
        "flush_time_interval": Field("duration", 10.0),
    },
    "log": {
        "level": Field("enum", "INFO",
                       enum=["DEBUG", "INFO", "WARNING", "ERROR",
                             "CRITICAL"]),
        "format": Field("enum", "text", enum=["text", "json"],
                        desc="emqx_logger_jsonfmt analog when json"),
    },
    "event_message": {
        "client_connected": Field("bool", False),
        "client_disconnected": Field("bool", False),
        "client_subscribed": Field("bool", False),
        "client_unsubscribed": Field("bool", False),
        "message_delivered": Field("bool", False),
        "message_acked": Field("bool", False),
        "message_dropped": Field("bool", False),
    },
    "flapping_detect": {
        "enable": Field("bool", False),
        "max_count": Field("int", 15),
        "window_time": Field("duration", 60.0),
        "ban_time": Field("duration", 300.0),
    },
    "force_shutdown": {
        "enable": Field("bool", True),
        "max_message_queue_len": Field(
            "int", 10000,
            desc="slow-consumer kill threshold, KiB of unflushed outbound (the reference counts mailbox messages)"),
    },
    "stats": {"enable": Field("bool", True)},
    "node": {
        "name": Field("str", "emqx_tpu@127.0.0.1"),
        "data_dir": Field("str", "data"),
        "cookie": Field("str", "emqxsecretcookie", desc="cluster shared secret"),
        "xla_cache_dir": Field(
            "str", "",
            desc="persistent XLA compile cache; empty = <data_dir>/"
                 "xla_cache.  Point co-hosted nodes at ONE dir so only "
                 "the first pays engine warm-up compilation",
        ),
    },
    "persistent_session_store": {
        "enable": Field("bool", False),
        "on_disc": Field("bool", False),
    },
    "limiter": {
        "connection_rate": Field("float", 0.0, desc="0 = unlimited"),
        "message_in_rate": Field("float", 0.0),
        "bytes_in_rate": Field("float", 0.0),
    },
    "wire": {
        # process-sharded wire plane (emqx_tpu/wire/): a parent
        # supervisor forks N wire-worker processes that each bind the
        # configured MQTT listeners via SO_REUSEPORT and run the full
        # connection/channel/session/delivery stack, clustered to the
        # parent (and each other) as zero-latency peers over UNIX-domain
        # PeerLinks — the esockd acceptor-pool model lifted to whole
        # processes so the broker scales past one event loop + one GIL
        "workers": Field(
            "int_or_auto", 0, min=0, max=64,
            desc="wire-worker process count; 0 = serve listeners "
                 "in-process (single event loop).  The reference sizes "
                 "acceptor pools at schedulers x 8; here one worker per "
                 "core is the analog — each worker is a full "
                 "connection/delivery plane, not just an acceptor. "
                 "\"auto\" sizes from os.cpu_count() minus the hub "
                 "core, clamped by wire.max_workers"),
        "max_workers": Field(
            "int", 8, min=1, max=64,
            desc="upper clamp for workers: \"auto\" (a 128-core host "
                 "should not fork 127 full broker planes by default)"),
        "backoff_reset": Field(
            "duration", 60.0,
            desc="a worker alive this long counts as healthy: the NEXT "
                 "respawn returns to the base restart_backoff instead "
                 "of the doubled crash-streak delay (a flaky-then-"
                 "stable worker must not pay minutes-long respawns "
                 "hours later)"),
        "reuseport": Field(
            "bool", True,
            desc="bind each worker's listeners with SO_REUSEPORT (the "
                 "kernel load-balances accepts across workers); false "
                 "= the parent binds each listener once and workers "
                 "inherit the listening FD (pre-fork accept sharing, "
                 "the fallback where SO_REUSEPORT is unavailable)"),
        "ipc_dir": Field(
            "str", "",
            desc="UNIX-socket + per-worker state directory; empty = "
                 "<node.data_dir>/wire (hub.sock, w<i>.sock, w<i>/ "
                 "data dirs).  Paths must stay under the ~100-byte "
                 "sun_path limit"),
        "max_conn_rate": Field(
            "float", 0.0,
            desc="per-worker accept-rate token bucket (accepts/sec, "
                 "burst 2x); past it new sockets are closed before any "
                 "protocol work and counted in olp.new_conn."
                 "rate_limited — a reconnect storm sheds instead of "
                 "stalling the loop.  0 = unlimited"),
        "restart_backoff": Field(
            "duration", 0.5,
            desc="base delay before restarting a dead wire worker; "
                 "doubles per consecutive crash up to 8x (parked "
                 "sessions and the parent's forward spool cover the "
                 "gap)"),
        "stats_interval": Field(
            "duration", 2.0,
            desc="per-worker stats poll cadence (wire_stats RPC over "
                 "the IPC link) feeding the wire.worker.<i>.* gauges "
                 "exported via $SYS/metrics, /monitor and Prometheus"),
    },
    "shm": {
        # shared-memory match plane (emqx_tpu/shm/): wire workers stop
        # owning engines and submit pre-packed publish ticks to the
        # hub's single device engine over per-worker SPSC rings in
        # multiprocessing.shared_memory — table bytes are O(1) across
        # the pool and ticks from different workers fuse into one
        # device dispatch
        "enable": Field(
            "bool", True,
            desc="share the hub's match engine with the wire-worker "
                 "pool over shared-memory rings; false = every worker "
                 "boots its own engine (the PR 13 per-process layout)"),
        "slots": Field(
            "int", 64, min=4, max=4096,
            desc="ring depth per direction per worker; a full submit "
                 "ring degrades the tick to the worker's local trie, "
                 "it never blocks the wire loop"),
        "slot_bytes": Field(
            "bytesize", 65536, min=4096,
            desc="slot stride (64-byte multiple): header + the packed "
                 "[B, 2L+2] u32 tick payload; batches too big for a "
                 "slot serve locally and count in shm.oversize"),
        "timeout": Field(
            "duration", 0.05,
            desc="worker-side wait for a hub match result before the "
                 "tick degrades to the local host trie; also the hub "
                 "heartbeat staleness threshold (floored at 250ms) "
                 "past which workers stop submitting entirely"),
        "poll_interval": Field(
            "duration", 0.002,
            desc="POLL-MODE fallback knob (shm.drain: poll): hub drain "
                 "cadence when every worker ring is idle; the "
                 "doorbell modes block on lane eventfds instead and "
                 "never consult this (under load every mode re-drains "
                 "immediately)"),
        "drain": Field(
            "enum", "auto", enum=["auto", "native", "thread", "poll"],
            desc="hub drain engine: doorbell-driven — workers ring a "
                 "per-lane eventfd on slot commit and the hub blocks "
                 "in a dedicated drain thread via native poll(2) over "
                 "all lane fds ('native', GIL released) or "
                 "select.poll ('thread'); 'auto' = native when the "
                 "lib is built else thread; 'poll' = the legacy "
                 "fixed-cadence asyncio loop (shm.poll_interval)"),
        "fuse_window_us": Field(
            "int", 0, min=0, max=10000,
            desc="adaptive cross-lane fusion window (µs): with >= 2 "
                 "lanes hot the hub holds a dispatch this long so "
                 "ticks from different workers coalesce into one "
                 "device call; auto-collapses to 0 when a single "
                 "lane is active, so a lone worker's p50 never pays "
                 "it; 0 = never wait"),
        "lane_credit": Field(
            "int", 64, min=0, max=4096,
            desc="max records drained per lane per pass (round-robin "
                 "carryover): a flooding worker keeps its surplus in "
                 "its own ring while siblings drain first; "
                 "exhaustions count in shm.hub.credit_exhausted and "
                 "trace as shm.credit; 0 = unlimited"),
        "pin_cores": Field(
            "str", "",
            desc="optional core list/ranges ('0-3', '0,2'): first "
                 "core pins the hub's drain thread, the rest are "
                 "assigned round-robin to worker lanes "
                 "(sched_setaffinity, advisory); empty = no pinning"),
        "region": Field(
            "str", "",
            desc="worker-side only (injected into derived configs): "
                 "the shm/registry.py region name of this worker's "
                 "slab; empty = the plane is off in this process"),
        "doorbell_fd": Field(
            "int", -1, min=-1,
            desc="worker-side only (injected into derived configs): "
                 "inherited eventfd number of this lane's doorbell "
                 "(crosses exec via pass_fds); -1 = no doorbell "
                 "(hub in poll mode)"),
        "pin_core": Field(
            "int", -1, min=-1,
            desc="worker-side only (injected into derived configs): "
                 "the core this lane pins to, derived from "
                 "shm.pin_cores; -1 = unpinned"),
    },
    "semantic": {
        # semantic subscription plane (emqx_tpu/semantic/): $semantic/<query>
        # subscriptions match publishes on payload meaning — a deterministic
        # feature-hash embedding + device top-k cosine over the hub-resident
        # query table — instead of topic-name structure
        "enable": Field(
            "bool", False,
            desc="accept $semantic/<query> subscription filters; off = "
                 "the classifier rejects them and no embedding/query "
                 "table is ever allocated"),
        "dim": Field(
            "int", 256, min=16, max=4096,
            desc="embedding dimensionality of the feature-hash space; "
                 "both sides of every cosine (query vector and publish "
                 "vector) live in this many float32 lanes"),
        "max_queries": Field(
            "int", 4096, min=16,
            desc="device query-table capacity (rows of [dim] f32 in "
                 "HBM); adds past the cap are rejected and count in "
                 "semantic.dropped"),
        "topk": Field(
            "int", 8, min=1, max=256,
            desc="matches returned per publish: the top-k queries by "
                 "cosine above the similarity threshold"),
        "probe_interval": Field(
            "duration", 10.0,
            desc="while one semantic path (device top-k / exact host) "
                 "serves, re-measure the other at most this often — "
                 "the same EWMA arbiter contract as "
                 "retainer.probe_interval"),
    },
    "dashboard": {
        "listen_port": Field("int", 18083),
        "default_username": Field("str", "admin"),
        "default_password": Field("str", "public"),
        "token_expired_time": Field("duration", 3600.0),
    },
}

# Structured sections: schema-validated at load, documented in OpenAPI
# from the same definitions (the `emqx_schema.erl` listener/cluster/authn
# blocks).  `open` structs pass through backend-specific keys (driver
# connection config, TLS blocks) the way the reference nests connector
# schemas.
_LISTENER = Struct({
    "type": Field("enum", "tcp", enum=["tcp", "ssl", "ws", "wss", "quic"]),
    "host": Field("str", "0.0.0.0"),
    "port": Field("int", 1883, min=0, max=65535),
    "zone": Field("str", desc="mqtt config overlay zone"),
    "mountpoint": Field("str", desc="topic prefix for this listener"),
    "max_connections": Field("int", 0, min=0, desc="0 = unlimited"),
    "path": Field("str", "/mqtt", desc="ws/wss HTTP path"),
    "ssl": Struct({}, open=True, desc="TLS block (certfile/keyfile/...)"),
}, open=True)

STRUCTURED: Dict[str, Any] = {
    "listeners": ListOf(_LISTENER, desc="MQTT listeners"),
    "cluster": Struct({
        "enable": Field("bool", False),
        "host": Field("str", "127.0.0.1"),
        "port": Field("int", 0, min=0, max=65535),
        "advertise_host": Field("str"),
        "role": Field("enum", "core", enum=["core", "replicant"]),
        "rpc_mode": Field("enum", "async", enum=["sync", "async"]),
        "peers": Field("map", desc="name -> [host, port] or "
                                   "[\"unix\", path]"),
        "unix_path": Field(
            "str", desc="also serve peer links on this UNIX-domain "
                        "socket (wire-plane IPC / same-host peers)"),
        "reconnect_ivl": Field(
            "duration", 0.5, desc="peer-link reconnect backoff base"),
        "reconnect_max": Field(
            "duration", 15.0,
            desc="peer-link reconnect backoff ceiling (wire-plane hubs "
                 "default to 2s: a worker respawns in seconds, not on "
                 "the cross-host partition timescale)"),
        "route_hold": Field(
            "duration", 5.0,
            desc="keep a down peer's routes this long before purging; "
                 "QoS>=1 forwards spool + replay across flaps shorter "
                 "than this instead of un-matching"),
        "spool_max_bytes": Field(
            "bytesize", 8 << 20,
            desc="per-peer forward-spool bound (drop-oldest overflow, "
                 "counted + alarmed)"),
        "discovery": Struct({
            "strategy": Field("enum", "static",
                              enum=["static", "dns", "etcd"]),
            "interval": Field("duration", 5.0),
        }, open=True),
    }, open=True, desc="cluster membership (mria/ekka analog)"),
    "authentication": ListOf(Struct({
        "mechanism": Field("enum", "password_based",
                           enum=["password_based", "scram", "jwt"]),
        "backend": Field("str", "built_in_database",
                         desc="built_in_database|jwt|scram|redis|mysql|..."),
        "query": Field("str", desc="credential lookup template (${var})"),
        "password_hash_algorithm": Field(
            "enum", "pbkdf2_sha256",
            enum=["pbkdf2_sha256", "sha256", "sha512", "bcrypt", "plain"]),
        "iterations": Field("int", 10_000, min=1),
        "user_id_type": Field("enum", "username",
                              enum=["username", "clientid"]),
        "users": Field("list", desc="seed users for built_in_database"),
        "secret": Field("str", desc="jwt hmac secret"),
    }, open=True), desc="authenticator chain (emqx_authn analog)"),
    "authorization": ListOf(Struct({
        "type": Field("str", "built_in_database",
                      desc="file|built_in_database|client_acl|redis|..."),
        "query": Field("str", desc="ACL lookup template (${var})"),
        "rules": Field("list", desc="file source rules"),
    }, open=True), desc="authz source chain (emqx_authz analog)"),
    "gateways": ListOf(Struct({
        "type": Field("enum", "mqttsn",
                      enum=["mqttsn", "stomp", "coap", "lwm2m", "exproto"]),
        "name": Field("str"),
        "host": Field("str", "127.0.0.1"),
        "port": Field("int", 0, min=0, max=65535),
    }, open=True), desc="protocol gateways (emqx_gateway analog)"),
    "bridges": ListOf(Struct({
        "name": Field("str"),
        "type": Field("enum", "http", enum=["http", "mqtt"],
                      desc="the reference ships http + mqtt bridges"),
        "direction": Field("enum", "egress", enum=["egress", "ingress"]),
        "enable": Field("bool", True),
        "local_topic": Field("str", "#"),
        "remote_topic": Field("str", desc="egress target / ingress source"),
        "payload": Field("str", desc="egress payload template"),
        "path": Field("str", "/", desc="http webhook path"),
        "qos": Field("int", 0, min=0, max=2),
        "durable": Field("bool", False,
                         desc="buffer through the disk replay queue"),
        "max_queue_bytes": Field("int", 0, min=0, desc="0 = unbounded"),
        "max_buffer": Field("int", 10_000, min=1),
        "retry_interval": Field("duration", 1.0),
        "health_check_interval": Field("duration", 15.0),
        "connector": Struct({}, open=True,
                            desc="connector config (base_url / host / ...)"),
    }), desc="data bridges (emqx_bridge analog)"),
    "exhook": ListOf(Struct({
        "name": Field("str", "default"),
        "host": Field("str", "127.0.0.1"),
        "port": Field("int", 9000, min=0, max=65535),
        "driver": Field("enum", "grpc", enum=["grpc", "json"]),
        "pool_size": Field("int", 4, min=1),
        "request_timeout": Field("duration", 5.0),
        "failed_action": Field("enum", "deny", enum=["deny", "ignore"]),
        "enable": Field("bool", True),
    }), desc="out-of-process hook providers (emqx_exhook analog)"),
    "rules": ListOf(Struct({
        "id": Field("str"),
        "sql": Field("str"),
        "description": Field("str", ""),
        "outputs": Field("list"),
    }, open=True), desc="rule engine rules"),
    "rewrite": ListOf(Struct({
        "action": Field("enum", "all", enum=["all", "publish", "subscribe"]),
        "source_topic": Field("str"),
        "re": Field("str"),
        "dest_topic": Field("str"),
    }), desc="topic rewrite rules (emqx_rewrite analog)"),
    "auto_subscribe": ListOf(Struct({
        "topic": Field("str"),
        "qos": Field("int", 0, min=0, max=2),
    }), desc="server-side subscriptions on connect"),
}

ENV_PREFIX = "EMQX_TPU__"


class Config:
    """Checked config store with zones + change handlers."""

    def __init__(self, raw: Optional[Dict[str, Any]] = None, env: bool = True):
        self._conf: Dict[str, Dict[str, Any]] = {}
        self._structured: Dict[str, Any] = {}
        self._zones: Dict[str, Dict[str, Dict[str, Any]]] = {}
        self._handlers: Dict[str, List[Callable]] = {}
        self.load(raw or {}, env=env)

    # ------------------------------------------------------------- load

    def load(self, raw: Dict[str, Any], env: bool = True) -> None:
        """Validate-everything-then-commit: a failing load leaves the
        previous config fully intact, and never mutates `raw`."""
        conf: Dict[str, Dict[str, Any]] = {}
        for ns, fields in SCHEMA.items():
            conf[ns] = {}
            raw_ns = raw.get(ns, {})
            unknown = set(raw_ns) - set(fields)
            if unknown:
                raise ConfigError(f"unknown config keys in {ns}: {sorted(unknown)}")
            for name, f in fields.items():
                if name in raw_ns:
                    conf[ns][name] = f.check(f"{ns}.{name}", raw_ns[name])
                else:
                    conf[ns][name] = copy.deepcopy(f.default)
        # structured sections (listeners/cluster/authn/...): validated +
        # coerced copies against the same schema that documents them
        structured: Dict[str, Any] = {}
        for name, schema in STRUCTURED.items():
            if name in raw and raw[name] is not None:
                structured[name] = schema.check(
                    name, copy.deepcopy(raw[name])
                )
        zones: Dict[str, Dict[str, Dict[str, Any]]] = {}
        for zname, overrides in (raw.get("zones") or {}).items():
            zones[zname] = self._check_zone(zname, overrides)
        self._conf = conf
        self._structured = structured
        self._zones = zones
        if env:
            self._apply_env()

    def _check_zone(
        self, zname: str, overrides: Dict[str, Any]
    ) -> Dict[str, Dict[str, Any]]:
        zconf: Dict[str, Dict[str, Any]] = {}
        for ns, kv in overrides.items():
            if ns not in SCHEMA:
                raise ConfigError(f"zone {zname}: unknown namespace {ns}")
            zconf[ns] = {}
            for name, value in kv.items():
                if name not in SCHEMA[ns]:
                    raise ConfigError(f"zone {zname}: unknown key {ns}.{name}")
                zconf[ns][name] = SCHEMA[ns][name].check(f"{zname}.{ns}.{name}", value)
        return zconf

    def _apply_env(self) -> None:
        for key, val in os.environ.items():
            if not key.startswith(ENV_PREFIX):
                continue
            path = key[len(ENV_PREFIX):].lower().split("__")
            if len(path) != 2:
                continue
            ns, name = path
            if ns in SCHEMA and name in SCHEMA[ns]:
                self._conf[ns][name] = SCHEMA[ns][name].check(f"{ns}.{name}", val)

    # -------------------------------------------------------------- get

    def get(self, path: str, zone: Optional[str] = None, default: Any = None) -> Any:
        ns, _, name = path.partition(".")
        if not name:
            if ns in STRUCTURED:  # listeners/cluster/authentication/...
                return self._structured.get(ns, default)
            out = dict(self._conf.get(ns, {}))
            if zone and zone in self._zones:
                out.update(self._zones[zone].get(ns, {}))
            return out
        if zone and zone in self._zones:
            zv = self._zones[zone].get(ns, {})
            if name in zv:
                return zv[name]
        return self._conf.get(ns, {}).get(name, default)

    def put(self, path: str, value: Any) -> Any:
        ns, _, name = path.partition(".")
        if ns not in SCHEMA or name not in SCHEMA[ns]:
            raise ConfigError(f"unknown config path {path}")
        value = SCHEMA[ns][name].check(path, value)
        old = self._conf[ns].get(name)
        self._conf[ns][name] = value
        for prefix in (ns, path):
            for h in self._handlers.get(prefix, []):
                h(path, old, value)
        return value

    def dump(self) -> Dict[str, Any]:
        """Everything the schema governs: typed namespaces + validated
        structured sections (matches the documented GET /configs shape)."""
        out: Dict[str, Any] = copy.deepcopy(self._conf)
        out.update(copy.deepcopy(self._structured))
        return out

    def zones(self) -> List[str]:
        return list(self._zones)

    # --------------------------------------------------- change handlers

    def on_change(self, path_prefix: str, handler: Callable) -> None:
        """handler(path, old, new) on put() under the prefix
        (`emqx_config_handler` analog)."""
        self._handlers.setdefault(path_prefix, []).append(handler)

    # -------------------------------------------------------- describe

    @staticmethod
    def openapi_schemas() -> Dict[str, Any]:
        """OpenAPI component schemas generated from the SAME definitions
        that validate config (typed namespaces + structured sections) —
        the `emqx_dashboard_swagger.erl:57-76` single source of truth:
        a key cannot be documented differently than it is validated."""
        out: Dict[str, Any] = {}
        for ns, fields in SCHEMA.items():
            out[f"config.{ns}"] = {
                "type": "object",
                "properties": {
                    name: f.to_openapi() for name, f in fields.items()
                },
            }
        for name, schema in STRUCTURED.items():
            out[f"config.{name}"] = schema.to_openapi()
        out["config"] = {
            "type": "object",
            "properties": {
                key.split(".", 1)[1]: {"$ref": f"#/components/schemas/{key}"}
                for key in out
            },
        }
        return out


def channel_config_from(conf: Config, zone: Optional[str] = None):
    """Build a ChannelConfig from the mqtt namespace (+zone overlay)."""
    from ..broker.channel import ChannelConfig

    m = conf.get("mqtt", zone=zone)
    return ChannelConfig(
        max_inflight=m["max_inflight"],
        max_mqueue=m["max_mqueue_len"],
        max_awaiting_rel=m["max_awaiting_rel"],
        await_rel_timeout=m["await_rel_timeout"],
        retry_interval=m["retry_interval"],
        upgrade_qos=m["upgrade_qos"],
        max_qos_allowed=m["max_qos_allowed"],
        retain_available=m["retain_available"],
        wildcard_sub_available=m["wildcard_subscription"],
        shared_sub_available=m["shared_subscription"],
        max_topic_levels=m["max_topic_levels"],
        max_session_expiry=int(m["session_expiry_interval"]),
        max_topic_alias=m["max_topic_alias"],
        server_keepalive=m["server_keepalive"] or None,
        max_clientid_len=m["max_clientid_len"],
        max_packet_size=m["max_packet_size"],
        mqueue_store_qos0=m["mqueue_store_qos0"],
        keepalive_multiplier=m["keepalive_multiplier"],
        idle_timeout=m["idle_timeout"],
        retained_batch=conf.get("retainer.flow_control_batch"),
        retained_interval=conf.get("retainer.flow_control_interval"),
    )
