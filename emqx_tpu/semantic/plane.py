"""Broker-facing semantic subscription plane.

`$semantic/<query>` filters NEVER touch the topic trie, churn plane,
WAL, checkpoint registry, or cluster route oplog — the subscribe path
classifies them here (the `$share/` special-case discipline) and the
plane owns its subscriber maps outright.  Queries survive restarts via
session persistence re-subscribing through this classifier, not via any
match-table snapshot.

Two backends share the subscriber bookkeeping:

* **local** — the node owns a :class:`SemanticEngine` (device table +
  arbiter).  Standalone nodes and the hub run this.
* **shm** — wire workers.  The worker ships payload ticks to the hub
  over a K_SEM ring record and NEVER boots an embedding table: it keeps
  only its OWN queries' vectors (a handful of [dim] rows) for the
  hub-death exact fallback.  Cross-worker hits come back as per-owner
  sections and ride the cluster FORWARD frames to the owning worker.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..observe import spans as _spans
from ..observe.tracepoints import tp
from .embedder import SIM_THRESHOLD, embed_text, payload_text

SEM_PREFIX = "$semantic"


class _PendingPlane:
    __slots__ = ("mode", "texts", "handle", "t0", "rows", "res")

    def __init__(self, mode: str, texts: List[str], handle, t0: float):
        self.mode = mode
        self.texts = texts
        self.handle = handle
        self.t0 = t0
        self.rows = None  # local mode: per-text matched qid lists
        self.res = None  # shm mode: hub reply records


class SemanticPlane:
    """Subscriber registry + dispatch fan-in for semantic filters."""

    def __init__(self, engine=None, shm=None, dim: int = 256,
                 topk: int = 8, threshold: float = SIM_THRESHOLD):
        if (engine is None) == (shm is None):
            raise ValueError("exactly one of engine/shm backs the plane")
        self.engine = engine  # SemanticEngine (local mode)
        self.shm = shm  # ShmMatchEngine (wire-worker mode)
        self.dim = int(engine.table.dim if engine is not None else dim)
        self.topk = int(engine.topk if engine is not None else topk)
        self.threshold = float(
            engine.threshold if engine is not None else threshold
        )
        # qid -> clientids; text -> qid; cid -> {text: qid}
        self.subs: Dict[int, Set[str]] = {}
        self._by_text: Dict[str, int] = {}
        self.by_client: Dict[str, Dict[str, int]] = {}
        # shm mode: the worker's OWN query rows (text + vector), keyed
        # by local qid — the entire worker-resident "table"
        self._own: Dict[int, Tuple[str, np.ndarray]] = {}
        self._next_lqid = 0
        self.queries_added = 0
        self.queries_removed = 0
        self.deliveries = 0
        self.degraded = 0
        self.dropped = 0

    # ------------------------------------------------------ subscription

    @property
    def n_subs(self) -> int:
        return sum(len(s) for s in self.subs.values())

    @property
    def n_queries(self) -> int:
        return len(self._by_text)

    def subscribe(self, clientid: str, query: str) -> bool:
        """Register one (client, query) pair; False on resub or a full
        query table (the subscription is refused, not silently trie'd)."""
        qid = self._by_text.get(query)
        if qid is None:
            qid = self._alloc(query)
            if qid < 0:
                self.dropped += 1
                return False
            self._by_text[query] = qid
            self.subs[qid] = set()
            self.queries_added += 1
            tp("semantic.query", op="add", qid=qid, n=len(self._by_text))
        cids = self.subs[qid]
        if clientid in cids:
            return False
        cids.add(clientid)
        self.by_client.setdefault(clientid, {})[query] = qid
        return True

    def unsubscribe(self, clientid: str, query: str) -> bool:
        qid = self.by_client.get(clientid, {}).pop(query, None)
        if qid is None:
            return False
        if not self.by_client.get(clientid):
            self.by_client.pop(clientid, None)
        cids = self.subs.get(qid)
        if cids is not None:
            cids.discard(clientid)
            if not cids:
                del self.subs[qid]
                del self._by_text[query]
                self._release(qid)
                self.queries_removed += 1
                tp("semantic.query", op="remove", qid=qid,
                   n=len(self._by_text))
        return True

    def client_down(self, clientid: str) -> int:
        """Drop every subscription a disconnecting client holds."""
        n = 0
        for query in list(self.by_client.get(clientid, {})):
            if self.unsubscribe(clientid, query):
                n += 1
        return n

    def _alloc(self, query: str) -> int:
        if self.engine is not None:
            return self.engine.add_query(query)
        lqid = self._next_lqid
        self._next_lqid += 1
        self._own[lqid] = (query, embed_text(query, self.dim))
        self.shm.semantic_add(lqid, query)
        return lqid

    def _release(self, qid: int) -> None:
        if self.engine is not None:
            self.engine.remove_query(qid)
            return
        self._own.pop(qid, None)
        self.shm.semantic_remove(qid)

    # --------------------------------------------------------- dispatch

    def active(self) -> bool:
        """Anything to match against?  Local: any live query.  Worker:
        any query ANYWHERE in the pool (the hub-maintained C_SEM count)
        — a publish here may feed a subscriber on another worker."""
        if self.engine is not None:
            return self.engine.n_queries > 0
        return bool(self._own) or self.shm.semantic_active()

    def submit(self, payloads: List[bytes]) -> Optional[_PendingPlane]:
        """Kick the match for a publish batch; None when the plane has
        nothing to do.  Pipelinable: device/hub work starts here."""
        if not payloads or not self.active():
            return None
        texts = [payload_text(p) for p in payloads]
        t0 = time.monotonic()
        if self.engine is not None:
            return _PendingPlane(
                "local", texts, self.engine.match_submit(texts), t0
            )
        h = self.shm.semantic_submit(texts)
        if h is None:  # hub down / ring full / oversize: exact fallback
            return _PendingPlane("degraded", texts, None, t0)
        return _PendingPlane("shm", texts, h, t0)

    def collect(self, pend: _PendingPlane) -> _PendingPlane:
        """Blocking half — executor-safe: resolves the device/hub match
        without touching the subscriber maps (those mutate on the loop
        thread; :meth:`finish` reads them there)."""
        if pend.mode == "local":
            pend.rows = [
                [q for q, _ in row]
                for row in self.engine.match_collect(pend.handle)
            ]
        elif pend.mode == "shm":
            pend.res = self.shm.semantic_collect(pend.handle)
        return pend

    def finish(self, pend: _PendingPlane):
        """Loop-thread half: fan matched queries out to subscriber
        pairs.

        Returns ``(local, remote)``: ``local[i]`` is the
        ``[(clientid, "$semantic/<query>")]`` list for payload i;
        ``remote`` is ``[(node, [hub_qid, ...], i)]`` forward orders for
        queries owned by other wire workers (shm mode only)."""
        local: List[List[Tuple[str, str]]] = []
        remote: List[Tuple[str, List[int], int]] = []
        if pend.mode == "local":
            for qids in pend.rows or []:
                local.append(self._fan_local(qids))
        elif pend.mode == "shm" and pend.res is not None:
            for i, rec in enumerate(pend.res):
                own = [
                    q for q in (
                        self.shm.semantic_hub2loc(h)
                        for h in rec.get("own", ())
                    ) if q is not None
                ]
                local.append(self._fan_local(own))
                for node, qids in (rec.get("rem") or {}).items():
                    remote.append((node, list(qids), i))
        else:  # degraded up front, or the hub timed out mid-flight
            local = self._serve_degraded(pend.texts)
        for row in local:
            self.deliveries += len(row)
        if _spans.enabled():
            _spans.plane().observe_stage(
                "sem", time.monotonic() - pend.t0
            )
        return local, remote

    def _fan_local(self, qids: List[int]) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        for qid in qids:
            cids = self.subs.get(qid)
            if not cids:
                continue
            if self.engine is not None:
                text = self.engine.table.texts.get(qid)
            else:
                rec = self._own.get(qid)
                text = rec[0] if rec else None
            if text is None:
                continue
            topic = SEM_PREFIX + "/" + text
            out.extend((cid, topic) for cid in cids)
        return out

    def _serve_degraded(self, texts: List[str]) -> List[List[Tuple[str, str]]]:
        """Hub unreachable: exact host scoring over the worker's OWN
        queries — correct for local subscribers, and the only honest
        answer while the pool table is unreachable."""
        self.degraded += len(texts)
        tp("semantic.degrade", n=len(texts), own=len(self._own))
        out = []
        for t in texts:
            vec = embed_text(t, self.dim)
            row = []
            for lq, (_q, v) in self._own.items():
                sc = float(np.dot(v, vec))
                if sc >= self.threshold:
                    row.append((sc, lq))
            row.sort(key=lambda x: (-x[0], x[1]))
            out.append(self._fan_local([lq for _, lq in row[: self.topk]]))
        return out

    def deliver_remote(self, hub_qids: List[int]) -> List[Tuple[str, str]]:
        """Receiver side of a sem-tagged cluster forward: map the hub's
        qids to this worker's local queries and fan out."""
        if self.shm is None:
            return []
        loc = [
            q for q in (self.shm.semantic_hub2loc(h) for h in hub_qids)
            if q is not None
        ]
        if len(loc) < len(hub_qids):
            # an idle worker has no publish traffic driving poll(), so
            # this query's K_SEMQ_ACK may still sit unread in the
            # response ring — drain once and retry the unknowns
            self.shm.poll()
            loc = [
                q for q in
                (self.shm.semantic_hub2loc(h) for h in hub_qids)
                if q is not None
            ]
        out = self._fan_local(loc)
        self.deliveries += len(out)
        return out

    # -------------------------------------------------------- telemetry

    def counters(self) -> Dict[str, int]:
        out = {
            "semantic.queries.added": self.queries_added,
            "semantic.queries.removed": self.queries_removed,
            "semantic.deliveries": self.deliveries,
            "semantic.degraded": self.degraded,
            "semantic.dropped": self.dropped,
        }
        if self.engine is not None:
            out.update(self.engine.counters())
        return out
