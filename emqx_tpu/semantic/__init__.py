"""TPU-native semantic subscription plane.

`$semantic/<query>` subscriptions match publishes on MEANING instead of
topic levels (the Neural Router routing primitive, PAPERS.md arxiv
2605.25701).  Query vectors live device-resident like the retained-index
entry plane; publish payloads embed in batches and top-k cosine matches
ride the same submit/collect split as the hash-match engine, with an
exact host-side scorer as the honest fallback and the retainer's EWMA
rate arbiter picking the path.

Layout:
  embedder.py  deterministic feature-hash/bag-of-ngrams text embedder
  table.py     query-vector registry + HBM mirror (dirty-row sync)
  engine.py    submit/collect match engine, adaptive kcap, arbiter
  plane.py     broker-facing subscription plane (local + shm backends)
"""

from .embedder import EMBED_PREFIX, SIM_THRESHOLD, embed_batch, embed_text
from .engine import SemanticEngine
from .plane import SemanticPlane
from .table import SemanticTable

__all__ = [
    "EMBED_PREFIX",
    "SIM_THRESHOLD",
    "SemanticEngine",
    "SemanticPlane",
    "SemanticTable",
    "embed_batch",
    "embed_text",
]
