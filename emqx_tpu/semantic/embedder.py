"""Deterministic feature-hash text embedder (the plane's model stub).

The semantic plane's contract is the DISPATCH architecture — device-
resident query table, batched payload embedding, top-k cosine through
the submit/collect split — not the embedding model.  This embedder is
the dependency-free stand-in: lowercase word tokens plus char-3-gram
shingles, FNV-1a hashed into a fixed-dim signed feature vector, L2
normalized.  Swapping in a learned encoder changes only this module.

Everything here is bit-deterministic (no `hash()`, which is salted per
process): the same text embeds to the same vector on every worker, the
hub, and the test oracle — the property the bit-agreement acceptance
test leans on.
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

# Only this many payload BYTES are ever embedded: K_SEM ring ticks must
# stay slot-sized, and bag-of-features saturates long before 2 KiB.
EMBED_PREFIX = 2048

# Cosine floor for membership: a query matches a publish iff the EXACT
# host-side cosine is >= this.  The device kernel only NOMINATES
# candidates (see engine.py), so the constant defines the match set on
# every path identically.
SIM_THRESHOLD = 0.30

# Device scores may drift from the host's f32 arithmetic by float
# reassociation; candidates are safe to trust only when the kcap-th
# device score is below SIM_THRESHOLD - SIM_MARGIN (else: refetch).
SIM_MARGIN = 1e-3

_WORD_RE = re.compile(r"[a-z0-9]+")

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def _fnv64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def _features(text: str) -> List[str]:
    """Word unigrams + char 3-gram shingles (NUL-prefixed so a 3-letter
    word and its own shingle land in different hash buckets)."""
    words = _WORD_RE.findall(text.lower())
    feats = list(words)
    for w in words:
        if len(w) > 3:
            for i in range(len(w) - 2):
                feats.append("\x00" + w[i:i + 3])
    return feats


def embed_text(text: str, dim: int, out: Optional[np.ndarray] = None) -> np.ndarray:
    """One L2-normalized [dim] f32 feature-hash embedding."""
    if out is None:
        vec = np.zeros(dim, dtype=np.float32)
    else:
        vec = out
        vec[:] = 0.0
    for f in _features(text):
        h = _fnv64(f.encode("utf-8", "surrogatepass"))
        idx = h % dim
        vec[idx] += 1.0 if (h >> 63) == 0 else -1.0
    n = float(np.sqrt(np.dot(vec, vec)))
    if n > 0.0:
        vec /= np.float32(n)
    return vec


def embed_batch(texts: List[str], dim: int,
                out: Optional[np.ndarray] = None) -> np.ndarray:
    """[B, dim] f32, row b = embed_text(texts[b]).  ``out`` recycles a
    staging buffer (rows past len(texts) are zeroed: padded rows have
    norm 0 and cosine 0 against everything, below any threshold)."""
    if out is None:
        out = np.zeros((len(texts), dim), dtype=np.float32)
    for b, t in enumerate(texts):
        embed_text(t, dim, out=out[b])
    if out.shape[0] > len(texts):
        out[len(texts):] = 0.0
    return out


def payload_text(payload: bytes) -> str:
    """The embeddable view of a publish payload: a bounded UTF-8 prefix
    with NULs stripped (the shm lane packs texts into NUL-separated
    blobs, and the embedder never assigns NUL tokens any weight)."""
    txt = payload[:EMBED_PREFIX].decode("utf-8", "replace")
    return txt.replace("\x00", " ")
