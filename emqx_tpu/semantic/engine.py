"""Semantic match engine: batched cosine top-k with an honest oracle.

The dispatch shape is the retained-index probe plane's: publish texts
embed into a recycled staging buffer, upload as ONE array, and the
device answers with `(scores, idxs)` candidates under a static adaptive
``kcap`` (ops.match.semantic_topk).  Membership is then decided HOST-
side by re-scoring the candidates with the exact numpy arithmetic the
oracle uses — the device only NOMINATES, so the matched set is
bit-identical to the exact scorer by construction; float drift can only
cost a refetch (kcap saturated near the threshold -> dense host scoring
for that row + a wider kcap next tick).

Path choice between this and the all-host dense scorer is the EWMA
rate arbiter lifted from broker/retainer.py: serve whichever path
measures faster, refresh the losing path's rate with bounded probes,
and count every flip.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observe.tracepoints import tp
from ..ops.match import next_pow2
from .embedder import SIM_MARGIN, SIM_THRESHOLD, embed_batch, embed_text
from .table import SemanticTable

_STAGING_POOL = 4  # recycled upload buffers kept per batch size
_PROBE_CAP = 64  # biggest batch a rate probe will ship


class _PendingSem:
    """One in-flight device tick (submit/collect split)."""

    __slots__ = ("scores", "idxs", "buf", "B", "n", "kcap", "t0")

    def __init__(self, scores, idxs, buf, B, n, kcap, t0):
        self.scores = scores
        self.idxs = idxs
        self.buf = buf
        self.B = B
        self.n = n
        self.kcap = kcap
        self.t0 = t0

    def is_ready(self) -> bool:
        try:
            return bool(self.scores.is_ready())
        except Exception:
            return True


class SemanticEngine:
    """Device-resident query table + arbitrated match dispatch."""

    def __init__(self, dim: int = 256, max_queries: int = 4096,
                 topk: int = 8, probe_interval: float = 10.0,
                 threshold: float = SIM_THRESHOLD):
        self.table = SemanticTable(dim=dim, cap=max_queries)
        self.topk = int(topk)
        self.threshold = float(threshold)
        self.probe_interval = float(probe_interval)
        self._lk = threading.Lock()
        # adaptive candidate window (models/retained.py discipline)
        self._kcap_floor = max(4, next_pow2(self.topk))
        self._kcap_ceil = min(256, next_pow2(max_queries))
        self._kcap_dyn = self._kcap_floor
        self._kmax_peak = 0
        self._kmax_ticks = 0
        # EWMA rate arbiter (broker/retainer.py trie-vs-index shape)
        self.rate_host: Optional[float] = None
        self.rate_dev: Optional[float] = None
        self._last_host_meas = 0.0
        self._last_dev_meas = 0.0
        self._last_path: Optional[bool] = None
        self._probe: Optional[Tuple[_PendingSem, float]] = None
        # telemetry (synced into broker metrics by the plane)
        self.matches_dev = 0
        self.matches_host = 0
        self.path_flips = 0
        self.probes = 0
        self.refetches = 0
        self._staging: Dict[int, List[np.ndarray]] = {}

    # ------------------------------------------------------------- churn

    def add_query(self, text: str, owner: str = "") -> int:
        with self._lk:
            return self.table.add(text, owner=owner)

    def remove_query(self, qid: int) -> bool:
        with self._lk:
            return self.table.remove(qid)

    def drop_owner(self, owner: str) -> List[int]:
        with self._lk:
            return self.table.drop_owner(owner)

    @property
    def n_queries(self) -> int:
        return self.table.n_live

    # ----------------------------------------------------------- staging

    def _acquire_staging(self, B: int) -> np.ndarray:
        pool = self._staging.get(B)
        try:
            return pool.pop()  # GIL-atomic; races fall through to alloc
        except (AttributeError, IndexError):
            return np.zeros((B, self.table.dim), dtype=np.float32)

    def _release_staging(self, buf: np.ndarray, B: int) -> None:
        pool = self._staging.setdefault(B, [])
        if len(pool) < _STAGING_POOL:
            pool.append(buf)

    # ------------------------------------------------------ device path

    def submit(self, texts: List[str],
               kcap: Optional[int] = None) -> _PendingSem:
        """Embed + upload ONE packed batch, dispatch the cosine top-k
        kernel, start the async result download.  Non-blocking."""
        from ..ops.match import semantic_topk
        import jax

        B = max(1, next_pow2(len(texts)))
        buf = self._acquire_staging(B)
        embed_batch(texts, self.table.dim, out=buf)
        kc = int(kcap if kcap is not None else self._kcap_dyn)
        with self._lk:
            dev_vecs, dev_valid = self.table.device_tables()
        scores, idxs = semantic_topk(
            dev_vecs, dev_valid, jax.device_put(buf), kcap=kc
        )
        try:
            scores.copy_to_host_async()
            idxs.copy_to_host_async()
        except Exception:
            pass
        return _PendingSem(scores, idxs, buf, B, len(texts),
                           kc, time.monotonic())

    def collect(self, pend: _PendingSem) -> List[List[Tuple[int, float]]]:
        """Block on the device result, then decide membership exactly.

        Returns one `[(qid, score), ...]` list per submitted text —
        threshold-passing queries by descending exact score (qid tie-
        break), truncated to topk: the oracle's definition verbatim."""
        s = np.asarray(pend.scores)
        ix = np.asarray(pend.idxs)
        out: List[List[Tuple[int, float]]] = []
        kmax = 0
        near = self.threshold - SIM_MARGIN
        with self._lk:
            for b in range(pend.n):
                # window saturated with near-threshold candidates: the
                # device may have ranked a passer out — refetch densely
                if ix[b, pend.kcap - 1] >= 0 and float(s[b, pend.kcap - 1]) >= near:
                    self.refetches += 1
                    kmax = max(kmax, pend.kcap)
                    self._kcap_dyn = min(
                        self._kcap_ceil, next_pow2(pend.kcap + 1)
                    )
                    tp("semantic.refetch", kcap=pend.kcap,
                       kcap_next=self._kcap_dyn)
                    out.append(self._exact_row(pend.buf[b]))
                    continue
                row = self._exact_over(
                    [q for q in ix[b].tolist() if q >= 0], pend.buf[b]
                )
                kmax = max(kmax, len(row))
                out.append(row[: self.topk])
        self._release_staging(pend.buf, pend.B)
        self._note_kmax(kmax)
        return out

    def _note_kmax(self, kmax: int) -> None:
        """Shrink the candidate window toward 2x the observed peak every
        64 ticks (the retained-index _note_kmax discipline)."""
        self._kmax_peak = max(self._kmax_peak, kmax)
        self._kmax_ticks += 1
        if self._kmax_ticks >= 64:
            want = max(self._kcap_floor,
                       next_pow2(max(1, 2 * self._kmax_peak)))
            if want < self._kcap_dyn:
                self._kcap_dyn = want
            self._kmax_peak = 0
            self._kmax_ticks = 0

    # -------------------------------------------------------- host path

    def _exact_over(self, qids: List[int],
                    vec: np.ndarray) -> List[Tuple[int, float]]:
        """Exact membership over candidate rows.  Deliberately
        `(rows * vec).sum(axis=1)` and NOT `rows @ vec`: BLAS gemv
        accumulation order varies with the matrix shape, so a
        device-nominated candidate subset would score rows at ULP
        distance from the dense pass — enough to flip membership at
        the threshold.  Per-row multiply+pairwise-sum depends only on
        (row, vec), so scores are bit-identical whichever path
        nominated the row."""
        live = [q for q in qids if self.table.valid[q]]
        if not live:
            return []
        scores = (self.table.vecs[live] * vec).sum(axis=1)
        row = [
            (q, float(sc)) for q, sc in zip(live, scores.tolist())
            if sc >= self.threshold
        ]
        row.sort(key=lambda t: (-t[1], t[0]))
        return row

    def _exact_row(self, vec: np.ndarray) -> List[Tuple[int, float]]:
        """Dense exact scorer for ONE embedded text (the oracle)."""
        rows = np.nonzero(self.table.valid)[0]
        return self._exact_over(rows.tolist(), vec)[: self.topk]

    def match_exact(self, texts: List[str]) -> List[List[Tuple[int, float]]]:
        """All-host dense path: embed + score every live query."""
        out = []
        with self._lk:
            for t in texts:
                vec = embed_text(t, self.table.dim)
                out.append(self._exact_row(vec))
        return out

    # ---------------------------------------------------------- arbiter

    def _pick_dev(self) -> bool:
        if self.table.n_live == 0:
            return False
        if self.rate_dev is None or self.rate_host is None:
            return False
        if self.rate_dev <= self.rate_host:
            return False
        # stale host measurement: serve host once to refresh it
        if time.monotonic() - self._last_host_meas > self.probe_interval:
            return False
        return True

    def _note_host_rate(self, rps: float) -> None:
        self.rate_host = (
            rps if self.rate_host is None else 0.5 * self.rate_host + 0.5 * rps
        )
        self._last_host_meas = time.monotonic()

    def _note_dev_rate(self, rps: float) -> None:
        self.rate_dev = (
            rps if self.rate_dev is None else 0.5 * self.rate_dev + 0.5 * rps
        )
        self._last_dev_meas = time.monotonic()

    def _note_path(self, dev: bool) -> None:
        if self._last_path is not None and self._last_path != dev:
            self.path_flips += 1
            tp("semantic.flip", to="device" if dev else "host",
               rate_dev=self.rate_dev, rate_host=self.rate_host)
        self._last_path = dev

    def _maybe_probe(self, texts: List[str]) -> None:
        """Host-serving steady state: ship a bounded non-blocking device
        probe so rate_dev stays honest (retainer _maybe_probe_index)."""
        if self._probe is not None:
            return
        now = time.monotonic()
        if self.rate_dev is not None and \
                now - self._last_dev_meas < self.probe_interval:
            return
        probe = texts[:_PROBE_CAP]
        self.probes += 1
        tp("semantic.probe", n=len(probe))
        self._probe = (self.submit(probe), now)

    def _poll_probe(self) -> None:
        if self._probe is None:
            return
        pend, t0 = self._probe
        if not pend.is_ready():
            return
        self._probe = None
        n = pend.n
        self.collect(pend)
        dt = time.monotonic() - t0
        if dt > 0:
            self._note_dev_rate(n / dt)

    # ------------------------------------------------------------ match

    def match_submit(self, texts: List[str]):
        """Arbitrated submit half: device work (when picked) starts NOW
        so the publish pipeline overlaps it with other planes."""
        self._poll_probe()
        if self._pick_dev():
            return ("dev", texts, self.submit(texts), time.monotonic())
        return ("host", texts, None, time.monotonic())

    def match_collect(self, handle) -> List[List[Tuple[int, float]]]:
        """Collect half: resolve the path taken, book its rate."""
        mode, texts, pend, t0 = handle
        if mode == "dev":
            out = self.collect(pend)
            dt = time.monotonic() - t0
            if dt > 0:
                self._note_dev_rate(len(texts) / dt)
            self.matches_dev += len(texts)
            self._note_path(True)
        else:
            out = self.match_exact(texts)
            dt = time.monotonic() - t0
            if dt > 0:
                self._note_host_rate(len(texts) / dt)
            self.matches_host += len(texts)
            self._note_path(False)
            if self.table.n_live:
                self._maybe_probe(texts)
        return out

    def match(self, texts: List[str]) -> List[List[Tuple[int, float]]]:
        """Arbitrated synchronous match: one `[(qid, exact score)]` list
        per text.  Used by the hub intake and the test oracle harness."""
        if not texts:
            return []
        return self.match_collect(self.match_submit(texts))

    # -------------------------------------------------------- telemetry

    def counters(self) -> Dict[str, int]:
        return {
            "semantic.matches.device": self.matches_dev,
            "semantic.matches.host": self.matches_host,
            "semantic.flips": self.path_flips,
            "semantic.probes": self.probes,
            "semantic.refetches": self.refetches,
        }
