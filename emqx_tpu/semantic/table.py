"""Query-vector registry + HBM mirror (the retained entry-plane analog).

One fixed-capacity [max_queries, dim] f32 row table holds every live
`$semantic/<query>` embedding; rows are refcounted by (owner, text) so
N subscribers to the same query share one row, and freed rows recycle
through a free heap.  The device mirror syncs dirty rows by scatter
(full re-upload only on first touch or bulk churn), mirroring
models/retained.py's dirty-row discipline — match ticks then dispatch
on RESIDENT buffers and upload only the publish batch.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..ops.match import next_pow2
from .embedder import embed_text

# past this many dirty rows a full re-upload beats per-row scatter
_SCATTER_MAX = 64


_scatter_jit = None


def _scatter_rows(dev_vecs, dev_valid, rows, vals, flags):
    """Scatter churned rows into the HBM mirror (padding rows carry an
    out-of-range index and drop, the apply_delta_packed discipline).
    jit built lazily so importing the table never drags jax into a
    process that only runs the host path (wire workers)."""
    global _scatter_jit
    if _scatter_jit is None:
        import jax

        def _impl(dv, dva, r, v, f):
            return (
                dv.at[r].set(v, mode="drop"),
                dva.at[r].set(f, mode="drop"),
            )

        _scatter_jit = jax.jit(_impl)
    return _scatter_jit(dev_vecs, dev_valid, rows, vals, flags)


class SemanticTable:
    """Host-of-record query table with a lazily-synced device mirror."""

    def __init__(self, dim: int = 256, cap: int = 4096):
        self.dim = int(dim)
        self.cap = int(cap)
        self.vecs = np.zeros((self.cap, self.dim), dtype=np.float32)
        self.valid = np.zeros(self.cap, dtype=bool)
        self.texts: Dict[int, str] = {}
        self.owners: Dict[int, str] = {}
        self.refs: Dict[int, int] = {}
        self._by_key: Dict[Tuple[str, str], int] = {}
        self._free: List[int] = list(range(self.cap))
        heapq.heapify(self._free)
        self.n_live = 0
        # None = full upload owed; else the set of churned row ids
        self._dirty: Optional[Set[int]] = None
        self._dev = None  # (dev_vecs [cap, dim], dev_valid [cap])

    # ------------------------------------------------------------- churn

    def add(self, text: str, owner: str = "") -> int:
        """Register (or ref) a query; returns its row id, -1 when full."""
        key = (owner, text)
        qid = self._by_key.get(key)
        if qid is not None:
            self.refs[qid] += 1
            return qid
        if not self._free:
            return -1
        qid = heapq.heappop(self._free)
        embed_text(text, self.dim, out=self.vecs[qid])
        self.valid[qid] = True
        self.texts[qid] = text
        self.owners[qid] = owner
        self.refs[qid] = 1
        self._by_key[key] = qid
        self.n_live += 1
        if self._dirty is not None:
            self._dirty.add(qid)
        return qid

    def remove(self, qid: int) -> bool:
        """Drop one reference; True when the row was actually freed."""
        if qid not in self.refs:
            return False
        self.refs[qid] -= 1
        if self.refs[qid] > 0:
            return False
        del self.refs[qid]
        self.valid[qid] = False
        self.vecs[qid] = 0.0
        del self._by_key[(self.owners.pop(qid), self.texts.pop(qid))]
        heapq.heappush(self._free, qid)
        self.n_live -= 1
        if self._dirty is not None:
            self._dirty.add(qid)
        return True

    def drop_owner(self, owner: str) -> List[int]:
        """Free every row an owner holds, whatever its refcount (hub
        lane-death reclaim: the worker incarnation is gone, so are its
        references).  Returns the freed row ids."""
        gone = [q for q, o in self.owners.items() if o == owner]
        for qid in gone:
            self.refs[qid] = 1
            self.remove(qid)
        return gone

    def lookup(self, text: str, owner: str = "") -> int:
        return self._by_key.get((owner, text), -1)

    # ------------------------------------------------------------- device

    def device_tables(self):
        """The HBM mirror, synced: full upload on first touch (or after
        bulk churn), per-row scatter for small deltas."""
        import jax

        if self._dev is None or self._dirty is None \
                or len(self._dirty) > _SCATTER_MAX:
            self._dev = (
                jax.device_put(self.vecs.copy()),
                jax.device_put(self.valid.copy()),
            )
        elif self._dirty:
            rows = sorted(self._dirty)
            n = next_pow2(max(1, len(rows)))
            ridx = np.full(n, self.cap, dtype=np.int32)
            ridx[: len(rows)] = rows
            vals = np.zeros((n, self.dim), dtype=np.float32)
            vals[: len(rows)] = self.vecs[rows]
            flags = np.zeros(n, dtype=bool)
            flags[: len(rows)] = self.valid[rows]
            self._dev = _scatter_rows(*self._dev, ridx, vals, flags)
        self._dirty = set()
        return self._dev

    def drop_device(self) -> None:
        self._dev = None
        self._dirty = None
