"""Broker-side exhook manager — `emqx_exhook_mgr`/`emqx_exhook_server` analog.

Loads configured provider servers, negotiates their hook lists
(OnProviderLoaded), bridges the broker's hookpoints to provider calls
with refcounted registration (`emqx_exhook_server.erl:211-234`), and
applies the per-server failure policy `failed_action: deny | ignore`
with `request_timeout` (`:89-90,310-311`).

Call semantics (`emqx_exhook.erl:38-80`):
  * valued hooks (authenticate / authorize / message.publish) fold over
    servers in declaration order; a "stop" response ends the chain; a
    failed request maps to deny (or is skipped under ignore);
  * all other hookpoints are events: shipped fire-and-forget through a
    background dispatch thread so the broker's hot path never blocks on
    a provider (the reference blocks its per-client process instead —
    an asyncio broker cannot afford that).
"""

from __future__ import annotations

import base64
import dataclasses
import logging
import queue
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..broker.access_control import ALLOW, DENY, ClientInfo
from ..broker.hooks import STOP, Hooks
from ..broker.message import Message
from ..broker.packet import ReasonCode
from .wire import HOOKPOINTS, VALUED_HOOKS, SyncConn

log = logging.getLogger(__name__)


@dataclass
class ExhookServerConfig:
    name: str
    host: str
    port: int
    pool_size: int = 4
    request_timeout: float = 5.0
    failed_action: str = "deny"  # deny | ignore
    enable: bool = True
    # grpc = the reference-compatible HookProvider service (default);
    # json = the framed-TCP fallback transport for grpc-less hosts
    driver: str = "grpc"


class _ServerState:
    def __init__(self, cfg: ExhookServerConfig):
        self.cfg = cfg
        self.pool = [
            SyncConn((cfg.host, cfg.port), cfg.request_timeout)
            for _ in range(cfg.pool_size)
        ]
        self.locks = [threading.Lock() for _ in self.pool]
        self._rr = 0
        self.enabled_hooks: List[str] = []

    def call(self, hook: str, data: dict) -> dict:
        """One pooled request (round-robin member, per-member lock)."""
        i = self._rr = (self._rr + 1) % len(self.pool)
        with self.locks[i]:
            return self.pool[i].call(hook, data)

    def wants_topic(self, hook: str, topic: str) -> bool:
        return True  # JSON transport has no HookSpec.topics scoping

    def close(self) -> None:
        for conn in self.pool:
            conn.close()


def _clientinfo_data(ci: ClientInfo) -> dict:
    d = dataclasses.asdict(ci)
    d.pop("attrs", None)
    out = {k: v for k, v in d.items() if isinstance(v, (str, int, bool, float, type(None)))}
    # the proto ClientInfo carries password as a string for authenticate
    # providers; bytes would otherwise be dropped by the filter above
    if isinstance(ci.password, (bytes, bytearray)):
        out["password"] = ci.password.decode("utf-8", "replace")
    return out


def _message_data(msg: Message) -> dict:
    return {
        "topic": msg.topic,
        "payload": base64.b64encode(msg.payload).decode(),
        "qos": msg.qos,
        "retain": msg.retain,
        "from": msg.from_client,
        "mid": msg.mid.hex(),
        "timestamp": msg.timestamp,
    }


class ExhookManager:
    def __init__(self, hooks: Hooks, metrics=None, queue_size: int = 10_000):
        self.hooks = hooks
        self.metrics = metrics
        self.servers: List[_ServerState] = []
        self._installed: Dict[str, Any] = {}  # hookpoint -> bridge callback
        self._events: "queue.Queue[Tuple[str, dict]]" = queue.Queue(queue_size)
        self._dispatcher: Optional[threading.Thread] = None
        self._stopping = False

    # ------------------------------------------------------------ lifecycle

    def load_server(self, cfg: ExhookServerConfig) -> List[str]:
        """Connect + OnProviderLoaded; returns the negotiated hook list."""
        if cfg.driver == "grpc":
            from .grpc_wire import GrpcServerState

            st = GrpcServerState(cfg)
            wanted = [h for h in st.load() if h in HOOKPOINTS]
        else:
            st = _ServerState(cfg)
            resp = st.call("provider.loaded", {"broker": "emqx_tpu"})
            wanted = [h for h in (resp.get("value") or []) if h in HOOKPOINTS]
        st.enabled_hooks = wanted
        self.servers.append(st)
        for point in wanted:
            self._ensure_hook(point)
        self._ensure_dispatcher()
        log.info("exhook server %s loaded hooks=%s", cfg.name, wanted)
        return wanted

    def unload_server(self, name: str) -> None:
        for st in list(self.servers):
            if st.cfg.name == name:
                try:
                    st.call("provider.unloaded", {})
                except Exception:
                    pass
                self.servers.remove(st)
                st.close()
        self._gc_hooks()

    def stop(self) -> None:
        self._stopping = True
        self._events.put(("__stop__", {}))
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=5)
        for st in self.servers:
            st.close()
        self.servers.clear()
        self._gc_hooks()

    def _ensure_hook(self, point: str) -> None:
        """Refcounted install (`ensure_hooks`): one bridge cb per point."""
        if point in self._installed:
            return
        if point in VALUED_HOOKS:
            cb = self._make_valued_cb(point)
        else:
            cb = self._make_event_cb(point)
        self.hooks.put(point, cb, priority=100)  # exhook runs first
        self._installed[point] = cb

    def _gc_hooks(self) -> None:
        still_wanted = {h for st in self.servers for h in st.enabled_hooks}
        for point in list(self._installed):
            if point not in still_wanted:
                self.hooks.delete(point, self._installed.pop(point))

    def _servers_for(self, point: str) -> List[_ServerState]:
        return [st for st in self.servers if point in st.enabled_hooks and st.cfg.enable]

    # ---------------------------------------------------------- valued path

    def _make_valued_cb(self, point: str):
        if point == "client.authenticate":
            def cb(clientinfo, acc):
                return self._fold_authenticate(clientinfo, acc)
        elif point == "client.authorize":
            def cb(clientinfo, action, topic, acc):
                return self._fold_authorize(clientinfo, action, topic, acc)
        else:  # message.publish
            def cb(msg):
                return self._fold_publish(msg)
        return cb

    def _fold_authenticate(self, clientinfo: ClientInfo, acc):
        data = {"clientinfo": _clientinfo_data(clientinfo)}
        for st in self._servers_for("client.authenticate"):
            try:
                resp = st.call("client.authenticate", data)
            except Exception:
                if st.cfg.failed_action == "deny":
                    return (STOP, {"result": DENY,
                                   "reason_code": ReasonCode.NOT_AUTHORIZED})
                continue
            value = resp.get("value")
            verdict = None
            if isinstance(value, bool):
                verdict = (
                    {"result": ALLOW}
                    if value
                    else {"result": DENY, "reason_code": ReasonCode.NOT_AUTHORIZED}
                )
            if resp.get("type") == "stop" and verdict is not None:
                return (STOP, verdict)
            if verdict is not None:
                acc = verdict
        return acc

    def _fold_authorize(self, clientinfo: ClientInfo, action: str, topic: str, acc):
        data = {
            "clientinfo": _clientinfo_data(clientinfo),
            "action": action,
            "topic": topic,
        }
        for st in self._servers_for("client.authorize"):
            try:
                resp = st.call("client.authorize", data)
            except Exception:
                if st.cfg.failed_action == "deny":
                    return (STOP, DENY)
                continue
            value = resp.get("value")
            if isinstance(value, bool):
                verdict = ALLOW if value else DENY
                if resp.get("type") == "stop":
                    return (STOP, verdict)
                acc = verdict
        return acc

    def _fold_publish(self, msg: Message):
        from dataclasses import replace

        for st in self._servers_for("message.publish"):
            if not st.wants_topic("message.publish", msg.topic):
                continue
            try:
                resp = st.call("message.publish", _message_data(msg))
            except Exception:
                if st.cfg.failed_action == "deny":
                    return (STOP, replace(
                        msg, headers=dict(msg.headers, allow_publish=False)
                    ))
                continue
            value = resp.get("value")
            if isinstance(value, dict):
                msg = replace(
                    msg,
                    topic=value.get("topic", msg.topic),
                    payload=base64.b64decode(value["payload"])
                    if "payload" in value
                    else msg.payload,
                    qos=value.get("qos", msg.qos),
                    retain=value.get("retain", msg.retain),
                    headers=dict(
                        msg.headers, **(value.get("headers") or {})
                    ),
                )
            if resp.get("type") == "stop":
                return (STOP, msg)
        return msg

    # ----------------------------------------------------------- event path

    def _make_event_cb(self, point: str):
        def cb(*args):
            data = self._encode_event(point, args)
            try:
                self._events.put_nowait((point, data))
            except queue.Full:
                if self.metrics is not None:
                    self.metrics.inc("exhook.events.dropped")
            return None

        return cb

    @staticmethod
    def _encode_event(point: str, args: tuple) -> dict:
        data: Dict[str, Any] = {}
        for a in args:
            if isinstance(a, ClientInfo):
                data["clientinfo"] = _clientinfo_data(a)
            elif isinstance(a, Message):
                data["message"] = _message_data(a)
            elif isinstance(a, str):
                data.setdefault("args", []).append(a)
            elif isinstance(a, bool):
                data["flag"] = a
            elif dataclasses.is_dataclass(a) and not isinstance(a, type):
                try:
                    data["opts"] = {
                        k: v
                        for k, v in dataclasses.asdict(a).items()
                        if isinstance(v, (str, int, bool, float, type(None)))
                    }
                except Exception:
                    pass
        return data

    def _ensure_dispatcher(self) -> None:
        if self._dispatcher is not None and self._dispatcher.is_alive():
            return
        self._stopping = False
        self._dispatcher = threading.Thread(target=self._dispatch_loop, daemon=True)
        self._dispatcher.start()

    def _dispatch_loop(self) -> None:
        while True:
            point, data = self._events.get()
            if point == "__stop__" or self._stopping:
                return
            for st in self._servers_for(point):
                if point.startswith("message.") and not st.wants_topic(
                    point, (data.get("message") or data).get("topic", "")
                ):
                    continue
                try:
                    st.call(point, data)
                except Exception:
                    if self.metrics is not None:
                        self.metrics.inc("exhook.events.failed")

    def pending_events(self) -> int:
        return self._events.qsize()
