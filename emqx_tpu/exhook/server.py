"""Provider-side server: hosts a provider object behind the wire protocol.

The standalone-service half of the exhook boundary — what the reference
calls the "HookProvider server" (external process implementing
exhook.proto).  A provider object exposes:

  hooks() -> list[str]                    which hookpoints to bridge
                                          (OnProviderLoaded's hook list)
  on_<hook_with_underscores>(data) ->     per-hook handler; valued hooks
      None | bool | dict                  return a verdict/new message,
                                          event hooks return None

Runs in its own asyncio loop; `ProviderServerThread` wraps it in a
daemon thread so tests (and same-process deployments) get the real
out-of-process call pattern — the broker side blocks on a socket while
the provider answers from another thread, exactly like the gRPC hop.
"""

from __future__ import annotations

import asyncio
import json
import struct
import threading
from typing import Optional

from .wire import MAX_FRAME, VALUED_HOOKS


class ProviderServer:
    def __init__(self, provider, host: str = "127.0.0.1", port: int = 0):
        self.provider = provider
        self.host = host
        self.port = port
        self._server: Optional[asyncio.base_events.Server] = None
        self._conns: set = set()

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._conns.add(writer)
        try:
            while True:
                hdr = await reader.readexactly(4)
                (n,) = struct.unpack("!I", hdr)
                if not 0 < n <= MAX_FRAME:
                    return
                req = json.loads(await reader.readexactly(n))
                resp = self._dispatch(req)
                body = json.dumps(resp, separators=(",", ":")).encode()
                writer.write(struct.pack("!I", len(body)) + body)
                await writer.drain()
        except asyncio.CancelledError:
            raise  # cancellation must propagate; finally closes the conn
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    def _dispatch(self, req: dict) -> dict:
        rid = req.get("id")
        hook = req.get("hook", "")
        data = req.get("data") or {}
        if hook == "provider.loaded":
            return {"id": rid, "type": "continue", "value": self.provider.hooks()}
        method = getattr(self.provider, "on_" + hook.replace(".", "_"), None)
        if method is None:
            return {"id": rid, "type": "continue", "value": None}
        try:
            result = method(data)
        except Exception as e:
            return {"id": rid, "type": "continue", "error": f"{type(e).__name__}: {e}"}
        if hook not in VALUED_HOOKS or result is None:
            return {"id": rid, "type": "continue", "value": None}
        # valued hook verdicts: (type, value) | bool | replacement message
        if isinstance(result, tuple):
            typ, value = result
            return {"id": rid, "type": typ, "value": value}
        return {"id": rid, "type": "continue", "value": result}


class ProviderServerThread:
    """Run a ProviderServer on a dedicated loop in a daemon thread."""

    def __init__(self, provider, host: str = "127.0.0.1", port: int = 0):
        self.server = ProviderServer(provider, host, port)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ProviderServerThread":
        started = threading.Event()

        def run():
            loop = asyncio.new_event_loop()
            self._loop = loop
            asyncio.set_event_loop(loop)
            loop.run_until_complete(self.server.start())
            started.set()
            loop.run_forever()
            loop.run_until_complete(self.server.stop())
            loop.close()

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("provider server failed to start")
        return self

    @property
    def port(self) -> int:
        return self.server.port

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=10)
