"""gRPC transport for the exhook boundary — both sides of the wire.

The reference's north-star integration point is the `HookProvider` gRPC
service (`emqx_exhook_server.erl:89-117` client pool;
`exhook.proto:27-69` contract).  This module provides:

* `GrpcProviderServer` — serve any provider object (e.g.
  `TpuMatchProvider`) as a HookProvider gRPC service, so a STOCK EMQ X
  broker can call the TPU match sidecar;
* `GrpcServerState` — the broker-side client (channel + stub +
  OnProviderLoaded negotiation) exposing the same `call(hook, data)`
  interface as the JSON-TCP `_ServerState`, so `ExhookManager` drives
  stock gRPC providers unchanged.

Dict<->protobuf translation keeps the manager's JSON shapes as the
internal lingua franca: payloads ride base64 in dicts and raw bytes in
pb; pb header maps are str->str, so "true"/"false" round-trip to bools
for the broker's allow_publish gate and list values ride as JSON.
"""

from __future__ import annotations

import base64
import json
import logging
from concurrent import futures
from typing import Any, Dict, List, Optional

from . import proto
from .wire import VALUED_HOOKS

log = logging.getLogger("emqx_tpu.exhook.grpc")


# ------------------------------------------------------------ converters

def _ci_to_pb(p, d: Dict[str, Any]):
    return p.ClientInfo(
        node=str(d.get("node", "")),
        clientid=str(d.get("clientid", "")),
        username=str(d.get("username") or ""),
        password=str(d.get("password") or ""),
        peerhost=str(d.get("peerhost", "")),
        protocol=str(d.get("protocol", "mqtt")),
        mountpoint=str(d.get("mountpoint") or ""),
        is_superuser=bool(d.get("is_superuser", False)),
        anonymous=not d.get("username"),
        cn=str(d.get("cn", "")),
        dn=str(d.get("dn", "")),
    )


def _ci_to_dict(ci) -> Dict[str, Any]:
    return {
        "node": ci.node,
        "clientid": ci.clientid,
        "username": ci.username or None,
        "password": ci.password or None,
        "peerhost": ci.peerhost,
        "protocol": ci.protocol,
        "mountpoint": ci.mountpoint or None,
        "is_superuser": ci.is_superuser,
        "cn": ci.cn,
        "dn": ci.dn,
    }


def _headers_to_pb(headers: Dict[str, Any]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for k, v in (headers or {}).items():
        if isinstance(v, bool):
            out[k] = "true" if v else "false"
        elif isinstance(v, (str, int, float)):
            out[k] = str(v)
        else:
            try:
                out[k] = json.dumps(v)
            except (TypeError, ValueError):
                continue
    return out


def _headers_from_pb(headers) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in dict(headers).items():
        if v == "true":
            out[k] = True
        elif v == "false":
            out[k] = False
        elif v[:1] in ("[", "{"):
            try:
                out[k] = json.loads(v)
            except ValueError:
                out[k] = v
        else:
            out[k] = v
    return out


def _msg_to_pb(p, d: Dict[str, Any]):
    payload = d.get("payload", b"")
    if isinstance(payload, str):  # manager dicts carry base64
        payload = base64.b64decode(payload)
    return p.Message(
        node=str(d.get("node", "")),
        id=str(d.get("id", d.get("mid", ""))),
        qos=int(d.get("qos", 0)),
        topic=str(d.get("topic", "")),
        payload=payload,
        timestamp=int(d.get("timestamp", 0)),
        headers=_headers_to_pb(d.get("headers") or {}),
        **{"from": str(d.get("from", d.get("from_client", "")))},
    )


def _msg_to_dict(m) -> Dict[str, Any]:
    return {
        "id": m.id,
        "qos": m.qos,
        "from": getattr(m, "from"),
        "topic": m.topic,
        "payload": base64.b64encode(m.payload).decode(),
        "timestamp": m.timestamp,
        "headers": _headers_from_pb(m.headers),
    }


def _build_request(p, hook: str, data: Dict[str, Any]):
    """Manager event/valued dict -> pb request for `hook`."""
    ci = _ci_to_pb(p, data.get("clientinfo") or {})
    args = data.get("args") or []
    if hook == "client.authenticate":
        return p.ClientAuthenticateRequest(clientinfo=ci, result=True)
    if hook == "client.authorize":
        t = (
            p.ClientAuthorizeRequest.PUBLISH
            if data.get("action") in ("publish", "pub")
            else p.ClientAuthorizeRequest.SUBSCRIBE
        )
        return p.ClientAuthorizeRequest(
            clientinfo=ci, type=t, topic=data.get("topic", ""), result=True
        )
    if hook == "message.publish":
        return p.MessagePublishRequest(message=_msg_to_pb(p, data))
    if hook in ("message.delivered", "message.acked"):
        return getattr(
            p, "MessageDeliveredRequest"
            if hook == "message.delivered"
            else "MessageAckedRequest",
        )(clientinfo=ci, message=_msg_to_pb(p, data.get("message") or data))
    if hook == "message.dropped":
        return p.MessageDroppedRequest(
            message=_msg_to_pb(p, data.get("message") or data),
            reason=args[0] if args else "",
        )
    if hook == "client.connect":
        return p.ClientConnectRequest(
            conninfo=p.ConnInfo(
                clientid=str((data.get("clientinfo") or {}).get("clientid", "")),
                username=str((data.get("clientinfo") or {}).get("username") or ""),
            )
        )
    if hook == "client.connack":
        return p.ClientConnackRequest(
            conninfo=p.ConnInfo(
                clientid=str((data.get("clientinfo") or {}).get("clientid", ""))
            ),
            result_code=args[0] if args else "success",
        )
    if hook == "client.disconnected":
        return p.ClientDisconnectedRequest(
            clientinfo=ci, reason=args[0] if args else ""
        )
    if hook in ("client.subscribe", "client.unsubscribe"):
        cls = (
            p.ClientSubscribeRequest
            if hook == "client.subscribe"
            else p.ClientUnsubscribeRequest
        )
        return cls(
            clientinfo=ci,
            topic_filters=[p.TopicFilter(name=a) for a in args],
        )
    if hook == "session.subscribed":
        # event args: (clientid, filter); opts from the SubOpts dataclass
        if not ci.clientid and args:
            ci = p.ClientInfo(clientid=args[0])
        opts = data.get("opts") or {}
        return p.SessionSubscribedRequest(
            clientinfo=ci,
            topic=args[1] if len(args) > 1 else "",
            subopts=p.SubOpts(
                qos=int(opts.get("qos", 0)),
                rh=int(opts.get("retain_handling", 0)),
                rap=int(bool(opts.get("retain_as_published", False))),
                nl=int(bool(opts.get("no_local", False))),
            ),
        )
    if hook == "session.unsubscribed":
        if not ci.clientid and args:
            ci = p.ClientInfo(clientid=args[0])
        return p.SessionUnsubscribedRequest(
            clientinfo=ci, topic=args[1] if len(args) > 1 else ""
        )
    if hook == "session.terminated":
        if not ci.clientid and args:
            ci = p.ClientInfo(clientid=args[0])
        return p.SessionTerminatedRequest(
            clientinfo=ci, reason=args[-1] if args else ""
        )
    # session.created / resumed / discarded / takenover / connected
    cls_name = proto.METHODS[proto.HOOK_TO_METHOD[hook]][0]
    return getattr(p, cls_name)(clientinfo=ci)


def _valued_to_dict(p, resp) -> Dict[str, Any]:
    """ValuedResponse -> the manager's {"type", "value"} shape."""
    typ = (
        "stop"
        if resp.type == p.ValuedResponse.STOP_AND_RETURN
        else "continue"
    )
    which = resp.WhichOneof("value")
    value: Any = None
    if resp.type != p.ValuedResponse.IGNORE:
        if which == "bool_result":
            value = resp.bool_result
        elif which == "message":
            value = _msg_to_dict(resp.message)
    return {"type": typ, "value": value}


# ------------------------------------------------------- broker side

class GrpcServerState:
    """Drop-in for ExhookManager's _ServerState over gRPC.

    One channel (HTTP/2 multiplexes; pool_size is satisfied by gRPC's
    own stream concurrency, mirroring the reference's channel pool)."""

    def __init__(self, cfg):
        import grpc

        self.cfg = cfg
        self._pb = proto.pb2()
        if self._pb is None:
            raise RuntimeError("gRPC exhook unavailable: protoc/grpcio missing")
        self.channel = grpc.insecure_channel(f"{cfg.host}:{cfg.port}")
        self.stub = proto.make_stub(self.channel)
        self.enabled_hooks: List[str] = []
        # message-hook topic filters from HookSpec.topics ([] = all)
        self.hook_topics: Dict[str, List[str]] = {}

    def load(self, broker_info: Optional[Dict[str, Any]] = None) -> List[str]:
        """OnProviderLoaded handshake -> hook names to register."""
        p = self._pb
        info = broker_info or {}
        req = p.ProviderLoadedRequest(
            broker=p.BrokerInfo(
                version=str(info.get("version", "")),
                sysdescr=str(info.get("sysdescr", "emqx_tpu")),
                uptime=int(info.get("uptime", 0)),
                datetime=str(info.get("datetime", "")),
            )
        )
        resp = self.stub.OnProviderLoaded(
            req, timeout=self.cfg.request_timeout
        )
        self.enabled_hooks = [spec.name for spec in resp.hooks]
        self.hook_topics = {
            spec.name: list(spec.topics) for spec in resp.hooks if spec.topics
        }
        return list(self.enabled_hooks)

    def wants_topic(self, hook: str, topic: str) -> bool:
        """HookSpec.topics scoping: the reference broker only fires
        message hooks whose topic matches the provider's filters."""
        filters = self.hook_topics.get(hook)
        if not filters:
            return True
        from ..broker import topic as topiclib

        return any(topiclib.match(topic, f) for f in filters)

    def call(self, hook: str, data: Dict[str, Any]) -> Dict[str, Any]:
        p = self._pb
        method = proto.HOOK_TO_METHOD.get(hook)
        if method is None:
            return {"type": "continue", "value": None}
        req = _build_request(p, hook, data)
        resp = getattr(self.stub, method)(
            req, timeout=self.cfg.request_timeout
        )
        if hook in VALUED_HOOKS:
            return _valued_to_dict(p, resp)
        return {"type": "continue", "value": None}

    def unload(self) -> None:
        try:
            self.stub.OnProviderUnloaded(
                self._pb.ProviderUnloadedRequest(), timeout=2.0
            )
        except Exception:
            pass

    def close(self) -> None:
        self.unload()
        try:
            self.channel.close()
        except Exception:
            pass


# ------------------------------------------------------ provider side

class _Servicer:
    """pb requests -> the provider's dict-based on_<hook> methods (the
    same API ProviderServer serves over JSON-TCP)."""

    def __init__(self, provider):
        self.provider = provider
        self._p = proto.pb2()

    # -- lifecycle

    def OnProviderLoaded(self, request, context):
        p = self._p
        # optional hook_specs(): hook -> topic filters (HookSpec.topics)
        specs = {}
        fn = getattr(self.provider, "hook_specs", None)
        if fn is not None:
            try:
                specs = fn() or {}
            except Exception:
                log.exception("provider hook_specs failed")
        return p.LoadedResponse(
            hooks=[
                p.HookSpec(name=h, topics=list(specs.get(h) or ()))
                for h in self.provider.hooks()
            ]
        )

    def OnProviderUnloaded(self, request, context):
        return self._p.EmptySuccess()

    # -- generic dispatch helpers

    def _event(self, hook: str, data: Dict[str, Any]):
        method = getattr(self.provider, "on_" + hook.replace(".", "_"), None)
        if method is not None:
            try:
                method(data)
            except Exception:
                log.exception("provider %s failed", hook)
        return self._p.EmptySuccess()

    def _valued(self, hook: str, data: Dict[str, Any]):
        p = self._p
        method = getattr(self.provider, "on_" + hook.replace(".", "_"), None)
        if method is None:
            return p.ValuedResponse(type=p.ValuedResponse.IGNORE)
        try:
            result = method(data)
        except Exception:
            log.exception("provider %s failed", hook)
            return p.ValuedResponse(type=p.ValuedResponse.IGNORE)
        if result is None:
            return p.ValuedResponse(type=p.ValuedResponse.IGNORE)
        typ, value = result if isinstance(result, tuple) else ("continue", result)
        pb_type = (
            p.ValuedResponse.STOP_AND_RETURN
            if typ == "stop"
            else p.ValuedResponse.CONTINUE
        )
        if isinstance(value, bool):
            return p.ValuedResponse(type=pb_type, bool_result=value)
        if isinstance(value, dict):
            base = dict(data)
            base_headers = dict(base.get("headers") or {})
            base_headers.update(value.get("headers") or {})
            merged = {**base, **value, "headers": base_headers}
            return p.ValuedResponse(
                type=pb_type, message=_msg_to_pb(p, merged)
            )
        return p.ValuedResponse(type=p.ValuedResponse.IGNORE)

    # -- per-rpc adapters (hook dicts mirror manager._encode_event)

    def OnClientConnect(self, request, context):
        ci = request.conninfo
        return self._event(
            "client.connect",
            {
                "clientinfo": {
                    "node": ci.node,
                    "clientid": ci.clientid,
                    "username": ci.username or None,
                    "peerhost": ci.peerhost,
                }
            },
        )

    def OnClientConnack(self, request, context):
        return self._event("client.connack", {"args": [request.result_code]})

    def OnClientConnected(self, request, context):
        return self._event(
            "client.connected", {"clientinfo": _ci_to_dict(request.clientinfo)}
        )

    def OnClientDisconnected(self, request, context):
        return self._event(
            "client.disconnected",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [request.reason],
            },
        )

    def OnClientAuthenticate(self, request, context):
        return self._valued(
            "client.authenticate",
            {"clientinfo": _ci_to_dict(request.clientinfo)},
        )

    def OnClientAuthorize(self, request, context):
        p = self._p
        return self._valued(
            "client.authorize",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "action": "publish"
                if request.type == p.ClientAuthorizeRequest.PUBLISH
                else "subscribe",
                "topic": request.topic,
            },
        )

    def OnClientSubscribe(self, request, context):
        return self._event(
            "client.subscribe",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [tf.name for tf in request.topic_filters],
            },
        )

    def OnClientUnsubscribe(self, request, context):
        return self._event(
            "client.unsubscribe",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [tf.name for tf in request.topic_filters],
            },
        )

    def OnSessionCreated(self, request, context):
        return self._event(
            "session.created", {"clientinfo": _ci_to_dict(request.clientinfo)}
        )

    def OnSessionSubscribed(self, request, context):
        so = request.subopts
        return self._event(
            "session.subscribed",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [request.clientinfo.clientid, request.topic],
                "opts": {
                    "qos": so.qos,
                    "retain_handling": so.rh,
                    "retain_as_published": bool(so.rap),
                    "no_local": bool(so.nl),
                    "share": so.share,
                },
            },
        )

    def OnSessionUnsubscribed(self, request, context):
        return self._event(
            "session.unsubscribed",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [request.clientinfo.clientid, request.topic],
            },
        )

    def OnSessionResumed(self, request, context):
        return self._event(
            "session.resumed", {"clientinfo": _ci_to_dict(request.clientinfo)}
        )

    def OnSessionDiscarded(self, request, context):
        return self._event(
            "session.discarded", {"clientinfo": _ci_to_dict(request.clientinfo)}
        )

    def OnSessionTakenover(self, request, context):
        return self._event(
            "session.takenover", {"clientinfo": _ci_to_dict(request.clientinfo)}
        )

    def OnSessionTerminated(self, request, context):
        return self._event(
            "session.terminated",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "args": [request.clientinfo.clientid, request.reason],
            },
        )

    def OnMessagePublish(self, request, context):
        return self._valued("message.publish", _msg_to_dict(request.message))

    def OnMessageDelivered(self, request, context):
        return self._event(
            "message.delivered",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "message": _msg_to_dict(request.message),
            },
        )

    def OnMessageDropped(self, request, context):
        return self._event(
            "message.dropped",
            {"message": _msg_to_dict(request.message), "args": [request.reason]},
        )

    def OnMessageAcked(self, request, context):
        return self._event(
            "message.acked",
            {
                "clientinfo": _ci_to_dict(request.clientinfo),
                "message": _msg_to_dict(request.message),
            },
        )


class GrpcProviderServer:
    """Serve a provider object as the HookProvider gRPC service."""

    def __init__(self, provider, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 8):
        import grpc

        if proto.pb2() is None:
            raise RuntimeError("gRPC exhook unavailable: protoc missing")
        self.provider = provider
        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        proto.add_servicer(self.server, _Servicer(provider))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise RuntimeError(f"could not bind gRPC provider to {host}:{port}")

    def start(self) -> "GrpcProviderServer":
        self.server.start()
        return self

    def stop(self, grace: float = 0.5) -> None:
        self.server.stop(grace).wait(timeout=5)
