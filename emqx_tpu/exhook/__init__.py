"""exhook: out-of-process hook provider boundary.

The reference's extension boundary (`apps/emqx_exhook`, SURVEY.md §1.9,
§3.5): a broker bridges its 19 hookpoints to an external "HookProvider"
service over gRPC; the provider answers valued hooks (authenticate /
authorize / message.publish) with continue/stop decisions and observes
the rest.  This is the integration point the TPU match engine was
designed to ride (SURVEY.md §7.2 step 4).

This package implements BOTH sides:

* `manager.ExhookManager` — broker side (`emqx_exhook_server` analog):
  per-server connection pool, OnProviderLoaded hook negotiation with
  refcounted registration, request timeouts, failed_action deny|ignore.
* `server.ProviderServer` — provider side: hosts a provider object
  (e.g. `provider.TpuMatchProvider`, which mirrors subscriptions into a
  `TopicMatchEngine` and answers publish hooks with device-matched
  subscriber sets).

Transport: length-prefixed JSON frames over TCP (`wire.py`) carrying
the exhook.proto request/response vocabulary (same hook names, same
valued-response semantics).  grpcio is not available in this image; if
it is present at runtime a gRPC transport can be slotted in behind the
same `HookClient` interface (`wire.GRPC_AVAILABLE` gates it).
"""

from .manager import ExhookManager, ExhookServerConfig
from .provider import TpuMatchProvider
from .server import ProviderServer, ProviderServerThread
from .wire import HOOKPOINTS, VALUED_HOOKS

__all__ = [
    "ExhookManager",
    "ExhookServerConfig",
    "TpuMatchProvider",
    "ProviderServer",
    "ProviderServerThread",
    "HOOKPOINTS",
    "VALUED_HOOKS",
]
