"""exhook: out-of-process hook provider boundary.

The reference's extension boundary (`apps/emqx_exhook`, SURVEY.md §1.9,
§3.5): a broker bridges its 19 hookpoints to an external "HookProvider"
service over gRPC; the provider answers valued hooks (authenticate /
authorize / message.publish) with continue/stop decisions and observes
the rest.  This is the integration point the TPU match engine was
designed to ride (SURVEY.md §7.2 step 4).

This package implements BOTH sides:

* `manager.ExhookManager` — broker side (`emqx_exhook_server` analog):
  per-server connection pool, OnProviderLoaded hook negotiation with
  refcounted registration, request timeouts, failed_action deny|ignore.
* `server.ProviderServer` — provider side: hosts a provider object
  (e.g. `provider.TpuMatchProvider`, which mirrors subscriptions into a
  `TopicMatchEngine` and answers publish hooks with device-matched
  subscriber sets).

Transports (ExhookServerConfig.driver):

* `grpc` (default) — the real HookProvider gRPC service, wire-compatible
  with the reference contract (`protos/exhook.proto`; messages generated
  by protoc on demand, stubs hand-written in `proto.py` since the
  grpc_tools codegen plugin is absent).  `grpc_wire.GrpcServerState` is
  the broker-side client; `grpc_wire.GrpcProviderServer` serves any
  provider object — including `TpuMatchProvider` — to a STOCK EMQ X.
* `json` — length-prefixed JSON frames over TCP (`wire.py`) carrying the
  same hook vocabulary, for hosts without grpcio/protoc.
"""

from .manager import ExhookManager, ExhookServerConfig
from .provider import TpuMatchProvider
from .server import ProviderServer, ProviderServerThread
from .wire import HOOKPOINTS, VALUED_HOOKS

__all__ = [
    "ExhookManager",
    "ExhookServerConfig",
    "TpuMatchProvider",
    "ProviderServer",
    "ProviderServerThread",
    "HOOKPOINTS",
    "VALUED_HOOKS",
]
