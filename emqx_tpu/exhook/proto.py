"""protoc codegen loader + hand-written gRPC service stubs.

The image has `grpcio` + `protoc` but not the `grpc_tools` codegen
plugin, so message classes come from `protoc --python_out` (generated
on demand into this package, like the native/ C++ build) and the
service stubs — normally emitted by the grpc plugin — are written here
against the generic-handler API.  Method table mirrors the reference
service (`apps/emqx_exhook/priv/protos/exhook.proto:27-69`).
"""

from __future__ import annotations

import importlib
import logging
import os
import subprocess
import threading

log = logging.getLogger("emqx_tpu.exhook.proto")

_HERE = os.path.dirname(__file__)
_PROTO = os.path.join(_HERE, "protos", "exhook.proto")
_PB2 = os.path.join(_HERE, "exhook_pb2.py")

_lock = threading.Lock()
_pb2 = None

SERVICE = "emqx.exhook.v1.HookProvider"

#: method -> (request message name, response message name)
METHODS = {
    "OnProviderLoaded": ("ProviderLoadedRequest", "LoadedResponse"),
    "OnProviderUnloaded": ("ProviderUnloadedRequest", "EmptySuccess"),
    "OnClientConnect": ("ClientConnectRequest", "EmptySuccess"),
    "OnClientConnack": ("ClientConnackRequest", "EmptySuccess"),
    "OnClientConnected": ("ClientConnectedRequest", "EmptySuccess"),
    "OnClientDisconnected": ("ClientDisconnectedRequest", "EmptySuccess"),
    "OnClientAuthenticate": ("ClientAuthenticateRequest", "ValuedResponse"),
    "OnClientAuthorize": ("ClientAuthorizeRequest", "ValuedResponse"),
    "OnClientSubscribe": ("ClientSubscribeRequest", "EmptySuccess"),
    "OnClientUnsubscribe": ("ClientUnsubscribeRequest", "EmptySuccess"),
    "OnSessionCreated": ("SessionCreatedRequest", "EmptySuccess"),
    "OnSessionSubscribed": ("SessionSubscribedRequest", "EmptySuccess"),
    "OnSessionUnsubscribed": ("SessionUnsubscribedRequest", "EmptySuccess"),
    "OnSessionResumed": ("SessionResumedRequest", "EmptySuccess"),
    "OnSessionDiscarded": ("SessionDiscardedRequest", "EmptySuccess"),
    "OnSessionTakenover": ("SessionTakenoverRequest", "EmptySuccess"),
    "OnSessionTerminated": ("SessionTerminatedRequest", "EmptySuccess"),
    "OnMessagePublish": ("MessagePublishRequest", "ValuedResponse"),
    "OnMessageDelivered": ("MessageDeliveredRequest", "EmptySuccess"),
    "OnMessageDropped": ("MessageDroppedRequest", "EmptySuccess"),
    "OnMessageAcked": ("MessageAckedRequest", "EmptySuccess"),
}

#: hookpoint name <-> rpc method
HOOK_TO_METHOD = {
    "client.connect": "OnClientConnect",
    "client.connack": "OnClientConnack",
    "client.connected": "OnClientConnected",
    "client.disconnected": "OnClientDisconnected",
    "client.authenticate": "OnClientAuthenticate",
    "client.authorize": "OnClientAuthorize",
    "client.subscribe": "OnClientSubscribe",
    "client.unsubscribe": "OnClientUnsubscribe",
    "session.created": "OnSessionCreated",
    "session.subscribed": "OnSessionSubscribed",
    "session.unsubscribed": "OnSessionUnsubscribed",
    "session.resumed": "OnSessionResumed",
    "session.discarded": "OnSessionDiscarded",
    "session.takenover": "OnSessionTakenover",
    "session.terminated": "OnSessionTerminated",
    "message.publish": "OnMessagePublish",
    "message.delivered": "OnMessageDelivered",
    "message.dropped": "OnMessageDropped",
    "message.acked": "OnMessageAcked",
}


def _generate() -> bool:
    try:
        subprocess.run(
            ["protoc", f"--python_out={_HERE}", f"--proto_path={os.path.dirname(_PROTO)}",
             _PROTO],
            check=True, capture_output=True, timeout=60,
        )
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.info("protoc generation failed: %s", e)
        return False


def pb2():
    """The generated message module (None when protoc/grpc are absent)."""
    global _pb2
    if _pb2 is not None:
        return _pb2
    with _lock:
        if _pb2 is not None:
            return _pb2
        have_proto = os.path.exists(_PROTO)
        if not os.path.exists(_PB2) or (
            have_proto and os.path.getmtime(_PROTO) > os.path.getmtime(_PB2)
        ):
            if not _generate():
                return None
        try:
            _pb2 = importlib.import_module("emqx_tpu.exhook.exhook_pb2")
        except Exception as e:  # stale gencode vs runtime, etc.
            log.info("exhook_pb2 import failed: %s", e)
            return None
    return _pb2


def grpc_available() -> bool:
    try:
        import grpc  # noqa: F401
    except ImportError:
        return False
    return pb2() is not None


def make_stub(channel):
    """Client stub for HookProvider, one unary-unary callable per rpc
    (what grpc_tools' *_pb2_grpc.py would emit)."""
    p = pb2()
    stubs = {}
    for method, (req_name, resp_name) in METHODS.items():
        req = getattr(p, req_name)
        resp = getattr(p, resp_name)
        stubs[method] = channel.unary_unary(
            f"/{SERVICE}/{method}",
            request_serializer=req.SerializeToString,
            response_deserializer=resp.FromString,
        )

    class _Stub:
        pass

    stub = _Stub()
    for name, fn in stubs.items():
        setattr(stub, name, fn)
    return stub


def add_servicer(server, servicer) -> None:
    """Register `servicer` (methods named like the rpcs) on a
    grpc.Server via generic handlers."""
    import grpc

    p = pb2()
    handlers = {}
    for method, (req_name, resp_name) in METHODS.items():
        fn = getattr(servicer, method, None)
        if fn is None:
            continue
        handlers[method] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=getattr(p, req_name).FromString,
            response_serializer=getattr(p, resp_name).SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),)
    )
