"""exhook wire protocol: hookpoint vocabulary + framed JSON transport.

Mirrors the request/response vocabulary of the reference's
`exhook.proto` (HookProvider service: OnProviderLoaded, OnClientConnect,
... OnMessageAcked) without gRPC: frames are `u32 length | JSON` over
TCP.  Each request is `{"id": n, "hook": name, "data": {...}}`; each
response `{"id": n, "type": "continue"|"stop", "value": ...}` — the
ValuedResponse semantics of the proto (`type` maps to its
`StopOrContinue`, `value` to the bool/message oneof).
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

try:  # pragma: no cover - not present in this image
    import grpc  # noqa: F401

    GRPC_AVAILABLE = True
except ImportError:
    GRPC_AVAILABLE = False

# the 19 bridged hookpoints (`emqx_exhook.hrl` ?ENABLED_HOOKS)
HOOKPOINTS = (
    "client.connect",
    "client.connack",
    "client.connected",
    "client.disconnected",
    "client.authenticate",
    "client.authorize",
    "client.subscribe",
    "client.unsubscribe",
    "session.created",
    "session.subscribed",
    "session.unsubscribed",
    "session.resumed",
    "session.discarded",
    "session.takenover",
    "session.terminated",
    "message.publish",
    "message.delivered",
    "message.acked",
    "message.dropped",
)

# hooks whose provider response feeds back into the chain
# (ValuedResponse in the proto; deny semantics on failure)
VALUED_HOOKS = frozenset(
    {"client.authenticate", "client.authorize", "message.publish"}
)

MAX_FRAME = 16 * 1024 * 1024


def pack(obj: dict) -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode()
    return struct.pack("!I", len(body)) + body


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def read_obj(sock: socket.socket) -> dict:
    (n,) = struct.unpack("!I", recv_exact(sock, 4))
    if not 0 < n <= MAX_FRAME:
        raise ConnectionError(f"bad frame length {n}")
    return json.loads(recv_exact(sock, n))


class SyncConn:
    """One pooled blocking connection to a provider (client side).

    The reference's per-server gRPC channel pool is pool_size =
    schedulers (`emqx_exhook_server.erl:89-117`); here each pooled
    member is a plain socket with a request timeout.
    """

    def __init__(self, addr: Tuple[str, int], timeout: float):
        self.addr = addr
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._next_id = 0

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.addr, timeout=self.timeout)
            s.settimeout(self.timeout)
            self._sock = s
        return self._sock

    def call(self, hook: str, data: dict) -> dict:
        self._next_id += 1
        req = {"id": self._next_id, "hook": hook, "data": data}
        try:
            s = self._ensure()
            s.sendall(pack(req))
            while True:
                resp = read_obj(s)
                if resp.get("id") == self._next_id:
                    return resp
        except (OSError, ConnectionError, socket.timeout):
            self.close()
            raise

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
