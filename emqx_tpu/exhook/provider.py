"""TpuMatchProvider — the north-star exhook provider (SURVEY.md §7.2 #4).

An out-of-process hook provider that mirrors a broker's subscription
table into a `TopicMatchEngine` (the HBM route/trie mirror) via the
session.subscribed / session.unsubscribed hook stream, and answers
message.publish hooks with the device-matched subscriber set attached
to the message headers.  Against a stock reference broker this is the
"TPU sidecar" deployment: the broker keeps its own dispatch, and the
provider supplies accelerated match verdicts; against our own broker it
doubles as an integration-test provider for the exhook boundary.

State here is a cache over the hook stream — on restart the broker's
session.subscribed replay (or a fresh OnProviderLoaded negotiation)
rebuilds it, matching the reference's device-state-is-a-cache failure
model (SURVEY.md §5.4).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from ..models.engine import TopicMatchEngine


class TpuMatchProvider:
    def __init__(self, engine: Optional[TopicMatchEngine] = None):
        self.engine = engine or TopicMatchEngine()
        self._subs: Dict[int, Set[str]] = {}  # fid -> clientids
        self._lock = threading.Lock()  # pool conns call concurrently
        self.stats = {"publish": 0, "subscribed": 0, "unsubscribed": 0}

    def hooks(self) -> List[str]:
        return [
            "session.subscribed",
            "session.unsubscribed",
            "session.terminated",
            "message.publish",
        ]

    # ------------------------------------------------------- oplog ingest

    def on_session_subscribed(self, data: dict) -> None:
        args = data.get("args") or []
        if len(args) < 2:
            return
        clientid, filt = args[0], args[1]
        with self._lock:
            fid = self.engine.add_filter(filt)
            self._subs.setdefault(fid, set()).add(clientid)
            self.stats["subscribed"] += 1

    def on_session_unsubscribed(self, data: dict) -> None:
        args = data.get("args") or []
        if len(args) < 2:
            return
        clientid, filt = args[0], args[1]
        with self._lock:
            fid = self.engine.fid_of(filt)
            if fid is None:
                return
            members = self._subs.get(fid)
            if members is not None:
                members.discard(clientid)
                if not members:
                    del self._subs[fid]
            self.engine.remove_filter(filt)
            self.stats["unsubscribed"] += 1

    def on_session_terminated(self, data: dict) -> None:
        """Best-effort cleanup when a session dies without unsubscribes."""
        args = data.get("args") or []
        if not args:
            return
        clientid = args[0]
        with self._lock:
            rev = {fid: f for f, fid in self.engine._fids.items()}
            for fid in list(self._subs):
                members = self._subs[fid]
                if clientid not in members:
                    continue
                members.discard(clientid)
                if not members:
                    del self._subs[fid]
                filt = rev.get(fid)
                if filt is not None:
                    # one engine ref was taken per (clientid, filter)
                    self.engine.remove_filter(filt)

    # ------------------------------------------------------------- publish

    def on_message_publish(self, data: dict):
        """Match one message; return it with the matched subscriber set."""
        with self._lock:
            fids = self.engine.match_one(data.get("topic", ""))
            matched = sorted({c for f in fids for c in self._subs.get(f, ())})
            self.stats["publish"] += 1
        return ("continue", {"headers": {"tpu_matched": matched}})

    # -------------------------------------------------------------- stats

    @property
    def n_filters(self) -> int:
        return self.engine.n_filters
