"""Churn write-ahead log, layered on the disk-backed replay queue.

Every engine mutation between snapshots — subscribes, unsubscribes,
whole churn ticks — is appended as one packed (adds, removes) record
through `TopicMatchEngine.on_churn` / `ShardedMatchEngine.on_churn`.
Records ride `utils/replayq.ReplayQ`, inheriting its durability
contract: per-record CRC32 framing, torn-tail truncation on reopen, and
pop-then-ack consumption.  A record is retired ONLY when a snapshot
that already contains its effect lands (`ack_through` at the snapshot's
watermark) — so a crash at ANY snapshot/WAL boundary replays exactly
the committed churn the newest snapshot is missing, never loses it.

Record format: u32 n_adds | u32 n_removes | NUL-joined utf-8 filter
strings (adds then removes; MQTT forbids U+0000 in filters, the same
invariant `ops.native.pack_strs` relies on).
"""

from __future__ import annotations

import struct
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from ..utils.replayq import ReplayQ

_CNT = struct.Struct("<II")


def pack_ops(adds: Sequence[str], removes: Sequence[str]) -> bytes:
    body = "\x00".join(list(adds) + list(removes)).encode("utf-8")
    return _CNT.pack(len(adds), len(removes)) + body


def unpack_ops(rec: bytes) -> Tuple[List[str], List[str]]:
    na, nr = _CNT.unpack_from(rec, 0)
    if na + nr == 0:
        return [], []
    parts = rec[_CNT.size:].decode("utf-8").split("\x00")
    if len(parts) != na + nr:
        raise ValueError("churn record count mismatch")
    return parts[:na], parts[na:]


class ChurnWal:
    """Thread-safe WAL facade over one ReplayQ directory.

    Appends come from the engine's mutation path (the event loop);
    `ack_through` runs on the checkpointer's writer thread — the lock
    keeps ReplayQ's segment bookkeeping consistent between them.
    """

    def __init__(
        self,
        directory: Optional[str],
        seg_bytes: int = 4 * 1024 * 1024,
        max_total_bytes: int = 0,
    ):
        self.q = ReplayQ(directory, seg_bytes=seg_bytes,
                         max_total_bytes=max_total_bytes)
        self._lock = threading.Lock()
        self._last = 0  # highest seqno this process appended or replayed
        self.records_appended = 0

    # ------------------------------------------------------------- append

    def append(self, adds: Sequence[str], removes: Sequence[str]) -> int:
        """Durably log one churn record; returns its seqno."""
        rec = pack_ops(adds, removes)
        with self._lock:
            seq = self.q.append(rec)
            self._last = seq
            self.records_appended += 1
        return seq

    def last_seq(self) -> int:
        """Watermark for `ack_through`: the newest record whose effect a
        snapshot captured NOW would contain."""
        with self._lock:
            return self._last

    # ------------------------------------------------------------- replay

    def replay(self) -> Iterator[Tuple[List[str], List[str]]]:
        """Yield every unacked (adds, removes) record, oldest first.

        Records stay ON DISK (popped, not acked): until the next
        snapshot lands, a second crash replays them again — the
        at-least-once contract; `apply_churn` replay is convergent
        (duplicate adds bump refcounts the matching duplicate removes
        release)."""
        while True:
            with self._lock:
                ref, items = self.q.pop(256)
                if items:
                    self._last = max(self._last, ref)
            if not items:
                return
            for rec in items:
                yield unpack_ops(rec)

    # ---------------------------------------------------------------- ack

    def ack_through(self, seq: int) -> None:
        """Retire records up to `seq` (a snapshot covering them landed).

        Drains the in-memory view first (appends accumulate there —
        nothing consumes the queue in steady state) and moves the commit
        cursor to `seq`; records past the watermark stay on disk unacked
        and replay after a crash."""
        with self._lock:
            while True:
                _ref, items = self.q.pop(1024)
                if not items:
                    break
            self.q.ack(seq)

    # -------------------------------------------------------------- state

    def pending_bytes(self) -> int:
        with self._lock:
            return self.q.pending_bytes()

    def pending_count(self) -> int:
        with self._lock:
            return self.q.pending_count()

    def close(self) -> None:
        with self._lock:
            self.q.close()
