"""Versioned binary snapshot store for the match-engine table state.

One snapshot file carries a JSON meta block plus named numpy arrays
(the `MatchTables` arrays, the packed filter registry, the retained
index rows).  The whole payload is CRC32-framed; writes go
temp + fsync + rename (+ directory fsync) so a power loss mid-write can
never surface a partial file as the newest snapshot; `load_newest()`
falls back to the next-older snapshot when the newest fails its frame
check — the disc-copies discipline of the reference's mnesia tables,
and the journal+snapshot layout of Pulsar-class brokers (PAPERS.md).

File layout (little-endian):

    magic "ETPUSNAP" | u32 version | u32 payload_crc | u64 payload_len
    payload:
        u32 meta_len | meta (JSON, utf-8)
        u32 n_arrays
        per array: u16 name_len | name | u16 dtype_len | dtype.str
                   | u8 ndim | ndim x u64 dims | u64 nbytes | raw bytes
"""

from __future__ import annotations

import json
import os
import struct
import tempfile
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import fault as _fault
from ..observe.tracepoints import tp

MAGIC = b"ETPUSNAP"
VERSION = 1
_HDR = struct.Struct("<8sIIQ")  # magic, version, payload crc, payload len


class SnapshotError(Exception):
    """A snapshot file failed its frame/CRC/format check."""


# ----------------------------------------------------------- string packing

def pack_str_list(strs: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
    """(u8 buffer, i64 offsets) for a string list — the registry wire
    format (`ops.native.pack_strs` contract), reused so a snapshot's
    packed filter blob feeds `FilterRegistry.set_bulk_packed` directly."""
    from ..ops.native import pack_strs

    if not strs:
        return np.zeros(1, dtype=np.uint8), np.zeros(1, dtype=np.int64)
    return pack_strs(list(strs))


def unpack_str_list(buf: np.ndarray, offs: np.ndarray) -> List[str]:
    data = buf.tobytes()
    ol = offs.tolist()
    return [
        data[ol[i]:ol[i + 1]].decode("utf-8") for i in range(len(ol) - 1)
    ]


def pack_nul_list(strs: Sequence[str]) -> np.ndarray:
    """String list as ONE NUL-joined u8 array — the snapshot's filter
    registry format.  MQTT forbids U+0000 in topics/filters (the same
    invariant `ops.native.pack_strs` and the churn WAL rely on), and
    UTF-8 never produces a 0x00 byte except for U+0000, so the
    separator is unambiguous and restore is one C-level decode+split
    instead of a 100k-iteration Python slice loop."""
    if not strs:
        return np.zeros(0, dtype=np.uint8)
    data = "\x00".join(strs).encode("utf-8")
    return np.frombuffer(data, dtype=np.uint8).copy()


def unpack_nul_list(arr: np.ndarray, n: int) -> List[str]:
    """Inverse of pack_nul_list; `n` disambiguates [] from [""]."""
    if n == 0:
        return []
    out = arr.tobytes().decode("utf-8").split("\x00")
    if len(out) != n:
        raise SnapshotError(
            f"packed string list holds {len(out)} entries, meta says {n}"
        )
    return out


def nul_to_packed(arr: np.ndarray, n: int) -> Tuple[np.ndarray, np.ndarray]:
    """NUL-joined blob -> the (buf, offsets) registry wire format
    (`FilterRegistry.set_bulk_packed`), three vectorized passes."""
    if n == 0:
        return np.zeros(1, dtype=np.uint8), np.zeros(1, dtype=np.int64)
    mask = arr == 0
    sep = np.flatnonzero(mask)
    if len(sep) != n - 1:
        raise SnapshotError("packed string list separator count mismatch")
    offs = np.empty(n + 1, dtype=np.int64)
    offs[0] = 0
    offs[1:n] = sep - np.arange(n - 1)
    offs[n] = len(arr) - (n - 1)
    packed = arr[~mask]
    if not len(packed):
        packed = np.zeros(1, dtype=np.uint8)
    return np.ascontiguousarray(packed), offs


def packed_to_nul(buf: np.ndarray, offs: np.ndarray, n: int) -> np.ndarray:
    """(buf, offsets) wire format -> the NUL-joined snapshot blob — the
    inverse of nul_to_packed, one vectorized scatter (the churn plane
    exports its registry in packed form; snapshots store NUL-joined)."""
    if n == 0:
        return np.zeros(0, dtype=np.uint8)
    total = int(offs[n])
    out = np.zeros(total + n - 1, dtype=np.uint8)
    lens = np.diff(offs[: n + 1])
    seg = np.repeat(np.arange(n, dtype=np.int64), lens)
    out[np.arange(total, dtype=np.int64) + seg] = buf[:total]
    return out


def pack_filter_blob(filters: Sequence[str]) -> bytes:
    """Compressed length-prefixed filter list — the cluster
    fast-bootstrap wire blob (`cluster/node.py` snapshot resync ships
    this instead of a JSON string array when a peer is far behind)."""
    body = b"".join(
        struct.pack("<I", len(b)) + b
        for b in (f.encode("utf-8") for f in filters)
    )
    return b"CKF1" + struct.pack("<I", len(filters)) + zlib.compress(body, 6)


def unpack_filter_blob(blob: bytes) -> List[str]:
    if blob[:4] != b"CKF1":
        raise SnapshotError("bad filter-blob magic")
    (n,) = struct.unpack_from("<I", blob, 4)
    body = zlib.decompress(blob[8:])
    out: List[str] = []
    off = 0
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", body, off)
        off += 4
        out.append(body[off:off + ln].decode("utf-8"))
        off += ln
    if off != len(body):
        raise SnapshotError("filter blob length mismatch")
    return out


# ---------------------------------------------------------- serialization

def _serialize(arrays: Dict[str, np.ndarray], meta: dict) -> bytes:
    parts: List[bytes] = []
    mblob = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    parts.append(struct.pack("<I", len(mblob)))
    parts.append(mblob)
    parts.append(struct.pack("<I", len(arrays)))
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        nb = name.encode("utf-8")
        db = arr.dtype.str.encode("ascii")
        parts.append(struct.pack("<H", len(nb)))
        parts.append(nb)
        parts.append(struct.pack("<H", len(db)))
        parts.append(db)
        parts.append(struct.pack("<B", arr.ndim))
        for d in arr.shape:
            parts.append(struct.pack("<Q", d))
        raw = arr.tobytes()
        parts.append(struct.pack("<Q", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _deserialize(payload: bytes) -> Tuple[Dict[str, np.ndarray], dict]:
    off = 0

    def take(fmt):
        nonlocal off
        s = struct.Struct(fmt)
        if off + s.size > len(payload):
            raise SnapshotError("truncated snapshot payload")
        vals = s.unpack_from(payload, off)
        off += s.size
        return vals if len(vals) > 1 else vals[0]

    mlen = take("<I")
    try:
        meta = json.loads(payload[off:off + mlen].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise SnapshotError(f"bad meta block: {e}")
    off += mlen
    n_arrays = take("<I")
    arrays: Dict[str, np.ndarray] = {}
    for _ in range(n_arrays):
        nlen = take("<H")
        name = payload[off:off + nlen].decode("utf-8")
        off += nlen
        dlen = take("<H")
        dtype = np.dtype(payload[off:off + dlen].decode("ascii"))
        off += dlen
        ndim = take("<B")
        shape = tuple(take("<Q") for _ in range(ndim))
        nbytes = take("<Q")
        if off + nbytes > len(payload):
            raise SnapshotError("truncated array block")
        # zero-copy WRITABLE views: load_file hands us a bytearray, so
        # restored tables can be mutated in place by later churn without
        # a per-array copy (the arrays share the payload as their base)
        arrays[name] = np.frombuffer(
            payload, dtype=dtype, count=nbytes // max(dtype.itemsize, 1),
            offset=off,
        ).reshape(shape)
        off += nbytes
    return arrays, meta


# ------------------------------------------------------------------- store

class SnapshotStore:
    """Keep-K snapshot directory with corruption fallback on load."""

    def __init__(self, directory: str, keep: int = 3):
        self.dir = directory
        self.keep = max(1, int(keep))
        self.fallbacks = 0  # newest-snapshot corruption events survived
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------ listing

    def list(self) -> List[Tuple[int, str]]:
        """(seq, path) newest first."""
        out = []
        for name in os.listdir(self.dir):
            if not (name.startswith("snap.") and name.endswith(".ckpt")):
                continue
            try:
                seq = int(name.split(".")[1])
            except (IndexError, ValueError):
                continue
            out.append((seq, os.path.join(self.dir, name)))
        out.sort(reverse=True)
        return out

    # --------------------------------------------------------------- save

    def save(self, arrays: Dict[str, np.ndarray], meta: dict) -> str:
        """Write one snapshot atomically; prune past keep-K.  Returns
        the snapshot path."""
        _fault.inject("ckpt.write", err=OSError)
        payload = _serialize(arrays, meta)
        hdr = _HDR.pack(MAGIC, VERSION, zlib.crc32(payload), len(payload))
        existing = self.list()
        seq = (existing[0][0] + 1) if existing else 1
        path = os.path.join(self.dir, f"snap.{seq}.ckpt")
        fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
        try:
            # production cadence runs save() via ckpt.write on a
            # to_thread worker (manager.py contract); the synchronous
            # checkpoint() convenience is shutdown/tests only
            with os.fdopen(fd, "wb") as f:
                f.write(hdr)  # analysis: allow-blocking(runs on the ckpt.write to_thread worker in production)
                f.write(payload)  # analysis: allow-blocking(runs on the ckpt.write to_thread worker in production)
                f.flush()  # analysis: allow-blocking(runs on the ckpt.write to_thread worker in production)
                os.fsync(f.fileno())  # analysis: allow-blocking(runs on the ckpt.write to_thread worker in production)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._fsync_dir()
        for old_seq, old_path in self.list()[self.keep:]:
            try:
                os.unlink(old_path)
            except OSError:
                pass
        return path

    def _fsync_dir(self) -> None:
        """Make the rename itself durable (best effort off-linux)."""
        try:
            dfd = os.open(self.dir, os.O_RDONLY)
        except OSError:
            return
        try:
            os.fsync(dfd)  # analysis: allow-blocking(runs on the ckpt.write to_thread worker in production)
        except OSError:
            pass
        finally:
            os.close(dfd)

    # --------------------------------------------------------------- load

    @staticmethod
    def load_file(path: str) -> Tuple[Dict[str, np.ndarray], dict]:
        """Parse + verify one snapshot file; SnapshotError on damage."""
        a = _fault.inject("ckpt.read", err=False)
        if a is not None and a.kind != "delay":
            # any injected damage surfaces as a frame-check failure, the
            # exact path load_newest's older-snapshot fallback handles
            raise SnapshotError(f"fault injected at ckpt.read ({a.kind})")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError as e:
            raise SnapshotError(f"unreadable: {e}")
        if len(data) < _HDR.size:
            raise SnapshotError("file shorter than header")
        magic, version, crc, plen = _HDR.unpack_from(data, 0)
        if magic != MAGIC:
            raise SnapshotError("bad magic")
        if version != VERSION:
            raise SnapshotError(f"unsupported snapshot version {version}")
        payload = data[_HDR.size:]
        if len(payload) != plen:
            raise SnapshotError("payload length mismatch (torn write)")
        if zlib.crc32(payload) != crc:
            raise SnapshotError("payload CRC mismatch")
        # one writable copy of the payload; every array is a view into it
        return _deserialize(bytearray(payload))

    def load_newest(
        self,
    ) -> Optional[Tuple[Dict[str, np.ndarray], dict, str]]:
        """Newest VALID snapshot (arrays, meta, path), falling back to
        older files when the newest fails its frame check; None when no
        loadable snapshot exists."""
        for i, (seq, path) in enumerate(self.list()):
            try:
                arrays, meta = self.load_file(path)
            except SnapshotError as e:
                self.fallbacks += 1
                tp("engine.ckpt.fallback", path=path, seq=seq, error=str(e))
                continue
            return arrays, meta, path
        return None
