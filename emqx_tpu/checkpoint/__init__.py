"""Table checkpoint & warm restart for the device match engine.

The reference survives restarts because route/session truth lives in
mnesia disc copies; this port's host truth (`MatchTables` + the filter
registries) previously had to be rebuilt from session files by replaying
every filter through `add_filters` on boot — at millions of routes, cold
start is bounded by a full table rebuild plus device re-upload.

This package is the durability subsystem for the engine's table state,
the same journal+snapshot shape a training stack calls checkpointing:

* `store.py`  — versioned CRC-framed binary snapshots of the table
  arrays + fid/shape registries (temp+fsync+rename, keep-K retention,
  fall back to an older snapshot on corruption);
* `wal.py`    — a churn write-ahead log on `utils/replayq.ReplayQ`:
  packed (adds, removes) records appended as engine mutations commit,
  acked atomically when a snapshot lands;
* `manager.py`— the background checkpointer (driven by the node
  housekeeping loop: snapshot on interval or WAL-bytes threshold) and
  `restore()` = newest valid snapshot + WAL-tail replay + ONE bulk
  device upload instead of per-filter inserts.
"""

from .store import SnapshotStore, pack_filter_blob, unpack_filter_blob
from .wal import ChurnWal
from .manager import CheckpointManager

__all__ = [
    "SnapshotStore",
    "ChurnWal",
    "CheckpointManager",
    "pack_filter_blob",
    "unpack_filter_blob",
]
