"""Background checkpointer: snapshot cadence, WAL hookup, warm restore.

Driven by the node housekeeping loop (`node.py _ticker`): a snapshot is
taken when the interval elapses OR the churn WAL's durable backlog
crosses `wal_max_bytes` — whichever first.  Capture is split from write
so the node can capture on the event loop (serialized with engine
mutations — consistent by construction, like a mnesia transaction view)
and serialize+fsync on a worker thread:

    if mgr.due(now):
        payload = mgr.capture()                  # loop thread, fast
        await asyncio.to_thread(mgr.write, payload)   # fsync off-loop

`restore()` is the warm-restart path: load the newest VALID snapshot
(older ones on corruption), rebuild host truth wholesale
(`engine.restore_checkpoint` — array adoption + dict zips, no
re-hashing or re-placement), replay the WAL tail through `apply_churn`,
and leave the device mirror marked rebuilt so the next dispatch ships
ONE bulk upload instead of per-filter inserts.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

from ..observe.tracepoints import tp
from .store import SnapshotStore
from .wal import ChurnWal

log = logging.getLogger("emqx_tpu.checkpoint")

ALARM_NAME = "engine_checkpoint_failure"


class CheckpointManager:
    def __init__(
        self,
        engine,
        directory: str,
        *,
        interval: float = 60.0,
        wal_max_bytes: int = 64 * 1024 * 1024,
        keep: int = 3,
        wal_seg_bytes: int = 4 * 1024 * 1024,
        retained_index=None,
        metrics=None,
        alarms=None,
    ):
        self.engine = engine
        self.retained = retained_index
        self.interval = float(interval)
        self.wal_max_bytes = int(wal_max_bytes)
        self.metrics = metrics  # broker Metrics (engine.ckpt.* counters)
        self.alarms = alarms  # observe.AlarmManager
        self.store = SnapshotStore(os.path.join(directory, "snap"), keep=keep)
        self.wal = ChurnWal(os.path.join(directory, "wal"),
                            seg_bytes=wal_seg_bytes)
        # write() runs on a to_thread worker while due()/stats readers
        # stay on the loop: every cadence/stat field below is guarded
        self._lock = threading.Lock()
        self._last_snap = time.monotonic()
        # filter -> refcount as of restore completion: released by
        # reconcile_sessions() once session restore re-added its own refs
        self._restored_refs: Optional[Dict[str, int]] = None
        self.save_count = 0
        self.save_failures = 0
        # pending alarm transition recorded by write()/restore() (worker
        # thread) and APPLIED by poll_alarm() on the event loop: the
        # alarm publish is itself a broker publish and must never run on
        # the checkpoint worker (same rule as poll_health_alarms)
        self._alarm_error: Optional[dict] = None
        self._alarm_dirty = False
        engine.on_churn = self.note_churn

    # ---------------------------------------------------------------- WAL

    def note_churn(self, adds, removes) -> None:
        """Engine mutation hook: one durable WAL record per commit."""
        seq = self.wal.append(adds, removes)
        if self.metrics is not None:
            self.metrics.inc("engine.ckpt.wal_records")
        tp("engine.ckpt.wal", seq=seq, adds=len(adds), removes=len(removes))

    # ----------------------------------------------------------- snapshot

    def due(self, now: Optional[float] = None) -> bool:
        now = now if now is not None else time.monotonic()
        with self._lock:
            if now - self._last_snap >= self.interval:
                return True
        return self.wal.pending_bytes() >= self.wal_max_bytes

    def capture(self):
        """Snapshot host truth (fast array copies + the WAL watermark).
        Must run serialized with engine mutations — the event loop, or
        any caller that owns the engine."""
        watermark = self.wal.last_seq()
        arrays, meta = self.engine.export_checkpoint()
        if self.retained is not None and len(self.retained):
            r_arr, r_meta = self.retained.export_state()
            for k, v in r_arr.items():
                arrays["ret/" + k] = v
            meta["retained"] = r_meta
        meta["wal_seq"] = watermark
        meta["wall_time"] = time.time()
        return arrays, meta, watermark

    def write(self, payload) -> Optional[str]:
        """Serialize + fsync a captured payload; ack the WAL through the
        captured watermark.  Thread-safe vs concurrent appends."""
        arrays, meta, watermark = payload
        t0 = time.monotonic()
        try:
            path = self.store.save(arrays, meta)
        except Exception as e:
            with self._lock:
                self.save_failures += 1
                self._alarm_error = {
                    "details": {"error": str(e)},
                    "message": "engine table checkpoint failed",
                }
                self._alarm_dirty = True
            if self.metrics is not None:
                self.metrics.inc("engine.ckpt.save_failures")
            log.exception("checkpoint save failed")
            return None
        self.wal.ack_through(watermark)
        with self._lock:
            self._last_snap = time.monotonic()
            self.save_count += 1
            self._alarm_error = None
            self._alarm_dirty = True
        if self.metrics is not None:
            self.metrics.inc("engine.ckpt.saves")
        tp("engine.ckpt.save", path=path, wal_seq=watermark,
           n_filters=self.engine.n_filters,
           dt_ms=(time.monotonic() - t0) * 1e3)
        return path

    def poll_alarm(self) -> None:
        """Apply the pending alarm transition recorded by write()/
        restore().  Called from the node ticker on the EVENT LOOP: the
        alarm publish fans out through the whole broker dispatch path
        (retainer, sessions, cluster forward) and must never run on the
        checkpoint worker thread."""
        if self.alarms is None:
            return
        with self._lock:
            if not self._alarm_dirty:
                return
            err, self._alarm_dirty = self._alarm_error, False
        if err is not None:
            self.alarms.activate(
                ALARM_NAME, details=err["details"], message=err["message"]
            )
        else:
            self.alarms.deactivate(ALARM_NAME)

    def checkpoint(self) -> Optional[str]:
        """Capture + write in one call (tests, shutdown, bench)."""
        return self.write(self.capture())

    def maybe_checkpoint(self, now: Optional[float] = None) -> Optional[str]:
        return self.checkpoint() if self.due(now) else None

    # ------------------------------------------------------------ restore

    def restore(self) -> Optional[int]:
        """Warm restart: newest valid snapshot + WAL-tail replay.

        Returns the restored filter count, or None on a cold start (no
        usable snapshot AND no replayable WAL base).  The engine's churn
        hook is detached during replay so replayed records are not
        re-logged.
        """
        t0 = time.monotonic()
        candidates = self.store.list()
        loaded = self.store.load_newest()
        if loaded is None and candidates:
            # snapshots existed but none passed verification: the WAL
            # tail's base state is unrecoverable — cold start, keep the
            # unacked WAL on disk for post-mortem
            log.error(
                "all %d snapshot(s) failed verification; cold start",
                len(candidates),
            )
            # restore() runs on the boot worker (_warm via to_thread):
            # record the alarm for the first loop-side poll_alarm()
            with self._lock:
                self._alarm_error = {
                    "details": {"snapshots": len(candidates)},
                    "message": "no loadable engine snapshot; cold start",
                }
                self._alarm_dirty = True
            return None
        hook, self.engine.on_churn = self.engine.on_churn, None
        try:
            restored_from = None
            if loaded is not None:
                arrays, meta, restored_from = loaded
                self.engine.restore_checkpoint(arrays, meta)
                if (
                    self.retained is not None
                    and meta.get("retained") is not None
                    and len(self.retained) == 0  # not already rebuilt
                ):
                    self.retained.from_state(
                        {k[4:]: v for k, v in arrays.items()
                         if k.startswith("ret/")},
                        meta["retained"],
                    )
            replayed = 0
            for adds, removes in self.wal.replay():
                self.engine.apply_churn(adds, removes)
                replayed += 1
        finally:
            self.engine.on_churn = hook
        if restored_from is None and replayed == 0:
            return None
        n = self.engine.n_filters
        self._restored_refs = self.engine.ref_snapshot()
        if self.metrics is not None:
            self.metrics.inc("engine.ckpt.restores")
        tp("engine.ckpt.restore", snapshot=restored_from,
           wal_records=replayed, n_filters=n,
           fallbacks=self.store.fallbacks,
           dt_ms=(time.monotonic() - t0) * 1e3)
        log.info(
            "engine warm restore: %d filters from %s + %d WAL record(s) "
            "in %.1f ms", n, restored_from or "WAL only", replayed,
            (time.monotonic() - t0) * 1e3,
        )
        return n

    def reconcile_sessions(self) -> int:
        """Release the checkpoint's filter references after session
        restore re-added its own (node boot order: engine restore ->
        persistence restore -> reconcile).  The persistence layer is the
        authority on which subscriptions still exist: filters whose only
        references came from the checkpoint (their sessions expired
        while the node was down) drop to zero and leave the table;
        re-subscribed filters keep exactly their session references —
        the table stayed warm the whole time (re-subscribing an existing
        filter is a refcount bump, not a hash+placement).  Returns the
        number of references released."""
        refs = self._restored_refs
        self._restored_refs = None
        if not refs:
            return 0
        removes = []
        for filt, rc in refs.items():
            removes.extend([filt] * int(rc))
        self.engine.apply_churn([], removes)
        return len(removes)

    # -------------------------------------------------------------- close

    def close(self) -> None:
        if self.engine.on_churn == self.note_churn:
            self.engine.on_churn = None
        self.wal.close()
