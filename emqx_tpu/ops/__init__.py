"""Device-side kernels and host-side table builders for the topic engine."""
