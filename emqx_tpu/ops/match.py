"""Device-side topic-match kernels (single chip).

The hot loop the reference runs per-publish over ETS
(`apps/emqx/src/emqx_trie.erl:272-334` + `emqx_router.erl:127-144`) becomes a
batched, fully static-shape computation:

    matched[b, m] = filter-id hit by topic b under wildcard-shape m (or -1)

All arrays are fixed capacity; churn mutates them via scatter
(:func:`apply_delta_packed`) without recompilation.  Multi-chip sharding
lives in `emqx_tpu.parallel`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .tables import MatchTables, PROBE, _MIX1, _MIX2


class DeviceTables(NamedTuple):
    """HBM-resident mirror of :class:`~emqx_tpu.ops.tables.MatchTables`."""

    key_a: jax.Array  # [cap] u32, 0/0 = empty
    key_b: jax.Array  # [cap] u32
    val: jax.Array  # [cap] i32 filter id, -1 = empty
    incl: jax.Array  # [M, L] u32 0/1 level-inclusion mask
    k_a: jax.Array  # [M] u32 per-shape additive constant
    k_b: jax.Array  # [M] u32
    min_len: jax.Array  # [M] i32
    max_len: jax.Array  # [M] i32
    wild_root: jax.Array  # [M] bool
    valid: jax.Array  # [M] bool

    @staticmethod
    def from_host(t: MatchTables, device=None) -> "DeviceTables":
        # upload COPIES: device_put is async (and may alias the numpy
        # buffer on the CPU backend), while the host keeps mutating these
        # arrays in place on later churn ticks — a live reference here is
        # a data race under pipelined submits
        arrs = t.device_arrays()
        put = lambda a: jax.device_put(a.copy(), device)
        return DeviceTables(**{k: put(v) for k, v in arrs.items()})


class TopicBatch(NamedTuple):
    """A hashed publish batch (host-prepared, see ops.hashing)."""

    terms_a: jax.Array  # [B, L] u32 per-level hash terms
    terms_b: jax.Array  # [B, L] u32
    length: jax.Array  # [B] i32 true level count
    dollar: jax.Array  # [B] bool first level starts with '$'


def pattern_hashes(t: DeviceTables, batch: TopicBatch):
    """[B, M] u32 lane-a/lane-b hashes of every topic under every shape."""
    # Masked wrap-around sum over levels. incl is 0/1 so multiply == select.
    ha = (batch.terms_a[:, None, :] * t.incl[None, :, :]).sum(
        axis=-1, dtype=jnp.uint32
    ) + t.k_a[None, :]
    hb = (batch.terms_b[:, None, :] * t.incl[None, :, :]).sum(
        axis=-1, dtype=jnp.uint32
    ) + t.k_b[None, :]
    return ha, hb


def match_batch(t: DeviceTables, batch: TopicBatch) -> jax.Array:
    """Match a topic batch against the table.

    Returns ``matched [B, M] i32``: the filter id matched by topic ``b``
    under shape ``m``, or -1.  (Each shape can hit at most one filter — a
    topic has exactly one masked hash per shape.)
    """
    # Batches may carry fewer term levels than the table (upload savings:
    # terms are the transfer payload).  Shapes deeper than the batch's
    # level budget are killed by the min_len check below, so truncating
    # their inclusion rows cannot create false hits.
    Lb = batch.terms_a.shape[1]
    if Lb < t.incl.shape[1]:
        t = t._replace(incl=t.incl[:, :Lb])
    cap = t.key_a.shape[0]
    log2cap = int(cap).bit_length() - 1
    ha, hb = pattern_hashes(t, batch)

    mixed = (ha + hb * jnp.uint32(_MIX1)) * jnp.uint32(_MIX2)
    home = (mixed >> jnp.uint32(32 - log2cap)).astype(jnp.int32)  # [B, M]

    offs = jnp.arange(PROBE, dtype=jnp.int32)
    slots = (home[:, :, None] + offs[None, None, :]) & (cap - 1)  # [B, M, P]

    ka = jnp.take(t.key_a, slots, axis=0)
    kb = jnp.take(t.key_b, slots, axis=0)
    vv = jnp.take(t.val, slots, axis=0)
    hit = (ka == ha[:, :, None]) & (kb == hb[:, :, None]) & (vv >= 0)
    fid = jnp.max(jnp.where(hit, vv, -1), axis=-1)  # [B, M]

    ok = (
        t.valid[None, :]
        & (batch.length[:, None] >= t.min_len[None, :])
        & (batch.length[:, None] <= t.max_len[None, :])
        & ~(batch.dollar[:, None] & t.wild_root[None, :])
    )
    return jnp.where(ok, fid, -1)


match_batch_jit = jax.jit(match_batch)


def apply_delta_impl(
    t: DeviceTables,
    slots: jax.Array,  # [K] i32 (may be padded with -1 -> dropped)
    key_a: jax.Array,  # [K] u32
    key_b: jax.Array,  # [K] u32
    val: jax.Array,  # [K] i32
) -> DeviceTables:
    """Scatter incremental subscribe/unsubscribe deltas into the HBM mirror.

    The churn path: route mutations (`emqx_router.erl:106-123`) become a
    single scatter — no reallocation, no re-upload.
    """
    cap = t.key_a.shape[0]
    # Padding entries (slot == -1) are routed out of range and dropped by the
    # scatter, so they can never race a real update on the same slot.
    safe = jnp.where(slots >= 0, slots, cap)
    return t._replace(
        key_a=t.key_a.at[safe].set(key_a, mode="drop"),
        key_b=t.key_b.at[safe].set(key_b, mode="drop"),
        val=t.val.at[safe].set(val, mode="drop"),
    )


def apply_delta_packed_impl(t: DeviceTables, packed: jax.Array) -> DeviceTables:
    """apply_delta with all four delta columns in ONE [4, K] u32 array.

    Over a tunneled device (axon) every host->device transfer pays a
    round trip; packing turns a churn tick's four small puts into one.
    """
    slots = jax.lax.bitcast_convert_type(packed[0], jnp.int32)
    key_a = packed[1]
    key_b = packed[2]
    val = jax.lax.bitcast_convert_type(packed[3], jnp.int32)
    return apply_delta_impl(t, slots, key_a, key_b, val)


# NOT donating: pipelined _PendingMatch handles snapshot table versions
# that must survive a later sync (see fused_step_sparse).
apply_delta_packed = jax.jit(apply_delta_packed_impl)


# --------------------------------------------------- packed host<->device
#
# The tunneled dev rig (axon) has a wildly asymmetric link — measured:
# host->device ~1.3 GB/s, device->host ~5 MB/s with ~100 ms per get op
# that does NOT overlap across ops.  Dispatches on resident buffers are
# ~0.03 ms.  The e2e design therefore (a) ships the topic batch up as
# ONE packed array, (b) returns matches as ONE sparse array sized by the
# actual hit count (~6 bytes per lookup), and (c) starts the device->
# host copy asynchronously at submit time.  On co-located hardware the
# same shape discipline minimizes PCIe traffic.


def pack_topic_batch_np(ta, tb, ln, dl) -> np.ndarray:
    """Host-side: one [B, 2L+2] u32 array instead of four puts."""
    B, L = ta.shape
    out = np.empty((B, 2 * L + 2), dtype=np.uint32)
    out[:, :L] = ta
    out[:, L:2 * L] = tb
    out[:, 2 * L] = ln.astype(np.int32, copy=False).view(np.uint32)
    out[:, 2 * L + 1] = dl.astype(np.uint32)
    return out


def unpack_topic_batch(p: jax.Array) -> TopicBatch:
    """Device-side (inside jit): undo pack_topic_batch_np."""
    L = (p.shape[1] - 2) // 2
    ta = p[:, :L]
    tb = p[:, L:2 * L]
    ln = jax.lax.bitcast_convert_type(p[:, 2 * L], jnp.int32)
    dl = p[:, 2 * L + 1] != 0
    return TopicBatch(ta, tb, ln, dl)


def sparse_pack(matched: jax.Array, hcap: int) -> jax.Array:
    """[B, M] shape-hit rows -> ONE [hcap + B/2 + 1] i32 result array:

      [0:hcap]            matched fids, flattened row-major (left-packed)
      [hcap:hcap+B/2]     per-topic hit counts, u16 pairs bitcast to i32
      [-1]                total hit count (> hcap means overflow: the
                          host must refetch the full row set)

    Hits beyond hcap are dropped on device (never corrupt earlier slots).
    Per-lookup download cost is ~(4*H/B + 2) bytes instead of 4*M.
    Compaction is gather-based (cumsum + binary search): a B*M-element
    scatter serializes on TPU (~1 s at 4M elements), gathers do not."""
    B, M = matched.shape
    flat = matched.reshape(-1)
    hit = flat >= 0
    cpos = jnp.cumsum(hit.astype(jnp.int32))  # hits up to and incl. j
    total = cpos[-1]
    # the s-th hit lives at the first j with cpos[j] == s+1
    idx = jnp.searchsorted(
        cpos, jnp.arange(1, hcap + 1, dtype=jnp.int32), side="left"
    )
    fids = jnp.where(
        jnp.arange(hcap) < total,
        jnp.take(flat, jnp.minimum(idx, B * M - 1)),
        -1,
    )
    # u16-saturated per-topic counts; 0xFFFF tells the host to refetch
    counts = jnp.minimum(
        jnp.sum(matched >= 0, axis=-1, dtype=jnp.int32), 0xFFFF
    ).astype(jnp.uint16)
    counts2 = jax.lax.bitcast_convert_type(
        counts.reshape(B // 2, 2), jnp.int32
    )
    return jnp.concatenate([fids, counts2, total[None]])


@functools.partial(jax.jit, static_argnames=("hcap",))
def match_batch_sparse(t: DeviceTables, pbatch: jax.Array, *, hcap: int):
    return sparse_pack(match_batch(t, unpack_topic_batch(pbatch)), hcap)


@functools.partial(jax.jit, static_argnames=("hcap",))
def fused_step_sparse(
    t: DeviceTables, packed: jax.Array, pbatch: jax.Array, *, hcap: int
):
    """Churn scatter + match + sparse compaction in ONE dispatch — the
    single-chip flagship step (delta upload rides the same round trip).

    Deliberately NOT buffer-donating: pipelined submits keep references
    to earlier table versions (for the sparse-overflow refetch, which
    must see the tables AS OF ITS OWN TICK); the non-donated scatter
    costs one on-device table copy (~HBM bandwidth, sub-ms even at 10M
    entries) per churn tick."""
    t = apply_delta_packed_impl(t, packed)
    return t, sparse_pack(match_batch(t, unpack_topic_batch(pbatch)), hcap)


@jax.jit
def match_batch_packed(t: DeviceTables, pbatch: jax.Array) -> jax.Array:
    """Full [B, M] row set from a packed batch (sparse-overflow fallback)."""
    return match_batch(t, unpack_topic_batch(pbatch))


def compact_topk(matched: jax.Array, k: int) -> jax.Array:
    """[B, M] hit rows -> the k largest entries per row, descending,
    -1 padded — k iterative max+mask passes instead of `jax.lax.top_k`.

    Correct as top-k whenever rows are duplicate-free (each publish
    shape hits at most one fid; retained bucket candidates are distinct
    row ids).  On the CPU mesh the sort-based `top_k` was ~40% of the
    whole dispatch (measured: 9.5 ms -> 5.7 ms per 512-topic tick at
    M=32); with an adaptive kcap keeping k small the k passes are
    O(k*B*M) elementwise ops, no sort anywhere.  Shared by the sharded
    publish dispatch and the retained-index probe kernel."""
    outs = []
    m = matched
    idx = jnp.arange(m.shape[-1], dtype=jnp.int32)[None, :]
    for _ in range(k):
        mx = jnp.max(m, axis=-1)
        outs.append(mx)
        am = jnp.argmax(m, axis=-1).astype(jnp.int32)
        m = jnp.where(idx == am[:, None], -1, m)
    return jnp.stack(outs, axis=-1)  # [B, k]


@functools.partial(jax.jit, static_argnames=("kcap",))
def semantic_topk(table: jax.Array, valid: jax.Array, batch: jax.Array,
                  *, kcap: int):
    """Cosine top-k over a device-resident query-vector table.

    ``table [Q, D]`` rows are pre-normalized query embeddings, ``valid
    [Q]`` masks live rows, ``batch [B, D]`` pre-normalized publish
    embeddings; cosine reduces to one matmul — the shape this device is
    built for.  Returns ``(scores [B, kcap] f32, idxs [B, kcap] i32)``
    descending per row, dead columns at score -2.0 / idx -1.

    The k extraction is compact_topk's float sibling: kcap iterative
    max+argmax+mask passes, no sort (duplicate scores are fine — argmax
    ties break by lowest index, so passes never revisit a column).  kcap
    is a static arg managed by the engine's adaptive-kcap discipline;
    membership itself is decided host-side by the exact scorer over
    these candidates, so float drift here can only cost a refetch,
    never a wrong match set."""
    scores = batch @ table.T  # [B, Q]
    scores = jnp.where(valid[None, :], scores, jnp.float32(-2.0))
    idx = jnp.arange(scores.shape[-1], dtype=jnp.int32)[None, :]
    vals, idxs = [], []
    m = scores
    for _ in range(kcap):
        mx = jnp.max(m, axis=-1)
        am = jnp.argmax(m, axis=-1).astype(jnp.int32)
        vals.append(mx)
        idxs.append(jnp.where(mx > jnp.float32(-2.0), am, -1))
        m = jnp.where(idx == am[:, None], jnp.float32(-2.0), m)
    return jnp.stack(vals, axis=-1), jnp.stack(idxs, axis=-1)


def make_topic_batch(ta: np.ndarray, tb: np.ndarray, ln: np.ndarray, dl: np.ndarray, device=None) -> TopicBatch:
    put = lambda a: jax.device_put(a, device)
    return TopicBatch(put(ta), put(tb), put(ln), put(dl))


def next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def live_levels(max_levels: int, lengths: np.ndarray) -> int:
    """Term levels actually worth uploading for a batch: its real max
    depth, rounded UP to the next EVEN count so the kernel compiles at
    most max_levels/2 variants (a fresh depth otherwise pays a
    multi-second XLA compile mid-traffic) while wasting at most one
    level of upload bytes.  Shared by the single-chip and sharded
    submit paths so their wire-floor arithmetic stays identical."""
    L_real = max(1, min(max_levels, int(lengths.max(initial=1))))
    return min(max_levels, L_real + (L_real & 1))


def prepare_topic_batch(space, word_lists, min_batch: int = 64):
    """Hash + pad a publish batch to a power-of-two size (limits retraces).

    Padded rows get length -1, which fails every shape's min_len check, so
    they can never match.  Returns (TopicBatch of numpy arrays, n_real).
    """
    from . import hashing

    ta, tb, ln, dl = hashing.hash_topic_batch(space, word_lists)
    return _pad_batch(ta, tb, ln, dl, len(word_lists), min_batch)


def prepare_topics_raw(space, topics, min_batch: int = 64):
    """Like prepare_topic_batch but straight from topic strings, using the
    C++ split+hash fast path when available."""
    from . import hashing

    ta, tb, ln, dl = hashing.hash_topics(space, list(topics))
    return _pad_batch(ta, tb, ln, dl, len(topics), min_batch)


def _pad_batch(ta, tb, ln, dl, n: int, min_batch: int):
    B = max(min_batch, next_pow2(n))
    if B > n:
        pad = B - n
        ta = np.pad(ta, ((0, pad), (0, 0)))
        tb = np.pad(tb, ((0, pad), (0, 0)))
        ln = np.pad(ln, (0, pad), constant_values=-1)
        dl = np.pad(dl, (0, pad))
    return TopicBatch(ta, tb, ln, dl), n
