"""Topic/filter hashing for the flattened TPU match tables.

The reference walks a per-level trie with branching on ``+``/``#``
(`apps/emqx/src/emqx_trie.erl:272-334`).  That shape-dynamic walk is hostile to
XLA, so the TPU engine replaces it with *pattern-hash enumeration*:

* every subscription filter has a **wildcard shape** — a bitmask of which
  levels are ``+`` plus an optional ``#`` cut point;
* a filter is stored once in an open-addressed hash table under the hash of
  its word sequence with ``+`` levels replaced by a sentinel;
* matching a topic = for each *distinct shape present in the table* (typically
  tens, even with millions of filters), compute the topic's hash under that
  shape's mask and probe the table.  All shapes are static; the per-shape
  plus-substitutions and ``#`` marker fold into one precomputed additive
  constant per shape, so the device only ever combines per-(topic, level)
  terms with a masked sum.

Hash construction (all mod 2**32, two independent lanes a/b):

    term_a[l]  = ((word_a[l] ^ C_a[l]) * R_a[l])          # per topic level
    h_a(shape) = sum_{l < plen, l not plus} term_a[l] + K_a[shape]
    K_a(shape) = sum_{l plus} ((PLUS_a ^ C_a[l]) * R_a[l]) + (#? HM_a * HR_a[plen])

The host computes the same formula when inserting filters; host and device
agree bit-for-bit because both use wrapping 32-bit arithmetic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np

# Maximum topic levels handled by the device fast path. Deeper topics fall
# back to the host matcher (see models/engine.py); the reference bounds trie
# depth the same way via prefix compaction (emqx_trie.erl:202-233).
DEFAULT_MAX_LEVELS = 16

_U32 = 0xFFFFFFFF
_PERTURB = 0xD6E8FEB86659FD93  # avoid hash('') == 0
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3


def word_hash64(word: str) -> int:
    """Deterministic 64-bit hash of one topic level (FNV-1a ^ perturb).

    Deterministic across processes — unlike Python's randomized `hash()` —
    so cluster peers and checkpoint restores agree on table keys.  The
    native batch path (native/matchhash.cc) computes the identical value.
    """
    h = _FNV_OFFSET
    for byte in word.encode("utf-8"):
        h = ((h ^ byte) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h ^ _PERTURB


class HashSpace:
    """Per-level mixing constants shared by host builder and device kernels."""

    def __init__(self, max_levels: int = DEFAULT_MAX_LEVELS, seed: int = 0x5EED):
        self.max_levels = max_levels
        rng = np.random.RandomState(seed)

        def u32s(n):
            return rng.randint(0, 1 << 32, size=n, dtype=np.uint64).astype(np.uint32)

        # Per-level xor constants and odd multipliers, one pair of lanes.
        self.C = np.stack([u32s(max_levels), u32s(max_levels)])  # [2, L]
        self.R = np.stack([u32s(max_levels) | 1, u32s(max_levels) | 1])  # [2, L]
        # '#'-marker multipliers indexed by prefix length (0..L inclusive).
        self.HR = np.stack([u32s(max_levels + 1) | 1, u32s(max_levels + 1) | 1])
        self.PLUS = u32s(2)  # sentinel word-hash lanes for '+'
        self.HM = u32s(2)  # '#' marker lanes

    # -- host-side scalar helpers (match device arithmetic bit-for-bit) ----

    def _term(self, lane: int, w: int, level: int) -> int:
        return ((w ^ int(self.C[lane, level])) * int(self.R[lane, level])) & _U32

    def word_lanes(self, word: str) -> Tuple[int, int]:
        h = word_hash64(word)
        return h & _U32, (h >> 32) & _U32

    def topic_terms(self, words: Sequence[str]) -> np.ndarray:
        """[2, L] per-level terms for a topic (zero-padded past len(words))."""
        out = np.zeros((2, self.max_levels), dtype=np.uint32)
        for l, w in enumerate(words[: self.max_levels]):
            a, b = self.word_lanes(w)
            out[0, l] = self._term(0, a, l)
            out[1, l] = self._term(1, b, l)
        return out

    def shape_of(self, filter_words: Sequence[str]) -> "Shape":
        """Extract the wildcard shape of a filter."""
        has_hash = bool(filter_words) and filter_words[-1] == "#"
        body = filter_words[:-1] if has_hash else list(filter_words)
        plus_mask = 0
        for l, w in enumerate(body):
            if w == "+":
                plus_mask |= 1 << l
        return Shape(plen=len(body), plus_mask=plus_mask, has_hash=has_hash)

    def shape_const(self, shape: "Shape") -> Tuple[int, int]:
        """Per-shape additive constant K (both lanes)."""
        ka = kb = 0
        for l in range(shape.plen):
            if shape.plus_mask >> l & 1:
                ka = (ka + self._term(0, int(self.PLUS[0]), l)) & _U32
                kb = (kb + self._term(1, int(self.PLUS[1]), l)) & _U32
        if shape.has_hash:
            ka = (ka + int(self.HM[0]) * int(self.HR[0, shape.plen])) & _U32
            kb = (kb + int(self.HM[1]) * int(self.HR[1, shape.plen])) & _U32
        return ka, kb

    def filter_key(self, filter_words: Sequence[str]) -> Tuple[int, int, "Shape"]:
        """Full (h_a, h_b) table key of a subscription filter + its shape."""
        shape = self.shape_of(filter_words)
        ka, kb = self.shape_const(shape)
        ha, hb = ka, kb
        for l in range(shape.plen):
            if not (shape.plus_mask >> l & 1):
                a, b = self.word_lanes(filter_words[l])
                ha = (ha + self._term(0, a, l)) & _U32
                hb = (hb + self._term(1, b, l)) & _U32
        if ha == 0 and hb == 0:  # (0,0) is the empty-slot sentinel
            hb = 1
        return ha, hb, shape


@dataclass(frozen=True)
class Shape:
    """A wildcard shape: which levels are '+', and the '#' cut point."""

    plen: int  # number of explicit levels (excluding '#')
    plus_mask: int  # bit l set => level l is '+'
    has_hash: bool

    @property
    def wild_root(self) -> bool:
        """Shape has a wildcard at level 0 (never matches $-topics)."""
        return bool(self.plus_mask & 1) or (self.has_hash and self.plen == 0)

    def min_len(self) -> int:
        return self.plen

    def max_len(self, max_levels: int) -> int:
        # '#' matches any number of trailing levels: a topic deeper than the
        # device level cap still matches, since only the first plen(<=cap)
        # levels contribute to the hash.
        return (1 << 30) if self.has_hash else self.plen


def hash_topic_batch(
    space: HashSpace, topics: List[List[str]]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side preparation of a publish batch for the device kernel.

    Returns (terms_a [B, L] u32, terms_b [B, L] u32, lengths [B] i32,
    dollar [B] bool).  This is the hot host loop; see ops/native for the C++
    fast path.
    """
    B = len(topics)
    L = space.max_levels
    ta = np.zeros((B, L), dtype=np.uint32)
    tb = np.zeros((B, L), dtype=np.uint32)
    ln = np.zeros(B, dtype=np.int32)
    dl = np.zeros(B, dtype=bool)
    Ca = [int(x) for x in space.C[0]]
    Cb = [int(x) for x in space.C[1]]
    Ra = [int(x) for x in space.R[0]]
    Rb = [int(x) for x in space.R[1]]
    for i, ws in enumerate(topics):
        ln[i] = len(ws)
        dl[i] = bool(ws) and ws[0].startswith("$")
        for l, w in enumerate(ws[:L]):
            h = word_hash64(w)
            a, b = h & _U32, (h >> 32) & _U32
            ta[i, l] = ((a ^ Ca[l]) * Ra[l]) & _U32
            tb[i, l] = ((b ^ Cb[l]) * Rb[l]) & _U32
    return ta, tb, ln, dl


def hash_topics(
    space: HashSpace, topics: List[str]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Prepare a publish batch straight from topic STRINGS.

    Uses the C++ fast path (native/matchhash.cc etpu_prep_topics: split +
    fnv1a64 + mix terms in one pass over the packed batch) when available,
    else splits on '/' and runs the Python loop above.
    """
    from . import native

    out = native.prep_topics(
        topics, space.max_levels,
        space.C[0], space.C[1], space.R[0], space.R[1],
    )
    if out is not None:
        return out
    return hash_topic_batch(space, [t.split("/") for t in topics])
