"""Pallas TPU kernel for the pattern-hash contraction.

The match hot op (`ops/match.py pattern_hashes`) is a masked wrap-around
sum: ``h[b, m] = sum_l terms[b, l] * incl[m, l] + k[m]`` over u32 — a
[B, L] x [M, L] contraction, the device-side analog of the per-level
trie walk in `emqx_trie.erl:272-334`.  XLA already fuses this well; the
Pallas version tiles it explicitly over (B, M) so both operand tiles sit
in VMEM and the two lanes (a/b) are computed in one pass over the terms
tile, halving HBM reads of `incl`.

The kernel is exact u32 wraparound arithmetic, bit-identical to the XLA
path (tests compare both).  `match_batch_pallas` drops into the same
probe/compare epilogue as `match_batch` — dynamic gathers stay in XLA,
which lowers them natively.

Enable per call (`match_batch_pallas`) or process-wide via the
``EMQX_TPU_PALLAS=1`` environment variable (`pattern_hashes_auto`).
Falls back to the XLA path on platforms without Mosaic support.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .match import DeviceTables, TopicBatch, PROBE, _MIX1, _MIX2


def _hash_kernel(ta_ref, tb_ref, incl_ref, ka_ref, kb_ref, ha_ref, hb_ref):
    """One (B-tile, M-tile) block: both lanes in a single pass."""
    ta = ta_ref[:]          # [bB, L] u32
    tb = tb_ref[:]          # [bB, L] u32
    incl = incl_ref[:]      # [bM, L] u32 (0/1)
    # u32 multiply-add wraps mod 2^32 — exactly the host/table arithmetic
    ha = (ta[:, None, :] * incl[None, :, :]).sum(axis=-1, dtype=jnp.uint32)
    hb = (tb[:, None, :] * incl[None, :, :]).sum(axis=-1, dtype=jnp.uint32)
    ha_ref[:] = ha + ka_ref[:][None, :]
    hb_ref[:] = hb + kb_ref[:][None, :]


@functools.partial(jax.jit, static_argnames=("block_b", "block_m", "interpret"))
def pattern_hashes_pallas(
    t: DeviceTables, batch: TopicBatch,
    block_b: int = 256, block_m: int = 128, interpret: bool = False,
):
    """[B, M] u32 hashes of every topic under every shape (Pallas path)."""
    B, L = batch.terms_a.shape
    M = t.incl.shape[0]
    bB = min(block_b, B)
    bM = min(block_m, M)
    # grid must tile exactly: B and M are already powers of two (the batch
    # is padded by _pad_batch; table capacities are pow2), so any smaller
    # pow2 block divides them
    assert B % bB == 0 and M % bM == 0, (B, bB, M, bM)
    grid = (B // bB, M // bM)
    ha, hb = pl.pallas_call(
        _hash_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bB, L), lambda i, j: (i, 0)),
            pl.BlockSpec((bB, L), lambda i, j: (i, 0)),
            pl.BlockSpec((bM, L), lambda i, j: (j, 0)),
            pl.BlockSpec((bM,), lambda i, j: (j,)),
            pl.BlockSpec((bM,), lambda i, j: (j,)),
        ],
        out_specs=[
            pl.BlockSpec((bB, bM), lambda i, j: (i, j)),
            pl.BlockSpec((bB, bM), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, M), jnp.uint32),
            jax.ShapeDtypeStruct((B, M), jnp.uint32),
        ],
        interpret=interpret,
    )(batch.terms_a, batch.terms_b, t.incl, t.k_a, t.k_b)
    return ha, hb


def match_batch_pallas(t: DeviceTables, batch: TopicBatch,
                       interpret: bool = False) -> jax.Array:
    """`match_batch` with the hash contraction on the Pallas path."""
    cap = t.key_a.shape[0]
    log2cap = int(cap).bit_length() - 1
    ha, hb = pattern_hashes_pallas(t, batch, interpret=interpret)

    mixed = (ha + hb * jnp.uint32(_MIX1)) * jnp.uint32(_MIX2)
    home = (mixed >> jnp.uint32(32 - log2cap)).astype(jnp.int32)
    offs = jnp.arange(PROBE, dtype=jnp.int32)
    slots = (home[:, :, None] + offs[None, None, :]) & (cap - 1)
    ka = jnp.take(t.key_a, slots, axis=0)
    kb = jnp.take(t.key_b, slots, axis=0)
    vv = jnp.take(t.val, slots, axis=0)
    hit = (ka == ha[:, :, None]) & (kb == hb[:, :, None]) & (vv >= 0)
    fid = jnp.max(jnp.where(hit, vv, -1), axis=-1)
    ok = (
        t.valid[None, :]
        & (batch.length[:, None] >= t.min_len[None, :])
        & (batch.length[:, None] <= t.max_len[None, :])
        & ~(batch.dollar[:, None] & t.wild_root[None, :])
    )
    return jnp.where(ok, fid, -1)


match_batch_pallas_jit = jax.jit(match_batch_pallas,
                                 static_argnames=("interpret",))


def pallas_enabled() -> bool:
    return os.environ.get("EMQX_TPU_PALLAS", "") == "1"
