"""Pallas TPU kernel for the pattern-hash contraction.

The match hot op (`ops/match.py pattern_hashes`) is a masked wrap-around
sum: ``h[b, m] = sum_l terms[b, l] * incl[m, l] + k[m]`` over u32 — a
[B, L] x [M, L] contraction, the device-side analog of the per-level
trie walk in `emqx_trie.erl:272-334`.  XLA already fuses this well; the
Pallas version tiles it explicitly over (B, M) so both operand tiles sit
in VMEM and the two lanes (a/b) are computed in one pass over the terms
tile, halving HBM reads of `incl`.

The kernel is exact u32-wraparound arithmetic (done in int32 — Mosaic
has no unsigned reductions; two's complement wraps identically),
bit-identical to the XLA path (tests compare both, and a real-TPU run
confirmed `matches_xla=True`).  `match_batch_pallas` drops into the same
probe/compare epilogue as `match_batch` — dynamic gathers stay in XLA,
which lowers them natively.

Status: EXPERIMENTAL, off by default.  Measured on a v5 lite chip
(100k filters, batch 4096): XLA fused path ~0.03-0.2 ms/batch vs this
kernel ~46 ms/batch — XLA's fusion of the masked-sum contraction +
gather is already near-optimal, so the production path stays XLA.  The
kernel remains as the scaffold for a future fused hash+probe kernel
(the gather is the next thing to pull into VMEM).

Enable per call (`match_batch_pallas`) or process-wide via the
``EMQX_TPU_PALLAS=1`` environment variable.  The engine falls back to
the XLA path if Mosaic rejects the platform.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .match import DeviceTables, TopicBatch, PROBE, _MIX1, _MIX2


def _hash_kernel(ta_ref, tb_ref, incl_ref, ka_ref, kb_ref, ha_ref, hb_ref):
    """One (B-tile, M-tile) block: both lanes in a single pass.

    All operands arrive bitcast to int32: Mosaic has no unsigned
    reductions, and two's-complement add/mul wrap bit-identically to the
    u32 arithmetic of the host tables.
    """
    ta = ta_ref[:]          # [bB, L] i32 (u32 bits)
    tb = tb_ref[:]          # [bB, L] i32
    incl = incl_ref[:]      # [bM, L] i32 (0/1)
    # L statically-unrolled rank-1 updates: every op is 2D with the shape
    # [bB, bM] (lane dim = bM), avoiding a [bB, bM, L] intermediate whose
    # minor axis is only L wide — hostile to the (8, 128) VPU tiling.
    L = ta.shape[1]
    ha = ka_ref[:][None, :] * jnp.ones((ta.shape[0], 1), jnp.int32)
    hb = kb_ref[:][None, :] * jnp.ones((ta.shape[0], 1), jnp.int32)
    for l in range(L):
        ha = ha + ta[:, l][:, None] * incl[:, l][None, :]
        hb = hb + tb[:, l][:, None] * incl[:, l][None, :]
    ha_ref[:] = ha
    hb_ref[:] = hb


@functools.partial(jax.jit, static_argnames=("block_b", "block_m", "interpret"))
def pattern_hashes_pallas(
    t: DeviceTables, batch: TopicBatch,
    block_b: int = 256, block_m: int = 128, interpret: bool = False,
):
    """[B, M] u32 hashes of every topic under every shape (Pallas path)."""
    B, L = batch.terms_a.shape
    M = t.incl.shape[0]
    bB = min(block_b, B)
    bM = min(block_m, M)
    # grid must tile exactly: B and M are already powers of two (the batch
    # is padded by _pad_batch; table capacities are pow2), so any smaller
    # pow2 block divides them
    assert B % bB == 0 and M % bM == 0, (B, bB, M, bM)
    grid = (B // bB, M // bM)
    i32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.int32)
    ha, hb = pl.pallas_call(
        _hash_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bB, L), lambda i, j: (i, 0)),
            pl.BlockSpec((bB, L), lambda i, j: (i, 0)),
            pl.BlockSpec((bM, L), lambda i, j: (j, 0)),
            pl.BlockSpec((bM,), lambda i, j: (j,)),
            pl.BlockSpec((bM,), lambda i, j: (j,)),
        ],
        out_specs=[
            pl.BlockSpec((bB, bM), lambda i, j: (i, j)),
            pl.BlockSpec((bB, bM), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, M), jnp.int32),
            jax.ShapeDtypeStruct((B, M), jnp.int32),
        ],
        interpret=interpret,
    )(i32(batch.terms_a), i32(batch.terms_b), i32(t.incl),
      i32(t.k_a), i32(t.k_b))
    u32 = lambda x: jax.lax.bitcast_convert_type(x, jnp.uint32)
    return u32(ha), u32(hb)


def match_batch_pallas(t: DeviceTables, batch: TopicBatch,
                       interpret: bool = False) -> jax.Array:
    """`match_batch` with the hash contraction on the Pallas path."""
    cap = t.key_a.shape[0]
    log2cap = int(cap).bit_length() - 1
    ha, hb = pattern_hashes_pallas(t, batch, interpret=interpret)

    mixed = (ha + hb * jnp.uint32(_MIX1)) * jnp.uint32(_MIX2)
    home = (mixed >> jnp.uint32(32 - log2cap)).astype(jnp.int32)
    offs = jnp.arange(PROBE, dtype=jnp.int32)
    slots = (home[:, :, None] + offs[None, None, :]) & (cap - 1)
    ka = jnp.take(t.key_a, slots, axis=0)
    kb = jnp.take(t.key_b, slots, axis=0)
    vv = jnp.take(t.val, slots, axis=0)
    hit = (ka == ha[:, :, None]) & (kb == hb[:, :, None]) & (vv >= 0)
    fid = jnp.max(jnp.where(hit, vv, -1), axis=-1)
    ok = (
        t.valid[None, :]
        & (batch.length[:, None] >= t.min_len[None, :])
        & (batch.length[:, None] <= t.max_len[None, :])
        & ~(batch.dollar[:, None] & t.wild_root[None, :])
    )
    return jnp.where(ok, fid, -1)


match_batch_pallas_jit = jax.jit(match_batch_pallas,
                                 static_argnames=("interpret",))


def pallas_enabled() -> bool:
    return os.environ.get("EMQX_TPU_PALLAS", "") == "1"
