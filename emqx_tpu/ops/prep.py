"""Fused publish-tick prep: split + hash + topic memo + dedup + pack.

Prep was ~80% of a sharded-mesh tick's host time (BENCH_TABLE.md mesh
phase columns pre-PR 12): per-tick Python memo walks, four gathered
arrays, and a staging-buffer fill, all GIL-bound.  This module collapses
the whole stage into ONE native pass (`native/prep.cc etpu_prep_hash` +
`etpu_prep_pack`, sharing `match_core.h` topic hashing with
`matchhash.cc`): the two-generation topic memo moves behind the native
boundary — C++-owned, the ChurnPlane discipline — and the split, hash,
memo lookup/promotion, in-tick dedup, and bucket-padded `[B, 2L+2]` u32
buffer fill run GIL-released, parallel over the worker pool.

Two classes:

* :class:`TopicPrep` — the prep op front.  Native plane when the lib is
  present; otherwise the pure-Python two-generation memo (moved here
  from `parallel/sharded.py`, PR 7) serves as the lib-less fallback AND
  as the serial oracle the fused-prep property test pins bit-for-bit
  (hashes, memo promotion behavior, bucket padding, dedup order).  Also
  owns the persistent staging-buffer pool ("pre-pinned" per-(B, L)
  buffers recycled across ticks).
* :class:`PrepStage` — the prep-ahead pipeline stage: a persistent
  worker thread that runs `TopicPrep.pack` for tick N+1..N+depth while
  tick N's dispatch is in flight.  Tickets degrade safely: a stalled
  worker (fault site ``engine.prep``) makes the consumer fall back to
  inline prep instead of freezing the dispatch window.

Thread model: `TopicPrep` state mutates under ONE lock (the prep-ahead
worker and the engine's inline path share the memo); `PrepTicket`
handoff is an Event + per-ticket lock; the stage's submit-order list is
only touched on the submitter's thread.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import native as _native
from .match import next_pow2

__all__ = ["TopicPrep", "PrepStage", "PrepTicket", "PrepResult"]


class PrepResult:
    """One packed tick: the `[B, 2L+2]` u32 staging buffer plus the
    sub-stage attribution the flight recorder records per tick."""

    __slots__ = ("buf", "n", "B", "L", "key", "hash_s", "pack_s",
                 "hits", "misses")

    def __init__(self, buf, n, B, L, key, hash_s, pack_s, hits, misses):
        self.buf = buf
        self.n = n
        self.B = B
        self.L = L
        self.key = key  # (B, L): the staging-pool bucket
        self.hash_s = hash_s  # split+hash+memo+dedup seconds
        self.pack_s = pack_s  # gather+pad seconds
        self.hits = hits  # memo hits this tick (in-tick dups included)
        self.misses = misses  # unique new topics this tick


class TopicPrep:
    """Fused prep front (see module docstring).

    All public entry points serialize on one lock: the prep-ahead worker
    and the engine's inline path share the memo, and the native plane is
    not internally synchronized (ChurnPlane discipline).
    """

    def __init__(self, space, cap: int = 1 << 16, min_batch: int = 64,
                 use_native: bool = True):
        self.space = space
        self.min_batch = min_batch
        self._lock = threading.Lock()
        self.plane = _native.make_prep_plane(space, cap) if use_native \
            else None
        self._cap = cap
        # ---- pure-Python fallback memo (PR 7 semantics, bit-for-bit
        # the native plane's contract; also the property-test oracle).
        # Every access to this state runs under self._lock — the public
        # entry points (pack / hash_rows / the counter properties) hold
        # it around the private memo helpers, which the races pass
        # cannot see through the call graph, hence the annotations.
        self._memo: Dict[str, int] = {}  # analysis: owner=any
        self._memo_old: Dict[str, int] = {}  # analysis: owner=any
        L = space.max_levels
        self._memo_ta = np.empty((1024, L), dtype=np.uint32)  # analysis: owner=any
        self._memo_tb = np.empty((1024, L), dtype=np.uint32)  # analysis: owner=any
        self._memo_ln = np.empty(1024, dtype=np.int32)  # analysis: owner=any
        self._memo_dl = np.empty(1024, dtype=np.uint8)  # analysis: owner=any
        self._memo_n = 0  # filled rows in the memo arrays  # analysis: owner=any
        self._py_hits = 0  # analysis: owner=any
        self._py_misses = 0  # analysis: owner=any
        # ---- persistent staging-buffer pool: per-(B, L) recycled
        # buffers (np.empty is fine: live rows are fully rewritten and
        # padded rows only need their length column — stale terms in the
        # pad region can never match, min_len kills the row)
        self._bufs: Dict[Tuple[int, int], List[np.ndarray]] = {}
        self.buf_keep = 8  # per-key retention (>= window depth + slack)

    # ------------------------------------------------------------ counters

    @property
    def hits(self) -> int:
        with self._lock:
            if self.plane is not None:
                return self.plane.stats()[0]
            return self._py_hits

    @property
    def misses(self) -> int:
        with self._lock:
            if self.plane is not None:
                return self.plane.stats()[1]
            return self._py_misses

    @property
    def live_n(self) -> int:
        """Entries in the live memo generation."""
        with self._lock:
            if self.plane is not None:
                return self.plane.stats()[2]
            return len(self._memo)

    @property
    def old_n(self) -> int:
        """Entries in the old (second-chance) generation."""
        with self._lock:
            if self.plane is not None:
                return self.plane.stats()[3]
            return len(self._memo_old)

    @property
    def cap(self) -> int:
        return self._cap

    @cap.setter
    def cap(self, v: int) -> None:
        with self._lock:
            self._cap = int(v)
            if self.plane is not None:
                self.plane.set_cap(int(v))

    def memo_gen(self, topic: str) -> int:
        """Generation holding the topic: 0 live, 1 old-only, -1 absent
        (tests/introspection)."""
        with self._lock:
            if self.plane is not None:
                return self.plane.lookup_gen(topic)
            if topic in self._memo:
                return 0
            return 1 if topic in self._memo_old else -1

    # ------------------------------------------------------ staging pool

    def acquire(self, key: Tuple[int, int]) -> np.ndarray:
        with self._lock:
            pool = self._bufs.get(key)
            if pool:
                return pool.pop()
        B, L = key
        return np.empty((B, 2 * L + 2), dtype=np.uint32)

    def release(self, buf: Optional[np.ndarray],
                key: Optional[Tuple[int, int]]) -> None:
        if buf is None or key is None:
            return
        with self._lock:
            pool = self._bufs.setdefault(key, [])
            if len(pool) < self.buf_keep:
                pool.append(buf)

    def reset_buffers(self) -> None:
        """Drop pooled staging buffers (checkpoint restore: in-flight
        pendings were discarded, their buffers with them)."""
        with self._lock:
            self._bufs = {}

    # ------------------------------------------------------------ prep op

    def _bucket(self, n: int, maxlen: int) -> Tuple[int, int]:
        """(B, L) for an n-topic batch whose deepest topic has `maxlen`
        levels — `ops.match.live_levels` arithmetic from the scalar."""
        B = max(self.min_batch, next_pow2(max(n, 1)))
        L_real = max(1, min(self.space.max_levels, maxlen))
        L = min(self.space.max_levels, L_real + (L_real & 1))
        return B, L

    def pack(self, topics: List[str], reuse: bool = True,
             out_alloc=None) -> PrepResult:
        """ONE fused prep pass: split + hash + memo + in-tick dedup +
        bucket-padded pack of a publish tick into a `[B, 2L+2]` u32
        staging buffer (`ops.match.pack_topic_batch_np` layout).

        ``reuse=False`` packs into a fresh buffer outside the pool (for
        callers whose buffer lifetime outlives the tick, e.g. the
        single-chip engine's pipelined pendings).

        ``out_alloc`` is the zero-copy hook for the shm match plane: a
        callable ``(B, L) -> ndarray[B, 2L+2] u32 | None`` invoked once
        the bucket geometry is known.  When it returns a buffer (e.g. a
        view straight into a shared-memory ring slot) the batch is
        packed INTO it with no extra copy and the returned result has
        ``key=None`` — it must never be pool-released.  Returning None
        (geometry doesn't fit the slot) falls back to the pool path and
        the caller can tell by checking ``res.key``."""
        n = len(topics)
        with self._lock:
            if self.plane is not None:
                t0 = time.perf_counter()
                tbuf, toffs = _native.pack_strs(topics)
                maxlen, _ns, bh, bm = self.plane.hash_batch(tbuf, toffs, n)
                t1 = time.perf_counter()
                B, L = self._bucket(n, maxlen)
                key = (B, L)
                buf = out_alloc(B, L) if out_alloc is not None else None
                if buf is not None:
                    key = None
                else:
                    buf = self._acquire_locked(key) if reuse else \
                        np.empty((B, 2 * L + 2), dtype=np.uint32)
                self.plane.pack_into(n, B, L, buf)
                t2 = time.perf_counter()
                return PrepResult(buf, n, B, L, key, t1 - t0, t2 - t1,
                                  bh, bm)
            t0 = time.perf_counter()
            h0, m0 = self._py_hits, self._py_misses
            ta, tb, ln, dl = self._hash_topics_memo(topics)
            h1, m1 = self._py_hits, self._py_misses
            t1 = time.perf_counter()
            maxlen = int(ln.max(initial=1)) if n else 1
            B, L = self._bucket(n, maxlen)
            key = (B, L)
            buf = out_alloc(B, L) if out_alloc is not None else None
            if buf is not None:
                key = None
            else:
                buf = self._acquire_locked(key) if reuse else \
                    np.empty((B, 2 * L + 2), dtype=np.uint32)
            buf[:n, :L] = ta[:, :L]
            buf[:n, L:2 * L] = tb[:, :L]
            buf[:n, 2 * L] = ln.view(np.uint32)
            buf[:n, 2 * L + 1] = dl
            if n < B:
                buf[n:, 2 * L] = np.uint32(0xFFFFFFFF)  # never match
            t2 = time.perf_counter()
            return PrepResult(buf, n, B, L, key, t1 - t0, t2 - t1,
                              h1 - h0, m1 - m0)

    def _acquire_locked(self, key: Tuple[int, int]) -> np.ndarray:
        pool = self._bufs.get(key)
        if pool:
            return pool.pop()
        B, L = key
        return np.empty((B, 2 * L + 2), dtype=np.uint32)

    def hash_rows(self, topics: List[str]):
        """Memoized split+hash returning full-width (ta, tb, ln, dl)
        arrays — the `TopicBatch` form (mesh `_prep_batch`, tests)."""
        n = len(topics)
        with self._lock:
            if self.plane is not None:
                tbuf, toffs = _native.pack_strs(topics)
                self.plane.hash_batch(tbuf, toffs, n)
                return self.plane.rows(n)
            return self._hash_topics_memo(topics)

    # ---------------------------------------------- python fallback memo
    # (PR 7 two-generation second-chance memo, verbatim semantics; the
    # native plane replicates these observables bit-for-bit and the
    # property test in tests/test_prep_pack.py holds them together)

    def _memo_grow(self, need: int) -> None:
        cap = len(self._memo_ln)
        while cap < need:
            cap *= 2
        L = self.space.max_levels
        for name, shape in (("_memo_ta", (cap, L)), ("_memo_tb", (cap, L)),
                            ("_memo_ln", (cap,)), ("_memo_dl", (cap,))):
            old = getattr(self, name)
            new = np.empty(shape, dtype=old.dtype)
            new[: len(old)] = old
            setattr(self, name, new)

    def _memo_swap(self) -> None:
        """Second-chance generation swap: the live memo becomes the old
        generation — its rows compacted to the front of the storage
        arrays — and the previous old generation (entries unseen for a
        full generation) is dropped.  Hot topics get promoted back into
        the live memo on their next hit, so hitting the cap no longer
        evicts the Zipf head with the tail."""
        cur = self._memo
        n = len(cur)
        if n:
            idx = np.fromiter(cur.values(), dtype=np.int64, count=n)
            self._memo_ta[:n] = self._memo_ta[idx]
            self._memo_tb[:n] = self._memo_tb[idx]
            self._memo_ln[:n] = self._memo_ln[idx]
            self._memo_dl[:n] = self._memo_dl[idx]
        self._memo_old = {t: j for j, t in enumerate(cur)}
        self._memo = {}
        self._memo_n = n

    def _hash_topics_memo(self, topics: List[str]):
        """Batch split+hash through the cross-tick topic memo: repeated
        topic strings (Zipf traffic, bench batches, retried publishes)
        fetch their (terms, len, dollar) row from the keyed cache
        instead of re-paying the native split+hash.  Returns
        (ta, tb, ln, dl) gathered rows."""
        from . import hashing

        if len(self._memo) + len(topics) > self._cap >> 1:
            self._memo_swap()
        memo = self._memo
        old = self._memo_old
        rows: List[int] = []
        for t in topics:
            r = memo.get(t, -1)
            if r < 0 and old:
                r = old.get(t, -1)
                if r >= 0:
                    memo[t] = r  # second chance: promote to the live gen
            rows.append(r)
        miss = [i for i, r in enumerate(rows) if r < 0]
        if miss:
            uniq = dict.fromkeys(topics[i] for i in miss)
            miss_list = list(uniq)
            mta, mtb, mln, mdl = hashing.hash_topics(self.space, miss_list)
            base = self._memo_n
            need = base + len(miss_list)
            if need > len(self._memo_ln):
                self._memo_grow(need)
            self._memo_ta[base:need] = mta
            self._memo_tb[base:need] = mtb
            self._memo_ln[base:need] = mln
            self._memo_dl[base:need] = mdl
            for j, t in enumerate(miss_list):
                memo[t] = base + j
            self._memo_n = need
            for i in miss:
                rows[i] = memo[topics[i]]
            self._py_misses += len(miss_list)
            # hits = rows served from cached lanes (cross-tick repeats
            # AND in-batch duplicates past each name's first occurrence)
            self._py_hits += len(topics) - len(miss_list)
        else:
            self._py_hits += len(topics)
        ridx = np.asarray(rows, dtype=np.int64)
        return (self._memo_ta[ridx], self._memo_tb[ridx],
                self._memo_ln[ridx], self._memo_dl[ridx])


# --------------------------------------------------------------- stage


class PrepTicket:
    """One staged prep job (see PrepStage).

    Lifecycle: queued -> done (res set, event fired) -> claimed by the
    consumer, or abandoned (timeout/mismatch/teardown: the worker's
    result — if any — returns its buffer to the pool).  ``pending`` is
    engine-side bookkeeping: the dispatched `_ShardedPending` when this
    ticket rode a coalesced group dispatch before being claimed."""

    __slots__ = ("topics", "res", "err", "pending", "_evt", "_lock",
                 "_state")

    def __init__(self, topics: List[str]):
        self.topics = topics
        self.res: Optional[PrepResult] = None
        self.err: Optional[BaseException] = None
        self.pending = None  # set by the engine on coalesced dispatch
        self._evt = threading.Event()
        self._lock = threading.Lock()
        self._state = "queued"

    def peek(self) -> Optional[PrepResult]:
        """The result if prepped and unclaimed, without claiming."""
        with self._lock:
            return self.res if self._state == "done" else None

    def claim(self, timeout: float) -> Optional[PrepResult]:
        """Take ownership of the result; None = not ready in time (the
        ticket is abandoned: a late worker result is discarded, so the
        consumer can safely prep inline — the degrade contract)."""
        if not self._evt.wait(timeout):
            with self._lock:
                if self._state == "done":  # finished during the race
                    self._state = "claimed"
                    return self.res
                self._state = "abandoned"
                return None
        with self._lock:
            if self._state != "done":
                return None
            self._state = "claimed"
            return self.res

    def abandon(self) -> Optional[PrepResult]:
        """Mark abandoned; returns the result if one must be recycled."""
        with self._lock:
            res, self.res = self.res, None
            self._state = "abandoned"
            return res

    def _fulfill(self, res: Optional[PrepResult],
                 err: Optional[BaseException]) -> bool:
        """Worker side: publish the result unless already abandoned."""
        with self._lock:
            if self._state != "queued":
                return False  # abandoned while prepping: caller recycles
            self.res = res
            self.err = err
            self._state = "done" if err is None else "failed"
            self._evt.set()
            return True


class PrepStage:
    """Prep-ahead pipeline stage: one persistent worker thread running
    `TopicPrep.pack` for future ticks while the current tick's dispatch
    is in flight.

    Lifecycle (PR 10 rules): the thread is retained on the stage and
    joined by :meth:`close`; the queue sentinel is the cancellation
    signal.  The fault site ``engine.prep`` (delay action) models a
    stalled prep worker — consumers degrade to inline prep via
    `PrepTicket.claim`'s timeout, never freezing the dispatch window.
    """

    def __init__(self, prep: TopicPrep, name: str = "etpu-prep-ahead"):
        self._prep = prep
        self._name = name
        self._q: "queue.Queue[Optional[PrepTicket]]" = queue.Queue()
        # submitted-but-undispatched tickets in submit order; touched
        # only on the submitter's thread (the engine's event loop)
        self._order: List[PrepTicket] = []  # analysis: owner=loop
        self._thread: Optional[threading.Thread] = None  # analysis: owner=loop
        self.prepped = 0  # ticks prepped by the worker  # analysis: owner=any

    # ------------------------------------------------------------- submit

    def submit(self, topics: List[str]) -> PrepTicket:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name=self._name, daemon=True
            )
            self._thread.start()
        t = PrepTicket(list(topics))
        self._order.append(t)
        self._q.put(t)
        return t

    @property
    def ready_count(self) -> int:
        """Tickets prepped and not yet dispatched/claimed (the
        prep-ahead occupancy the bench column reports)."""
        return sum(1 for t in self._order if t.peek() is not None)

    def ready_group(self, key: Tuple[int, int],
                    limit: int) -> List[PrepTicket]:
        """The prepped-unclaimed-undispatched ticket PREFIX in the same
        (B, L) bucket — the coalescible group for a dispatch whose head
        ticket was just consumed.  Stops at the first gap: coalescing
        must preserve submit order."""
        out: List[PrepTicket] = []
        for t in self._order:
            if len(out) >= limit:
                break
            r = t.peek()
            if r is None or r.key != key or t.pending is not None:
                break
            out.append(t)
        return out

    def consume(self, ticket: PrepTicket) -> None:
        """Drop a claimed/dispatched/abandoned ticket from the order."""
        try:
            self._order.remove(ticket)
        except ValueError:
            pass

    # ----------------------------------------------------------- teardown

    def close(self, timeout: float = 10.0) -> None:
        """Cancel the worker (sentinel + join) and recycle every
        undispatched ticket's buffer."""
        th, self._thread = self._thread, None
        if th is not None and th.is_alive():
            self._q.put(None)
            th.join(timeout)
        for t in self._order:
            res = t.abandon()
            if res is not None:
                self._prep.release(res.buf, res.key)
        self._order = []

    # ------------------------------------------------------------- worker

    def _run(self) -> None:
        from .. import fault as _fault

        while True:
            t = self._q.get()
            if t is None:
                return  # sentinel: stage closed
            if _fault.enabled():
                # delay-only site: models a stalled prep worker; the
                # consumer's claim() times out and preps inline
                _fault.inject("engine.prep", err=False)
            res = err = None
            try:
                res = self._prep.pack(t.topics)
            except BaseException as e:  # surfaced via ticket.err
                err = e
            if not t._fulfill(res, err):
                # abandoned while prepping: recycle the buffer
                if res is not None:
                    self._prep.release(res.buf, res.key)
            else:
                self.prepped += 1
